"""Serve-side telemetry: windowed latency/occupancy records + /statsz.

The ``serve`` record family (telemetry/schema.py) mirrors the training
layer's ``step_window``/``run_summary`` pair:

* ``kind="serve_window"`` — emitted every ``window`` completed requests:
  request count, end-to-end and on-device latency percentiles
  (p50/p95/p99, milliseconds), batch count, mean batch occupancy
  (real tokens / dispatched slot budget — the serving analog of
  ``padding_efficiency``), max queue depth, the number of XLA
  compiles observed in the window (zero in steady state — the engine
  AOT-compiles every (task, bucket) at startup), and two
  continuous-batching gauges (docs/serving.md "Continuous batching"):
  ``admitted_late`` (requests that joined a forming batch through the
  admission window) and ``device_idle_share`` (executor gap between
  consecutive forwards / (gap + busy) — the idle the pipelined
  dispatch plane exists to squeeze out, and the metric behind the
  "serve device idle share" report gate);
* ``kind="serve_summary"`` — the end-of-run rollup ``finish()`` emits,
  plus the live snapshot ``/statsz`` serves.

Records flow through the same JSONLHandler/schema machinery as training
telemetry, so ``tools/check_telemetry_schema.py`` lints them (p50 <= p95
<= p99, occupancy in (0, 1]) and ``telemetry-report`` summarizes and
baseline-diffs them (p95 latency gate).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, List, Optional

# Run-level percentile basis: the MOST RECENT this-many request samples.
# A long-running server at heavy traffic would otherwise grow its latency
# history without bound and sort it under the lock on every /statsz scrape
# (window records are exact — they reset per window).
RUN_SAMPLE_CAP = 8192


def _pctl(sorted_vals: List[float], frac: float) -> float:
    """Nearest-rank percentile of an already-sorted list (the step_timer
    convention)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              int(frac * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[idx]


def _latency_fields(prefix: str, seconds: List[float]) -> dict:
    s = sorted(seconds)
    return {
        f"{prefix}_p50_ms": round(_pctl(s, 0.50) * 1000.0, 3),
        f"{prefix}_p95_ms": round(_pctl(s, 0.95) * 1000.0, 3),
        f"{prefix}_p99_ms": round(_pctl(s, 0.99) * 1000.0, 3),
    }


class ServeTelemetry:
    """Accumulates per-batch serving observations; emits window records.

    Thread-safety: ``observe_batch`` is called by the single dispatch
    thread, but ``snapshot()`` is read by HTTP worker threads — one lock
    covers both. ``emit`` receives plain record dicts (a JSONLHandler's
    ``write_record``, or TrainTelemetry.emit); None disables emission
    while the in-memory rollup keeps working (/statsz, bench).
    """

    def __init__(self, emit: Optional[Callable[[dict], None]] = None,
                 window: int = 64,
                 clock: Callable[[], float] = time.monotonic):
        self.emit = emit
        self.window = max(1, int(window))
        self._clock = clock
        self._lock = threading.Lock()
        self._t0 = clock()
        # current window
        self._e2e: List[float] = []
        self._device: List[float] = []
        self._batches = 0
        self._real_tokens = 0
        self._budget_tokens = 0
        self._depth_max = 0
        self._compiles = 0
        self._admitted_late = 0
        # Executor-gap accounting: device idle seconds between
        # consecutive forwards vs the busy (forward) seconds they
        # bracket — only batches that carried a gap sample contribute
        # to the busy basis, so the share is a true ratio.
        self._gap_s = 0.0
        self._gap_busy_s = 0.0
        self._window_t0 = clock()
        # run totals; latency samples bounded to the RUN_SAMPLE_CAP most
        # recent so a long-lived server's memory and /statsz cost stay flat
        self.total_requests = 0
        self.total_batches = 0
        self.total_errors = 0
        self._run_e2e = collections.deque(maxlen=RUN_SAMPLE_CAP)
        self._run_device = collections.deque(maxlen=RUN_SAMPLE_CAP)
        self._run_real_tokens = 0
        self._run_budget_tokens = 0
        self._run_depth_max = 0
        self._run_compiles = 0
        self._run_admitted_late = 0
        self._run_gap_s = 0.0
        self._run_gap_busy_s = 0.0
        # Engine startup stats (cold_start_s, warm/cold compile split,
        # quantize mode, weight bytes): written once by observe_cold_start
        # on the thread that ran warmup, read by HTTP workers via
        # snapshot() for /statsz — same lock as the other rollup state
        # (concurrency registry, analysis/concurrency.py).
        self._cold_start: Optional[dict] = None
        # Optional request tracer (serve/tracing.py): attached once by
        # the service before dispatch starts, read by snapshot()/finish()
        # on scrape threads — guarded by the same lock (registry entry).
        self._tracer = None

    # -- producer --------------------------------------------------------

    def observe_batch(self, e2e_s: List[float], device_s: float,
                      rows: int, bucket: int, real_tokens: int,
                      queue_depth: int = 0, compiles: int = 0,
                      admitted_late: int = 0,
                      exec_gap_s: Optional[float] = None) -> None:
        """Record one dispatched batch: per-request end-to-end latencies,
        the batch's forward wall time (incl. device sync), its dispatched
        slot budget (``rows * bucket``), and the real tokens it carried.
        ``admitted_late`` counts the batch's requests that joined its
        forming plan through the admission window; ``exec_gap_s`` is the
        device-idle gap between the previous forward's end and this
        one's start (None for the first batch — no gap exists yet)."""
        budget = int(rows) * int(bucket)
        with self._lock:
            self._e2e.extend(e2e_s)
            self._device.append(device_s)
            self._batches += 1
            self._real_tokens += int(real_tokens)
            self._budget_tokens += budget
            self._depth_max = max(self._depth_max, int(queue_depth))
            self._compiles += int(compiles)
            self._admitted_late += int(admitted_late)
            if exec_gap_s is not None:
                gap = max(0.0, float(exec_gap_s))
                self._gap_s += gap
                self._gap_busy_s += float(device_s)
                self._run_gap_s += gap
                self._run_gap_busy_s += float(device_s)
            self.total_requests += len(e2e_s)
            self.total_batches += 1
            self._run_e2e.extend(e2e_s)
            self._run_device.append(device_s)
            self._run_real_tokens += int(real_tokens)
            self._run_budget_tokens += budget
            self._run_depth_max = max(self._run_depth_max,
                                      int(queue_depth))
            self._run_compiles += int(compiles)
            self._run_admitted_late += int(admitted_late)
            due = len(self._e2e) >= self.window
        if due:
            self.flush_window()

    def observe_error(self) -> None:
        with self._lock:
            self.total_errors += 1

    def attach_tracer(self, tracer) -> None:
        """Fold a :class:`~bert_pytorch_tpu.serve.tracing.TraceCollector`
        into this rollup: ``snapshot()``/``/statsz`` gain the run-level
        ``phases`` sub-object (queue-wait share, per-phase p95s, SLO
        accounting) and ``finish()`` flushes the tracer's partial
        serve_phase windows — one scrape surface stays consistent with
        /metricsz."""
        with self._lock:
            self._tracer = tracer

    def request_count(self) -> int:
        """Completed-request total, read under the lock (the serve
        heartbeat's step counter — a bare ``total_requests`` read would
        race the dispatch thread, jaxlint LK501)."""
        with self._lock:
            return self.total_requests

    def observe_cold_start(self, startup: dict) -> Optional[dict]:
        """Record the engine's startup stats (``InferenceEngine.startup``)
        and emit one ``serve_cold_start`` record: how long the AOT warmup
        took and how many of its compiles were real XLA compiles vs
        persistent-cache hits — THE restart-cost signal (a warm replica
        shows ``compiles_cold == 0``; the cache counter events behind the
        split are the authority, docs/serving.md). Fields also ride
        ``snapshot()``/``/statsz`` so a router can see each replica's
        quantize mode and startup cost."""
        if not startup:
            return None
        with self._lock:
            if self._cold_start == startup:
                # A stop()/start() cycle re-observes the SAME engine
                # start (warmup didn't run again); re-emitting would
                # double-count cold compiles in the report's summed
                # warm-restart gate. A genuine re-warmup produces a
                # fresh stats dict (new cold_start_s) and is recorded.
                return None
            self._cold_start = dict(startup)
        record = {"kind": "serve_cold_start", "tag": "serve"}
        record.update(startup)
        if self.emit is not None:
            self.emit(record)
        return record

    def reset_clock(self) -> None:
        """Restart the run/window wall-clock base. Called by the service
        after engine warmup so ``requests_per_sec`` measures serving time,
        not the AOT compile phase it would otherwise amortize in."""
        with self._lock:
            now = self._clock()
            self._t0 = now
            self._window_t0 = now

    # -- records ---------------------------------------------------------

    def _occupancy(self, real: int, budget: int) -> Optional[float]:
        if budget <= 0:
            return None
        # Clamp into the schema's (0, 1] — an all-pad window (real == 0)
        # cannot happen because every dispatched request carries >= 2
        # tokens, but guard the floor anyway.
        return round(min(1.0, max(real, 1) / budget), 4)

    @staticmethod
    def _idle_share(gap_s: float, busy_s: float) -> Optional[float]:
        """Device-idle share over the batches that carried a gap sample
        (None before a second forward exists — one batch has no gap)."""
        total = gap_s + busy_s
        if total <= 0:
            return None
        return round(min(1.0, max(0.0, gap_s / total)), 4)

    def flush_window(self) -> Optional[dict]:
        """Emit (and return) the current window record; None when empty."""
        with self._lock:
            if not self._e2e:
                return None
            now = self._clock()
            wall = max(now - self._window_t0, 1e-9)
            record = {
                "kind": "serve_window",
                "tag": "serve",
                "window_requests": len(self._e2e),
                "batches": self._batches,
                "requests_per_sec": round(len(self._e2e) / wall, 3),
                "queue_depth_max": self._depth_max,
                "compiles": self._compiles,
            }
            record.update(_latency_fields("latency", self._e2e))
            record.update(_latency_fields("device", self._device))
            occ = self._occupancy(self._real_tokens, self._budget_tokens)
            if occ is not None:
                record["batch_occupancy"] = occ
            record["admitted_late"] = self._admitted_late
            idle = self._idle_share(self._gap_s, self._gap_busy_s)
            if idle is not None:
                record["device_idle_share"] = idle
            self._e2e = []
            self._device = []
            self._batches = 0
            self._real_tokens = 0
            self._budget_tokens = 0
            self._depth_max = 0
            self._compiles = 0
            self._admitted_late = 0
            self._gap_s = 0.0
            self._gap_busy_s = 0.0
            self._window_t0 = now
        if self.emit is not None:
            self.emit(record)
        return record

    def snapshot(self, include_phases: bool = True) -> dict:
        """Run-level rollup for /statsz and the serve_summary record.
        With a tracer attached, carries its run-level phase rollup as
        the ``phases`` sub-object (same numbers /metricsz exports);
        ``include_phases=False`` skips that merge for callers that only
        want the base gauges (the /metricsz renderer — computing the
        tracer's full percentile rollup per scrape just to discard it
        would hold the tracer lock against the dispatch thread)."""
        with self._lock:
            tracer = self._tracer if include_phases else None
            wall = max(self._clock() - self._t0, 1e-9)
            record = {
                "requests": self.total_requests,
                "batches": self.total_batches,
                "errors": self.total_errors,
                "requests_per_sec": round(self.total_requests / wall, 3),
                "queue_depth_max": self._run_depth_max,
                "compiles": self._run_compiles,
            }
            record.update(_latency_fields("latency", self._run_e2e))
            record.update(_latency_fields("device", self._run_device))
            occ = self._occupancy(self._run_real_tokens,
                                  self._run_budget_tokens)
            if occ is not None:
                record["batch_occupancy"] = occ
            record["admitted_late"] = self._run_admitted_late
            idle = self._idle_share(self._run_gap_s, self._run_gap_busy_s)
            if idle is not None:
                record["device_idle_share"] = idle
            if self._cold_start is not None:
                # 'compiles' here is the STEADY-STATE count (zero after
                # warmup — the serve acceptance); the warmup compile
                # split keeps its own prefix.
                cs = self._cold_start
                record["cold_start_s"] = cs.get("cold_start_s")
                for key in ("compiles", "compiles_cold", "compiles_warm"):
                    if cs.get(key) is not None:
                        record[f"warmup_{key}"] = cs[key]
                for key in ("quantize", "attention_backend",
                            "weight_bytes", "fuse_epilogues", "autotune"):
                    if cs.get(key) is not None:
                        record[key] = cs[key]
        # Outside the lock: the tracer takes its own lock, and nesting
        # the two buys nothing (the binding was read consistently above).
        if tracer is not None:
            phases = tracer.phase_snapshot()
            if phases:
                record["phases"] = phases
        return record

    def finish(self) -> Optional[dict]:
        """Flush the partial window and emit the serve_summary record
        (and the attached tracer's partial serve_phase windows)."""
        with self._lock:
            tracer = self._tracer
        if tracer is not None:
            tracer.finish()
        self.flush_window()
        # snapshot() reads the run totals under the lock — the bare
        # total_requests read that used to sit here raced the dispatch
        # thread's observe_batch (jaxlint LK501 finding, fixed in PR 7).
        snap = self.snapshot()
        if not snap["requests"]:
            return None
        record = {"kind": "serve_summary", "tag": "serve"}
        record.update(snap)
        if self.emit is not None:
            self.emit(record)
        return record
