"""Replica supervisor: own N serving subprocesses, keep them alive
(docs/serving.md "Fleet tier", docs/fault_tolerance.md "Serve failover").

A single serving engine process is a single point of failure — the fleet
tier's first primitive (The Tail at Scale, Dean & Barroso 2013) is
simply N replicas that something RESTARTS. The :class:`Supervisor` owns
one subprocess per :class:`ReplicaSpec` (a ``run_server.py`` on its own
port, warmed from the shared persistent AOT compile cache so a restart
costs seconds, not a recompile — PR 8's zero-cold-compile property is
what makes supervision worth having) and runs one monitor thread that:

* **reaps exits** — a replica that died is classified by exit code:
  ``EXIT_PREEMPTED`` (75) means a SIGTERM-initiated drain finished
  cleanly (run_server.py holds the training runners' preemption
  contract); anything else is a crash and schedules a restart;
* **applies restart-storm backoff** — consecutive crash restarts walk a
  full-jitter exponential schedule (``utils/retry.py RetryPolicy``) so a
  crash-looping replica cannot hot-spin the host; a replica that stays
  up ``stable_reset_s`` earns its backoff index back. After
  ``policy.attempts`` consecutive crashes the supervisor GIVES UP on
  that replica (emits the event; the router's health gate has long since
  stopped routing to it);
* **catches wedges the health check cannot** — a dispatch thread stuck
  in a hung device call keeps ``/healthz`` answering 200 (the thread is
  alive, just never finishing a batch). The supervisor instead watches
  the replica's HEARTBEAT FILE (the same resumable liveness file the
  training runners write; the serve dispatch loop beats it once per
  second with its request count): a counter that stops advancing past
  ``heartbeat_timeout_s`` gets the replica SIGKILLed and restarted —
  the watchdog path ``tools/chaos_serve.py`` proves;
* **optionally probes /healthz** — ``probe_failures_to_kill``
  consecutive failed probes of a process that still looks alive also
  force a restart (listener wedged while dispatch runs).

Every decision emits a schema-v1 ``fleet_event`` record, so the chaos
harness (and an operator reading the artifact) can reconstruct exactly
what the supervisor saw and did.

This module is **stdlib-only and dual-loadable**: imported normally it
is part of the serve package; loaded by FILE PATH (tools/_bootstrap.py)
it pulls its two utility dependencies the same way, so the jax-free
chaos/fleet parents never execute the package ``__init__`` chain — a
hung accelerator runtime can hang a REPLICA (which the watchdog kills),
never the supervisor itself.
"""

from __future__ import annotations

import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.parse
from typing import Callable, List, Optional, Sequence


def _load_pkg_module(subpkg: str, modname: str):
    """Import a stdlib-only package sibling both ways: through the
    package when this module was imported normally, by file path when
    this module was itself loaded by path (the package ``__init__``
    chain imports jax — the property tools/chaos_serve.py needs)."""
    if __package__:
        import importlib

        return importlib.import_module(
            f"bert_pytorch_tpu.{subpkg}.{modname}")
    import importlib.util

    alias = f"_fleet_{subpkg}_{modname}"
    module = sys.modules.get(alias)
    if module is not None:
        return module
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), subpkg, f"{modname}.py")
    spec = importlib.util.spec_from_file_location(alias, path)
    module = importlib.util.module_from_spec(spec)
    sys.modules[alias] = module
    spec.loader.exec_module(module)
    return module


def _load_util(modname: str):
    return _load_pkg_module("utils", modname)


RetryPolicy = _load_util("retry").RetryPolicy
EXIT_PREEMPTED = _load_util("preemption").EXIT_PREEMPTED
# The same resumable liveness file the training runners and run_server
# write — the supervisor is the fleet's last liveness blind spot
# (telemetry/sentinels.py is stdlib-only, like utils/retry.py).
Heartbeat = _load_pkg_module("telemetry", "sentinels").Heartbeat

# How many of a harvested postmortem's newest records/lines ride the
# fleet_event (the full file stays on disk for the operator; the event
# names WHY the replica died without bloating the fleet artifact).
_HARVEST_TAIL = 5

# Replica lifecycle states (status()/fleet_event records).
STARTING = "starting"    # spawned; no heartbeat observed yet
RUNNING = "running"      # heartbeat advancing / probe ok
BACKOFF = "backoff"      # crashed; restart scheduled
FAILED = "failed"        # gave up (restart storm exhausted the policy)
STOPPED = "stopped"      # drained/stopped by the supervisor


class ReplicaSpec:
    """One replica's immutable launch description."""

    def __init__(self, index: int, port: int, cmd: Sequence[str],
                 heartbeat_file: Optional[str] = None,
                 postmortem_file: Optional[str] = None,
                 env: Optional[dict] = None,
                 host: str = "127.0.0.1"):
        self.index = int(index)
        self.port = int(port)
        self.cmd = list(cmd)
        self.heartbeat_file = heartbeat_file
        # The replica's flight-recorder flush target (telemetry/
        # flightrec.py): harvested into a fleet_event when the replica
        # dies, so the failover story names WHY.
        self.postmortem_file = postmortem_file
        self.env = dict(env) if env is not None else None
        self.host = host

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"


def run_server_command(port: int, output_dir: str,
                       extra_args: Sequence[str],
                       python: Optional[str] = None,
                       script: Optional[str] = None) -> List[str]:
    """The ``run_server.py`` argv for one replica: shared engine/model
    flags (``extra_args``) plus the per-replica port and output dir (the
    telemetry JSONL and the heartbeat file the supervisor watches both
    default under it)."""
    if script is None:
        script = os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))), "run_server.py")
    return ([python or sys.executable, script, *extra_args,
             "--port", str(port), "--output_dir", output_dir])


class ReplicaTemplate:
    """Shared launch recipe for elastically spawned replicas
    (docs/serving.md "Elastic fleet").

    The engine/model flags — including the AOT compile-cache dir that
    makes a new replica warm in seconds — are fixed ONCE; each
    :meth:`make_spec` call mints only the per-replica pieces: a fresh
    port (bind-to-zero unless the caller supplies one), an output dir
    named after the replica index, and the heartbeat/postmortem files
    the supervisor watches under it. ``Supervisor.add_replica`` and the
    chaos harness both build argv from this one recipe instead of two
    hand-rolled copies drifting apart.
    """

    def __init__(self, shared_args: Sequence[str], output_root: str,
                 python: Optional[str] = None,
                 script: Optional[str] = None,
                 env: Optional[dict] = None,
                 host: str = "127.0.0.1",
                 dir_name: str = "replica_{index}",
                 heartbeat_name: str = "heartbeat.json",
                 postmortem_name: Optional[str] = None):
        self.shared_args = list(shared_args)
        self.output_root = output_root
        self.python = python
        self.script = script
        self.env = dict(env) if env is not None else {}
        self.host = host
        self.dir_name = dir_name
        self.heartbeat_name = heartbeat_name
        self.postmortem_name = postmortem_name

    @staticmethod
    def alloc_port() -> int:
        """A free local port, kernel-assigned (bind to 0)."""
        s = socket.socket()
        try:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]
        finally:
            s.close()

    def make_spec(self, index: int, port: Optional[int] = None,
                  extra_args: Sequence[str] = (),
                  env: Optional[dict] = None) -> ReplicaSpec:
        """One replica's spec from the shared recipe: fresh port, its
        own output dir (created), heartbeat file under it."""
        port = int(port) if port is not None else self.alloc_port()
        out_dir = os.path.join(self.output_root,
                               self.dir_name.format(index=int(index)))
        os.makedirs(out_dir, exist_ok=True)
        merged_env = dict(self.env)
        if env:
            merged_env.update(env)
        return ReplicaSpec(
            int(index), port,
            run_server_command(port, out_dir,
                               [*self.shared_args, *extra_args],
                               python=self.python, script=self.script),
            heartbeat_file=os.path.join(out_dir, self.heartbeat_name),
            postmortem_file=(os.path.join(out_dir, self.postmortem_name)
                             if self.postmortem_name else None),
            env=merged_env, host=self.host)


class _Replica:
    """Mutable runtime state for one supervised subprocess (internal;
    every field is read/written under ``Supervisor._lock``)."""

    def __init__(self, spec: ReplicaSpec):
        self.spec = spec
        self.proc = None
        self.state = STOPPED
        self.restarts = 0            # total spawns beyond the first
        self.consecutive = 0         # crash restarts since last stable run
        self.rapid_graceful = 0      # consecutive graceful exits that
                                     # never reached stable_reset_s
        self.started_at = 0.0
        self.restart_at: Optional[float] = None
        self.last_rc: Optional[int] = None
        self.hb_counter: Optional[int] = None
        self.hb_advance_at = 0.0     # clock time the counter last moved
        self.probe_failures = 0
        # Decommission flag (drain_replica): once set it NEVER clears —
        # the exit is reaped WITHOUT respawn and the slot stays retired
        # (its index is never reused; add_replica mints fresh ones).
        self.draining = False


class Supervisor:
    """Keep ``specs``'s replica subprocesses alive until :meth:`stop`.

    Every collaborator is injectable for deterministic tests: ``spawn``
    (a ``subprocess.Popen``-alike factory), ``probe`` (url -> health
    dict or None), ``read_heartbeat`` (spec -> counter int or None),
    ``clock``/``sleep``. Production uses the defaults.
    """

    def __init__(
        self,
        specs: Sequence[ReplicaSpec],
        emit: Optional[Callable[[dict], None]] = None,
        spawn: Optional[Callable[[ReplicaSpec], object]] = None,
        policy: Optional[RetryPolicy] = None,
        heartbeat_timeout_s: float = 15.0,
        startup_grace_s: float = 120.0,
        stable_reset_s: float = 30.0,
        probe: Optional[Callable[[str], Optional[dict]]] = None,
        probe_failures_to_kill: int = 3,
        poll_interval_s: float = 0.5,
        drain_grace_s: float = 15.0,
        read_heartbeat: Optional[Callable[[ReplicaSpec],
                                          Optional[int]]] = None,
        heartbeat_file: Optional[str] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if not specs:
            raise ValueError("need at least one ReplicaSpec")
        self._emit_fn = emit
        self._spawn = spawn or self._default_spawn
        # Full jitter: when a shared cause (OOM, bad rollout) crashes
        # several replicas at once, their restart storms must not march
        # in lockstep against the same compile cache / port range.
        self.policy = policy or RetryPolicy(
            attempts=6, base_delay_s=0.5, max_delay_s=30.0,
            full_jitter=True)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.startup_grace_s = float(startup_grace_s)
        self.stable_reset_s = float(stable_reset_s)
        self._probe = probe
        self.probe_failures_to_kill = int(probe_failures_to_kill)
        self.poll_interval_s = float(poll_interval_s)
        self.drain_grace_s = float(drain_grace_s)
        self._read_heartbeat = read_heartbeat or self._heartbeat_counter
        self._clock = clock
        self._sleep = sleep
        # Guards _replicas (and every _Replica field): the monitor
        # thread mutates replica state while start()/stop()/status()
        # callers read it (concurrency registry, analysis/concurrency.py).
        self._lock = threading.Lock()
        self._replicas = [_Replica(spec) for spec in specs]
        # Monotone replica-index mint for add_replica: an index is
        # NEVER reused, so every fleet_event/scale_event stream entry
        # stays attributable to exactly one replica incarnation lineage.
        self._next_index = max(spec.index for spec in specs) + 1
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # The supervisor's OWN liveness file (step = supervision ticks):
        # the same resumable heartbeat the runners and run_server write,
        # closing the chaos harness's last liveness blind spot. Beaten
        # only from poll_once (the monitor thread, or the fake-clock
        # test driving passes itself) — Heartbeat relies on that
        # single-caller lifecycle, like the serve dispatch loop's.
        self._heartbeat = Heartbeat(heartbeat_file) if heartbeat_file \
            else None
        self._ticks = 0

    # -- telemetry --------------------------------------------------------

    def _emit(self, event: str, replica: _Replica, **extra) -> None:
        if self._emit_fn is None:
            return
        record = {"kind": "fleet_event", "tag": "fleet", "event": event,
                  "replica": replica.spec.index,
                  "port": replica.spec.port}
        record.update(extra)
        try:
            self._emit_fn(record)
        except Exception:
            pass  # observability must never take the fleet down

    # -- default collaborators -------------------------------------------

    @staticmethod
    def _default_spawn(spec: ReplicaSpec):
        env = dict(os.environ)
        if spec.env:
            env.update(spec.env)
        return subprocess.Popen(spec.cmd, env=env)

    @staticmethod
    def _heartbeat_counter(spec: ReplicaSpec) -> Optional[int]:
        """The replica's heartbeat counter (telemetry/sentinels.py
        Heartbeat writes it atomically); None = no/torn file, treated
        as "no evidence of liveness"."""
        if not spec.heartbeat_file:
            return None
        try:
            with open(spec.heartbeat_file) as f:
                return int(json.load(f).get("counter", 0))
        except (OSError, ValueError, TypeError):
            return None

    # -- lifecycle --------------------------------------------------------

    def start(self, monitor: bool = True) -> None:
        """Spawn every replica and the monitor thread. ``monitor=False``
        skips the thread — fake-clock tests drive :meth:`poll_once`
        themselves."""
        now = self._clock()
        with self._lock:
            for rep in self._replicas:
                if rep.proc is None and not rep.draining:
                    self._spawn_locked(rep, now)
        self._stop_event.clear()
        if monitor:
            self._thread = threading.Thread(
                target=self._loop, name="fleet-supervisor", daemon=True)
            self._thread.start()

    def _spawn_locked(self, rep: _Replica, now: float) -> None:
        if rep.spec.postmortem_file:
            # Fresh forensics per incarnation: the dead predecessor's
            # postmortem was harvested at reap time (the fleet_event);
            # leaving the file would let a NEXT crash-before-first-flush
            # harvest the wrong incarnation's last seconds.
            try:
                os.remove(rep.spec.postmortem_file)
            except OSError:
                pass
        rep.proc = self._spawn(rep.spec)
        rep.state = STARTING
        rep.started_at = now
        rep.restart_at = None
        # Baseline the heartbeat BEFORE the new process beats: the file
        # survives restarts (the counter resumes from it), so the dead
        # predecessor's last value must not read as an "advance" — that
        # would flip a still-warming replica to RUNNING and arm the
        # short wedge timeout against its startup time.
        rep.hb_counter = self._read_heartbeat(rep.spec)
        rep.hb_advance_at = now
        rep.probe_failures = 0
        self._emit("spawn", rep, restarts=rep.restarts,
                   pid=getattr(rep.proc, "pid", None))

    def _loop(self) -> None:
        while not self._stop_event.is_set():
            self.poll_once()
            self._sleep(self.poll_interval_s)

    # -- the monitoring pass (public for fake-clock tests) ---------------

    def poll_once(self) -> None:
        """One monitoring pass over every replica: reap exits, schedule
        and execute backoff restarts, kill wedged processes. Each pass
        beats the supervisor's own heartbeat (step = tick count), so
        "is the supervisor itself alive" is readable the same way
        replica liveness is."""
        now = self._clock()
        with self._lock:
            for rep in self._replicas:
                self._poll_replica_locked(rep, now)
        self._ticks += 1
        if self._heartbeat is not None:
            self._heartbeat.beat(self._ticks)

    def _poll_replica_locked(self, rep: _Replica, now: float) -> None:
        if rep.state == FAILED or (rep.state == STOPPED
                                   and rep.proc is None):
            return
        if rep.state == BACKOFF:
            if rep.restart_at is not None and now >= rep.restart_at:
                rep.restarts += 1
                self._spawn_locked(rep, now)
            return
        proc = rep.proc
        if proc is None:
            return
        rc = proc.poll()
        if rc is not None:
            self._handle_exit_locked(rep, rc, now)
            return
        # Alive: fold in heartbeat progress, then the wedge/probe checks.
        counter = self._read_heartbeat(rep.spec)
        if counter is not None and counter != rep.hb_counter:
            rep.hb_counter = counter
            rep.hb_advance_at = now
            if rep.state == STARTING:
                rep.state = RUNNING
            # A stable stretch pays the restart-storm debt back (and
            # re-earns the free graceful respawn).
            if ((rep.consecutive or rep.rapid_graceful)
                    and now - rep.started_at >= self.stable_reset_s):
                rep.consecutive = 0
                rep.rapid_graceful = 0
        if rep.spec.heartbeat_file:
            limit = (self.heartbeat_timeout_s if rep.state == RUNNING
                     else self.startup_grace_s)
            age = now - max(rep.hb_advance_at, rep.started_at)
            if age > limit:
                self._emit("wedged_kill", rep,
                           heartbeat_age_s=round(age, 3),
                           requests=rep.hb_counter)
                self._kill_locked(rep)
                self._harvest_postmortem_locked(rep, context="wedged")
                if rep.draining:
                    # A drain that wedged instead of exiting: the kill
                    # completes the decommission, never a respawn.
                    rep.state = STOPPED
                    self._emit("drain_complete", rep, rc=rep.last_rc,
                               graceful=False)
                    return
                self._schedule_restart_locked(rep, now, crash=True,
                                              reason="wedged")
                return
        if self._probe is not None and rep.state == RUNNING:
            health = None
            try:
                health = self._probe(rep.spec.url)
            except Exception:
                health = None
            ok = bool(health) and health.get("status") in ("ok", "draining")
            rep.probe_failures = 0 if ok else rep.probe_failures + 1
            if rep.probe_failures >= self.probe_failures_to_kill:
                self._emit("probe_kill", rep,
                           failures=rep.probe_failures)
                self._kill_locked(rep)
                self._harvest_postmortem_locked(rep, context="probe")
                if rep.draining:
                    rep.state = STOPPED
                    self._emit("drain_complete", rep, rc=rep.last_rc,
                               graceful=False)
                    return
                self._schedule_restart_locked(rep, now, crash=True,
                                              reason="probe")

    def _handle_exit_locked(self, rep: _Replica, rc: int,
                            now: float) -> None:
        rep.last_rc = rc
        rep.proc = None
        graceful = rc in (0, EXIT_PREEMPTED)
        self._emit("exit", rep, rc=rc, graceful=graceful,
                   uptime_s=round(now - rep.started_at, 3))
        if not graceful:
            # The failover story should name WHY the replica died, not
            # just that it did: harvest the dead process's flight-
            # recorder flush (its last telemetry records and log lines)
            # into the fleet artifact before the slot is respawned.
            self._harvest_postmortem_locked(rep, context="exit")
        if rep.draining:
            # A scale-down drain (drain_replica): the ONE exit the
            # supervisor's "N alive" contract does not replace. Reap,
            # mark the slot retired, and tell the autoscaler the drain
            # is confirmed — the router target is removed only now, so
            # every in-flight request already got its answer.
            rep.state = STOPPED
            self._emit("drain_complete", rep, rc=rc, graceful=graceful)
            return
        if self._stop_event.is_set():
            rep.state = STOPPED
            return
        # A replica that drained on an external SIGTERM still leaves the
        # fleet a replica short — the supervisor's contract is N alive,
        # so graceful exits respawn too, just WITHOUT burning the
        # restart-storm budget (the exit was asked for, not a crash).
        # ONE free graceful respawn per stable stretch, though: a
        # replica that keeps exiting 0/75 within stable_reset_s of each
        # spawn is a crash loop wearing a polite exit code (a config
        # that drains instantly, an external agent SIGTERMing every
        # startup), and a zero-backoff respawn every poll tick is
        # exactly the storm the backoff schedule exists to prevent.
        if graceful:
            rapid = (now - rep.started_at) < self.stable_reset_s
            churn = rapid and rep.rapid_graceful > 0
            rep.rapid_graceful = rep.rapid_graceful + 1 if rapid else 0
            self._schedule_restart_locked(
                rep, now, crash=churn,
                reason="graceful_churn" if churn else "exit")
        else:
            self._schedule_restart_locked(rep, now, crash=True,
                                          reason="exit")

    def _schedule_restart_locked(self, rep: _Replica, now: float,
                                 crash: bool, reason: str) -> None:
        if crash:
            if rep.consecutive + 1 >= self.policy.attempts:
                rep.state = FAILED
                self._emit("gave_up", rep, restarts=rep.restarts,
                           consecutive=rep.consecutive + 1)
                return
            backoff = self.policy.backoff_s(rep.consecutive)
            rep.consecutive += 1
        else:
            backoff = 0.0
        rep.state = BACKOFF
        rep.restart_at = now + backoff
        self._emit("restart_scheduled", rep, backoff_s=round(backoff, 3),
                   restarts=rep.restarts, crash=crash, reason=reason)

    def _harvest_postmortem_locked(self, rep: _Replica,
                                   context: str) -> None:
        """Emit the dead replica's postmortem (telemetry/flightrec.py
        flush) as a ``fleet_event``: the ring's newest records/lines
        (bounded to ``_HARVEST_TAIL`` each — the file keeps the full
        ring for the operator), the flush reason, and whether a
        postmortem existed at all (a crash before the first flush is
        itself diagnostic)."""
        spec = rep.spec
        if not spec.postmortem_file:
            return
        pm = None
        try:
            with open(spec.postmortem_file, "r", encoding="utf-8") as f:
                pm = json.load(f)
        except (OSError, ValueError):
            pm = None
        if not isinstance(pm, dict):
            self._emit("postmortem", rep, context=context, found=False,
                       path=spec.postmortem_file)
            return
        records = pm.get("records") or []
        lines = pm.get("lines") or []
        self._emit(
            "postmortem", rep, context=context, found=True,
            path=spec.postmortem_file,
            reason=pm.get("reason"), process=pm.get("process"),
            flushed_at=pm.get("flushed_at"),
            ring_entries=pm.get("ring_entries"),
            ring_bytes=pm.get("ring_bytes"),
            dropped=pm.get("dropped"),
            records=records[-_HARVEST_TAIL:]
            if isinstance(records, list) else [],
            lines=lines[-_HARVEST_TAIL:]
            if isinstance(lines, list) else [])

    def _kill_locked(self, rep: _Replica) -> None:
        proc = rep.proc
        rep.proc = None
        if proc is None:
            return
        try:
            proc.kill()
            proc.wait(timeout=10.0)
        except Exception:
            pass

    # -- drain / stop -----------------------------------------------------

    def stop(self) -> dict:
        """Drain the fleet: SIGTERM every replica, wait up to
        ``drain_grace_s`` for the preemption-contract exits (rc 75 /
        0), SIGKILL stragglers, join the monitor thread. Returns a
        summary the chaos harness asserts on: per-replica final rc and
        whether every live replica drained gracefully."""
        self._stop_event.set()
        with self._lock:
            live = [rep for rep in self._replicas if rep.proc is not None]
            for rep in live:
                self._emit("drain", rep)
                try:
                    rep.proc.send_signal(signal.SIGTERM)
                except Exception:
                    pass
        deadline = self._clock() + self.drain_grace_s
        while self._clock() < deadline:
            with self._lock:
                waiting = False
                for rep in self._replicas:
                    if rep.proc is None:
                        continue
                    rc = rep.proc.poll()
                    if rc is None:
                        waiting = True
                    else:
                        self._handle_exit_locked(rep, rc, self._clock())
            if not waiting:
                break
            self._sleep(min(0.05, self.poll_interval_s))
        killed = 0
        with self._lock:
            for rep in self._replicas:
                if rep.proc is not None:
                    killed += 1
                    self._emit("drain_kill", rep)
                    self._kill_locked(rep)
                    rep.state = STOPPED
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        with self._lock:
            rcs = {rep.spec.index: rep.last_rc for rep in self._replicas}
        graceful = all(rc in (0, EXIT_PREEMPTED)
                       for rc in rcs.values() if rc is not None)
        return {"rcs": rcs, "drain_killed": killed,
                "all_graceful": graceful and killed == 0}

    # -- elastic membership (serve/autoscaler.py, docs/serving.md
    # "Elastic fleet") ----------------------------------------------------

    def add_replica(self, template: ReplicaTemplate,
                    port: Optional[int] = None) -> ReplicaSpec:
        """Grow the fleet by one: mint a spec from ``template`` under a
        NEVER-REUSED replica index (fresh port + output dir + heartbeat
        baseline per incarnation), spawn it, and return the spec. The
        caller registers ``spec.url`` with the router, where the new
        target enters UNHEALTHY until its first clean scrape — a
        still-warming replica never absorbs traffic."""
        now = self._clock()
        with self._lock:
            index = self._next_index
            self._next_index += 1
            spec = template.make_spec(index, port=port)
            rep = _Replica(spec)
            self._replicas.append(rep)
            self._spawn_locked(rep, now)
        return spec

    def drain_replica(self, index: int) -> dict:
        """Shrink the fleet by one: SIGTERM replica ``index`` and reap
        its exit WITHOUT respawn — the one exit the supervisor's "N
        alive" contract does not replace. The replica drains through
        the same preemption contract :meth:`stop` uses (finish in-flight
        work, exit rc 75); the monitor pass marks it STOPPED when the
        exit lands. The caller removes the router target only after
        :meth:`status` confirms the drain, so no request is stranded.
        The slot stays decommissioned forever (``draining`` never
        clears; the index is never reused)."""
        with self._lock:
            matches = [rep for rep in self._replicas
                       if rep.spec.index == int(index)]
            if not matches:
                raise ValueError(f"no replica with index {index}")
            rep = matches[0]
            if rep.draining:
                return {"replica": rep.spec.index, "state": rep.state}
            rep.draining = True
            self._emit("scale_drain", rep, state=rep.state)
            if rep.proc is None:
                # Nothing running (backoff slot / already exited):
                # decommission directly — there is no drain to wait on.
                rep.state = STOPPED
                rep.restart_at = None
                self._emit("drain_complete", rep, rc=rep.last_rc,
                           graceful=True)
                return {"replica": rep.spec.index, "state": rep.state}
            try:
                rep.proc.send_signal(signal.SIGTERM)
            except Exception:
                pass
            return {"replica": rep.spec.index, "state": rep.state}

    def active_count(self) -> int:
        """Replicas that count as fleet capacity: not decommissioned
        and not given up on. A slot mid-crash-restart (BACKOFF) still
        counts — its respawn is already owed, and counting the respawn
        as NEW capacity would double-book a SIGKILLed replica (exactly
        the drift the autoscaler's membership chain lint forbids)."""
        with self._lock:
            return sum(1 for rep in self._replicas
                       if not rep.draining
                       and rep.state not in (STOPPED, FAILED))

    # -- hot-swap control (docs/serving.md "Model registry & canary
    # rollouts") ----------------------------------------------------------

    def swap_replica(self, index: int, task: str, checkpoint: str,
                     version: str, timeout_s: float = 120.0) -> dict:
        """Drive one replica's ``POST /swapz`` (serve/http.py): load the
        checkpoint on the replica's control thread, flip its serving
        params atomically. The supervisor resolves the checkpoint path
        from the registry PARENT-SIDE — the replica never needs the
        registry module, only a readable file. Returns the swap info
        dict; raises RuntimeError on a non-200 answer (the caller —
        rollout controller or chaos harness — decides whether that is
        fatal). Every attempt emits fleet_event swap_requested and then
        swap_ok (with the compile split) or swap_failed."""
        with self._lock:
            matches = [rep for rep in self._replicas
                       if rep.spec.index == int(index)]
        if not matches:
            raise ValueError(f"no replica with index {index}")
        rep = matches[0]
        self._emit("swap_requested", rep, task=str(task),
                   version=str(version))
        body = json.dumps({"task": str(task),
                           "checkpoint": str(checkpoint),
                           "version": str(version)}).encode("utf-8")
        parsed = urllib.parse.urlsplit(rep.spec.url)
        conn = http.client.HTTPConnection(
            parsed.hostname, parsed.port, timeout=max(1.0, timeout_s))
        try:
            try:
                conn.request("POST", "/swapz", body=body,
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                data = resp.read().decode("utf-8", "replace")
                status = resp.status
            except OSError as exc:
                self._emit("swap_failed", rep, task=str(task),
                           version=str(version),
                           error=f"{type(exc).__name__}: {exc}")
                raise RuntimeError(
                    f"swap transport failure on replica {index}: "
                    f"{exc}") from exc
        finally:
            conn.close()
        try:
            info = json.loads(data) if data else {}
        except ValueError:
            info = {"error": data[:200]}
        if status != 200:
            self._emit("swap_failed", rep, task=str(task),
                       version=str(version), status=int(status),
                       error=str(info.get("error", ""))[:200])
            raise RuntimeError(
                f"swap failed on replica {index} "
                f"(status {status}): {info.get('error')}")
        self._emit("swap_ok", rep, task=str(task), version=str(version),
                   load_s=info.get("load_s"),
                   compiles_cold=info.get("compiles_cold"),
                   compiles_warm=info.get("compiles_warm"))
        return info

    def swap_all(self, task: str, checkpoint: str, version: str,
                 timeout_s: float = 120.0,
                 skip_indices: Sequence[int] = ()) -> List[dict]:
        """Swap every replica SEQUENTIALLY (skipping ``skip_indices`` —
        the canary replicas that already serve the version). Sequential
        on purpose: with N-1 replicas still serving, one replica busy
        loading costs capacity, never availability; swapping the fleet
        at once would stack every load on the same window."""
        skip = {int(i) for i in skip_indices}
        with self._lock:
            indices = [rep.spec.index for rep in self._replicas
                       if rep.spec.index not in skip]
        return [self.swap_replica(i, task, checkpoint, version,
                                  timeout_s=timeout_s)
                for i in indices]

    # -- introspection ----------------------------------------------------

    def status(self) -> List[dict]:
        """Per-replica snapshot (state, restarts, pid, port) under the
        lock — what the chaos harness and tests assert on."""
        with self._lock:
            return [{
                "replica": rep.spec.index,
                "port": rep.spec.port,
                "url": rep.spec.url,
                "state": rep.state,
                "restarts": rep.restarts,
                "consecutive_crashes": rep.consecutive,
                "pid": getattr(rep.proc, "pid", None),
                "last_rc": rep.last_rc,
                "heartbeat_counter": rep.hb_counter,
                "draining": rep.draining,
            } for rep in self._replicas]

    def replica_urls(self) -> List[str]:
        with self._lock:
            return [rep.spec.url for rep in self._replicas]
