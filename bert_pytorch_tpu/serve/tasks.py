"""Per-task-head request pre/post-processing (docs/serving.md).

One :class:`TaskHandler` per served head turns a JSON payload into the
unpadded feature arrays the engine batches (``prepare``) and the model's
per-request output slice back into a JSON-able result (``postprocess``).
The model side reuses :mod:`bert_pytorch_tpu.models.bert` heads unchanged;
the host side reuses the existing tokenizer surfaces
(data/tokenization.py — both the fast ``encode().ids`` tokenizers and the
pure-Python :class:`BertTokenizer`) and, for SQuAD, the battle-tested
n-best decode of :mod:`bert_pytorch_tpu.squad`.

Tasks (``TASKS``):

* ``fill_mask`` — MLM head: top-k token predictions per ``[MASK]`` slot;
* ``classify`` — sequence classification: label + softmax probabilities
  (single sentence or sentence pair);
* ``squad``    — extractive QA: n-best span decode with the character-level
  answer realignment (single-window: the context is truncated to the
  largest bucket — the online-serving convention; offline multi-window
  scoring stays with run_squad.py);
* ``ner``      — token classification: one tag per word (first-subtoken
  convention, label ids start at 1 per run_ner.py).

Every ``postprocess`` consumes fp32 numpy slices already demultiplexed per
request by the engine (packed or not), so results are bit-identical
between the padded/packed batched path and a direct single-request
forward — the parity tests/test_serve.py asserts.

Tracing contract (serve/tracing.py, docs/serving.md "Request tracing &
metrics"): ``prepare`` runs on the submitting HTTP worker BEFORE the
request is enqueued, so its cost rides sampled trace records as
``prepare_ms`` context; ``postprocess`` runs on the dispatch thread
after the forward and IS the trace's ``postprocess`` span — a handler
that grows an expensive decode shows up per-request in the span tree
and per-task in the /metricsz phase histograms, attributed, not folded
into an opaque end-to-end number.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional

import numpy as np

from bert_pytorch_tpu import squad as squad_lib


class GatheredTokens(NamedTuple):
    """Per-request output of a FUSED-EPILOGUE forward (docs/serving.md
    "Raw-speed kernels"): token-level logits already gathered at the
    request's positions of interest — for fill_mask, one row per [MASK]
    slot in ``features['mask_positions']`` order — instead of the full
    [request_len, vocab] plane. An explicit wrapper type, not a bare
    array: ``postprocess`` must never have to guess from shape whether
    row i means token i or the i-th gathered position."""

    logits: np.ndarray  # [n_positions, vocab]


# -- tokenizer surface shims (the squad.py/ner_dataset.py conventions) ----

def _encode_ids(tokenizer, text: str) -> List[int]:
    if hasattr(tokenizer, "encode"):
        return tokenizer.encode(text, add_special_tokens=False).ids
    return tokenizer.convert_tokens_to_ids(tokenizer.tokenize(text))


def _encode_tokens(tokenizer, text: str) -> List[str]:
    if hasattr(tokenizer, "encode"):
        return tokenizer.encode(text, add_special_tokens=False).tokens
    return tokenizer.tokenize(text)


def _token_to_id(tokenizer, token: str) -> int:
    if hasattr(tokenizer, "token_to_id"):
        tid = tokenizer.token_to_id(token)
        if tid is None:
            tid = tokenizer.token_to_id("[UNK]")
        return tid
    return tokenizer.vocab.get(token, tokenizer.vocab["[UNK]"])


def _id_to_token(tokenizer, token_id: int) -> str:
    if hasattr(tokenizer, "id_to_token"):
        return tokenizer.id_to_token(int(token_id))
    return tokenizer.ids_to_tokens.get(int(token_id), "[UNK]")


def _softmax(x: np.ndarray) -> np.ndarray:
    x = x.astype(np.float64)
    x = x - x.max(axis=-1, keepdims=True)
    e = np.exp(x)
    return e / e.sum(axis=-1, keepdims=True)


class TaskHandler:
    """Pre/post-processing for one task head.

    ``prepare(payload, max_len)`` returns the feature dict the engine
    batches: ``input_ids``/``segment_ids`` (unpadded python lists, specials
    included, truncated to ``max_len``) plus whatever decode context
    ``postprocess`` needs. ``postprocess(features, outputs, payload)``
    receives the per-request fp32 numpy output slice (length ==
    ``len(features['input_ids'])`` for token-level outputs).
    """

    name: str = ""
    # Model output arity: how the engine slices per request.
    #   "tokens"  -> [S, ...] per-token array sliced to the request span
    #   "pooled"  -> one vector per request (pooled/classifier logits)
    #   "span"    -> (start_logits[S], end_logits[S]) tuple
    output_kind: str = "tokens"
    # Fused-epilogue capability (serve/engine.py fuse_epilogues;
    # docs/serving.md "Raw-speed kernels"):
    #   "gather"     -> the forward gathers this head's positions of
    #                   interest (gather_positions below) before its
    #                   final projection; demux hands postprocess a
    #                   GatheredTokens instead of the full token plane
    #   "stack_span" -> the forward stacks start/end into one [B, 2, S]
    #                   output (one D2H transfer; demux re-splits, so
    #                   postprocess sees the usual tuple)
    #   None         -> no epilogue to fuse (pooled heads already
    #                   extract in-model; ner reads per-word rows whose
    #                   count is unbounded, so a fixed gather quota
    #                   would cap the served word count)
    epilogue: Optional[str] = None

    def gather_positions(self, features: dict) -> List[int]:
        """Positions (request-relative) a ``"gather"`` epilogue must
        extract for this request; only heads declaring that epilogue
        implement it."""
        raise NotImplementedError

    def __init__(self, tokenizer):
        self.tokenizer = tokenizer

    def prepare(self, payload: dict, max_len: int) -> dict:
        raise NotImplementedError

    def postprocess(self, features: dict, outputs, payload: dict) -> dict:
        raise NotImplementedError

    # Shared [CLS] x [SEP] wrapping with truncation to the bucket budget.
    def _wrap(self, ids: List[int], max_len: int,
              ids_b: Optional[List[int]] = None) -> Dict[str, list]:
        cls_id = _token_to_id(self.tokenizer, "[CLS]")
        sep_id = _token_to_id(self.tokenizer, "[SEP]")
        if ids_b:
            # Balanced longest-first popping — the BERT sentence-pair
            # truncation convention (data/glue.py ``_truncate_pair``).
            ids, ids_b = list(ids), list(ids_b)
            while len(ids) + len(ids_b) > max_len - 3:
                (ids if len(ids) >= len(ids_b) else ids_b).pop()
            input_ids = [cls_id] + ids + [sep_id] + ids_b + [sep_id]
            segment_ids = [0] * (len(ids) + 2) + [1] * (len(ids_b) + 1)
        else:
            ids = ids[: max_len - 2]
            input_ids = [cls_id] + ids + [sep_id]
            segment_ids = [0] * len(input_ids)
        return {"input_ids": input_ids, "segment_ids": segment_ids}


class FillMaskHandler(TaskHandler):
    """MLM head: predict the top-k tokens for every ``[MASK]`` in the text.

    The text is split on the literal ``[MASK]`` marker and the pieces are
    encoded separately — tokenizer backends disagree on whether special
    tokens survive normalization (the pure-Python BasicTokenizer's
    ``never_split`` keeps them; byte-level BPE would shred them), so the
    mask id is inserted explicitly between encoded pieces.
    """

    name = "fill_mask"
    output_kind = "tokens"
    epilogue = "gather"

    def gather_positions(self, features: dict) -> List[int]:
        return features["mask_positions"]

    def prepare(self, payload: dict, max_len: int) -> dict:
        text = payload["text"]
        mask_id = _token_to_id(self.tokenizer, "[MASK]")
        ids: List[int] = []
        pieces = text.split("[MASK]")
        for i, piece in enumerate(pieces):
            if i:
                ids.append(mask_id)
            if piece.strip():
                ids.extend(_encode_ids(self.tokenizer, piece.strip()))
        if mask_id not in ids:
            raise ValueError("fill_mask payload text carries no [MASK]")
        budget = max_len - 2
        if len(ids) > budget:
            # Window AROUND the first mask instead of truncating the tail
            # blind — an over-long text must not lose its [MASK].
            m = ids.index(mask_id)
            start = max(0, min(m - budget // 2, len(ids) - budget))
            ids = ids[start:start + budget]
        features = self._wrap(ids, max_len)
        features["mask_positions"] = [
            i for i, t in enumerate(features["input_ids"]) if t == mask_id]
        if not features["mask_positions"]:
            raise ValueError(
                "[MASK] truncated away; shorten the text or raise buckets")
        return features

    def postprocess(self, features: dict, outputs, payload: dict) -> dict:
        if isinstance(outputs, GatheredTokens):
            # Fused-epilogue engines already gathered one row per mask
            # slot (mask_positions order) on device; rows are bit-equal
            # to the unfused plane's rows at those positions.
            rows = [np.asarray(outputs.logits, np.float32)[i]
                    for i in range(len(features["mask_positions"]))]
        else:
            logits = np.asarray(outputs, np.float32)  # [len, vocab]
            rows = [logits[pos] for pos in features["mask_positions"]]
        top_k = int(payload.get("top_k", 5))
        slots = []
        for row in rows:
            best = np.argsort(-row)[:top_k]
            probs = _softmax(row)[best]
            slots.append([
                {"token": _id_to_token(self.tokenizer, tid),
                 "id": int(tid), "score": float(p)}
                for tid, p in zip(best, probs)])
        return {"masks": slots}


class ClassifyHandler(TaskHandler):
    """Sequence classification over the pooled [CLS] vector."""

    name = "classify"
    output_kind = "pooled"

    def __init__(self, tokenizer, labels: List[str]):
        super().__init__(tokenizer)
        self.labels = list(labels)

    def prepare(self, payload: dict, max_len: int) -> dict:
        ids = _encode_ids(self.tokenizer, payload["text"])
        ids_b = (_encode_ids(self.tokenizer, payload["text_pair"])
                 if payload.get("text_pair") else None)
        return self._wrap(ids, max_len, ids_b)

    def postprocess(self, features: dict, outputs, payload: dict) -> dict:
        logits = np.asarray(outputs, np.float32).reshape(-1)
        probs = _softmax(logits)
        best = int(np.argmax(logits))
        return {
            "label": self.labels[best] if best < len(self.labels) else best,
            "scores": {
                (self.labels[i] if i < len(self.labels) else str(i)):
                    float(p)
                for i, p in enumerate(probs)},
        }


class SquadHandler(TaskHandler):
    """Extractive QA with the run_squad n-best decode.

    Serving is single-window: the context is truncated to the request's
    length budget (``max_len`` = largest bucket) instead of sliding
    ``doc_stride`` windows — one request maps to one row, so batching
    stays request-atomic. ``convert_examples_to_features`` is reused with
    the doc tokens pre-truncated, and ``get_answers`` performs the same
    n-best + character-realignment decode the offline evaluator uses.
    """

    name = "squad"
    output_kind = "span"
    epilogue = "stack_span"

    def __init__(self, tokenizer, do_lower_case: bool = True,
                 max_query_length: int = 64):
        super().__init__(tokenizer)
        self.do_lower_case = do_lower_case
        self.max_query_length = max_query_length

    def prepare(self, payload: dict, max_len: int) -> dict:
        example = squad_lib.SquadExample(
            qas_id="live",
            question_text=payload["question"],
            doc_tokens=squad_lib.whitespace_tokenize(payload["context"]),
        )
        query_tokens = _encode_tokens(self.tokenizer, example.question_text)
        query_len = min(len(query_tokens), self.max_query_length)
        budget = max(1, max_len - query_len - 3)
        # Truncate doc WORDS until their subtoken expansion fits the single
        # window, so convert_examples_to_features emits exactly one span.
        # Each word tokenizes ONCE (O(W)) — this runs per request on the
        # HTTP worker thread.
        doc_tokens = list(example.doc_tokens)
        counts = [len(_encode_tokens(self.tokenizer, w))
                  for w in doc_tokens]
        total = sum(counts)
        while doc_tokens and total > budget:
            total -= counts.pop()
            doc_tokens.pop()
        example.doc_tokens = doc_tokens or ["."]
        feats = squad_lib.convert_examples_to_features(
            [example], self.tokenizer, max_seq_length=max_len,
            doc_stride=max_len, max_query_length=self.max_query_length,
            is_training=False)
        feat = feats[0]
        n = len(feat.tokens)
        return {
            "input_ids": list(feat.input_ids[:n]),
            "segment_ids": list(feat.segment_ids[:n]),
            "example": example,
            "feature": feat,
        }

    def postprocess(self, features: dict, outputs, payload: dict) -> dict:
        start, end = outputs
        start = np.asarray(start, np.float32)
        end = np.asarray(end, np.float32)
        feat = features["feature"]
        pad = len(feat.input_ids) - len(start)
        if pad > 0:  # re-pad to the featurizer's max_seq_length basis
            start = np.concatenate([start, np.full(pad, -1e4, np.float32)])
            end = np.concatenate([end, np.full(pad, -1e4, np.float32)])

        class _Args:
            n_best_size = int(payload.get("n_best", 5))
            max_answer_length = int(payload.get("max_answer_length", 30))
            version_2_with_negative = False
            null_score_diff_threshold = 0.0
            do_lower_case = self.do_lower_case

        answers, nbest, _ = squad_lib.get_answers(
            [features["example"]], [feat],
            [squad_lib.RawResult(feat.unique_id, start.tolist(),
                                 end.tolist())],
            _Args())
        return {
            "answer": answers["live"],
            "n_best": [
                {"text": e["text"], "probability": float(e["probability"]),
                 "start_logit": float(e["start_logit"]),
                 "end_logit": float(e["end_logit"])}
                for e in nbest["live"]],
        }


class NerHandler(TaskHandler):
    """Token classification: one tag per whitespace word.

    Follows the run_ner.py conventions: per-word subtokens all exist in the
    row, the word's tag is read from its FIRST subtoken, and label ids
    start at 1 (0 is the reserved non-entity/padding class).
    """

    name = "ner"
    output_kind = "tokens"

    def __init__(self, tokenizer, labels: List[str]):
        super().__init__(tokenizer)
        self.labels = list(labels)  # id i+1 -> labels[i]

    def prepare(self, payload: dict, max_len: int) -> dict:
        words = payload["text"].split()
        ids: List[int] = []
        word_starts: List[int] = []  # offset of each word's first subtoken
        for word in words:
            subtokens = _encode_tokens(self.tokenizer, word)
            if not subtokens:
                subtokens = ["[UNK]"]
            if len(ids) + len(subtokens) > max_len - 2:
                break
            word_starts.append(len(ids) + 1)  # +1 for [CLS]
            ids.extend(_token_to_id(self.tokenizer, t) for t in subtokens)
        features = self._wrap(ids, max_len)
        features["words"] = words[: len(word_starts)]
        features["word_starts"] = word_starts
        return features

    def postprocess(self, features: dict, outputs, payload: dict) -> dict:
        logits = np.asarray(outputs, np.float32)  # [len, n_labels+1]
        tags = []
        for word, pos in zip(features["words"], features["word_starts"]):
            pred = int(np.argmax(logits[pos]))
            # id 0 is the reserved class; real labels are 1-based.
            tag = (self.labels[pred - 1]
                   if 1 <= pred <= len(self.labels) else "O")
            tags.append({"word": word, "tag": tag,
                         "score": float(_softmax(logits[pos])[pred])})
        return {"entities": tags}


TASK_NAMES = ("fill_mask", "classify", "squad", "ner")


def build_handlers(tokenizer, task_config: dict) -> Dict[str, TaskHandler]:
    """Instantiate handlers for the configured tasks.

    ``task_config`` maps task name -> per-task options (serve/engine.py
    ``TaskSpec`` carries the model/params side): ``classify`` needs
    ``labels``; ``ner`` needs ``labels``; ``squad`` accepts
    ``do_lower_case``/``max_query_length``.
    """
    handlers: Dict[str, TaskHandler] = {}
    for name, options in task_config.items():
        options = options or {}
        if name == "fill_mask":
            handlers[name] = FillMaskHandler(tokenizer)
        elif name == "classify":
            handlers[name] = ClassifyHandler(
                tokenizer, options.get("labels") or ["0", "1"])
        elif name == "squad":
            handlers[name] = SquadHandler(
                tokenizer,
                do_lower_case=bool(options.get("do_lower_case", True)),
                max_query_length=int(options.get("max_query_length", 64)))
        elif name == "ner":
            handlers[name] = NerHandler(
                tokenizer, options.get("labels") or ["O"])
        else:
            raise ValueError(f"unknown serve task {name!r}; "
                             f"known: fill_mask, classify, squad, ner")
    return handlers
