"""Request-level tracing and the metrics export plane for the serving
tier (docs/serving.md "Request tracing & metrics").

The serving path's coarse window stats (serve/stats.py) say how slow a
replica is; they cannot say WHERE a request's time went — a router doing
admission control, or an engineer attributing a tail-latency incident,
needs the request's life decomposed. This module owns that
decomposition, in the Dapper mold (Sigelman et al. 2010, PAPERS.md):

* **span taxonomy** — every completed request is decomposed into four
  disjoint phases measured by the dispatch path (serve/service.py):

  ========== ==========================================================
  ``queue``        submit/enqueue until the batcher pops the request
                   (includes any plan-leftover requeue round trips)
  ``assembly``     pop until device dispatch: batch planning, bucket
                   choice, packing/padding the fixed-shape arrays, plus
                   the batch's demux host conversion
  ``execute``      the jitted forward including the device sync (shared
                   by every request in the batch)
  ``postprocess``  the request's OWN task-handler decode
  ========== ==========================================================

  The phases are sub-intervals of the request's end-to-end latency, so
  ``sum(phase durations) <= total`` and ``queue <= total`` hold by
  construction — schema-lintable invariants (telemetry/schema.py), not
  hopes. Host-side ``prepare`` time (tokenization on the HTTP worker,
  serve/tasks.py) happens BEFORE the request is enqueued, so it rides
  the trace record as ``prepare_ms`` context rather than a span.

* **head sampling + always-sample-slow** — ``sample_rate`` picks the
  head-sampled fraction deterministically from the request id (a Knuth
  multiplicative hash, so reruns of a trace replay sample the same
  requests); any request whose total exceeds the SLO target is traced
  REGARDLESS of the rate ("The Tail at Scale", Dean & Barroso 2013: the
  slow requests are precisely the ones worth explaining), bounded by a
  per-(task, window) budget of :data:`SLOW_TRACE_WINDOW_CAP` forced
  exports so an everything-is-slow incident cannot make trace volume
  proportional to load (the over-SLO counters are never capped).
  Emitted records carry ``sampled`` (was it head-sampled) and
  ``sample_reason`` (``slow`` whenever the request was over the SLO —
  even if it was also head-sampled — else ``head``).

* **schema-v1 export** — sampled requests emit ``kind="serve_trace"``
  records (span tree + bucket/packing context); every ``window``
  completed requests per task emit one ``kind="serve_phase"``
  latency-decomposition aggregate (per-phase p50/p95, total p50/p95/p99,
  ``queue_wait_share``, over-SLO count). Both flow through the same
  JSONL sink as the rest of telemetry and are summarized/gated by
  ``telemetry-report`` ("serve queue-wait share", "serve SLO p99").

* **/metricsz** — :meth:`TraceCollector.metrics_text` renders the
  per-task counters and phase-latency histograms in Prometheus text
  exposition format so the future router and standard scrapers consume
  one surface; serve/http.py serves it, with the service-level gauges
  (queue depth, occupancy, cold start) appended by
  ``ServingService.metrics_text``.

Thread-safety: ``observe``/``flush``/``finish`` run on the single
dispatch thread while ``observe_error`` (HTTP workers) and
``metrics_text``/``phase_snapshot`` (/metricsz and /statsz scrapes) run
on HTTP worker threads — all shared state lives in the per-task stats
map behind one lock (declared in the jaxlint concurrency registry,
analysis/concurrency.py).
"""

from __future__ import annotations

import collections
import threading
import uuid
from typing import Callable, Dict, List, Optional

# Nearest-rank percentile: ONE implementation for the whole serve
# telemetry surface (serve_window and serve_phase records must agree on
# the rank convention).
from bert_pytorch_tpu.serve.stats import _pctl

PHASES = ("queue", "assembly", "execute", "postprocess")

# Histogram bucket upper bounds (milliseconds) for the /metricsz
# phase-latency histograms. Fixed and shared across tasks/phases so
# scrapes aggregate; +Inf is implicit (the _count series).
HIST_BUCKETS_MS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                   500.0, 1000.0, 2500.0)

# Run-level percentile basis per (task, phase): most recent this-many
# samples — the bounded-memory rationale of serve/stats.py
# RUN_SAMPLE_CAP, deliberately smaller here because the tracer keeps
# one series per (task, phase + total), ~5x as many as the stats rollup.
RUN_SAMPLE_CAP = 4096

# At most this many slow-FORCED serve_trace emissions per (task,
# serve_phase window): during an incident where most traffic breaches
# the SLO, the always-sample-slow rule would otherwise make trace
# output proportional to load exactly when the replica is drowning —
# and each emit is dispatch-thread disk I/O. The over-SLO COUNTERS
# (/metricsz, serve_phase windows, the report verdict) stay exact;
# only the per-request span-tree exports are budgeted (Dapper-style).
# Head-sampled traces never draw on this budget.
SLOW_TRACE_WINDOW_CAP = 16


def _sample_hash(request_id: int) -> float:
    """Deterministic [0, 1) hash of a request id (Knuth multiplicative):
    head sampling must not depend on interleaving or a shared RNG, so a
    replayed trace samples the SAME requests every run."""
    return ((int(request_id) * 2654435761) & 0xFFFFFFFF) / float(1 << 32)


# -- cross-tier trace-context propagation (ISSUE 16, Dapper-style) -------
# The router mints one fleet-unique trace id + sampling decision per
# client request and forwards them on every dispatch attempt:
#
#     X-Bert-Trace: <trace_id>;attempt=<n>;sampled=<0|1>
#
# and every HTTP response (replica and router relay alike) echoes
#
#     X-Bert-Trace-Id: <trace_id>
#
# so clients and the chaos harness can correlate WITHOUT relying on
# sampling. serve/router.py keeps its own copy of the wire format (it
# loads by file path, jax-free, and must not import this module); the
# round-trip is pinned by tests/test_fleet_tracing.py.
TRACE_HEADER = "X-Bert-Trace"
TRACE_ID_RESPONSE_HEADER = "X-Bert-Trace-Id"


def parse_trace_header(value) -> Optional[dict]:
    """Decode an inbound ``X-Bert-Trace`` header into a trace context
    ``{"trace_id", "attempt", "sampled"}``; None on anything malformed
    (a bad header must never fail the request — tracing is best-effort
    observability, not admission control)."""
    if not isinstance(value, str) or not value.strip():
        return None
    parts = [p.strip() for p in value.split(";")]
    trace_id = parts[0]
    if not trace_id:
        return None
    ctx = {"trace_id": trace_id, "attempt": 1, "sampled": False}
    for part in parts[1:]:
        key, sep, raw = part.partition("=")
        if not sep:
            return None
        if key == "attempt":
            try:
                attempt = int(raw)
            except ValueError:
                return None
            if attempt < 1:
                return None
            ctx["attempt"] = attempt
        elif key == "sampled":
            if raw not in ("0", "1"):
                return None
            ctx["sampled"] = raw == "1"
        # Unknown keys are forward-compatible: ignored, not fatal.
    return ctx


def format_trace_header(trace_id: str, attempt: int,
                        sampled: bool) -> str:
    """Encode a trace context for the ``X-Bert-Trace`` request header
    (the inverse of :func:`parse_trace_header`)."""
    return f"{trace_id};attempt={int(attempt)};sampled={1 if sampled else 0}"


class _TaskStats:
    """Per-task aggregates: run counters, /metricsz histograms, and the
    current serve_phase window. Only ever touched under the collector's
    lock."""

    def __init__(self):
        self.requests = 0
        self.errors = 0
        self.sampled = 0
        self.over_slo = 0
        # Requests that joined a forming batch through the admission
        # window (continuous batching, docs/serving.md) — the
        # bert_serve_admitted_late_total counter.
        self.admitted_late = 0
        # Prometheus histogram state per phase (+ "total"): non-cumulative
        # per-bucket counts, rendered cumulative at scrape time.
        self.hist = {p: [0] * (len(HIST_BUCKETS_MS) + 1)
                     for p in PHASES + ("total",)}
        self.hist_sum = {p: 0.0 for p in PHASES + ("total",)}
        # Run-level percentile samples (bounded).
        self.run_samples = {p: collections.deque(maxlen=RUN_SAMPLE_CAP)
                            for p in PHASES + ("total",)}
        self.run_phase_s = {p: 0.0 for p in PHASES}
        self.run_total_s = 0.0
        self.reset_window()

    def reset_window(self):
        self.win_samples = {p: [] for p in PHASES + ("total",)}
        self.win_phase_s = {p: 0.0 for p in PHASES}
        self.win_total_s = 0.0
        self.win_over_slo = 0
        self.win_sampled = 0
        self.win_slow_forced = 0
        self.win_admitted_late = 0

    def note(self, phases_s: Dict[str, float], total_s: float) -> None:
        self.requests += 1
        for name, dur in list(phases_s.items()) + [("total", total_s)]:
            ms = dur * 1000.0
            idx = len(HIST_BUCKETS_MS)
            for i, bound in enumerate(HIST_BUCKETS_MS):
                if ms <= bound:
                    idx = i
                    break
            self.hist[name][idx] += 1
            self.hist_sum[name] += ms
            self.run_samples[name].append(ms)
            self.win_samples[name].append(ms)
        for name, dur in phases_s.items():
            self.run_phase_s[name] += dur
            self.win_phase_s[name] += dur
        self.run_total_s += total_s
        self.win_total_s += total_s


class TraceCollector:
    """Collects per-request phase decompositions; emits ``serve_trace``
    and ``serve_phase`` records and renders the /metricsz export.

    ``slo_p99_ms`` is the per-request latency target the SLO machinery
    and the always-sample-slow rule key on (None/0 disables both);
    ``error_budget`` is the fraction of requests allowed over the target
    before the error budget is burned (telemetry-report turns the pair
    into the rolling-window SLO verdict).
    """

    def __init__(self, emit: Optional[Callable[[dict], None]] = None,
                 sample_rate: float = 0.0,
                 slo_p99_ms: Optional[float] = None,
                 error_budget: float = 0.01,
                 window: int = 64):
        if not 0.0 <= float(sample_rate) <= 1.0:
            raise ValueError(
                f"sample_rate must be in [0, 1], got {sample_rate}")
        self.emit = emit
        self.sample_rate = float(sample_rate)
        self.slo_p99_ms = (float(slo_p99_ms)
                           if slo_p99_ms else None)  # 0/None = disabled
        self.error_budget = float(error_budget)
        self.window = max(1, int(window))
        # One run-scoped token namespaces trace ids across restarts (the
        # request-id counter alone restarts at 0 with the process).
        self._run_token = uuid.uuid4().hex[:8]
        self._lock = threading.Lock()
        # task -> _TaskStats; the ONLY shared mutable state (registered
        # in the concurrency registry): written by the dispatch thread
        # (observe) and HTTP workers (observe_error), read by /metricsz
        # and /statsz scrape threads.
        self._tasks: Dict[str, _TaskStats] = {}

    # -- producer side (dispatch thread) --------------------------------

    def observe(self, task: str, request_id: int,
                phases_s: Dict[str, float], total_s: float,
                bucket: Optional[int] = None, packed: Optional[bool] = None,
                batch_requests: Optional[int] = None,
                occupancy: Optional[float] = None,
                prepare_s: Optional[float] = None,
                pack_s: Optional[float] = None,
                admitted_late: Optional[bool] = None,
                staged_wait_s: Optional[float] = None,
                trace_ctx: Optional[dict] = None) -> Optional[dict]:
        """Record one completed request's phase decomposition; returns
        the emitted ``serve_trace`` record when the request was sampled
        (head rate, or forced by the over-SLO slow rule), else None.
        ``phases_s`` maps each name in :data:`PHASES` to its duration in
        seconds. ``admitted_late`` marks a request that joined a forming
        batch through the pipelined plane's admission window;
        ``staged_wait_s`` is its batch's staging-complete -> executor
        pickup delay (pipeline buffering — context, not a span).
        ``trace_ctx`` is the inbound router context
        (:func:`parse_trace_header`): when present, the ROUTER'S
        sampling decision replaces the local head hash — both ways, so
        sampling is consistent fleet-wide — while the always-sample-slow
        rule still fires locally, and the emitted record chains to the
        router's span tree via ``parent_trace_id``/``attempt``."""
        phases_s = {name: max(0.0, float(phases_s.get(name, 0.0)))
                    for name in PHASES}
        total_s = max(float(total_s), sum(phases_s.values()))
        total_ms = total_s * 1000.0
        over_slo = bool(self.slo_p99_ms and total_ms > self.slo_p99_ms)
        if trace_ctx is not None and trace_ctx.get("trace_id"):
            head = bool(trace_ctx.get("sampled"))
        else:
            trace_ctx = None
            head = (self.sample_rate > 0.0
                    and _sample_hash(request_id) < self.sample_rate)
        phase_record = None
        emit_trace = False
        with self._lock:
            stats = self._tasks.setdefault(task, _TaskStats())
            stats.note(phases_s, total_s)
            if admitted_late:
                stats.admitted_late += 1
                stats.win_admitted_late += 1
            if over_slo:
                stats.over_slo += 1
                stats.win_over_slo += 1
            if self.emit is not None:
                if head:
                    emit_trace = True
                elif (over_slo
                      and stats.win_slow_forced < SLOW_TRACE_WINDOW_CAP):
                    # Slow-forced export draws on the per-window budget
                    # (SLOW_TRACE_WINDOW_CAP); the over-SLO counters
                    # above are never capped.
                    stats.win_slow_forced += 1
                    emit_trace = True
            if emit_trace:
                stats.sampled += 1
                stats.win_sampled += 1
            if len(stats.win_samples["total"]) >= self.window:
                # Build the record only when a sink will take it; the
                # reset stays unconditional so win_samples stays bounded
                # and the slow-forced budget is per-window either way.
                if self.emit is not None:
                    phase_record = self._window_record_locked(task, stats)
                stats.reset_window()
        trace_record = None
        if emit_trace:
            trace_record = self._trace_record(
                task, request_id, phases_s, total_ms, sampled=head,
                over_slo=over_slo,
                bucket=bucket, packed=packed, batch_requests=batch_requests,
                occupancy=occupancy, prepare_s=prepare_s, pack_s=pack_s,
                admitted_late=admitted_late, staged_wait_s=staged_wait_s,
                trace_ctx=trace_ctx)
            self.emit(trace_record)
        if phase_record is not None:
            self.emit(phase_record)
        return trace_record

    def observe_error(self, task: str) -> None:
        """Count one failed request for /metricsz (called from HTTP
        worker threads on timeout/postprocess/execute errors)."""
        with self._lock:
            self._tasks.setdefault(task, _TaskStats()).errors += 1

    def _trace_record(self, task, request_id, phases_s, total_ms, sampled,
                      over_slo, bucket, packed, batch_requests, occupancy,
                      prepare_s, pack_s=None, admitted_late=None,
                      staged_wait_s=None, trace_ctx=None) -> dict:
        spans = []
        start = 0.0
        for name in PHASES:
            dur = phases_s[name] * 1000.0
            spans.append({"name": name,
                          "start_ms": round(start, 3),
                          "dur_ms": round(dur, 3)})
            start += dur
        record = {
            "kind": "serve_trace",
            "tag": "serve",
            "trace_id": f"{self._run_token}-{int(request_id):x}",
            "task": task,
            # Round the total UP at the same precision so the lint's
            # "sum of span durations <= total_ms" survives rounding.
            "total_ms": round(max(total_ms, start), 3),
            "queue_wait_ms": round(phases_s["queue"] * 1000.0, 3),
            "sampled": bool(sampled),
            # "slow" takes priority: the report's tail-attribution count
            # (serve_traces_slow) keys on it, and an over-SLO request
            # that also happened to be head-sampled is still an over-SLO
            # request. `sampled` alone records head-sampledness.
            "sample_reason": "slow" if over_slo else "head",
            "spans": spans,
        }
        if trace_ctx is not None:
            # Chain to the router's span tree (the fleet collector's
            # stitch join key). `attempt` is the router's 1-based
            # dispatch attempt that reached this replica — a failed-over
            # request's surviving serve_trace carries attempt 2+.
            record["parent_trace_id"] = trace_ctx["trace_id"]
            record["attempt"] = int(trace_ctx.get("attempt", 1))
        if self.slo_p99_ms:
            record["slo_target_ms"] = self.slo_p99_ms
        if bucket is not None:
            record["bucket"] = int(bucket)
        if packed is not None:
            record["packed"] = bool(packed)
        if batch_requests is not None:
            record["batch_requests"] = int(batch_requests)
        if occupancy is not None:
            record["occupancy"] = round(float(occupancy), 4)
        if prepare_s is not None:
            record["prepare_ms"] = round(float(prepare_s) * 1000.0, 3)
        if pack_s is not None:
            # The engine's array-fill share of the assembly span
            # (serve/engine.py execute info["pack_s"]) — sub-attribution
            # context, already counted inside the assembly duration.
            record["pack_ms"] = round(float(pack_s) * 1000.0, 3)
        if admitted_late is not None:
            # Continuous batching: did this request join a FORMING batch
            # through the admission window (pipelined dispatch) instead
            # of waiting for its own flush? Schema-linted as a real
            # boolean — the A/B acceptance counts on it.
            record["admitted_late"] = bool(admitted_late)
        if staged_wait_s is not None:
            # Pipeline buffering between staging and the executor's
            # pickup — context like pack_ms, NOT a span: it is waiting,
            # not work, and sits in the slack between sum(spans) and
            # total_ms.
            record["staged_wait_ms"] = round(
                float(staged_wait_s) * 1000.0, 3)
        return record

    def _window_record_locked(self, task: str, stats: _TaskStats) -> dict:
        """Build one serve_phase record from the task's current window
        (caller holds the lock and resets the window after)."""
        record = {
            "kind": "serve_phase",
            "tag": "serve",
            "task": task,
            "window_requests": len(stats.win_samples["total"]),
            "sampled_traces": stats.win_sampled,
            "admitted_late": stats.win_admitted_late,
        }
        for name in PHASES:
            s = sorted(stats.win_samples[name])
            record[f"{name}_p50_ms"] = round(_pctl(s, 0.50), 3)
            record[f"{name}_p95_ms"] = round(_pctl(s, 0.95), 3)
        s = sorted(stats.win_samples["total"])
        record["total_p50_ms"] = round(_pctl(s, 0.50), 3)
        record["total_p95_ms"] = round(_pctl(s, 0.95), 3)
        record["total_p99_ms"] = round(_pctl(s, 0.99), 3)
        share = (stats.win_phase_s["queue"] / stats.win_total_s
                 if stats.win_total_s > 0 else 0.0)
        record["queue_wait_share"] = round(min(1.0, share), 4)
        if self.slo_p99_ms:
            record["slo_target_ms"] = self.slo_p99_ms
            record["slo_budget"] = self.error_budget
            record["over_slo"] = stats.win_over_slo
        return record

    def finish(self) -> None:
        """Flush every task's partial serve_phase window (end of run /
        service stop)."""
        if self.emit is None:
            return
        flushed = []
        with self._lock:
            for task, stats in self._tasks.items():
                if stats.win_samples["total"]:
                    flushed.append(self._window_record_locked(task, stats))
                    stats.reset_window()
        for record in flushed:
            self.emit(record)

    # -- consumer side (scrape threads) ----------------------------------

    def phase_snapshot(self) -> Optional[dict]:
        """Run-level phase rollup for /statsz and the bench result JSON:
        request-weighted queue-wait share, per-phase p95s, SLO
        accounting. None before the first completed request.

        The lock only covers copying the aggregates out — sorting the
        sample history happens after release, so a /statsz scrape never
        stalls the dispatch thread's ``observe`` for the sort."""
        with self._lock:
            if not self._tasks:
                return None
            requests = sum(s.requests for s in self._tasks.values())
            if not requests:
                return None
            out = {
                "requests": requests,
                "errors": sum(s.errors for s in self._tasks.values()),
                "sampled_traces": sum(
                    s.sampled for s in self._tasks.values()),
                "admitted_late": sum(
                    s.admitted_late for s in self._tasks.values()),
            }
            total_s = sum(s.run_total_s for s in self._tasks.values())
            queue_s = sum(s.run_phase_s["queue"]
                          for s in self._tasks.values())
            merged = {name: [v for s in self._tasks.values()
                             for v in s.run_samples[name]]
                      for name in PHASES}
            over = sum(s.over_slo for s in self._tasks.values())
        if total_s > 0:
            out["queue_wait_share"] = round(min(1.0, queue_s / total_s), 4)
        for name in PHASES:
            if merged[name]:
                out[f"{name}_p95_ms"] = round(
                    _pctl(sorted(merged[name]), 0.95), 3)
        if self.slo_p99_ms:
            out["slo_target_ms"] = self.slo_p99_ms
            out["over_slo"] = over
            budget = self.error_budget * requests
            out["slo_budget_burn"] = round(
                over / budget, 4) if budget > 0 else None
        return out

    def metrics_text(self, prefix: str = "bert_serve") -> str:
        """Prometheus text-exposition rendering of the per-task request/
        error/over-SLO counters, sampled-trace counters, and per-(task,
        phase) latency histograms. Service-level gauges (queue depth,
        occupancy, cold start) are appended by
        ``ServingService.metrics_text`` (serve/service.py).

        The lock only covers copying the counters and histogram arrays
        out — the exposition text is formatted after release (same
        discipline as ``phase_snapshot``), so a scrape never stalls the
        dispatch thread's ``observe`` for the render."""
        with self._lock:
            copied = {
                task: {
                    "requests": stats.requests,
                    "errors": stats.errors,
                    "sampled": stats.sampled,
                    "over_slo": stats.over_slo,
                    "admitted_late": stats.admitted_late,
                    "hist": {p: list(stats.hist[p])
                             for p in PHASES + ("total",)},
                    "hist_sum": dict(stats.hist_sum),
                }
                for task, stats in sorted(self._tasks.items())}
        lines: List[str] = []

        def header(name, kind, help_text):
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {kind}")

        header(f"{prefix}_requests_total", "counter",
               "Completed requests per task head.")
        for task, stats in copied.items():
            lines.append(f'{prefix}_requests_total{{task="{task}"}} '
                         f"{stats['requests']}")
        header(f"{prefix}_errors_total", "counter",
               "Failed requests per task head (timeouts, execute/"
               "postprocess errors).")
        for task, stats in copied.items():
            lines.append(f'{prefix}_errors_total{{task="{task}"}} '
                         f"{stats['errors']}")
        header(f"{prefix}_traces_sampled_total", "counter",
               "Requests exported as serve_trace records.")
        for task, stats in copied.items():
            lines.append(
                f'{prefix}_traces_sampled_total{{task="{task}"}} '
                f"{stats['sampled']}")
        header(f"{prefix}_admitted_late_total", "counter",
               "Requests admitted into a forming batch through the "
               "admission window (continuous batching).")
        for task, stats in copied.items():
            lines.append(
                f'{prefix}_admitted_late_total{{task="{task}"}} '
                f"{stats['admitted_late']}")
        if self.slo_p99_ms:
            header(f"{prefix}_over_slo_total", "counter",
                   "Requests over the p99 SLO target per task head.")
            for task, stats in copied.items():
                lines.append(
                    f'{prefix}_over_slo_total{{task="{task}"}} '
                    f"{stats['over_slo']}")
            header(f"{prefix}_slo_p99_target_ms", "gauge",
                   "Per-request latency SLO target (ms).")
            lines.append(
                f"{prefix}_slo_p99_target_ms {self.slo_p99_ms:g}")
        name = f"{prefix}_phase_latency_ms"
        header(name, "histogram",
               "Per-phase request latency (ms) per task head; phases: "
               + ",".join(PHASES) + ",total.")
        for task, stats in copied.items():
            for phase in PHASES + ("total",):
                acc = 0
                labels = f'task="{task}",phase="{phase}"'
                for bound, count in zip(HIST_BUCKETS_MS,
                                        stats["hist"][phase]):
                    acc += count
                    lines.append(
                        f'{name}_bucket{{{labels},le="{bound:g}"}} '
                        f"{acc}")
                acc += stats["hist"][phase][-1]
                lines.append(
                    f'{name}_bucket{{{labels},le="+Inf"}} {acc}')
                lines.append(f"{name}_sum{{{labels}}} "
                             f"{stats['hist_sum'][phase]:.3f}")
                lines.append(f"{name}_count{{{labels}}} {acc}")
        return "\n".join(lines) + "\n"
