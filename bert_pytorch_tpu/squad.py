"""SQuAD v1.1/v2.0 data processing and answer decoding.

Behavioral parity with reference run_squad.py (cited per function):
example reading (:131-206), sliding-window featurization with max-context
bookkeeping (:209-420), n-best span decoding with null handling (:427-556),
and the character-level answer realignment that depends on the pure-Python
BasicTokenizer semantics (:570-664).

These are host-side (numpy) components; the model side is
BertForQuestionAnswering + span_loss run by run_squad.py.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import math
from typing import Dict, List, Optional, Tuple

from bert_pytorch_tpu.data.tokenization import BasicTokenizer, whitespace_tokenize


@dataclasses.dataclass
class SquadExample:
    """One question (+ its paragraph); reference run_squad.py:61-98."""

    qas_id: str
    question_text: str
    doc_tokens: List[str]
    orig_answer_text: Optional[str] = None
    start_position: Optional[int] = None
    end_position: Optional[int] = None
    is_impossible: bool = False


@dataclasses.dataclass
class InputFeatures:
    """One sliding-window view of one example; reference run_squad.py:101-128."""

    unique_id: int
    example_index: int
    doc_span_index: int
    tokens: List[str]
    token_to_orig_map: Dict[int, int]
    token_is_max_context: Dict[int, bool]
    input_ids: List[int]
    input_mask: List[int]
    segment_ids: List[int]
    start_position: Optional[int] = None
    end_position: Optional[int] = None
    is_impossible: bool = False


RawResult = collections.namedtuple(
    "RawResult", ["unique_id", "start_logits", "end_logits"]
)


def _squad_whitespace(c: str) -> bool:
    return c in (" ", "\t", "\r", "\n") or ord(c) == 0x202F


def read_squad_examples(
    input_file: str, is_training: bool, version_2_with_negative: bool
) -> List[SquadExample]:
    """Parse the SQuAD JSON into examples with word-level answer spans
    (reference run_squad.py:131-206). Training answers that cannot be
    recovered from the whitespace-tokenized document are skipped."""
    with open(input_file, "r", encoding="utf-8") as reader:
        input_data = json.load(reader)["data"]

    examples = []
    for entry in input_data:
        for paragraph in entry["paragraphs"]:
            text = paragraph["context"]
            doc_tokens: List[str] = []
            char_to_word: List[int] = []
            prev_ws = True
            for c in text:
                if _squad_whitespace(c):
                    prev_ws = True
                else:
                    if prev_ws:
                        doc_tokens.append(c)
                    else:
                        doc_tokens[-1] += c
                    prev_ws = False
                char_to_word.append(len(doc_tokens) - 1)

            for qa in paragraph["qas"]:
                start_position = end_position = None
                orig_answer_text = None
                is_impossible = False
                if is_training:
                    if version_2_with_negative:
                        is_impossible = qa["is_impossible"]
                    if len(qa["answers"]) != 1 and not is_impossible:
                        raise ValueError(
                            "For training, each question should have exactly "
                            "1 answer."
                        )
                    if not is_impossible:
                        answer = qa["answers"][0]
                        orig_answer_text = answer["text"]
                        offset = answer["answer_start"]
                        start_position = char_to_word[offset]
                        end_position = char_to_word[
                            offset + len(orig_answer_text) - 1
                        ]
                        actual = " ".join(
                            doc_tokens[start_position : end_position + 1]
                        )
                        cleaned = " ".join(whitespace_tokenize(orig_answer_text))
                        if actual.find(cleaned) == -1:
                            continue  # unrecoverable answer; skip example
                    else:
                        start_position = end_position = -1
                        orig_answer_text = ""
                examples.append(
                    SquadExample(
                        qas_id=qa["id"],
                        question_text=qa["question"],
                        doc_tokens=doc_tokens,
                        orig_answer_text=orig_answer_text,
                        start_position=start_position,
                        end_position=end_position,
                        is_impossible=is_impossible,
                    )
                )
    return examples


_DocSpan = collections.namedtuple("DocSpan", ["start", "length"])


def _improve_answer_span(
    doc_tokens, input_start, input_end, tokenizer, orig_answer_text
) -> Tuple[int, int]:
    """Tighten a word-span to the subtoken span matching the annotated answer
    (reference run_squad.py:349-383)."""
    tok_answer_text = " ".join(_encode_tokens(tokenizer, orig_answer_text))
    for new_start in range(input_start, input_end + 1):
        for new_end in range(input_end, new_start - 1, -1):
            span = " ".join(doc_tokens[new_start : new_end + 1])
            if span == tok_answer_text:
                return new_start, new_end
    return input_start, input_end


def _check_is_max_context(doc_spans, cur_span_index, position) -> bool:
    """True iff this span gives the token its maximum min(left,right) context
    (reference run_squad.py:386-420)."""
    best_score, best_index = None, None
    for span_index, span in enumerate(doc_spans):
        end = span.start + span.length - 1
        if position < span.start or position > end:
            continue
        score = min(position - span.start, end - position) + 0.01 * span.length
        if best_score is None or score > best_score:
            best_score, best_index = score, span_index
    return cur_span_index == best_index


def _encode_tokens(tokenizer, text: str) -> List[str]:
    """Subtoken strings from either a fast tokenizer (``encode().tokens``) or
    the pure-Python BertTokenizer (``tokenize()``)."""
    if hasattr(tokenizer, "encode"):
        return tokenizer.encode(text, add_special_tokens=False).tokens
    return tokenizer.tokenize(text)


def _token_to_id(tokenizer, token: str) -> int:
    if hasattr(tokenizer, "token_to_id"):
        tid = tokenizer.token_to_id(token)
        if tid is None:
            tid = tokenizer.token_to_id("[UNK]")
        return tid
    return tokenizer.vocab.get(token, tokenizer.vocab["[UNK]"])


def convert_examples_to_features(
    examples: List[SquadExample],
    tokenizer,
    max_seq_length: int,
    doc_stride: int,
    max_query_length: int,
    is_training: bool,
) -> List[InputFeatures]:
    """Sliding-window featurization (reference run_squad.py:209-346)."""
    unique_id = 1000000000
    features = []
    for example_index, example in enumerate(examples):
        query_tokens = _encode_tokens(tokenizer, example.question_text)
        query_tokens = query_tokens[:max_query_length]

        tok_to_orig_index: List[int] = []
        orig_to_tok_index: List[int] = []
        all_doc_tokens: List[str] = []
        for i, token in enumerate(example.doc_tokens):
            orig_to_tok_index.append(len(all_doc_tokens))
            for sub_token in _encode_tokens(tokenizer, token):
                tok_to_orig_index.append(i)
                all_doc_tokens.append(sub_token)

        tok_start = tok_end = None
        if is_training and example.is_impossible:
            tok_start = tok_end = -1
        if is_training and not example.is_impossible:
            tok_start = orig_to_tok_index[example.start_position]
            if example.end_position < len(example.doc_tokens) - 1:
                tok_end = orig_to_tok_index[example.end_position + 1] - 1
            else:
                tok_end = len(all_doc_tokens) - 1
            tok_start, tok_end = _improve_answer_span(
                all_doc_tokens, tok_start, tok_end, tokenizer,
                example.orig_answer_text,
            )

        max_tokens_for_doc = max_seq_length - len(query_tokens) - 3  # CLS+2SEP
        doc_spans = []
        start_offset = 0
        while start_offset < len(all_doc_tokens):
            length = min(len(all_doc_tokens) - start_offset, max_tokens_for_doc)
            doc_spans.append(_DocSpan(start=start_offset, length=length))
            if start_offset + length == len(all_doc_tokens):
                break
            start_offset += min(length, doc_stride)

        for doc_span_index, doc_span in enumerate(doc_spans):
            tokens = ["[CLS]"] + query_tokens + ["[SEP]"]
            segment_ids = [0] * len(tokens)
            token_to_orig_map: Dict[int, int] = {}
            token_is_max_context: Dict[int, bool] = {}
            for i in range(doc_span.length):
                split_index = doc_span.start + i
                token_to_orig_map[len(tokens)] = tok_to_orig_index[split_index]
                token_is_max_context[len(tokens)] = _check_is_max_context(
                    doc_spans, doc_span_index, split_index
                )
                tokens.append(all_doc_tokens[split_index])
                segment_ids.append(1)
            tokens.append("[SEP]")
            segment_ids.append(1)

            input_ids = [_token_to_id(tokenizer, t) for t in tokens]
            input_mask = [1] * len(input_ids)
            pad = max_seq_length - len(input_ids)
            input_ids += [0] * pad
            input_mask += [0] * pad
            segment_ids += [0] * pad

            start_position = end_position = None
            if is_training and not example.is_impossible:
                doc_start = doc_span.start
                doc_end = doc_span.start + doc_span.length - 1
                if tok_start >= doc_start and tok_end <= doc_end:
                    offset = len(query_tokens) + 2
                    start_position = tok_start - doc_start + offset
                    end_position = tok_end - doc_start + offset
                else:
                    start_position = end_position = 0  # span not in window
            if is_training and example.is_impossible:
                start_position = end_position = 0

            features.append(
                InputFeatures(
                    unique_id=unique_id,
                    example_index=example_index,
                    doc_span_index=doc_span_index,
                    tokens=tokens,
                    token_to_orig_map=token_to_orig_map,
                    token_is_max_context=token_is_max_context,
                    input_ids=input_ids,
                    input_mask=input_mask,
                    segment_ids=segment_ids,
                    start_position=start_position,
                    end_position=end_position,
                    is_impossible=example.is_impossible,
                )
            )
            unique_id += 1
    return features


# --------------------------------------------------------------------------
# Answer decoding (reference run_squad.py:427-699)
# --------------------------------------------------------------------------

Prediction = collections.namedtuple(
    "Prediction", ["text", "start_logit", "end_logit"]
)
_PrelimPrediction = collections.namedtuple(
    "PrelimPrediction", ["start_index", "end_index", "start_logit", "end_logit"]
)


def _best_indices(logits, n_best_size: int) -> List[int]:
    order = sorted(range(len(logits)), key=lambda i: logits[i], reverse=True)
    return order[:n_best_size]


def _softmax(scores: List[float]) -> List[float]:
    if not scores:
        return []
    m = max(scores)
    exps = [math.exp(s - m) for s in scores]
    total = sum(exps)
    return [e / total for e in exps]


def _valid_prelim_predictions(start_indices, end_indices, feature, result, args):
    """Filter index pairs to in-document, max-context, length-bounded spans
    (reference run_squad.py:527-556)."""
    prelim = []
    for start_index in start_indices:
        for end_index in end_indices:
            if start_index >= len(feature.tokens):
                continue
            if end_index >= len(feature.tokens):
                continue
            if start_index not in feature.token_to_orig_map:
                continue
            if end_index not in feature.token_to_orig_map:
                continue
            if not feature.token_is_max_context.get(start_index, False):
                continue
            if end_index < start_index:
                continue
            if end_index - start_index + 1 > args.max_answer_length:
                continue
            prelim.append(
                _PrelimPrediction(
                    start_index,
                    end_index,
                    result.start_logits[start_index],
                    result.end_logits[end_index],
                )
            )
    return prelim


def _match_results(examples, features, results):
    by_id = {r.unique_id: r for r in results}
    feats = sorted(
        (f for f in features if f.unique_id in by_id), key=lambda f: f.unique_id
    )
    for f in feats:
        yield examples[f.example_index], f, by_id[f.unique_id]


def get_answer_text(example, feature, pred, args) -> str:
    """Detokenize the span and realign to the original text
    (reference run_squad.py:508-525)."""
    tok_tokens = feature.tokens[pred.start_index : pred.end_index + 1]
    orig_doc_start = feature.token_to_orig_map[pred.start_index]
    orig_doc_end = feature.token_to_orig_map[pred.end_index]
    orig_tokens = example.doc_tokens[orig_doc_start : orig_doc_end + 1]
    tok_text = " ".join(tok_tokens).replace(" ##", "").replace("##", "")
    tok_text = " ".join(tok_text.strip().split())
    orig_text = " ".join(orig_tokens)
    return get_final_text(tok_text, orig_text, args.do_lower_case)


def get_final_text(pred_text: str, orig_text: str, do_lower_case: bool) -> str:
    """Character-level projection of the normalized prediction back onto the
    original text (reference run_squad.py:570-664). Falls back to
    ``orig_text`` whenever the alignment heuristic fails."""

    def strip_spaces(text):
        ns_chars = []
        ns_to_s = collections.OrderedDict()
        for i, c in enumerate(text):
            if c == " ":
                continue
            ns_to_s[len(ns_chars)] = i
            ns_chars.append(c)
        return "".join(ns_chars), ns_to_s

    tokenizer = BasicTokenizer(do_lower_case=do_lower_case)
    tok_text = " ".join(tokenizer.tokenize(orig_text))

    start_position = tok_text.find(pred_text)
    if start_position == -1:
        return orig_text
    end_position = start_position + len(pred_text) - 1

    orig_ns_text, orig_ns_to_s = strip_spaces(orig_text)
    tok_ns_text, tok_ns_to_s = strip_spaces(tok_text)
    if len(orig_ns_text) != len(tok_ns_text):
        return orig_text

    tok_s_to_ns = {s: ns for ns, s in tok_ns_to_s.items()}

    def project(pos):
        if pos in tok_s_to_ns and tok_s_to_ns[pos] in orig_ns_to_s:
            return orig_ns_to_s[tok_s_to_ns[pos]]
        return None

    orig_start = project(start_position)
    orig_end = project(end_position)
    if orig_start is None or orig_end is None:
        return orig_text
    return orig_text[orig_start : orig_end + 1]


def get_answers(examples, features, results, args):
    """n-best decode over all windows of each question
    (reference run_squad.py:427-506). Returns (answers, nbest_answers,
    null_odds); null_odds is empty unless version_2_with_negative, and
    holds each question's null score diff (null score minus best non-null
    span score — higher means more likely unanswerable), the score the
    official v2.0 metric's best-threshold search consumes."""
    predictions = collections.defaultdict(list)
    null_vals = collections.defaultdict(lambda: (float("inf"), 0, 0))

    for ex, feat, result in _match_results(examples, features, results):
        start_indices = _best_indices(result.start_logits, args.n_best_size)
        end_indices = _best_indices(result.end_logits, args.n_best_size)
        prelim = _valid_prelim_predictions(
            start_indices, end_indices, feat, result, args
        )
        prelim.sort(key=lambda p: p.start_logit + p.end_logit, reverse=True)

        if args.version_2_with_negative:
            score = result.start_logits[0] + result.end_logits[0]
            if score < null_vals[ex.qas_id][0]:
                null_vals[ex.qas_id] = (
                    score, result.start_logits[0], result.end_logits[0]
                )

        curr, seen = [], []
        for pred in prelim:
            if len(curr) == args.n_best_size:
                break
            if pred.start_index > 0:
                final_text = get_answer_text(ex, feat, pred, args)
                if final_text in seen:
                    continue
            else:
                final_text = ""
            seen.append(final_text)
            curr.append(Prediction(final_text, pred.start_logit, pred.end_logit))
        predictions[ex.qas_id] += curr

    if args.version_2_with_negative:
        for qas_id in predictions.keys():
            _, s, e = null_vals[qas_id]
            predictions[qas_id].append(Prediction("", s, e))

    nbest_answers = collections.defaultdict(list)
    answers = {}
    null_odds = {}
    for qas_id, preds in predictions.items():
        nbest = sorted(
            preds, key=lambda p: p.start_logit + p.end_logit, reverse=True
        )[: args.n_best_size]
        if not nbest:
            nbest = [Prediction("empty", 0.0, 0.0)]
        total_scores = [p.start_logit + p.end_logit for p in nbest]
        best_non_null = next((p for p in nbest if p.text), None)
        probs = _softmax(total_scores)
        for i, entry in enumerate(nbest):
            nbest_answers[qas_id].append(
                collections.OrderedDict(
                    text=entry.text,
                    probability=probs[i],
                    start_logit=entry.start_logit,
                    end_logit=entry.end_logit,
                )
            )
        if args.version_2_with_negative:
            if best_non_null is None:
                # No non-null candidate at all: definitively unanswerable
                # (finite stand-in for +inf; null_odds must stay JSON).
                answers[qas_id] = ""
                null_odds[qas_id] = 1e9
                continue
            score_diff = (
                null_vals[qas_id][0]
                - best_non_null.start_logit
                - best_non_null.end_logit
            )
            null_odds[qas_id] = score_diff
            answers[qas_id] = (
                "" if score_diff > args.null_score_diff_threshold
                else best_non_null.text
            )
        else:
            answers[qas_id] = nbest_answers[qas_id][0]["text"]
    return answers, nbest_answers, null_odds
