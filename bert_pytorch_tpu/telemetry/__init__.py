"""Unified training telemetry (docs/telemetry.md).

Step-time decomposition with device-sync discipline (step_timer), bounded
``jax.profiler`` trace windows (profiler), compile/cache observability
(compile_events), failure sentinels + heartbeat (sentinels), and the
versioned JSONL record schema (schema). ``TrainTelemetry`` (runner) is the
facade every training entry point threads its loop through.
"""

from bert_pytorch_tpu.telemetry.cli import (add_cli_args,
                                            default_jsonl_path,
                                            from_args,
                                            stats_every)
from bert_pytorch_tpu.telemetry.collector import (FleetCollector,
                                                  JsonlTailer,
                                                  Target)
from bert_pytorch_tpu.telemetry.flightrec import (FlightRecorder,
                                                  read_postmortem)
from bert_pytorch_tpu.telemetry.introspect import (IntrospectionHub,
                                                   make_debug_server,
                                                   start_debug_server)
from bert_pytorch_tpu.telemetry.compile_events import (CompileMonitor,
                                                       shapes_digest)
from bert_pytorch_tpu.telemetry.memory import (MemorySampler,
                                               analyze_executable)
from bert_pytorch_tpu.telemetry.model_stats import (DivergenceError,
                                                    DivergenceMonitor,
                                                    finetune_grad_health,
                                                    gated_grad_health,
                                                    grad_health)
from bert_pytorch_tpu.telemetry.profiler import (ProfilerWindow,
                                                 parse_profile_spec)
from bert_pytorch_tpu.telemetry.runner import TrainTelemetry
from bert_pytorch_tpu.telemetry.schema import (SCHEMA_VERSION,
                                               validate_file,
                                               validate_record)
from bert_pytorch_tpu.telemetry.sentinels import (FailureSentinel, Heartbeat,
                                                  NonFiniteError)
from bert_pytorch_tpu.telemetry.step_timer import StepTimer

__all__ = [
    "CompileMonitor",
    "DivergenceError",
    "DivergenceMonitor",
    "FleetCollector",
    "FlightRecorder",
    "IntrospectionHub",
    "JsonlTailer",
    "MemorySampler",
    "Target",
    "make_debug_server",
    "read_postmortem",
    "start_debug_server",
    "add_cli_args",
    "analyze_executable",
    "default_jsonl_path",
    "from_args",
    "FailureSentinel",
    "finetune_grad_health",
    "gated_grad_health",
    "grad_health",
    "Heartbeat",
    "NonFiniteError",
    "ProfilerWindow",
    "SCHEMA_VERSION",
    "StepTimer",
    "stats_every",
    "TrainTelemetry",
    "parse_profile_spec",
    "shapes_digest",
    "validate_file",
    "validate_record",
]
