"""Shared telemetry CLI surface for the runners.

Every runner exposes the same canonical flag set via :func:`add_cli_args`
and builds its :class:`~bert_pytorch_tpu.telemetry.runner.TrainTelemetry`
via :func:`from_args` — one copy of the flags, help text, and
default-path fallbacks instead of five drifting ones. Per-runner knobs are
constructor arguments (``window_default``: pretraining logs denser windows
than the short finetune runs; ``sync_every_default``: the small-model
finetune runners keep the full per-step decomposition — a per-step sync
is cheap there and buys step-exact sentinels — while the pretraining hot
loop samples it; since PR 7 no loop fetches the loss outside the sync
cadence, jaxlint HS101 enforces it).
"""

from __future__ import annotations

import os
from typing import Optional


def add_cli_args(parser, window_default: int = 50,
                 sync_every_default: int = 4) -> None:
    """Register the canonical telemetry flags (docs/telemetry.md)."""
    parser.add_argument("--profile_steps", type=str, default="0",
                        help="capture a JAX profiler trace: 'N' traces N "
                             "steady-state steps (after the compile step), "
                             "'N:M' traces the explicit step range [N, M). "
                             "Auto-stops at the range end (or end of run). "
                             "'0' disables (docs/telemetry.md)")
    parser.add_argument("--profile_dir", type=str, default="",
                        help="profiler trace output directory; default "
                             "<output_dir>/profile")
    parser.add_argument("--telemetry_jsonl", type=str, default="",
                        help="JSONL telemetry sink path; default "
                             "<output_dir>/<prefix>_telemetry.jsonl (no "
                             "sink without an output dir)")
    parser.add_argument("--telemetry_window", type=int,
                        default=window_default,
                        help="steps per telemetry window record "
                             "(step-time percentiles + MFU)")
    parser.add_argument("--telemetry_sync_every", type=int,
                        default=sync_every_default,
                        help="device-sync cadence for the step-time "
                             "decomposer: 1 = block on every step's metrics "
                             "(full data/host/device split, step-exact "
                             "sentinel), N = sample every Nth step (each "
                             "sync is a host<->device round trip; per-step "
                             "blocking costs real throughput through a "
                             "remote-TPU tunnel — bench.py docstring: "
                             "~35%%), 0 = never sync (data/host only)")
    parser.add_argument("--sentinel_policy", type=str, default="continue",
                        choices=["continue", "abort"],
                        help="non-finite loss/grad-norm policy: 'continue' "
                             "logs a sentinel record per observed bad step; "
                             "'abort' raises after --sentinel_patience "
                             "consecutive observed bad steps")
    parser.add_argument("--sentinel_patience", type=int, default=3,
                        help="consecutive OBSERVED non-finite steps before "
                             "'abort' raises (one scaler-recovered fp16 "
                             "overflow step should not kill a run). The "
                             "sentinel observes on the sync/log cadence, so "
                             "detection lag scales with "
                             "--telemetry_sync_every; pass 1 there for "
                             "step-exact abort")
    parser.add_argument("--heartbeat_file", type=str, default="",
                        help="rank-0 liveness file (step/wallclock/"
                             "last_loss/counter, atomically replaced); "
                             "default <output_dir>/heartbeat.json. The "
                             "capture harness reads it instead of guessing "
                             "liveness from checkpoint mtimes")
    parser.add_argument("--debug_port", type=int, default=0,
                        help="live training introspection plane "
                             "(telemetry/introspect.py, docs/"
                             "observability.md): serve /healthz "
                             "(heartbeat-backed step liveness), /statsz "
                             "(live window/grad-health/compile snapshot) "
                             "and /metricsz (Prometheus text, consistent "
                             "with the JSONL windows per metric name) on "
                             "127.0.0.1:<port>. 0 (default) disables")
    parser.add_argument("--debug_stale_after_s", type=float, default=0.0,
                        help="debug-plane /healthz staleness bound: 503 "
                             "once no step completed for this many "
                             "seconds. 0 (default) follows "
                             "--watchdog_timeout_s when set, else 60 — "
                             "size it above the worst healthy step time")
    parser.add_argument("--postmortem_file", type=str, default="",
                        help="crash flight recorder (telemetry/"
                             "flightrec.py): bounded ring of the last "
                             "telemetry records + log lines, flushed "
                             "atomically here on fault/divergence/crash "
                             "(and periodically, so even a SIGKILLed "
                             "process leaves forensics); default "
                             "<output_dir>/postmortem.json, disabled "
                             "without an output dir. A clean run removes "
                             "the file")
    parser.add_argument("--grad_stats_every", type=int, default=-1,
                        help="in-jit grad-health cadence (per-layer-group "
                             "grad/param norms + update:weight ratios, "
                             "telemetry/model_stats.py): N computes every "
                             "Nth optimizer step, 0 disables, -1 (default) "
                             "follows --telemetry_sync_every so the host "
                             "reads every computed block for free on its "
                             "existing sync")
    parser.add_argument("--grad_spike_factor", type=float, default=10.0,
                        help="divergence early-warning: warn when the "
                             "global grad norm exceeds this factor x its "
                             "own EMA (0 disables). Warnings follow "
                             "--sentinel_policy/--sentinel_patience")
    parser.add_argument("--update_ratio_max", type=float, default=1.0,
                        help="divergence early-warning: warn when the "
                             "global update:weight ratio exceeds this "
                             "absolute bound (0 disables) — a per-step "
                             "relative weight change near 1 is a blown "
                             "learning rate, caught before the loss NaNs")
    parser.add_argument("--watchdog_timeout_s", type=float, default=0.0,
                        help="hung-step watchdog (docs/fault_tolerance.md): "
                             "flag (one fault record + warning; never a "
                             "kill) when no step completes for this many "
                             "seconds. Arms at the FIRST completed step, so "
                             "the step-0 compile never counts — size it "
                             "well above the worst healthy step time. "
                             "0 (default) disables")
    parser.add_argument("--telemetry_cost_analysis", type=str,
                        default="auto", choices=["auto", "off", "full"],
                        help="static per-executable cost attribution "
                             "(compile_cost records: FLOPs, bytes "
                             "accessed, argument/output/temp bytes). "
                             "'auto' compiles for memory_analysis only "
                             "when that is cheap (CPU, or persistent "
                             "compile cache on) and falls back to the "
                             "compile-free HLO cost analysis elsewhere; "
                             "'full' always compiles (one extra backend "
                             "compile per shapes digest)")


def stats_every(args) -> int:
    """Resolve --grad_stats_every: -1 follows the sync cadence (the host
    can only READ the block on synced steps, so computing it off-cadence
    would burn device FLOPs on values nobody fetches)."""
    every = getattr(args, "grad_stats_every", 0)
    if every is None or every < 0:
        return max(0, int(getattr(args, "telemetry_sync_every", 0)))
    return int(every)


def default_jsonl_path(args, output_dir: Optional[str],
                       prefix: str) -> Optional[str]:
    """Resolve the JSONL sink path (None = no sink)."""
    if args.telemetry_jsonl:
        return args.telemetry_jsonl
    if output_dir:
        return os.path.join(output_dir, f"{prefix}_telemetry.jsonl")
    return None


def from_args(args, sink=None, is_primary: bool = True,
              seq_per_step: Optional[int] = None,
              flops_per_seq: Optional[float] = None,
              tokens_per_step: Optional[int] = None,
              output_dir: Optional[str] = None,
              process: str = "train"):
    """Build a TrainTelemetry from the :func:`add_cli_args` namespace.

    ``output_dir`` anchors the profile-dir / heartbeat / postmortem
    fallbacks; without one, traces go to ``./profile`` and the heartbeat
    and flight recorder are disabled unless the flags name paths
    explicitly. ``process`` labels the runner in the debug plane's
    exports and the postmortem payload ("pretrain", "glue", ...), so a
    fleet timeline can attribute trainer samples by name.

    Rank-0 only for the observability plane: non-primary ranks get
    neither a debug server (one port per JOB, like the artifacts) nor a
    flight recorder (their sink is disabled; an empty ring would flush
    empty postmortems over rank 0's).
    """
    import jax

    from bert_pytorch_tpu.telemetry.runner import TrainTelemetry

    profile_dir = args.profile_dir or (
        os.path.join(output_dir, "profile") if output_dir else "profile")
    heartbeat = args.heartbeat_file or (
        os.path.join(output_dir, "heartbeat.json") if output_dir else None)
    introspect = None
    recorder = None
    if is_primary:
        postmortem = getattr(args, "postmortem_file", "") or (
            os.path.join(output_dir, "postmortem.json")
            if output_dir else None)
        if postmortem:
            from bert_pytorch_tpu.telemetry.flightrec import FlightRecorder
            from bert_pytorch_tpu.utils import logging as logging_util

            recorder = FlightRecorder(
                postmortem, process=process).install_exit_hooks()
            # Log lines tee into the ring too (the runners initialized
            # their handlers before building telemetry, so append).
            logging_util.add_handler(recorder.log_handler())
        if getattr(args, "debug_port", 0):
            from bert_pytorch_tpu.telemetry.introspect import \
                IntrospectionHub

            stale_after = getattr(args, "debug_stale_after_s", 0.0) or \
                getattr(args, "watchdog_timeout_s", 0.0) or 60.0
            introspect = IntrospectionHub(
                process=process, stale_after_s=stale_after)
    tele = TrainTelemetry(
        sink=sink,
        is_primary=is_primary,
        window=args.telemetry_window,
        sync_every=args.telemetry_sync_every,
        seq_per_step=seq_per_step,
        flops_per_seq=flops_per_seq,
        tokens_per_step=tokens_per_step,
        device_kind=jax.devices()[0].device_kind,
        n_devices=jax.device_count(),
        profile_steps=args.profile_steps,
        profile_dir=profile_dir,
        sentinel_policy=args.sentinel_policy,
        sentinel_patience=args.sentinel_patience,
        heartbeat_path=heartbeat,
        watchdog_timeout_s=getattr(args, "watchdog_timeout_s", 0.0),
        grad_spike_factor=args.grad_spike_factor,
        update_ratio_max=args.update_ratio_max,
        cost_analysis=args.telemetry_cost_analysis,
        introspect=introspect,
        flight_recorder=recorder)
    if introspect is not None:
        from bert_pytorch_tpu.telemetry.introspect import start_debug_server
        from bert_pytorch_tpu.utils import logging as logging_util

        try:
            tele.debug_server = start_debug_server(
                introspect, port=int(args.debug_port))
        except OSError as exc:
            # Observability must never take the run down: a port
            # already held (a second runner on the host, a stale
            # process) costs the debug plane, not the training job.
            logging_util.info(
                f"telemetry: debug plane DISABLED — could not bind "
                f"port {args.debug_port}: {exc}")
        else:
            host, port = tele.debug_server.server_address[:2]
            logging_util.info(
                f"telemetry: debug plane on http://{host}:{port} "
                "(/healthz /statsz /metricsz)")
    return tele
