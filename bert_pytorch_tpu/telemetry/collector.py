"""The fleet collector: one ordered timeline for the whole fleet
(docs/observability.md "Fleet collector").

PR 11's fleet is many processes, each with its own signal surface: every
trainer now has a live debug plane (telemetry/introspect.py), every
replica exports ``/metricsz`` (serve/tracing.py), the router serves
``/statsz`` and ``/metricsz``, and each process writes its own JSONL
sink. Nobody merges them — answering "what did the fleet look like when
replica 1 died" means hand-joining five files and three scrape formats.
The :class:`FleetCollector` owns that join:

* **concurrent scrape** — every registered :class:`Target` is probed
  once per pass, one thread per target bounded by the transport
  timeout, so one black-holed target costs max(per-target) and can
  never stale the others' samples (the ``Router.scrape_once``
  discipline). Each probe yields one schema-v1 ``obs_scrape`` record:
  the target's headline gauges plus ``staleness_s`` — seconds since the
  last GOOD sample, the number the "fleet scrape staleness" report gate
  regresses on;
* **JSONL tailing** — every registered sink file is tailed
  incrementally (offset + partial-line buffer, rotation-safe); new
  records merge into the timeline stamped with their source name;
* **one ordered timeline** — each pass's harvest (tailed records +
  scrape samples + the pass's ``obs_fleet_window`` aggregate) is sorted
  by ``(ts, source, sequence)`` and appended to the output JSONL. The
  sort is deterministic: replaying the same sources yields the same
  timeline byte for byte (out-of-order source timestamps land in
  timestamp order within the pass);
* **fleet aggregates** — one ``obs_fleet_window`` per pass: healthy /
  total target counts (the dip-and-recovery signal when a replica
  dies), fleet request rate (delta of replica request counters between
  passes), worst-replica p99 (histogram-quantile over each replica's
  exported phase-latency histogram — the "fleet worst-replica p99"
  gate), trainer step rate, max staleness, and the fleet error-budget
  burn (over-SLO counts against the configured budget).

Stdlib-only and dual-loadable like the supervisor/router: imported
normally it is part of the telemetry package; loaded by FILE PATH
(``tools/obs_collect.py`` via tools/_bootstrap.py) it pulls the schema
module the same way, so the collector process never needs an
accelerator runtime — it must keep collecting while the processes it
watches hang.
"""

from __future__ import annotations

import http.client
import json
import os
import sys
import threading
import time
import urllib.parse
from typing import Callable, Dict, List, Optional, Sequence, Tuple


def _load_schema():
    """Schema module both ways: package import when this module was
    imported normally, sibling file-path import when it was itself
    loaded by path (the jax-free parent property)."""
    if __package__:
        import importlib

        return importlib.import_module(
            "bert_pytorch_tpu.telemetry.schema")
    import importlib.util

    module = sys.modules.get("_collector_schema")
    if module is not None:
        return module
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "schema.py")
    spec = importlib.util.spec_from_file_location("_collector_schema", path)
    module = importlib.util.module_from_spec(spec)
    sys.modules["_collector_schema"] = module
    spec.loader.exec_module(module)
    return module


_schema = _load_schema()
SCHEMA_VERSION = _schema.SCHEMA_VERSION
TARGET_KINDS = _schema.OBS_TARGET_KINDS


# -- scrape transports -------------------------------------------------------

def _http_get(url: str, path: str, timeout_s: float) -> Tuple[int, str]:
    parsed = urllib.parse.urlsplit(url)
    conn = http.client.HTTPConnection(parsed.hostname, parsed.port,
                                      timeout=max(0.05, timeout_s))
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read().decode("utf-8", "replace")
    finally:
        conn.close()


def parse_prometheus(text: str) -> List[Tuple[str, Dict[str, str], float]]:
    """(name, labels, value) per sample line of a text exposition."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name_part, _, value_part = line.rpartition(" ")
        if not name_part:
            continue
        try:
            value = float(value_part)
        except ValueError:
            continue
        labels: Dict[str, str] = {}
        name = name_part
        if "{" in name_part and name_part.endswith("}"):
            name, _, raw = name_part.partition("{")
            for item in raw[:-1].split(","):
                if "=" in item:
                    k, _, v = item.partition("=")
                    labels[k.strip()] = v.strip().strip('"')
        out.append((name, labels, value))
    return out


def _histogram_quantile(buckets: Dict[float, float], frac: float,
                        total: Optional[float] = None) -> Optional[float]:
    """Upper-bound quantile estimate from cumulative Prometheus buckets
    (le -> cumulative count, finite bounds only). ``total`` is the TRUE
    observation count (the ``_count`` series / +Inf bucket) — without
    it the overflow observations above the largest finite bound would
    be invisible and a tail blowup would UNDER-report the quantile.
    Returns the smallest finite bound covering ``frac`` of the total,
    or the largest finite bound when the quantile sits in the +Inf
    bucket (a floor, not an average-away)."""
    if not buckets:
        return None
    bounds = sorted(buckets)
    if total is None or total < max(buckets.values()):
        total = max(buckets.values())
    if total <= 0:
        return None
    want = frac * total
    for bound in bounds:
        if buckets[bound] >= want:
            return bound
    return bounds[-1]


def scrape_trainer(url: str, timeout_s: float = 2.0) -> Optional[dict]:
    """One trainer debug-plane sample: the headline ``bert_train_*``
    gauges off /metricsz. None = unreachable."""
    try:
        status, text = _http_get(url, "/metricsz", timeout_s)
    except OSError:
        return None
    if status != 200:
        return None
    gauges: Dict[str, float] = {}
    for name, _labels, value in parse_prometheus(text):
        if name.startswith("bert_train_"):
            gauges[name[len("bert_train_"):]] = value
    if not gauges:
        return None
    # Healthy = answering AND stepping: a trainer wedged in a hung
    # collective keeps serving /metricsz (the HTTP threads are fine) —
    # only the step age vs the exported staleness bound says whether
    # training is actually advancing (the /healthz verdict, readable
    # from the same scrape). No step age yet = still warming = healthy.
    age = gauges.get("step_age_seconds")
    bound = gauges.get("stale_after_seconds")
    stepping = age is None or bound is None or age <= bound
    out = {"healthy": gauges.get("up", 0.0) >= 1.0 and stepping}
    for src, dst in (("step", "step"),
                     ("step_age_seconds", "step_age_s"),
                     ("window_steps_per_sec", "steps_per_sec"),
                     ("window_mfu", "mfu"),
                     ("nonfinite_steps_total", "nonfinite_steps"),
                     ("faults_total", "faults")):
        if src in gauges:
            out[dst] = gauges[src]
    return out


def scrape_replica(url: str, timeout_s: float = 2.0) -> Optional[dict]:
    """One serving-replica sample off /metricsz: liveness/queue gauges,
    request/error/over-SLO counters summed over task heads, and a p99
    estimate from the total-phase latency histogram."""
    try:
        status, text = _http_get(url, "/metricsz", timeout_s)
    except OSError:
        return None
    if status != 200:
        return None
    series = parse_prometheus(text)
    sums = {"requests": 0.0, "errors": 0.0, "over_slo": 0.0}
    buckets: Dict[float, float] = {}
    hist_total = 0.0
    gauges: Dict[str, float] = {}
    for name, labels, value in series:
        if name == "bert_serve_requests_total":
            sums["requests"] += value
        elif name == "bert_serve_errors_total":
            sums["errors"] += value
        elif name == "bert_serve_over_slo_total":
            sums["over_slo"] += value
        elif name == "bert_serve_phase_latency_ms_bucket" and \
                labels.get("phase") == "total":
            le = labels.get("le", "")
            if le == "+Inf":
                # The TRUE total: observations past the largest finite
                # bound live only here, and a quantile computed without
                # them under-reports exactly during a tail blowup.
                hist_total += value
            elif le:
                try:
                    bound = float(le)
                except ValueError:
                    continue
                buckets[bound] = buckets.get(bound, 0.0) + value
        elif not labels and name.startswith("bert_serve_"):
            gauges[name[len("bert_serve_"):]] = value
    if not series:
        return None
    out = {
        "healthy": gauges.get("dispatch_alive", 0.0) >= 1.0
        and gauges.get("draining", 0.0) < 1.0,
        "dispatch_alive": gauges.get("dispatch_alive", 0.0) >= 1.0,
        "draining": gauges.get("draining", 0.0) >= 1.0,
        "queue_depth": gauges.get("queue_depth", 0.0),
        "requests": sums["requests"],
        "errors": sums["errors"],
        "over_slo": sums["over_slo"],
    }
    p99 = _histogram_quantile(buckets, 0.99, total=hist_total or None)
    if p99 is not None:
        out["latency_p99_ms"] = p99
    return out


def scrape_router(url: str, timeout_s: float = 2.0) -> Optional[dict]:
    """One router sample off its JSON /statsz (the human surface; the
    router's /metricsz carries the same counters for standard
    scrapers)."""
    try:
        status, text = _http_get(url, "/statsz", timeout_s)
        stats = json.loads(text)
    except (OSError, ValueError):
        return None
    if status != 200 or not isinstance(stats, dict):
        return None
    out = {"healthy": stats.get("healthy_replicas", 0) > 0}
    for key in ("requests", "sheds", "errors", "retries",
                "failovers", "healthy_replicas", "replicas",
                "latency_p99_ms"):
        if stats.get(key) is not None:
            out[key] = stats[key]
    if stats.get("ok") is not None:
        # Renamed: the obs_scrape record's own boolean ``ok`` (did the
        # scrape succeed) must never be clobbered by the router's
        # ok-request counter.
        out["requests_ok"] = stats["ok"]
    return out


_SCRAPERS = {
    "trainer": scrape_trainer,
    "replica": scrape_replica,
    "router": scrape_router,
}


class Target:
    """One scrape target. ``scrape`` is injectable for deterministic
    tests (a callable ``url -> Optional[dict]``); production resolves it
    from ``kind``. Mutable sample state (last good sample + its clock
    time) is only touched by :meth:`FleetCollector.collect_once` under
    the collector's lock."""

    def __init__(self, name: str, kind: str, url: str,
                 scrape: Optional[Callable[[str], Optional[dict]]] = None,
                 timeout_s: float = 2.0):
        if kind not in TARGET_KINDS:
            raise ValueError(
                f"target kind must be one of {TARGET_KINDS}, got {kind!r}")
        self.name = str(name)
        self.kind = kind
        self.url = url.rstrip("/")
        self.timeout_s = float(timeout_s)
        self._scrape = scrape or (
            lambda u: _SCRAPERS[kind](u, timeout_s=self.timeout_s))
        # Sample state (collector-thread-owned, under the collector lock)
        self.last_ok_at: Optional[float] = None
        self.last_sample: Optional[dict] = None
        self.prev_sample: Optional[dict] = None
        self.prev_ok_at: Optional[float] = None
        self.failures = 0


class JsonlTailer:
    """Incremental reader of one JSONL sink: returns only the records
    appended since the last poll. A partial trailing line stays buffered
    until its newline lands (a writer mid-line never yields a torn
    record); a truncated/rotated file restarts from the top."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = str(source)
        self._offset = 0
        self._buf = ""

    def poll(self) -> List[dict]:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []
        if size < self._offset:
            self._offset = 0  # rotated/truncated: start over
            self._buf = ""
        records: List[dict] = []
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                f.seek(self._offset)
                chunk = f.read()
                self._offset = f.tell()
        except OSError:
            return []
        data = self._buf + chunk
        lines = data.split("\n")
        self._buf = lines.pop()  # "" after a complete final line
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # the schema lint owns strictness
            if isinstance(rec, dict):
                records.append(rec)
        return records


class FleetCollector:
    """Merge scrapes + tailed sinks into one ordered timeline JSONL.

    Drive it either with a background thread (:meth:`start` /
    :meth:`stop`) or by calling :meth:`collect_once` per pass
    (deterministic tests, the chaos harness) — one lock serializes the
    two, so a manual pass and the thread never interleave a pass.
    ``emit`` optionally receives every timeline record as it is written
    (the in-memory index the E2E asserts on)."""

    def __init__(
        self,
        targets: Sequence[Target],
        tails: Sequence[JsonlTailer] = (),
        out_path: Optional[str] = None,
        emit: Optional[Callable[[dict], None]] = None,
        interval_s: float = 1.0,
        slo_error_budget: float = 0.01,
        clock: Callable[[], float] = time.monotonic,
        wall: Callable[[], float] = time.time,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self._emit_fn = emit
        self.interval_s = float(interval_s)
        self.slo_error_budget = float(slo_error_budget)
        self._clock = clock
        self._wall = wall
        self._sleep = sleep
        # One lock guards the target table, the tailers, the output
        # file, and the pass counter: collect_once may be driven by a
        # test/harness thread while the background loop runs (registry,
        # analysis/concurrency.py).
        self._lock = threading.Lock()
        self._targets = list(targets)
        self._tails = list(tails)
        self._passes = 0
        self._started_at = clock()
        self._out_f = open(out_path, "a", encoding="utf-8") \
            if out_path else None
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- one pass ---------------------------------------------------------

    def collect_once(self) -> Optional[dict]:
        """Scrape every target concurrently, drain every tailer, write
        the pass's records in deterministic order. Returns the pass's
        ``obs_fleet_window`` record (None only when the collector has no
        targets at all)."""
        with self._lock:
            targets = list(self._targets)
            # Concurrent probes: one bounded thread per target, results
            # by slot — the scrape_once discipline (a black-holed target
            # costs max(per-target), and its staleness is RECORDED, not
            # propagated to the others).
            results: list = [None] * len(targets)
            costs: list = [0.0] * len(targets)

            def probe(i: int, target: Target) -> None:
                t0 = self._clock()
                try:
                    results[i] = target._scrape(target.url)
                except Exception:
                    results[i] = None
                finally:
                    # Per-target cost, stamped inside the probe: the
                    # pass-level join time is the SLOWEST target's cost
                    # and must not be misattributed to the healthy ones.
                    costs[i] = self._clock() - t0

            threads = [threading.Thread(target=probe, args=(i, t),
                                        name="obs-collect-probe",
                                        daemon=True)
                       for i, t in enumerate(targets)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            now = self._clock()
            wall_ts = self._wall()
            self._passes += 1
            harvest: List[Tuple[float, int, int, dict]] = []
            scrapes = []
            for idx, (target, sample) in enumerate(zip(targets, results)):
                target.prev_sample, target.prev_ok_at = (
                    (target.last_sample, target.last_ok_at)
                    if sample is not None else
                    (target.prev_sample, target.prev_ok_at))
                if sample is not None:
                    target.failures = 0
                    staleness = 0.0
                    target.last_sample = sample
                    target.last_ok_at = now
                else:
                    target.failures += 1
                    # Never-scraped targets age from collector start:
                    # a target that was never up is maximally stale,
                    # not zero-stale.
                    anchor = (target.last_ok_at
                              if target.last_ok_at is not None
                              else self._started_at)
                    staleness = now - anchor
                rec = {
                    "kind": "obs_scrape", "tag": "obs",
                    "target": target.name, "target_kind": target.kind,
                    "url": target.url,
                    "ok": sample is not None,
                    "staleness_s": round(max(0.0, staleness), 3),
                    "scrape_ms": round(costs[idx] * 1000.0, 3),
                }
                if sample is not None:
                    # The scrape envelope's own fields win: a sample key
                    # colliding with ok/target/staleness_s/... must not
                    # rewrite the record's identity.
                    rec.update({k: v for k, v in sample.items()
                                if k not in rec})
                scrapes.append((target, sample, rec))
            window = self._fleet_window_locked(targets, scrapes, now)
            for tail_idx, tailer in enumerate(self._tails):
                for line_no, rec in enumerate(tailer.poll()):
                    rec = dict(rec)
                    rec.setdefault("obs_source", tailer.source)
                    ts = rec.get("ts")
                    ts = float(ts) if isinstance(ts, (int, float)) \
                        and not isinstance(ts, bool) else wall_ts
                    harvest.append((ts, 1 + tail_idx, line_no, rec))
            for scrape_idx, (_, _, rec) in enumerate(scrapes):
                harvest.append((wall_ts, 0, scrape_idx, rec))
            if window is not None:
                harvest.append((wall_ts, 0, len(scrapes), window))
            # Deterministic merge: timestamp order, ties broken by
            # (source index, per-source sequence) — replaying the same
            # sources reproduces the same timeline byte for byte.
            harvest.sort(key=lambda item: (item[0], item[1], item[2]))
            for ts, _, _, rec in harvest:
                self._write_locked(rec, ts)
        return window

    def _fleet_window_locked(self, targets: List[Target],
                             scrapes, now: float) -> Optional[dict]:
        if not targets:
            return None
        healthy = 0
        replicas = replicas_healthy = 0
        trainers_rate: List[float] = []
        worst_p99: Optional[float] = None
        fleet_rps = 0.0
        rps_seen = False
        over_slo = requests = 0.0
        max_staleness = 0.0
        for target, sample, rec in scrapes:
            max_staleness = max(max_staleness, rec["staleness_s"])
            ok = sample is not None and bool(sample.get("healthy"))
            healthy += 1 if ok else 0
            if target.kind == "replica":
                replicas += 1
                replicas_healthy += 1 if ok else 0
                if sample is not None:
                    p99 = sample.get("latency_p99_ms")
                    if p99 is not None:
                        worst_p99 = p99 if worst_p99 is None \
                            else max(worst_p99, p99)
                    requests += float(sample.get("requests", 0.0))
                    over_slo += float(sample.get("over_slo", 0.0))
                    prev = target.prev_sample
                    if prev is not None and target.prev_ok_at is not None \
                            and now > target.prev_ok_at:
                        delta = (float(sample.get("requests", 0.0))
                                 - float(prev.get("requests", 0.0)))
                        if delta >= 0:
                            fleet_rps += delta / (now - target.prev_ok_at)
                            rps_seen = True
            elif target.kind == "trainer" and sample is not None:
                rate = sample.get("steps_per_sec")
                if rate is not None:
                    trainers_rate.append(float(rate))
        record = {
            "kind": "obs_fleet_window", "tag": "obs",
            "targets_total": len(targets),
            "targets_healthy": healthy,
            "max_staleness_s": round(max_staleness, 3),
        }
        if replicas:
            record["replicas_total"] = replicas
            record["replicas_healthy"] = replicas_healthy
        if worst_p99 is not None:
            record["worst_replica_p99_ms"] = round(worst_p99, 3)
        if rps_seen:
            record["fleet_rps"] = round(fleet_rps, 3)
        if trainers_rate:
            record["trainer_steps_per_sec"] = round(
                sum(trainers_rate) / len(trainers_rate), 4)
        if requests > 0:
            budget = self.slo_error_budget * requests
            if budget > 0:
                record["error_budget_burn"] = round(over_slo / budget, 4)
        return record

    def _write_locked(self, rec: dict, ts: float) -> None:
        out = dict(rec)
        out.setdefault("schema", SCHEMA_VERSION)
        out.setdefault("ts", round(ts, 3))
        if self._out_f is not None:
            self._out_f.write(json.dumps(out) + "\n")
            self._out_f.flush()
        if self._emit_fn is not None:
            try:
                self._emit_fn(out)
            except Exception:
                pass  # observability must never take the collector down

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        self._stop_event.clear()
        self._thread = threading.Thread(
            target=self._loop, name="obs-collector", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop_event.is_set():
            self.collect_once()
            self._sleep(self.interval_s)

    def stop(self) -> None:
        """Stop the background loop, run one final pass (drain anything
        the sinks appended since the last tick), close the output.
        Manual drivers (the CLI's own pass loop) that already ran their
        last pass use :meth:`close` instead — stop()'s drain pass would
        be an extra, uncounted round."""
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        self.collect_once()
        self.close()

    def close(self) -> None:
        """Close the timeline output without another pass."""
        with self._lock:
            if self._out_f is not None:
                self._out_f.close()
                self._out_f = None

    def passes(self) -> int:
        with self._lock:
            return self._passes
