"""The fleet collector: one ordered timeline for the whole fleet
(docs/observability.md "Fleet collector").

PR 11's fleet is many processes, each with its own signal surface: every
trainer now has a live debug plane (telemetry/introspect.py), every
replica exports ``/metricsz`` (serve/tracing.py), the router serves
``/statsz`` and ``/metricsz``, and each process writes its own JSONL
sink. Nobody merges them — answering "what did the fleet look like when
replica 1 died" means hand-joining five files and three scrape formats.
The :class:`FleetCollector` owns that join:

* **concurrent scrape** — every registered :class:`Target` is probed
  once per pass, one thread per target bounded by the transport
  timeout, so one black-holed target costs max(per-target) and can
  never stale the others' samples (the ``Router.scrape_once``
  discipline). Each probe yields one schema-v1 ``obs_scrape`` record:
  the target's headline gauges plus ``staleness_s`` — seconds since the
  last GOOD sample, the number the "fleet scrape staleness" report gate
  regresses on;
* **JSONL tailing** — every registered sink file is tailed
  incrementally (offset + partial-line buffer, rotation-safe); new
  records merge into the timeline stamped with their source name;
* **one ordered timeline** — each pass's harvest (tailed records +
  scrape samples + the pass's ``obs_fleet_window`` aggregate) is sorted
  by ``(ts, source, sequence)`` and appended to the output JSONL. The
  sort is deterministic: replaying the same sources yields the same
  timeline byte for byte (out-of-order source timestamps land in
  timestamp order within the pass);
* **fleet aggregates** — one ``obs_fleet_window`` per pass: healthy /
  total target counts (the dip-and-recovery signal when a replica
  dies), fleet request rate (delta of replica request counters between
  passes), worst-replica p99 (histogram-quantile over each replica's
  exported phase-latency histogram — the "fleet worst-replica p99"
  gate), trainer step rate, max staleness, and the fleet error-budget
  burn (over-SLO counts against the configured budget);
* **coordinated capture** — :meth:`FleetCollector.trigger_profile`
  POSTs ``/profilez`` to every trainer/replica target concurrently
  (fired together, so the bounded capture windows ALIGN across the
  fleet) and records one trigger ``obs_scrape`` (``probe:
  "profilez"``) per target; the resulting ``profile_window`` records
  land in each process's sink and are tailed into the same timeline
  (``tools/obs_collect.py --profile``);
* **trace stitching** — tailed ``router_trace`` and ``serve_trace``
  records that share a trace id (the ``X-Bert-Trace`` propagation,
  docs/observability.md "Trace propagation") are joined into one
  ``trace_stitch`` record per client request: the router's winning
  attempt span is matched to the replica's ``serve_trace`` by attempt
  index and the client-observed total is decomposed into router
  overhead + network gap + replica time (the gap is the residual, so
  the decomposition sums exactly at record precision). A side that
  never shows up within :data:`STITCH_GRACE_PASSES` passes is emitted
  as ``orphan: true`` — counted, never dropped silently.

Stdlib-only and dual-loadable like the supervisor/router: imported
normally it is part of the telemetry package; loaded by FILE PATH
(``tools/obs_collect.py`` via tools/_bootstrap.py) it pulls the schema
module the same way, so the collector process never needs an
accelerator runtime — it must keep collecting while the processes it
watches hang.
"""

from __future__ import annotations

import http.client
import json
import os
import sys
import threading
import time
import urllib.parse
from typing import Callable, Dict, List, Optional, Sequence, Tuple


def _load_schema():
    """Schema module both ways: package import when this module was
    imported normally, sibling file-path import when it was itself
    loaded by path (the jax-free parent property)."""
    if __package__:
        import importlib

        return importlib.import_module(
            "bert_pytorch_tpu.telemetry.schema")
    import importlib.util

    module = sys.modules.get("_collector_schema")
    if module is not None:
        return module
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "schema.py")
    spec = importlib.util.spec_from_file_location("_collector_schema", path)
    module = importlib.util.module_from_spec(spec)
    sys.modules["_collector_schema"] = module
    spec.loader.exec_module(module)
    return module


_schema = _load_schema()
SCHEMA_VERSION = _schema.SCHEMA_VERSION
TARGET_KINDS = _schema.OBS_TARGET_KINDS

# How many collector passes an unmatched trace side may wait for its
# counterpart before it is emitted as an orphan. Router and replica
# sinks are tailed by the same pass loop, so the only real skew is one
# flush interval; three passes is generous without letting the pending
# table grow unboundedly under sustained one-sided traffic.
STITCH_GRACE_PASSES = 3
_STITCH_EPS_MS = _schema._STITCH_EPS_MS


# -- scrape transports -------------------------------------------------------

def _http_get(url: str, path: str, timeout_s: float) -> Tuple[int, str]:
    parsed = urllib.parse.urlsplit(url)
    conn = http.client.HTTPConnection(parsed.hostname, parsed.port,
                                      timeout=max(0.05, timeout_s))
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read().decode("utf-8", "replace")
    finally:
        conn.close()


def _http_post_json(url: str, path: str, body: dict,
                    timeout_s: float) -> Tuple[int, str]:
    parsed = urllib.parse.urlsplit(url)
    conn = http.client.HTTPConnection(parsed.hostname, parsed.port,
                                      timeout=max(0.05, timeout_s))
    try:
        data = json.dumps(body).encode("utf-8")
        conn.request("POST", path, body=data,
                     headers={"Content-Type": "application/json",
                              "Content-Length": str(len(data))})
        resp = conn.getresponse()
        return resp.status, resp.read().decode("utf-8", "replace")
    finally:
        conn.close()


def parse_prometheus(text: str) -> List[Tuple[str, Dict[str, str], float]]:
    """(name, labels, value) per sample line of a text exposition."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name_part, _, value_part = line.rpartition(" ")
        if not name_part:
            continue
        try:
            value = float(value_part)
        except ValueError:
            continue
        labels: Dict[str, str] = {}
        name = name_part
        if "{" in name_part and name_part.endswith("}"):
            name, _, raw = name_part.partition("{")
            for item in raw[:-1].split(","):
                if "=" in item:
                    k, _, v = item.partition("=")
                    labels[k.strip()] = v.strip().strip('"')
        out.append((name, labels, value))
    return out


def _histogram_quantile(buckets: Dict[float, float], frac: float,
                        total: Optional[float] = None) -> Optional[float]:
    """Upper-bound quantile estimate from cumulative Prometheus buckets
    (le -> cumulative count, finite bounds only). ``total`` is the TRUE
    observation count (the ``_count`` series / +Inf bucket) — without
    it the overflow observations above the largest finite bound would
    be invisible and a tail blowup would UNDER-report the quantile.
    Returns the smallest finite bound covering ``frac`` of the total,
    or the largest finite bound when the quantile sits in the +Inf
    bucket (a floor, not an average-away)."""
    if not buckets:
        return None
    bounds = sorted(buckets)
    if total is None or total < max(buckets.values()):
        total = max(buckets.values())
    if total <= 0:
        return None
    want = frac * total
    for bound in bounds:
        if buckets[bound] >= want:
            return bound
    return bounds[-1]


def scrape_trainer(url: str, timeout_s: float = 2.0) -> Optional[dict]:
    """One trainer debug-plane sample: the headline ``bert_train_*``
    gauges off /metricsz. None = unreachable."""
    try:
        status, text = _http_get(url, "/metricsz", timeout_s)
    except OSError:
        return None
    if status != 200:
        return None
    gauges: Dict[str, float] = {}
    for name, _labels, value in parse_prometheus(text):
        if name.startswith("bert_train_"):
            gauges[name[len("bert_train_"):]] = value
    if not gauges:
        return None
    # Healthy = answering AND stepping: a trainer wedged in a hung
    # collective keeps serving /metricsz (the HTTP threads are fine) —
    # only the step age vs the exported staleness bound says whether
    # training is actually advancing (the /healthz verdict, readable
    # from the same scrape). No step age yet = still warming = healthy.
    age = gauges.get("step_age_seconds")
    bound = gauges.get("stale_after_seconds")
    stepping = age is None or bound is None or age <= bound
    out = {"healthy": gauges.get("up", 0.0) >= 1.0 and stepping}
    for src, dst in (("step", "step"),
                     ("step_age_seconds", "step_age_s"),
                     ("window_steps_per_sec", "steps_per_sec"),
                     ("window_mfu", "mfu"),
                     ("nonfinite_steps_total", "nonfinite_steps"),
                     ("faults_total", "faults")):
        if src in gauges:
            out[dst] = gauges[src]
    return out


def scrape_replica(url: str, timeout_s: float = 2.0) -> Optional[dict]:
    """One serving-replica sample off /metricsz: liveness/queue gauges,
    request/error/over-SLO counters summed over task heads, and a p99
    estimate from the total-phase latency histogram."""
    try:
        status, text = _http_get(url, "/metricsz", timeout_s)
    except OSError:
        return None
    if status != 200:
        return None
    series = parse_prometheus(text)
    sums = {"requests": 0.0, "errors": 0.0, "over_slo": 0.0}
    buckets: Dict[float, float] = {}
    hist_total = 0.0
    gauges: Dict[str, float] = {}
    for name, labels, value in series:
        if name == "bert_serve_requests_total":
            sums["requests"] += value
        elif name == "bert_serve_errors_total":
            sums["errors"] += value
        elif name == "bert_serve_over_slo_total":
            sums["over_slo"] += value
        elif name == "bert_serve_phase_latency_ms_bucket" and \
                labels.get("phase") == "total":
            le = labels.get("le", "")
            if le == "+Inf":
                # The TRUE total: observations past the largest finite
                # bound live only here, and a quantile computed without
                # them under-reports exactly during a tail blowup.
                hist_total += value
            elif le:
                try:
                    bound = float(le)
                except ValueError:
                    continue
                buckets[bound] = buckets.get(bound, 0.0) + value
        elif not labels and name.startswith("bert_serve_"):
            gauges[name[len("bert_serve_"):]] = value
    if not series:
        return None
    out = {
        "healthy": gauges.get("dispatch_alive", 0.0) >= 1.0
        and gauges.get("draining", 0.0) < 1.0,
        "dispatch_alive": gauges.get("dispatch_alive", 0.0) >= 1.0,
        "draining": gauges.get("draining", 0.0) >= 1.0,
        "queue_depth": gauges.get("queue_depth", 0.0),
        "requests": sums["requests"],
        "errors": sums["errors"],
        "over_slo": sums["over_slo"],
    }
    p99 = _histogram_quantile(buckets, 0.99, total=hist_total or None)
    if p99 is not None:
        out["latency_p99_ms"] = p99
    return out


def scrape_router(url: str, timeout_s: float = 2.0) -> Optional[dict]:
    """One router sample off its JSON /statsz (the human surface; the
    router's /metricsz carries the same counters for standard
    scrapers)."""
    try:
        status, text = _http_get(url, "/statsz", timeout_s)
        stats = json.loads(text)
    except (OSError, ValueError):
        return None
    if status != 200 or not isinstance(stats, dict):
        return None
    out = {"healthy": stats.get("healthy_replicas", 0) > 0}
    for key in ("requests", "sheds", "errors", "retries",
                "failovers", "healthy_replicas", "replicas",
                "latency_p99_ms"):
        if stats.get(key) is not None:
            out[key] = stats[key]
    if stats.get("ok") is not None:
        # Renamed: the obs_scrape record's own boolean ``ok`` (did the
        # scrape succeed) must never be clobbered by the router's
        # ok-request counter.
        out["requests_ok"] = stats["ok"]
    return out


_SCRAPERS = {
    "trainer": scrape_trainer,
    "replica": scrape_replica,
    "router": scrape_router,
}


class Target:
    """One scrape target. ``scrape`` is injectable for deterministic
    tests (a callable ``url -> Optional[dict]``); production resolves it
    from ``kind``. Mutable sample state (last good sample + its clock
    time) is only touched by :meth:`FleetCollector.collect_once` under
    the collector's lock."""

    def __init__(self, name: str, kind: str, url: str,
                 scrape: Optional[Callable[[str], Optional[dict]]] = None,
                 timeout_s: float = 2.0):
        if kind not in TARGET_KINDS:
            raise ValueError(
                f"target kind must be one of {TARGET_KINDS}, got {kind!r}")
        self.name = str(name)
        self.kind = kind
        self.url = url.rstrip("/")
        self.timeout_s = float(timeout_s)
        self._scrape = scrape or (
            lambda u: _SCRAPERS[kind](u, timeout_s=self.timeout_s))
        # Sample state (collector-thread-owned, under the collector lock)
        self.last_ok_at: Optional[float] = None
        self.last_sample: Optional[dict] = None
        self.prev_sample: Optional[dict] = None
        self.prev_ok_at: Optional[float] = None
        self.failures = 0
        # Set by FleetCollector.add_target: a dynamically joined target
        # ages from its JOIN time, not from collector start — an
        # autoscaled replica added ten minutes in must not be born ten
        # minutes stale.
        self.added_at: Optional[float] = None


class JsonlTailer:
    """Incremental reader of one JSONL sink: returns only the records
    appended since the last poll. A partial trailing line stays buffered
    until its newline lands (a writer mid-line never yields a torn
    record); a truncated/rotated file restarts from the top."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = str(source)
        self._offset = 0
        self._buf = ""

    def poll(self) -> List[dict]:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []
        if size < self._offset:
            self._offset = 0  # rotated/truncated: start over
            self._buf = ""
        records: List[dict] = []
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                f.seek(self._offset)
                chunk = f.read()
                self._offset = f.tell()
        except OSError:
            return []
        data = self._buf + chunk
        lines = data.split("\n")
        self._buf = lines.pop()  # "" after a complete final line
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # the schema lint owns strictness
            if isinstance(rec, dict):
                records.append(rec)
        return records


class FleetMembership:
    """Reconcile a collector's replica targets from a supervisor's
    fleet-telemetry event stream (serve/supervisor.py).

    The scrape set used to be static at launch, which breaks under an
    elastic fleet (serve/autoscaler.py): a replica spawned mid-run never
    joins the timeline, and a drained one counts as a stale scrape
    failure forever. The supervisor's event stream is the membership
    truth, so we read it instead of inventing a side-channel status
    file: ``spawn`` announces a replica (join — idempotent by name, so
    crash-respawns of a known replica are no-ops), ``drain_complete``
    confirms a decommission and ``gave_up`` retires a crash-looping
    replica (leave). Removal waits for ``drain_complete``, not the
    ``scale_drain`` request — the same confirm-then-remove discipline
    the router uses (docs/serving.md "Elastic fleet")."""

    def __init__(self, collector: "FleetCollector", tailer: JsonlTailer,
                 host: str = "127.0.0.1", prefix: str = "replica",
                 timeout_s: float = 2.0,
                 scrape: Optional[Callable[[str], Optional[dict]]] = None):
        self._collector = collector
        self._tailer = tailer
        self._host = str(host)
        self._prefix = str(prefix)
        self._timeout_s = float(timeout_s)
        self._scrape = scrape

    def sync(self) -> dict:
        """Drain the event stream once and apply joins/leaves. Returns
        ``{"joined": [names], "left": [names]}`` for this pass."""
        joined: List[str] = []
        left: List[str] = []
        for rec in self._tailer.poll():
            if rec.get("kind") != "fleet_event":
                continue
            idx = rec.get("replica")
            if idx is None:
                continue
            name = f"{self._prefix}-{idx}"
            event = rec.get("event")
            port = rec.get("port")
            if event == "spawn" and port:
                target = Target(name, "replica",
                                f"http://{self._host}:{port}",
                                scrape=self._scrape,
                                timeout_s=self._timeout_s)
                if self._collector.add_target(target):
                    joined.append(name)
            elif event in ("drain_complete", "gave_up"):
                if self._collector.remove_target(name):
                    left.append(name)
        return {"joined": joined, "left": left}


class FleetCollector:
    """Merge scrapes + tailed sinks into one ordered timeline JSONL.

    Drive it either with a background thread (:meth:`start` /
    :meth:`stop`) or by calling :meth:`collect_once` per pass
    (deterministic tests, the chaos harness) — one lock serializes the
    two, so a manual pass and the thread never interleave a pass.
    ``emit`` optionally receives every timeline record as it is written
    (the in-memory index the E2E asserts on)."""

    def __init__(
        self,
        targets: Sequence[Target],
        tails: Sequence[JsonlTailer] = (),
        out_path: Optional[str] = None,
        emit: Optional[Callable[[dict], None]] = None,
        interval_s: float = 1.0,
        slo_error_budget: float = 0.01,
        clock: Callable[[], float] = time.monotonic,
        wall: Callable[[], float] = time.time,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self._emit_fn = emit
        self.interval_s = float(interval_s)
        self.slo_error_budget = float(slo_error_budget)
        self._clock = clock
        self._wall = wall
        self._sleep = sleep
        # One lock guards the target table, the tailers, the output
        # file, and the pass counter: collect_once may be driven by a
        # test/harness thread while the background loop runs (registry,
        # analysis/concurrency.py).
        self._lock = threading.Lock()
        self._targets = list(targets)
        self._tails = list(tails)
        self._passes = 0
        # Pending trace joins keyed by router trace id: each entry holds
        # the router_trace record (if seen), the sampled serve_trace
        # records chained to it, and the pass it was first seen on (the
        # orphan-grace clock). Guarded by the collector lock like
        # everything else.
        self._stitch_pending: Dict[str, dict] = {}
        self._stitch_finalized = False
        self._started_at = clock()
        self._out_f = open(out_path, "a", encoding="utf-8") \
            if out_path else None
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- dynamic membership -----------------------------------------------

    def add_target(self, target: Target) -> bool:
        """Join a target to the scrape set mid-run (elastic fleets: a
        replica the autoscaler just spawned). Idempotent by name —
        re-announcing an existing member is a no-op, so replaying a
        supervisor event stream is safe. Returns True if added."""
        with self._lock:
            if any(t.name == target.name for t in self._targets):
                return False
            target.added_at = self._clock()
            self._targets.append(target)
            return True

    def remove_target(self, name: str) -> bool:
        """Retire a target from the scrape set (a drained replica is
        decommissioned capacity, not a stale scrape failure — leaving it
        in would poison max staleness forever). Returns True if a target
        of that name was present."""
        with self._lock:
            kept = [t for t in self._targets if t.name != name]
            removed = len(kept) != len(self._targets)
            self._targets = kept
            return removed

    def target_names(self) -> List[str]:
        with self._lock:
            return [t.name for t in self._targets]

    # -- one pass ---------------------------------------------------------

    def collect_once(self) -> Optional[dict]:
        """Scrape every target concurrently, drain every tailer, write
        the pass's records in deterministic order. Returns the pass's
        ``obs_fleet_window`` record (None only when the collector has no
        targets at all)."""
        with self._lock:
            targets = list(self._targets)
            # Concurrent probes: one bounded thread per target, results
            # by slot — the scrape_once discipline (a black-holed target
            # costs max(per-target), and its staleness is RECORDED, not
            # propagated to the others).
            results: list = [None] * len(targets)
            costs: list = [0.0] * len(targets)

            def probe(i: int, target: Target) -> None:
                t0 = self._clock()
                try:
                    results[i] = target._scrape(target.url)
                except Exception:
                    results[i] = None
                finally:
                    # Per-target cost, stamped inside the probe: the
                    # pass-level join time is the SLOWEST target's cost
                    # and must not be misattributed to the healthy ones.
                    costs[i] = self._clock() - t0

            threads = [threading.Thread(target=probe, args=(i, t),
                                        name="obs-collect-probe",
                                        daemon=True)
                       for i, t in enumerate(targets)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            now = self._clock()
            wall_ts = self._wall()
            self._passes += 1
            harvest: List[Tuple[float, int, int, dict]] = []
            scrapes = []
            for idx, (target, sample) in enumerate(zip(targets, results)):
                target.prev_sample, target.prev_ok_at = (
                    (target.last_sample, target.last_ok_at)
                    if sample is not None else
                    (target.prev_sample, target.prev_ok_at))
                if sample is not None:
                    target.failures = 0
                    staleness = 0.0
                    target.last_sample = sample
                    target.last_ok_at = now
                else:
                    target.failures += 1
                    # Never-scraped targets age from collector start:
                    # a target that was never up is maximally stale,
                    # not zero-stale. Dynamically joined targets age
                    # from their join time instead.
                    anchor = (target.last_ok_at
                              if target.last_ok_at is not None
                              else target.added_at
                              if target.added_at is not None
                              else self._started_at)
                    staleness = now - anchor
                rec = {
                    "kind": "obs_scrape", "tag": "obs",
                    "target": target.name, "target_kind": target.kind,
                    "url": target.url,
                    "ok": sample is not None,
                    "staleness_s": round(max(0.0, staleness), 3),
                    "scrape_ms": round(costs[idx] * 1000.0, 3),
                }
                if sample is not None:
                    # The scrape envelope's own fields win: a sample key
                    # colliding with ok/target/staleness_s/... must not
                    # rewrite the record's identity.
                    rec.update({k: v for k, v in sample.items()
                                if k not in rec})
                scrapes.append((target, sample, rec))
            window = self._fleet_window_locked(targets, scrapes, now)
            for tail_idx, tailer in enumerate(self._tails):
                for line_no, rec in enumerate(tailer.poll()):
                    rec = dict(rec)
                    rec.setdefault("obs_source", tailer.source)
                    ts = rec.get("ts")
                    ts = float(ts) if isinstance(ts, (int, float)) \
                        and not isinstance(ts, bool) else wall_ts
                    self._feed_stitch_locked(rec)
                    harvest.append((ts, 1 + tail_idx, line_no, rec))
            for scrape_idx, (_, _, rec) in enumerate(scrapes):
                harvest.append((wall_ts, 0, scrape_idx, rec))
            if window is not None:
                harvest.append((wall_ts, 0, len(scrapes), window))
            # Deterministic merge: timestamp order, ties broken by
            # (source index, per-source sequence) — replaying the same
            # sources reproduces the same timeline byte for byte.
            harvest.sort(key=lambda item: (item[0], item[1], item[2]))
            for ts, _, _, rec in harvest:
                self._write_locked(rec, ts)
            # Stitch AFTER the pass's harvest lands: a router_trace and
            # its serve_trace tailed in the same pass join immediately;
            # one-sided entries age toward the orphan grace.
            self._flush_stitch_locked(wall_ts, final=False)
        return window

    def _fleet_window_locked(self, targets: List[Target],
                             scrapes, now: float) -> Optional[dict]:
        if not targets:
            return None
        healthy = 0
        replicas = replicas_healthy = 0
        trainers_rate: List[float] = []
        worst_p99: Optional[float] = None
        fleet_rps = 0.0
        rps_seen = False
        over_slo = requests = 0.0
        max_staleness = 0.0
        for target, sample, rec in scrapes:
            max_staleness = max(max_staleness, rec["staleness_s"])
            ok = sample is not None and bool(sample.get("healthy"))
            healthy += 1 if ok else 0
            if target.kind == "replica":
                replicas += 1
                replicas_healthy += 1 if ok else 0
                if sample is not None:
                    p99 = sample.get("latency_p99_ms")
                    if p99 is not None:
                        worst_p99 = p99 if worst_p99 is None \
                            else max(worst_p99, p99)
                    requests += float(sample.get("requests", 0.0))
                    over_slo += float(sample.get("over_slo", 0.0))
                    prev = target.prev_sample
                    if prev is not None and target.prev_ok_at is not None \
                            and now > target.prev_ok_at:
                        delta = (float(sample.get("requests", 0.0))
                                 - float(prev.get("requests", 0.0)))
                        if delta >= 0:
                            fleet_rps += delta / (now - target.prev_ok_at)
                            rps_seen = True
            elif target.kind == "trainer" and sample is not None:
                rate = sample.get("steps_per_sec")
                if rate is not None:
                    trainers_rate.append(float(rate))
        record = {
            "kind": "obs_fleet_window", "tag": "obs",
            "targets_total": len(targets),
            "targets_healthy": healthy,
            "max_staleness_s": round(max_staleness, 3),
        }
        if replicas:
            record["replicas_total"] = replicas
            record["replicas_healthy"] = replicas_healthy
        if worst_p99 is not None:
            record["worst_replica_p99_ms"] = round(worst_p99, 3)
        if rps_seen:
            record["fleet_rps"] = round(fleet_rps, 3)
        if trainers_rate:
            record["trainer_steps_per_sec"] = round(
                sum(trainers_rate) / len(trainers_rate), 4)
        if requests > 0:
            budget = self.slo_error_budget * requests
            if budget > 0:
                record["error_budget_burn"] = round(over_slo / budget, 4)
        return record

    # -- coordinated capture ----------------------------------------------

    def trigger_profile(self, duration_s: float = 2.0,
                        params: Optional[dict] = None,
                        post: Optional[Callable] = None) -> List[dict]:
        """One ALIGNED fleet-wide capture: POST ``/profilez`` to every
        trainer/replica target concurrently (one bounded thread per
        target, all fired together — alignment is the point: the
        windows cover the same wall-clock slice, so the timeline shows
        the fleet under the same load). Routers have no capture plane
        and are skipped. Returns (and writes to the timeline) one
        trigger ``obs_scrape`` record per target, ``probe:
        "profilez"``; the captures themselves land as
        ``profile_window`` records in each process's sink and reach
        the timeline through the normal tailers. ``post`` is
        injectable for deterministic tests: ``(url, path, body,
        timeout_s) -> (status, text)``."""
        body = dict(params or {})
        body["duration_s"] = float(duration_s)
        body.setdefault("trigger", "fleet")
        do_post = post or _http_post_json
        with self._lock:
            targets = [t for t in self._targets
                       if t.kind in ("trainer", "replica")]
            results: list = [None] * len(targets)
            costs: list = [0.0] * len(targets)

            def probe(i: int, target: Target) -> None:
                t0 = self._clock()
                try:
                    status, text = do_post(target.url, "/profilez", body,
                                           target.timeout_s)
                    try:
                        payload = json.loads(text)
                    except ValueError:
                        payload = {}
                    results[i] = (status,
                                  payload if isinstance(payload, dict)
                                  else {})
                except Exception:
                    results[i] = None
                finally:
                    costs[i] = self._clock() - t0

            threads = [threading.Thread(target=probe, args=(i, t),
                                        name="obs-profile-trigger",
                                        daemon=True)
                       for i, t in enumerate(targets)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall_ts = self._wall()
            out: List[dict] = []
            for idx, (target, res) in enumerate(zip(targets, results)):
                rec = {
                    "kind": "obs_scrape", "tag": "obs",
                    "target": target.name, "target_kind": target.kind,
                    "url": target.url, "probe": "profilez",
                    "ok": res is not None and res[0] == 200,
                    "staleness_s": 0.0,
                    "scrape_ms": round(costs[idx] * 1000.0, 3),
                }
                if res is not None:
                    status, payload = res
                    rec["status"] = status
                    if payload.get("error"):
                        rec["error"] = str(payload["error"])
                    elif payload.get("armed"):
                        rec["armed_duration_s"] = payload.get("duration_s")
                else:
                    rec["error"] = "unreachable"
                out.append(rec)
                self._write_locked(rec, wall_ts)
        return out

    # -- trace stitching --------------------------------------------------

    def _feed_stitch_locked(self, rec: dict) -> None:
        """Index one tailed record into the pending-stitch table.
        Only head-sampled serve_traces enter: a slow-forced record
        (``sampled: false``) has no router_trace counterpart by
        construction (the router's sampling decision wins fleet-wide),
        so stitching it would manufacture orphans."""
        kind = rec.get("kind")
        if kind == "router_trace":
            tid = rec.get("trace_id")
            if isinstance(tid, str) and tid:
                entry = self._stitch_pending.setdefault(
                    tid, {"router": None, "replicas": [],
                          "pass": self._passes})
                entry["router"] = rec
        elif kind == "serve_trace":
            parent = rec.get("parent_trace_id")
            if isinstance(parent, str) and parent \
                    and rec.get("sampled") is True:
                entry = self._stitch_pending.setdefault(
                    parent, {"router": None, "replicas": [],
                             "pass": self._passes})
                entry["replicas"].append(rec)

    def _flush_stitch_locked(self, wall_ts: float, final: bool) -> None:
        """Emit every pending entry that is complete, expired past the
        orphan grace, or (``final``) being force-drained at close."""
        for tid in list(self._stitch_pending):
            entry = self._stitch_pending[tid]
            aged = (self._passes - entry["pass"]) >= STITCH_GRACE_PASSES
            rec = self._stitch_record(tid, entry, force=final or aged)
            if rec is not None:
                del self._stitch_pending[tid]
                self._write_locked(rec, wall_ts)

    def _stitch_record(self, tid: str, entry: dict,
                       force: bool) -> Optional[dict]:
        """One ``trace_stitch`` for a pending entry, or None to keep
        waiting. Complete = router 2xx joined to the winning attempt's
        serve_trace; router non-2xx is a non-orphan singleton (the
        router tracer only hands a request to a replica span on
        successful dispatch); anything one-sided past the grace is an
        orphan — counted, never dropped."""
        router = entry["router"]
        reps = entry["replicas"]
        if router is None:
            if not force:
                return None
            rec = {"kind": "trace_stitch", "tag": "obs", "trace_id": tid,
                   "orphan": True, "orphan_side": "router",
                   "router_spans": 0, "replica_spans": len(reps)}
            if reps:
                rec["replica_ms"] = round(
                    float(reps[0].get("total_ms", 0.0)), 3)
            return rec
        spans = router.get("spans") or []
        status = int(router.get("status", 0))
        winning = router.get("winning_attempt")
        base = {
            "kind": "trace_stitch", "tag": "obs", "trace_id": tid,
            "orphan": False,
            "router_spans": len(spans), "replica_spans": len(reps),
            "status": status,
            "task": router.get("task"),
            "attempts": int(router.get("attempts", 0)),
            "hedges": int(router.get("hedges", 0)),
            "hedge_wasted_ms": round(
                float(router.get("hedge_wasted_ms", 0.0)), 3),
            "client_total_ms": round(float(router.get("total_ms", 0.0)), 3),
        }
        if not (200 <= status < 300):
            # No replica span expected; emit immediately so error bursts
            # never pool in the pending table.
            return base
        win = None
        if winning is not None:
            win = next((r for r in reps if r.get("attempt") == winning),
                       None)
        elif len(reps) == 1:
            win = reps[0]
        if win is None:
            if not force:
                return None
            base["orphan"] = True
            base["orphan_side"] = "replica"
            return base
        wspan = next(
            (s for s in spans if s.get("name") == "attempt"
             and s.get("attempt") == win.get("attempt")), None)
        total = base["client_total_ms"]
        attempt_ms = float(wspan.get("dur_ms", 0.0)) if wspan else 0.0
        replica_ms = round(float(win.get("total_ms", 0.0)), 3)
        # Decomposition with the gap as the RESIDUAL: overhead is the
        # client total minus the winning attempt's wall time (queueing,
        # admission, backoff, hedge management), the gap is whatever the
        # attempt spent outside the replica (network + HTTP framing +
        # cross-process clock noise) — so the three parts sum to the
        # client total EXACTLY at record precision, and the schema's
        # decomposition identity holds by construction.
        overhead = round(max(0.0, total - attempt_ms), 3)
        gap = round(total - overhead - replica_ms, 3)
        base.update({
            "router_overhead_ms": overhead,
            "network_gap_ms": gap,
            "replica_ms": replica_ms,
            # Slightly negative gaps are unsynchronized-clock noise, not
            # broken stitching; anything past the epsilon is flagged.
            "consistent": bool(gap >= -_STITCH_EPS_MS),
            "winning_attempt": int(win.get("attempt", 1)),
            "winning_trace_id": win.get("trace_id"),
        })
        if win.get("obs_source"):
            base["winning_source"] = win["obs_source"]
        rep_spans = win.get("spans") or []
        if rep_spans:
            dominant = max(rep_spans,
                           key=lambda s: float(s.get("dur_ms", 0.0)))
            base["replica_critical_phase"] = dominant.get("name")
        return base

    def _write_locked(self, rec: dict, ts: float) -> None:
        out = dict(rec)
        out.setdefault("schema", SCHEMA_VERSION)
        out.setdefault("ts", round(ts, 3))
        if self._out_f is not None:
            self._out_f.write(json.dumps(out) + "\n")
            self._out_f.flush()
        if self._emit_fn is not None:
            try:
                self._emit_fn(out)
            except Exception:
                pass  # observability must never take the collector down

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        self._stop_event.clear()
        self._thread = threading.Thread(
            target=self._loop, name="obs-collector", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop_event.is_set():
            self.collect_once()
            self._sleep(self.interval_s)

    def stop(self) -> None:
        """Stop the background loop, run one final pass (drain anything
        the sinks appended since the last tick), close the output.
        Manual drivers (the CLI's own pass loop) that already ran their
        last pass use :meth:`close` instead — stop()'s drain pass would
        be an extra, uncounted round."""
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        self.collect_once()
        self.close()

    def close(self) -> None:
        """Close the timeline output without another pass. Pending trace
        joins are force-drained first — an entry still waiting for its
        counterpart becomes an orphan stitch rather than vanishing with
        the process."""
        with self._lock:
            if not self._stitch_finalized:
                self._stitch_finalized = True
                self._flush_stitch_locked(self._wall(), final=True)
            if self._out_f is not None:
                self._out_f.close()
                self._out_f = None

    def passes(self) -> int:
        with self._lock:
            return self._passes


def stitch_tree(records: Sequence[dict], trace_id: str) -> str:
    """Render one client request's stitched trace as an indented tree
    (``tools/obs_collect.py --trace <id>``): the router's span taxonomy
    in dispatch order, each attempt's replica ``serve_trace`` phases
    nested under the attempt that reached it, and the stitch verdict
    last. Works on any record iterable — a timeline read back from
    disk, or the chaos harness's in-memory index."""
    router = None
    stitch = None
    reps_by_id: Dict[str, dict] = {}
    for rec in records:
        kind = rec.get("kind")
        if kind == "router_trace" and rec.get("trace_id") == trace_id:
            router = rec
        elif kind == "serve_trace" \
                and rec.get("parent_trace_id") == trace_id:
            # Dedup by the replica's own trace id (the same record can
            # reach a merged iterable twice, e.g. sink + timeline); the
            # copy carrying obs_source attribution wins.
            key = str(rec.get("trace_id"))
            if key not in reps_by_id or rec.get("obs_source"):
                reps_by_id[key] = rec
        elif kind == "trace_stitch" and rec.get("trace_id") == trace_id:
            stitch = rec
    reps = list(reps_by_id.values())
    if router is None and stitch is None and not reps:
        return f"trace {trace_id}: not found in timeline"

    lines: List[str] = []
    by_attempt: Dict[int, List[dict]] = {}
    for rep in reps:
        by_attempt.setdefault(int(rep.get("attempt", 1)), []).append(rep)

    def replica_lines(rep: dict, indent: str, span_indent: str) -> None:
        src = f" ({rep['obs_source']})" if rep.get("obs_source") else ""
        lines.append(
            f"{indent}serve_trace {rep.get('trace_id', '?')}{src}"
            f"  total={rep.get('total_ms', '?')}ms"
            f"  reason={rep.get('sample_reason', '?')}")
        for span in rep.get("spans") or []:
            lines.append(
                f"{span_indent}{span.get('name', '?'):<12}"
                f"@{span.get('start_ms', 0)}ms"
                f"  +{span.get('dur_ms', 0)}ms")

    if router is not None:
        winning = router.get("winning_attempt")
        lines.append(
            f"trace {trace_id}  task={router.get('task', '?')}"
            f"  status={router.get('status', '?')}"
            f"  client_total={router.get('total_ms', '?')}ms"
            f"  attempts={router.get('attempts', '?')}"
            f"  hedges={router.get('hedges', 0)}")
        for span in router.get("spans") or []:
            name = span.get("name", "?")
            head = (f"├─ router {name:<9}"
                    f"@{span.get('start_ms', 0)}ms"
                    f"  +{span.get('dur_ms', 0)}ms")
            if name == "attempt":
                att = span.get("attempt")
                marks = []
                if span.get("hedge"):
                    marks.append("hedge")
                if winning is not None and att == winning:
                    marks.append("win")
                mark = f"  [{','.join(marks)}]" if marks else ""
                head += (f"  #{att} -> {span.get('replica', '?')}"
                         f"  outcome={span.get('outcome', '?')}{mark}")
            lines.append(head)
            if name == "attempt":
                for rep in by_attempt.get(span.get("attempt"), ()):  # type: ignore[arg-type]
                    replica_lines(rep, "│    └─ ", "│       ")
        matched = {s.get("attempt")
                   for s in router.get("spans") or []
                   if s.get("name") == "attempt"}
        strays = [rep for rep in reps
                  if int(rep.get("attempt", 1)) not in matched]
    else:
        lines.append(f"trace {trace_id}  (no router_trace span — orphan)")
        strays = reps
    for rep in strays:
        lines.append(f"├─ unmatched replica attempt "
                     f"{rep.get('attempt', '?')}")
        replica_lines(rep, "│    └─ ", "│       ")
    if stitch is not None:
        if stitch.get("orphan"):
            lines.append(
                f"└─ stitch: ORPHAN ({stitch.get('orphan_side', '?')} "
                f"side missing)  router_spans="
                f"{stitch.get('router_spans')}"
                f"  replica_spans={stitch.get('replica_spans')}")
        else:
            lines.append(
                f"└─ stitch: overhead={stitch.get('router_overhead_ms')}ms"
                f"  gap={stitch.get('network_gap_ms')}ms"
                f"  replica={stitch.get('replica_ms')}ms"
                f"  == client {stitch.get('client_total_ms')}ms"
                f"  consistent={stitch.get('consistent')}"
                f"  critical={stitch.get('replica_critical_phase', '-')}")
    else:
        lines.append("└─ stitch: (pending — no trace_stitch record yet)")
    return "\n".join(lines)
