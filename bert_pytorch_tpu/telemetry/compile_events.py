"""Compile/cache observability: every XLA compile becomes a telemetry record.

Cold-vs-warm ambiguity burned rounds 1-3 (a 10-30 min BERT-large compile
through the TPU tunnel is indistinguishable from a hang in a flat seq/s
log). This module makes compilation explicit: a :class:`CompileMonitor`
wraps each jitted entry point, and JAX's ``jax.monitoring`` events — which
``utils/compile_cache.py`` taps via :func:`install_compile_listeners` —
attribute every backend compile and persistent-cache hit/miss to the
wrapped function and the argument-shapes digest that triggered it.

Emitted record (``kind="compile"``, schema.py)::

    {"kind": "compile", "fn": "train_step", "shapes_digest": "ab12…",
     "compile_s": 12.31, "backend_compile_s": 11.90, "cache": "miss"}

``cache`` is one of:

* ``"hit"``  — served from the persistent compile cache (warm start);
* ``"miss"`` — a real XLA compile ran and the executable was persisted to
  the cache;
* ``"uncached"`` — a real compile that was NOT persisted: the persistent
  cache is disabled, or the compile was cheaper than the
  min-compile-time/min-entry-size persistence bars (jax fires the miss
  counter only when it writes the entry, so a below-the-bar compile is
  indistinguishable from a disabled cache — both mean "next run recompiles
  this");
* ``"jit"``  — no compile activity at all for a first-seen shapes digest
  (served by JAX's in-process executable cache, e.g. a re-jit of an
  identical program).

Attribution uses a per-thread current-call context: jit tracing and
compilation run synchronously on the calling thread, so events fired while
the wrapper is on-stack belong to it. Listener registration is global and
permanent (jax.monitoring has no unregister), so listeners are installed
once and route through a module-level active-monitor registry.

With ``cost_analysis`` != "off" every first-seen (fn, shapes_digest) pair
additionally emits one ``kind="compile_cost"`` record — the executable's
static FLOPs / bytes-accessed / argument-output-temp bytes
(telemetry/memory.py :func:`analyze_executable`) — so each compile event
in the stream carries the cost of what it compiled.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Callable, Optional

from bert_pytorch_tpu.utils import compile_cache as compile_cache_util

_BACKEND_COMPILE_EVENTS = (
    "/jax/core/compile/backend_compile_duration",
    # older/newer spellings kept for forward compatibility
    "/jax/backend_compile_duration",
)
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"

_tls = threading.local()


def _current_call():
    return getattr(_tls, "call", None)


def _on_duration(event: str, duration_secs: float, **_kw) -> None:
    call = _current_call()
    if call is None:
        return
    if event in _BACKEND_COMPILE_EVENTS:
        call["backend_compile_s"] += float(duration_secs)
        call["compiled"] = True


def _on_event(event: str, **_kw) -> None:
    call = _current_call()
    if call is None:
        return
    if event == _CACHE_HIT_EVENT:
        call["cache_hits"] += 1
    elif event == _CACHE_MISS_EVENT:
        call["cache_misses"] += 1


_install_lock = threading.Lock()
_installed = False


def _ensure_listeners() -> None:
    global _installed
    with _install_lock:
        if _installed:
            return
        compile_cache_util.install_compile_listeners(_on_event, _on_duration)
        _installed = True


def shapes_digest(tree) -> str:
    """Stable digest of the arg tree's structure + shapes/dtypes — the
    compile-relevant signature of a call (values don't recompile; shapes,
    dtypes, and tree structure do)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    parts = [str(treedef)]
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None:
            parts.append(f"py:{type(leaf).__name__}:{leaf!r}")
        else:
            parts.append(f"{dtype}{tuple(shape)}")
    return hashlib.sha1("|".join(parts).encode()).hexdigest()[:12]


class CompileMonitor:
    """Wrap jitted callables; emit one record per observed compile/lookup.

    ``emit`` receives the record dict; a ``clock`` is injectable for tests.
    """

    def __init__(self, emit: Callable[[dict], None],
                 clock: Callable[[], float] = time.perf_counter,
                 cost_analysis: str = "off"):
        _ensure_listeners()
        self._emit = emit
        self._clock = clock
        self.events: list = []  # everything emitted, for programmatic access
        # Static cost/memory attribution (telemetry/memory.py): one
        # kind="compile_cost" record per (fn, shapes_digest), emitted
        # right after that signature's first compile event so every
        # compile in the stream carries its cost. Mode semantics —
        # auto/off/full — are analyze_executable's; validate HERE so a
        # bad mode fails at construction, not mid-run after the first
        # (expensive) compile already happened.
        from bert_pytorch_tpu.telemetry.memory import COST_MODES

        if cost_analysis not in COST_MODES:
            raise ValueError(
                f"cost_analysis must be one of {COST_MODES}, got "
                f"{cost_analysis!r}")
        self.cost_analysis = cost_analysis
        self._cost_done: set = set()

    def note(self, record: dict) -> None:
        """Append + emit one caller-built record through this monitor's
        sink — the side channel for kernel-layer events that belong in
        the same stream as the compile records they explain (the serve
        engine's ``kind="autotune"`` geometry records ride here, next to
        the compile events whose fn names carry the winner digest)."""
        self.events.append(record)
        self._emit(record)

    def instrument(self, fn, name: str):
        """Return ``fn`` wrapped so first-seen shape signatures (and any
        call during which compile activity fires) emit a compile record.

        The digest walks the FULL arg tree (params + optimizer state for a
        train step — hundreds of leaves), so it is computed only when a
        record might be emitted: on the wrapper's first call, or when
        compile/cache activity actually fired during the call (a new shape
        signature always triggers a real trace+compile, so it can't slip
        by). Steady-state calls — the ones inside bench.py's measured
        window and the StepTimer's host-dispatch segment — add only a
        thread-local set/restore and two clock reads.
        """
        seen: set = set()

        def wrapper(*args, **kwargs):
            prev = _current_call()
            call = {"backend_compile_s": 0.0, "compiled": False,
                    "cache_hits": 0, "cache_misses": 0}
            _tls.call = call
            t0 = self._clock()
            try:
                out = fn(*args, **kwargs)
            finally:
                _tls.call = prev
            elapsed = self._clock() - t0
            activity = (call["compiled"] or call["cache_hits"]
                        or call["cache_misses"])
            if activity or not seen:
                # Donated args are deleted by now, but aval metadata
                # (shape/dtype) stays readable — only data access raises.
                digest = shapes_digest((args, kwargs))
                first = digest not in seen
                seen.add(digest)
                if first or activity:
                    self._record(name, digest, elapsed, call)
                    self._attribute_cost(fn, name, digest, args, kwargs)
            return out

        wrapper.__name__ = f"{name}_monitored"
        return wrapper

    def _record(self, name, digest, elapsed, call) -> None:
        # The persistent-cache counter events are authoritative: every
        # lookup fires exactly one hit or miss for the MAIN program, while
        # backend_compile_duration also fires for tiny auxiliary modules
        # (constant conversions) even on a cache-hit call — so `compiled`
        # alone cannot distinguish warm from cold.
        if call["cache_misses"]:
            cache = "miss"
        elif call["cache_hits"]:
            cache = "hit"
        elif call["compiled"]:
            cache = "uncached"
        else:
            cache = "jit"
        record = {
            "kind": "compile",
            "tag": "telemetry",
            "fn": name,
            "shapes_digest": digest,
            # dispatch wall time of the call that compiled: trace + lower +
            # backend compile (+ the async enqueue, which is noise at
            # compile timescales)
            "compile_s": round(elapsed, 4),
            "backend_compile_s": round(call["backend_compile_s"], 4),
            "cache": cache,
        }
        self.events.append(record)
        self._emit(record)

    def _attribute_cost(self, fn, name, digest, args, kwargs) -> None:
        if self.cost_analysis == "off":
            return
        key = (name, digest)
        if key in self._cost_done:
            return
        self._cost_done.add(key)
        from bert_pytorch_tpu.telemetry import memory as memory_util

        fields = memory_util.analyze_executable(
            fn, args, kwargs, mode=self.cost_analysis)
        if fields is None:
            return
        record = {"kind": "compile_cost", "tag": "telemetry", "fn": name,
                  "shapes_digest": digest, **fields}
        self.events.append(record)
        self._emit(record)
