"""Crash flight recorder: a bounded ring of each process's last
telemetry records and log lines, flushed atomically to
``postmortem.json`` (docs/observability.md "Flight recorder").

When a training runner or serving replica dies, the JSONL artifact says
what the run looked like; it does not say what the process saw in its
final seconds — the records and log lines closest to the fault are
exactly the ones an operator (or the supervisor's harvest,
serve/supervisor.py) wants. The :class:`FlightRecorder` keeps a
byte-bounded ring of the newest entries and persists it:

* **incident flush** — a teed record with ``kind`` in ``fault`` /
  ``divergence`` / ``sentinel`` flushes immediately (the preemption
  fault record every runner and run_server emits rides this path, so a
  drained process leaves forensics too);
* **periodic flush** — at most every ``flush_interval_s`` seconds on
  the note path, so a SIGKILLed process — which gets no atexit, no
  excepthook, nothing — still leaves an at-most-seconds-stale
  postmortem for the supervisor to harvest;
* **crash flush** — an installed ``sys.excepthook`` chains to the
  previous hook after flushing with the exception rendered into the
  payload, and an ``atexit`` handler catches exits that never reached
  :meth:`close`;
* **clean exit** — :meth:`close` (``TrainTelemetry.finish`` /
  run_server teardown) disarms the exit hooks and REMOVES the
  postmortem unless an incident flush happened during the run: a clean
  run leaves no stale forensics for the next harvest to misread.

Writes are tmp + rename (the heartbeat's torn-write discipline): a
reader — the supervisor reaping a SIGKILLed replica — never sees a
partial file. The ring never exceeds ``max_bytes`` of serialized
payload; an oversized single entry is replaced by a stub naming its
size. All shared state sits behind one lock (concurrency registry,
analysis/concurrency.py): background emitters (watchdog, async-writer
threads) note records concurrently with the train loop.

Stdlib-only and import-free of the package chain, like the schema
module: the postmortem file itself is plain JSON any jax-free parent
can read.
"""

from __future__ import annotations

import atexit
import collections
import json
import math
import os
import sys
import threading
import time
import traceback
from typing import Callable, Optional

# Record kinds that flush the ring immediately (the incident signal).
INCIDENT_KINDS = ("fault", "divergence", "sentinel")

# A single over-budget entry is stubbed, never allowed to evict the
# whole ring.
_STUB_KEYS = ("kind", "tag", "event")


def _sanitize(obj):
    """JSON-safe copy: non-finite floats become null (the JSONL sink's
    convention — a postmortem full of bare NaN would be unreadable by
    the strict parsers the timeline feeds)."""
    if isinstance(obj, float) and not math.isfinite(obj):
        return None
    if isinstance(obj, dict):
        return {str(k): _sanitize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_sanitize(v) for v in obj]
    if isinstance(obj, (str, int, bool)) or obj is None:
        return obj
    return str(obj)


class FlightRecorder:
    def __init__(self, path: str, process: str = "train",
                 max_bytes: int = 192 * 1024,
                 flush_interval_s: float = 2.0,
                 max_line_chars: int = 400,
                 clock: Callable[[], float] = time.time):
        self.path = path
        self.process = str(process)
        self.max_bytes = max(1024, int(max_bytes))
        self.flush_interval_s = float(flush_interval_s)
        self.max_line_chars = int(max_line_chars)
        self._clock = clock
        self._lock = threading.Lock()
        # Disk writes serialize on their own lock with a sequence
        # number: payloads are built under _lock but written after
        # releasing it, and a descheduled periodic flush must never
        # land AFTER (and clobber) a newer crash/incident payload.
        self._write_lock = threading.Lock()
        self._flush_seq = 0     # under _lock: payload build order
        self._written_seq = 0   # under _write_lock: newest on disk
        # Ring entries: ("record", json_str, nbytes) | ("log", str, nbytes)
        self._ring: "collections.deque" = collections.deque()
        self._bytes = 0
        self._dropped = 0           # entries evicted by the byte bound
        self._noted = 0             # entries ever noted
        self._incident = False      # an incident flush happened this run
        self._closed = False
        self._last_flush = 0.0
        self._last_reason: Optional[str] = None
        self._unflushed = 0         # entries noted since the last flush
        self._exit_hooks_installed = False
        self._prev_excepthook = None

    # -- producer side ----------------------------------------------------

    def note_record(self, rec: dict) -> None:
        """Append one telemetry record; incident kinds flush the ring
        immediately, anything else at most every ``flush_interval_s``."""
        if not isinstance(rec, dict):
            return
        entry = dict(rec)
        entry.setdefault("ts", round(self._clock(), 3))
        try:
            line = json.dumps(_sanitize(entry))
        except (TypeError, ValueError):
            line = json.dumps({"unserializable": str(type(rec))})
        kind = rec.get("kind")
        incident = kind in INCIDENT_KINDS
        with self._lock:
            if self._closed:
                return
            self._append_locked("record", line)
            reason = None
            now = self._clock()
            if incident:
                fault = rec.get("fault") or rec.get("reason")
                reason = f"{kind}:{fault}" if fault else str(kind)
            elif now - self._last_flush >= self.flush_interval_s:
                reason = "periodic"
            if reason is None:
                return
            payload = self._payload_locked(reason)
            self._incident = self._incident or incident
            self._last_flush = now
            self._last_reason = reason
            self._unflushed = 0
            self._flush_seq += 1
            seq = self._flush_seq
        self._write(payload, seq)

    def note_line(self, line: str) -> None:
        """Append one log line (truncated to ``max_line_chars``)."""
        text = str(line)[: self.max_line_chars]
        with self._lock:
            if self._closed:
                return
            self._append_locked("log", text)

    def log_handler(self):
        """A utils/logging-compatible handler (duck-typed: write_message
        / write_record / close) teeing the process log into the ring —
        hand it to ``logger.init`` alongside the real sinks."""
        return _RecorderLogHandler(self)

    def tee(self, emit: Optional[Callable[[dict], None]]
            ) -> Callable[[dict], None]:
        """Wrap an emit callable so every record also lands in the ring
        (run_server threads its serve telemetry through this)."""

        def teed(rec: dict) -> None:
            self.note_record(rec)
            if emit is not None:
                emit(rec)

        return teed

    def _append_locked(self, typ: str, payload: str) -> None:
        nbytes = len(payload.encode("utf-8", "replace"))
        if nbytes > self.max_bytes:
            # Stub, never evict-everything: keep the entry's identity.
            try:
                rec = json.loads(payload) if typ == "record" else {}
            except ValueError:
                rec = {}
            stub = {"truncated": True, "bytes": nbytes}
            stub.update({k: rec[k] for k in _STUB_KEYS if k in rec})
            payload = json.dumps(stub)
            nbytes = len(payload.encode("utf-8"))
        self._ring.append((typ, payload, nbytes))
        self._bytes += nbytes
        self._noted += 1
        self._unflushed += 1
        while self._bytes > self.max_bytes and len(self._ring) > 1:
            _, _, evicted = self._ring.popleft()
            self._bytes -= evicted
            self._dropped += 1

    # -- flush side -------------------------------------------------------

    def ring_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def flush(self, reason: str, exc: Optional[BaseException] = None
              ) -> Optional[str]:
        """Persist the ring now (tmp + rename); returns the path written
        or None when the recorder is closed. ``exc`` renders a bounded
        traceback into the payload (the crash-flush context atexit alone
        cannot provide)."""
        with self._lock:
            if self._closed:
                return None
            payload = self._payload_locked(reason, exc=exc)
            self._incident = self._incident or reason not in (
                "periodic", "clean")
            self._last_flush = self._clock()
            self._last_reason = reason
            self._unflushed = 0
            self._flush_seq += 1
            seq = self._flush_seq
        self._write(payload, seq)
        return self.path

    def _payload_locked(self, reason: str,
                        exc: Optional[BaseException] = None) -> dict:
        records = []
        lines = []
        for typ, payload, _ in self._ring:
            if typ == "record":
                try:
                    records.append(json.loads(payload))
                except ValueError:
                    records.append({"unparseable": payload[:120]})
            else:
                lines.append(payload)
        out = {
            "process": self.process,
            "pid": os.getpid(),
            "reason": reason,
            "flushed_at": round(self._clock(), 3),
            "ring_bytes": self._bytes,
            "ring_entries": len(self._ring),
            "dropped": self._dropped,
            "noted": self._noted,
            "records": records,
            "lines": lines,
        }
        if exc is not None:
            out["exception"] = "".join(traceback.format_exception(
                type(exc), exc, exc.__traceback__))[-4000:]
        return out

    def _write(self, payload: dict, seq: int) -> None:
        """tmp + rename (a harvesting reader never sees a torn file),
        ordered by flush sequence (an older payload never replaces a
        newer one already on disk)."""
        with self._write_lock:
            if seq < self._written_seq:
                return
            self._written_seq = seq
            tmp = f"{self.path}.tmp"
            try:
                os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                            exist_ok=True)
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(payload, f)
                os.replace(tmp, self.path)
            except OSError:
                pass  # forensics must never take the process down

    # -- lifecycle --------------------------------------------------------

    def install_exit_hooks(self) -> "FlightRecorder":
        """Arm the crash paths: an excepthook that flushes with the
        traceback (chaining to the previous hook), and an atexit flush
        for exits that never reached :meth:`close`. Call once, from the
        process entry point (telemetry/cli.from_args, run_server)."""
        with self._lock:
            if self._exit_hooks_installed:
                return self
            self._exit_hooks_installed = True
            self._prev_excepthook = sys.excepthook
        atexit.register(self._atexit_flush)
        sys.excepthook = self._excepthook
        return self

    def _excepthook(self, exc_type, exc, tb) -> None:
        try:
            self.flush("crash", exc=exc)
        except Exception:
            pass
        prev = self._prev_excepthook or sys.__excepthook__
        prev(exc_type, exc, tb)

    def _atexit_flush(self) -> None:
        with self._lock:
            closed = self._closed
            # An excepthook flush already captured this exit WITH its
            # traceback; re-flushing here would overwrite that payload
            # with a contextless one. Only flush when something was
            # noted since the last flush (an empty ring has no
            # forensic value either).
            stale = self._unflushed > 0
        if not closed and stale:
            # The process is exiting without ever reaching close():
            # a crash path (os._exit sidesteps this; SIGKILL relies on
            # the periodic flush instead).
            self.flush("atexit")

    def close(self, clean: bool = True) -> None:
        """End of run. ``clean=True`` removes the postmortem unless an
        incident flush happened (a clean run leaves no stale forensics
        for the next crash harvest to misread); ``clean=False`` flushes
        one final snapshot instead."""
        if not clean:
            self.flush("close")
        with self._lock:
            if self._closed:
                return
            self._closed = True
            incident = self._incident
        if self._prev_excepthook is not None and \
                sys.excepthook == self._excepthook:
            sys.excepthook = self._prev_excepthook
        if clean and not incident:
            try:
                os.remove(self.path)
            except OSError:
                pass


class _RecorderLogHandler:
    """Duck-typed utils/logging handler: log lines and structured log
    records tee into the ring (the 'last log lines' half of the
    postmortem). Never a real sink — write failures are impossible and
    close() is a no-op (the recorder owns its own lifecycle)."""

    verbose = True
    is_primary = True

    def __init__(self, recorder: FlightRecorder):
        self._recorder = recorder

    def write_message(self, message: str) -> None:
        self._recorder.note_line(message)

    def write_record(self, record: dict) -> None:
        self._recorder.note_record(dict(record))

    def close(self) -> None:
        pass


def read_postmortem(path: str) -> Optional[dict]:
    """Parse a postmortem file; None when absent/torn (the tmp+rename
    write makes torn unlikely, but a reader must not crash on it)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            out = json.load(f)
        return out if isinstance(out, dict) else None
    except (OSError, ValueError):
        return None
