"""Live training introspection: the debug endpoint every runner can open
(docs/observability.md "Training introspection plane").

Training telemetry has been file-only since PR 1 — JSONL windows plus a
heartbeat file an offline harness polls. The serving tier meanwhile grew
a live scrape surface (``/healthz``/``/statsz``/``/metricsz``, PR 9)
that the fleet router balances on. This module gives TRAINING processes
the same three routes, from the same stdlib ``ThreadingHTTPServer``
recipe, so one collector (telemetry/collector.py) can scrape trainers
and replicas with one format:

* ``GET /healthz``  — heartbeat-backed step liveness: 200 while a step
  completed within ``stale_after_s`` (or the run is still warming
  toward its first step), 503 once the step counter goes stale — the
  live twin of the heartbeat file the capture harness tails;
* ``GET /statsz``   — JSON snapshot: the last emitted ``step_window``
  record verbatim (loader/prefetch gauges ride inside it), the last
  grad-health envelope, compile counters split by cache outcome, and
  the sentinel/divergence/fault tallies;
* ``GET /metricsz`` — the same numbers in Prometheus text exposition
  (version 0.0.4), ``bert_train_*``-prefixed. Every numeric field of
  the last step_window record is exported as
  ``bert_train_window_<field>`` VERBATIM (rendered with ``repr`` so the
  float round-trips), which is what makes "the scrape agrees with the
  JSONL artifact per metric name" a testable property, not a hope;
* ``POST /profilez`` — arm a bounded on-demand capture at the next step
  boundary (telemetry/sampler.py; docs/observability.md "Profiling
  plane"): 200 with the armed parameters, 409 while a capture is
  already armed or active (jax traces cannot nest), 404 when the
  runner attached no capture controller.

The :class:`IntrospectionHub` is the shared state: ``TrainTelemetry``
tees every emitted record into :meth:`observe_record` and notes step
completions via :meth:`note_step`; HTTP worker threads read snapshots.
One lock guards the single state dict (declared in the concurrency
registry, analysis/concurrency.py) — the hub never calls back into
telemetry or jax, so a slow scrape can never stall the train loop for
more than the lock's copy window.

Deliberately stdlib-only: the debug server must cost nothing when
``--debug_port`` is 0 (the default) and must never pull the accelerator
runtime into an HTTP thread.
"""

from __future__ import annotations

import http.server
import json
import math
import threading
import time
from typing import Callable, Dict, Optional, Tuple

# Record kinds folded into the hub's live counters; anything else only
# bumps the record tally.
_COUNTER_KINDS = ("sentinel", "divergence", "fault")


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


class IntrospectionHub:
    """Lock-guarded live snapshot of one training process's telemetry.

    ``process`` labels the exports (``bert_train_up{process="glue"}``)
    so a fleet timeline can attribute trainer samples; ``stale_after_s``
    is the /healthz liveness bound — size it well above the worst
    healthy step time (the hung-step watchdog's advice applies: a false
    503 only flips a probe, never kills anything).
    """

    def __init__(self, process: str = "train",
                 stale_after_s: float = 60.0,
                 clock: Callable[[], float] = time.time):
        self.process = str(process)
        self.stale_after_s = float(stale_after_s)
        self._clock = clock
        # On-demand capture controller (telemetry/sampler.py), attached
        # once by TrainTelemetry before the debug server starts; None
        # keeps /profilez answering 404. Frozen binding (concurrency
        # registry): the controller locks itself.
        self.capture = None
        self._lock = threading.Lock()
        # The ONE shared mutable slot (concurrency registry): written by
        # the train loop (note_step) and background emitters (the
        # watchdog's fault records arrive via the emit tee), read by
        # HTTP worker threads rendering /healthz //statsz //metricsz.
        self._state: dict = {
            "started_at": clock(),
            "step": None,
            "last_step_at": None,
            "steps": 0,
            "last_loss": None,
            "records": 0,
            "last_window": None,
            "last_grad_health": None,
            "last_memory": None,
            "compiles": 0,
            "compile_s": 0.0,
            "compile_cache": {},
            "nonfinite_steps": 0,
            "divergence_warnings": 0,
            "faults": 0,
        }

    # -- producer side (train loop + background emitters) ----------------

    def note_step(self, step: int, loss=None) -> None:
        """One completed step: the /healthz liveness signal (every step,
        synced or not — liveness must not depend on the sync cadence)."""
        now = self._clock()
        with self._lock:
            self._state["step"] = int(step)
            self._state["last_step_at"] = now
            self._state["steps"] += 1
            if loss is not None:
                self._state["last_loss"] = float(loss)

    def observe_record(self, rec: dict) -> None:
        """Fold one emitted telemetry record into the live snapshot
        (called from the TrainTelemetry.emit tee, any emitting thread)."""
        if not isinstance(rec, dict):
            return
        kind = rec.get("kind")
        with self._lock:
            self._state["records"] += 1
            if kind == "step_window":
                self._state["last_window"] = dict(rec)
            elif kind == "grad_health":
                self._state["last_grad_health"] = dict(rec)
            elif kind == "memory":
                self._state["last_memory"] = dict(rec)
            elif kind == "compile":
                self._state["compiles"] += 1
                self._state["compile_s"] += float(rec.get("compile_s", 0.0)
                                                  or 0.0)
                cache = str(rec.get("cache", "?"))
                by = self._state["compile_cache"]
                by[cache] = by.get(cache, 0) + 1
            elif kind == "sentinel":
                self._state["nonfinite_steps"] += 1
            elif kind == "divergence":
                self._state["divergence_warnings"] += 1
            elif kind == "fault":
                self._state["faults"] += 1

    # -- consumer side (HTTP worker threads) -----------------------------

    def healthz(self) -> Tuple[int, dict]:
        """(http_status, body): 200 while warming or stepping within
        ``stale_after_s``; 503 once the step counter has gone stale."""
        now = self._clock()
        with self._lock:
            step = self._state["step"]
            last = self._state["last_step_at"]
            started = self._state["started_at"]
            loss = self._state["last_loss"]
        if last is None:
            status, code = "warming", 200
            age = now - started
        else:
            age = now - last
            stale = age > self.stale_after_s
            status, code = ("stale", 503) if stale else ("ok", 200)
        return code, {
            "status": status,
            "process": self.process,
            "step": step,
            "step_age_s": round(age, 3),
            "stale_after_s": self.stale_after_s,
            "uptime_s": round(now - started, 3),
            "last_loss": loss,
        }

    def statsz(self) -> dict:
        """The full live snapshot as JSON-able state."""
        now = self._clock()
        with self._lock:
            state = dict(self._state)
            state["compile_cache"] = dict(state["compile_cache"])
        state["process"] = self.process
        state["uptime_s"] = round(now - state.pop("started_at"), 3)
        if state["last_step_at"] is not None:
            state["step_age_s"] = round(now - state["last_step_at"], 3)
        state.pop("last_step_at", None)
        if self.capture is not None:
            # Capture status rides the same surface operators already
            # watch: armed/active phase, completed-capture count, and
            # the last window's headline (docs/observability.md
            # "Profiling plane").
            state["profile"] = self.capture.status()
        return state

    def metrics_text(self, prefix: str = "bert_train") -> str:
        """Prometheus text exposition of the live snapshot.

        The last step_window record's numeric fields are exported
        verbatim as ``<prefix>_window_<field>`` (repr-rendered so floats
        round-trip) — the per-metric-name agreement with the JSONL
        artifact the observatory E2E asserts. Nested gauge sub-objects
        (``loader``, ``prefetch``) flatten to
        ``<prefix>_loader_<field>`` / ``<prefix>_prefetch_<field>``.
        """
        now = self._clock()
        with self._lock:
            state = dict(self._state)
            window = dict(state["last_window"] or {})
            health = dict(state["last_grad_health"] or {})
            by_cache = dict(state["compile_cache"])
        label = f'process="{self.process}"'
        lines = []

        def metric(name, value, kind="gauge", help_text="", labels=label):
            if value is None:
                return
            if help_text:
                lines.append(f"# HELP {prefix}_{name} {help_text}")
            lines.append(f"# TYPE {prefix}_{name} {kind}")
            lines.append(f"{prefix}_{name}{{{labels}}} "
                         f"{_render(value)}")

        metric("up", 1, help_text="1 while the training process serves "
                                  "this debug endpoint.")
        metric("stale_after_seconds", self.stale_after_s,
               help_text="The /healthz step-staleness bound; scrapers "
                         "compare step_age_seconds against it.")
        metric("uptime_seconds", round(now - state["started_at"], 3))
        metric("step", state["step"],
               help_text="Last completed training step.")
        if state["last_step_at"] is not None:
            metric("step_age_seconds",
                   round(now - state["last_step_at"], 3),
                   help_text="Seconds since the last completed step "
                             "(the /healthz liveness signal).")
        metric("steps_total", state["steps"], kind="counter")
        metric("last_loss", state["last_loss"])
        metric("records_total", state["records"], kind="counter",
               help_text="Telemetry records emitted so far.")
        lines.append(f"# TYPE {prefix}_compiles_total counter")
        for cache in sorted(by_cache):
            lines.append(
                f'{prefix}_compiles_total{{{label},cache="{cache}"}} '
                f"{by_cache[cache]}")
        metric("compile_seconds_total", round(state["compile_s"], 6),
               kind="counter")
        metric("nonfinite_steps_total", state["nonfinite_steps"],
               kind="counter")
        metric("divergence_warnings_total", state["divergence_warnings"],
               kind="counter")
        metric("faults_total", state["faults"], kind="counter")
        # The last window, field for field (the JSONL-agreement export).
        for key, value in sorted(window.items()):
            if key in ("kind", "tag", "schema", "ts"):
                continue
            if _num(value):
                metric(f"window_{key}", value)
            elif isinstance(value, dict):
                for sub, sv in sorted(value.items()):
                    if _num(sv):
                        metric(f"{key}_{sub}", sv)
        for key in ("grad_norm", "param_norm", "update_ratio"):
            if _num(health.get(key)):
                metric(f"grad_health_{key}", health[key])
        return "\n".join(lines) + "\n"


def _finite_json(payload) -> str:
    """JSON with non-finite floats as null (the JSONL sink's
    _FiniteEncoder convention): a NaN loss — the exact incident you'd
    scrape during — must not make /healthz emit invalid JSON that
    strict clients (jq, fetch().json()) reject."""
    def sanitize(obj):
        if isinstance(obj, float) and not math.isfinite(obj):
            return None
        if isinstance(obj, dict):
            return {k: sanitize(v) for k, v in obj.items()}
        if isinstance(obj, list):
            return [sanitize(v) for v in obj]
        return obj

    return json.dumps(sanitize(payload))


def _render(value) -> str:
    """Exposition-format value: repr for floats (full round-trip
    precision — the JSONL-agreement property), plain int otherwise."""
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, float):
        return repr(value)
    return str(value)


# -- the HTTP plane ----------------------------------------------------------

class DebugHTTPServer(http.server.ThreadingHTTPServer):
    daemon_threads = True
    # Above the stdlib backlog of 5: a coordinated scrape/capture sweep
    # (obs_collect --profile) connects to every process at once.
    request_queue_size = 64
    hub: IntrospectionHub = None


def _make_handler():
    class Handler(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # telemetry is the log
            pass

        def _reply(self, code: int, text: str, content_type: str) -> None:
            body = text.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            hub = self.server.hub
            if self.path == "/healthz":
                code, payload = hub.healthz()
                self._reply(code, _finite_json(payload),
                            "application/json")
            elif self.path == "/statsz":
                self._reply(200, _finite_json(hub.statsz()),
                            "application/json")
            elif self.path == "/metricsz":
                self._reply(200, hub.metrics_text(),
                            "text/plain; version=0.0.4; charset=utf-8")
            else:
                self._reply(404, json.dumps(
                    {"error": f"no route {self.path}"}), "application/json")

        def do_POST(self):
            hub = self.server.hub
            if self.path != "/profilez":
                self._reply(404, json.dumps(
                    {"error": f"no route {self.path}"}), "application/json")
                return
            if hub.capture is None:
                self._reply(404, json.dumps(
                    {"error": "profiling plane not attached (the runner "
                              "built no capture controller)"}),
                    "application/json")
                return
            try:
                length = int(self.headers.get("Content-Length", 0) or 0)
                body = json.loads(
                    self.rfile.read(length).decode("utf-8") or "{}")
                if not isinstance(body, dict):
                    raise ValueError("body must be a JSON object")
            except ValueError as exc:
                self._reply(400, json.dumps(
                    {"error": f"bad JSON body: {exc}"}), "application/json")
                return
            ok, payload = hub.capture.arm(**{
                k: body[k] for k in ("duration_s", "sample_interval_s",
                                     "max_samples", "top_k", "trigger")
                if k in body})
            # 409, not 500, on double-arm: jax traces cannot nest, and
            # the second operator must learn a capture is already
            # running, not crash the first one's window. A refused
            # PARAMETER (no blocking phase in the payload) is 400.
            code = 200 if ok else (409 if "phase" in payload else 400)
            self._reply(code, _finite_json(payload), "application/json")

    return Handler


def make_debug_server(hub: IntrospectionHub, host: str = "127.0.0.1",
                      port: int = 0) -> DebugHTTPServer:
    """Build (but do not start) the debug server; ``port=0`` binds an
    ephemeral port (read ``server.server_address``)."""
    server = DebugHTTPServer((host, port), _make_handler())
    server.hub = hub
    return server


def start_debug_server(hub: IntrospectionHub, host: str = "127.0.0.1",
                       port: int = 0) -> DebugHTTPServer:
    """Bind and serve in a daemon thread; returns the live server (call
    ``shutdown()`` to stop — TrainTelemetry.finish does)."""
    server = make_debug_server(hub, host=host, port=port)
    threading.Thread(target=server.serve_forever,
                     name="telemetry-introspect", daemon=True).start()
    return server
