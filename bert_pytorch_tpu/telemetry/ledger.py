"""The longitudinal perf ledger: an append-mode, schema-linted JSONL
trajectory of headline performance numbers across runs
(docs/telemetry.md "Perf ledger").

Every ``BENCH_*`` capture so far has been a point sample diffed against
ONE hand-picked baseline artifact — pick a lucky baseline and a slow
drift walks in one in-tolerance step at a time. The ledger records one
``ledger_entry`` per bench leg / telemetry-report run (step p50/p95,
MFU, serve p50/p99, cold start, padding efficiency, plus the config
digest that makes entries comparable) and the drift gate compares the
NEWEST entry against the ROLLING MEDIAN of its leg's history — the
Chowdhery-2022 MFU-accounting lineage only pays off when successive
measurements are comparable over time, which is exactly what a single
baseline cannot give you.

Writers: ``bench.py`` appends automatically after every successful
capture; ``tools/telemetry_report.py --ledger`` appends the run under
test and then gates ("perf ledger drift" by name, exit 1);
``tools/perf_ledger.py`` is the standalone CLI (show / append / check).

Deliberately stdlib-only and jax-free like telemetry/schema.py: every
consumer here is repo-root tooling that loads this module by FILE PATH
(tools/_bootstrap.py) and must keep working while the accelerator
processes it audits are hung.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import time
from typing import Dict, List, Optional

SCHEMA_VERSION = 1

# Metric direction for the drift verdict ("up" regresses by growing,
# "down" by shrinking) — kept in lockstep with
# schema.LEDGER_METRIC_DIRECTIONS (the lint side of the same contract).
METRIC_DIRECTIONS = {
    "step_ms_p50": "up",
    "step_ms_p95": "up",
    "mfu": "down",
    "serve_p50_ms": "up",
    "serve_p99_ms": "up",
    "cold_start_s": "up",
    "padding_efficiency": "down",
}

DEFAULT_WINDOW = 8          # rolling-median history depth per leg
DEFAULT_TOLERANCE = 0.25    # relative drift allowed vs the median
_MIN_HISTORY = 3            # fewer prior entries than this: no verdict


def config_digest(config: Optional[dict]) -> str:
    """Short stable digest of the run configuration (the comparability
    join key): sorted-key JSON, sha256, 12 hex chars. ``None``/empty
    digests to the fixed ``"unconfigured"`` marker so ad-hoc entries
    still carry a non-empty key."""
    if not config:
        return "unconfigured"
    blob = json.dumps(config, sort_keys=True, separators=(",", ":"),
                      default=str)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:12]


def make_entry(leg: str, metrics: Dict[str, float],
               config: Optional[dict] = None,
               digest: Optional[str] = None,
               extra: Optional[dict] = None,
               ts: Optional[float] = None) -> dict:
    """One schema-stamped ``ledger_entry`` record (not yet written).
    Non-finite and negative metric values are dropped rather than
    poisoning the trajectory — an entry is evidence, and evidence that
    fails its own lint is worse than a gap."""
    clean = {}
    for key, value in (metrics or {}).items():
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            continue
        if not math.isfinite(value) or value < 0:
            continue
        clean[str(key)] = round(float(value), 6)
    rec = {
        "schema": SCHEMA_VERSION,
        "ts": round(float(ts if ts is not None else time.time()), 3),
        "kind": "ledger_entry",
        "leg": str(leg),
        "config_digest": digest or config_digest(config),
        "metrics": clean,
    }
    if extra:
        for key, value in extra.items():
            rec.setdefault(str(key), value)
    return rec


def append_entry(path: str, leg: str, metrics: Dict[str, float],
                 config: Optional[dict] = None,
                 digest: Optional[str] = None,
                 extra: Optional[dict] = None,
                 ts: Optional[float] = None) -> Optional[dict]:
    """Append one entry to the ledger (append mode — the trajectory is
    the point). Returns the record written, or None when no metric
    survived cleaning (an all-empty entry would fail its own schema
    lint and gate every future run on garbage)."""
    rec = make_entry(leg, metrics, config=config, digest=digest,
                     extra=extra, ts=ts)
    if not rec["metrics"]:
        return None
    line = json.dumps(rec, sort_keys=False)
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "a", encoding="utf-8") as f:
        f.write(line + "\n")
    return rec


def read_entries(path: str, leg: Optional[str] = None) -> List[dict]:
    """The ledger's ``ledger_entry`` records in file order (optionally
    one leg's). Unparseable lines are skipped — the schema lint names
    them; the reader's job is the trajectory that exists."""
    if not os.path.exists(path):
        return []
    out = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict) or \
                    rec.get("kind") != "ledger_entry":
                continue
            if leg is not None and rec.get("leg") != leg:
                continue
            out.append(rec)
    return out


def _median(values: List[float]) -> float:
    vals = sorted(values)
    n = len(vals)
    mid = n // 2
    if n % 2:
        return vals[mid]
    return 0.5 * (vals[mid - 1] + vals[mid])


def check_drift(entries: List[dict], window: int = DEFAULT_WINDOW,
                tolerance: float = DEFAULT_TOLERANCE) -> List[dict]:
    """Rolling-median drift findings for the NEWEST entry of each
    (leg, config_digest) trajectory.

    For every direction-known metric the newest entry carries, compare
    it against the median of the previous up-to-``window`` entries of
    the same leg AND digest (cross-config comparisons are the
    incomparability the digest exists to refuse). Fewer than
    ``_MIN_HISTORY`` prior entries yields no verdict — two points are a
    line, not a trajectory. Returns one finding dict per drifted
    metric: ``{leg, digest, metric, median, latest, change, window}``.
    """
    findings = []
    by_key: Dict[tuple, List[dict]] = {}
    for rec in entries:
        key = (rec.get("leg"), rec.get("config_digest"))
        by_key.setdefault(key, []).append(rec)
    for (leg, digest), recs in sorted(by_key.items(),
                                      key=lambda kv: str(kv[0])):
        if len(recs) < _MIN_HISTORY + 1:
            continue
        latest = recs[-1].get("metrics") or {}
        history = recs[max(0, len(recs) - 1 - window):-1]
        for metric, direction in METRIC_DIRECTIONS.items():
            new = latest.get(metric)
            if not isinstance(new, (int, float)) or isinstance(new, bool):
                continue
            past = [r["metrics"][metric] for r in history
                    if isinstance(r.get("metrics"), dict)
                    and isinstance(r["metrics"].get(metric), (int, float))
                    and not isinstance(r["metrics"].get(metric), bool)]
            if len(past) < _MIN_HISTORY:
                continue
            med = _median(past)
            if not med:
                continue
            rel = (new - med) / abs(med)
            drifted = rel > tolerance if direction == "up" \
                else rel < -tolerance
            if drifted:
                findings.append({
                    "leg": leg,
                    "digest": digest,
                    "metric": metric,
                    "median": round(med, 6),
                    "latest": round(float(new), 6),
                    "change": round(rel, 4),
                    "tolerance": tolerance,
                    "window": len(past),
                })
    return findings


# Mapping from a telemetry-report summary (telemetry/report.py
# summarize_file) to ledger metric names — the one place the two
# vocabularies meet, so bench.py and telemetry-report land identical
# entries from the same artifact.
SUMMARY_METRIC_MAP = (
    ("step_p50_s", "step_ms_p50", 1000.0),
    ("step_p95_s", "step_ms_p95", 1000.0),
    ("mfu", "mfu", 1.0),
    ("serve_latency_p50_ms", "serve_p50_ms", 1.0),
    ("serve_latency_p99_ms", "serve_p99_ms", 1.0),
    ("serve_cold_start_s", "cold_start_s", 1.0),
    ("padding_efficiency", "padding_efficiency", 1.0),
)


def metrics_from_summary(summary: dict) -> Dict[str, float]:
    """Ledger metrics out of a report summary dict (missing keys simply
    stay absent — a train-only run lands no serve metrics)."""
    out = {}
    for src, dst, scale in SUMMARY_METRIC_MAP:
        v = summary.get(src)
        if isinstance(v, (int, float)) and not isinstance(v, bool) \
                and math.isfinite(v):
            out[dst] = float(v) * scale
    return out


def format_trajectory(entries: List[dict]) -> str:
    """Human rendering of a ledger (the ``perf_ledger.py show`` table)."""
    if not entries:
        return "perf ledger: empty"
    lines = []
    for rec in entries:
        metrics = rec.get("metrics") or {}
        rendered = " ".join(f"{k}={metrics[k]:g}" for k in sorted(metrics))
        lines.append(
            f"{rec.get('ts', 0):>14.3f} {rec.get('leg', '?'):>10} "
            f"{rec.get('config_digest', '?'):>12} {rendered}")
    return "\n".join(lines)
