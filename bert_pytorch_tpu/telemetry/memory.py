"""Device-memory observability: live/peak watermark sampling + one-shot
static cost attribution per jitted executable.

Two halves, both feeding the JSONL stream:

* :class:`MemorySampler` — samples ``device.memory_stats()`` on the
  existing sync cadence (the host is already blocked there, so the
  PJRT stats call adds no extra round trip) and emits one
  ``kind="memory"`` record per telemetry window with the live-bytes
  last/max and the peak watermark across devices. Backends without
  allocator stats (CPU returns ``None``; some runtimes raise) get ONE
  ``memory_supported: false`` note and the sampler disables itself —
  never a per-step warning storm.

* :func:`analyze_executable` — static attribution for one jitted
  function: HLO ``cost_analysis`` (FLOPs, bytes accessed) and — when a
  compile is affordable — ``compiled.memory_analysis()``
  (argument/output/temp/generated-code bytes). The CompileMonitor calls
  it once per (fn, shapes-digest) and joins the result to the compile
  event's digest, so every compile in the stream carries its cost.

The compile-affordability rule matters: JAX's AOT ``lower().compile()``
does NOT share the executable the call path compiled, so asking for
``memory_analysis`` costs one extra backend compile per digest. That is
noise on CPU (and exactly once per shape), and a persistent-cache
deserialize when ``--compile_cache_dir`` is on — but a second 10-30 min
BERT-large compile through a TPU tunnel when it is off. ``mode="auto"``
therefore compiles only on CPU or with the persistent cache enabled and
falls back to the (cheap, compile-free) lowered-HLO cost analysis
elsewhere; ``"full"`` always compiles; ``"off"`` disables the whole
attribution.
"""

from __future__ import annotations

from typing import Callable, Optional

COST_MODES = ("auto", "off", "full")


class MemorySampler:
    """Window-aggregated ``device.memory_stats()`` watermarks."""

    def __init__(self, emit: Callable[[dict], None], enabled: bool = True):
        self._emit = emit
        self.enabled = enabled
        self.supported: Optional[bool] = None  # unknown until first sample
        self._reset()

    def _reset(self):
        self._samples = 0
        self._live_last = 0
        self._live_max = 0
        self._peak_max = 0
        self._limit = 0
        self._n_devices = 0

    def _read(self):
        """(live_bytes_total, peak_bytes_max, limit_total, n_devices) or
        None when no local device exposes allocator stats."""
        import jax

        live = peak = limit = 0
        n = 0
        for dev in jax.local_devices():
            try:
                stats = dev.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            n += 1
            live += int(stats.get("bytes_in_use", 0))
            peak = max(peak, int(stats.get("peak_bytes_in_use", 0)))
            limit += int(stats.get("bytes_limit", 0))
        return (live, peak, limit, n) if n else None

    def sample(self, step: int) -> None:
        """Take one watermark sample (call on synced steps only — the
        device is quiesced there, so 'live' means post-step residency)."""
        if not self.enabled or self.supported is False:
            return
        reading = self._read()
        if reading is None:
            self.supported = False
            # One note, then silence: the absence of memory records is
            # explained in-stream instead of by a log storm.
            self._emit({"kind": "memory", "tag": "telemetry",
                        "step": int(step), "memory_supported": False})
            return
        self.supported = True
        live, peak, limit, n = reading
        self._samples += 1
        self._live_last = live
        self._live_max = max(self._live_max, live)
        self._peak_max = max(self._peak_max, peak)
        self._limit = limit
        self._n_devices = n

    def flush(self, step: int) -> Optional[dict]:
        """Emit the window's aggregate record (None when no samples)."""
        if not self.enabled or not self._samples:
            return None
        record = {
            "kind": "memory",
            "tag": "telemetry",
            "step": int(step),
            "memory_supported": True,
            "samples": self._samples,
            "n_devices": self._n_devices,
            "bytes_in_use": self._live_last,
            "bytes_in_use_max": self._live_max,
            "peak_bytes_in_use": self._peak_max,
            "bytes_limit": self._limit,
        }
        self._reset()
        self._emit(record)
        return record


def _compile_affordable() -> bool:
    """One extra AOT compile is cheap: CPU backend, or the persistent
    compile cache will serve (or at worst persist) it."""
    import jax

    if jax.default_backend() == "cpu":
        return True
    return bool(jax.config.jax_compilation_cache_dir)


def analyze_executable(fn, args, kwargs, mode: str = "auto"):
    """Static cost/memory attribution for one jitted call signature.

    Returns a dict of record fields (``analysis`` says which path ran:
    ``"compiled"`` with memory_analysis bytes, or ``"lowered"`` with
    HLO cost analysis only) — or None when the function exposes no AOT
    surface or the backend supports neither analysis. Never raises:
    attribution is telemetry, not control flow.

    Works after the call even with donated arguments: lowering needs
    only aval metadata (shape/dtype), which deleted arrays retain.
    """
    if mode not in COST_MODES:
        raise ValueError(f"cost-analysis mode must be one of {COST_MODES}, "
                         f"got {mode!r}")
    if mode == "off":
        return None
    lower = getattr(fn, "lower", None)
    if lower is None:
        return None
    try:
        lowered = lower(*args, **kwargs)
    except Exception:
        return None
    fields: dict = {}
    if mode == "full" or _compile_affordable():
        try:
            compiled = lowered.compile()
            mem = compiled.memory_analysis()
            for name, key in (
                    ("argument_bytes", "argument_size_in_bytes"),
                    ("output_bytes", "output_size_in_bytes"),
                    ("temp_bytes", "temp_size_in_bytes"),
                    ("alias_bytes", "alias_size_in_bytes"),
                    ("generated_code_bytes", "generated_code_size_in_bytes")):
                value = getattr(mem, key, None)
                if value is not None:
                    fields[name] = int(value)
            cost = compiled.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            if cost.get("flops") is not None:
                fields["flops"] = float(cost["flops"])
            if cost.get("bytes accessed") is not None:
                fields["bytes_accessed"] = float(cost["bytes accessed"])
            fields["analysis"] = "compiled"
            return fields
        except Exception:
            fields = {}  # discard any partial compiled fields: a record
            # labeled analysis="lowered" must not carry memory_analysis
            # bytes from the compiled path that then failed mid-way
    try:
        cost = lowered.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        if cost.get("flops") is not None:
            fields["flops"] = float(cost["flops"])
        if cost.get("bytes accessed") is not None:
            fields["bytes_accessed"] = float(cost["bytes accessed"])
        fields["analysis"] = "lowered"
        return fields
    except Exception:
        return None
