"""Model-internals health: in-jit grad/param/update statistics + the
host-side divergence early-warning that consumes them.

The systems telemetry (step_timer/compile_events/sentinels) says where the
wallclock goes; this module says whether the MODEL is healthy while it
goes there. Rounds 2-4 lost runs to divergences the flat loss log only
showed after the fact: the K-FAC kl_clip mistunes and fp16 overflows all
announced themselves as a grad-norm spike (or an update:weight ratio
drifting toward 1) many steps before the loss went NaN and the
FailureSentinel's non-finite tripwire could fire.

In-jit half (:func:`gated_grad_health`, called by both pretraining step
builders and every finetune runner's inline step): per-layer-group
gradient norms, parameter norms, and update:weight ratios, reduced to a
handful of scalars INSIDE the jitted step — one elementwise
square+reduce over the trees, fused into the step program. The block is
``lax.cond``-gated on the optimizer-step counter so off-cadence steps pay
a predicate instead of the reduction, and the host only reads it on
synced steps (the ``--telemetry_sync_every`` machinery), so steady-state
steps stay fetch-free.

Layer groups follow the parameter tree: the shared ``bert`` container
splits one level deeper (``bert/embeddings``, ``bert/encoder``,
``bert/pooler``), every other top-level module (``predictions``,
``qa_outputs``, ``classifier``, ...) is one group. The ``nn.scan``-stacked
encoder additionally reports a per-layer gradient-norm vector (leading
``layers`` axis), which localises a divergence to a layer index.

Host half (:class:`DivergenceMonitor`, driven by
``TrainTelemetry.step_done``): an EMA envelope over the global grad norm
plus an absolute bound on the update:weight ratio. Violations emit
``kind="divergence"`` records and follow the existing FailureSentinel
policy: ``continue`` logs, ``abort`` raises :class:`DivergenceError`
(a :class:`~bert_pytorch_tpu.telemetry.sentinels.NonFiniteError`, so
runner-level handling is shared) after ``patience`` consecutive warned
observations.
"""

from __future__ import annotations

from typing import Callable, Optional

from bert_pytorch_tpu.telemetry.sentinels import NonFiniteError

_EPS = 1e-12


class DivergenceError(NonFiniteError):
    """Raised by the abort policy after ``patience`` consecutive
    grad-health warnings (grad-norm spike / update-ratio drift)."""


def _path_names(path):
    names = []
    for p in path:
        name = getattr(p, "key", None)
        if name is None:
            name = getattr(p, "idx", None)
        names.append(str(name))
    return names


def _group_key(path) -> str:
    """Layer-group name for one parameter path: the shared 'bert'
    container splits one level deeper; everything else groups by its
    top-level module."""
    names = _path_names(path)
    if not names:
        return "params"
    if len(names) >= 2 and names[0] == "bert":
        return f"{names[0]}/{names[1]}"
    return names[0]


def grad_health(params, grads, updates, grad_scale=None) -> dict:
    """Tree-reduced grad/param/update statistics (device scalars).

    Returns ``{"grad_norm", "param_norm", "update_ratio", "groups":
    {group: {"grad_norm", "param_norm", "update_ratio"}}}`` plus
    ``"per_layer_grad_norm"`` ([L]) when the tree has an ``nn.scan``-
    stacked ``layers`` axis. ``update_ratio`` is ||update|| / ||param||
    — the step-relative weight change LAMB/AdamW aim to keep small; a
    ratio drifting toward 1 means the optimizer is rewriting the weights
    wholesale. ``grads`` are the gradients the step applied (post-clip
    where the step clips); ``grad_scale`` divides the reported grad norms
    (the fp16 path's gradients carry the dynamic loss scale — reporting
    the scaled norm would make the spike detector see every loss-scale
    doubling as a 2x 'spike').
    """
    import jax
    import jax.numpy as jnp

    def sumsq(x):
        return jnp.sum(jnp.square(x.astype(jnp.float32)))

    g_leaves = jax.tree_util.tree_flatten_with_path(grads)[0]
    p_leaves = jax.tree_util.tree_leaves(params)
    u_leaves = jax.tree_util.tree_leaves(updates)

    groups: dict = {}
    per_layer: dict = {}
    for (path, g), p, u in zip(g_leaves, p_leaves, u_leaves):
        key = _group_key(path)
        acc = groups.setdefault(key, [0.0, 0.0, 0.0])
        acc[0] = acc[0] + sumsq(g)
        acc[1] = acc[1] + sumsq(p)
        acc[2] = acc[2] + sumsq(u)
        if "layers" in _path_names(path) and g.ndim > 0:
            # Stacked encoder: reduce every axis but the leading layer
            # axis, giving a per-layer grad-norm vector.
            vec = jnp.sum(jnp.square(g.astype(jnp.float32)),
                          axis=tuple(range(1, g.ndim)))
            dim = int(g.shape[0])
            per_layer[dim] = per_layer.get(dim, 0.0) + vec

    inv_scale = 1.0 if grad_scale is None else 1.0 / grad_scale
    out_groups = {}
    tot_g = tot_p = tot_u = 0.0
    for key, (gsq, psq, usq) in groups.items():
        tot_g, tot_p, tot_u = tot_g + gsq, tot_p + psq, tot_u + usq
        pn = jnp.sqrt(psq)
        out_groups[key] = {
            "grad_norm": jnp.sqrt(gsq) * inv_scale,
            "param_norm": pn,
            "update_ratio": jnp.sqrt(usq) / (pn + _EPS),
        }
    pn = jnp.sqrt(tot_p)
    out = {
        "grad_norm": jnp.sqrt(tot_g) * inv_scale,
        "param_norm": pn,
        "update_ratio": jnp.sqrt(tot_u) / (pn + _EPS),
        "groups": out_groups,
    }
    if len(per_layer) == 1:  # unambiguous single stacked-layer axis
        (vec,) = per_layer.values()
        out["per_layer_grad_norm"] = jnp.sqrt(vec) * inv_scale
    return out


def gated_grad_health(params, grads, updates, count, every: int,
                      grad_scale=None, phase: int = 0):
    """The in-jit grad-health block, ``lax.cond``-gated on the optimizer
    step counter: due steps (``(count - phase) % every == 0``) pay the
    tree reduction, all others a predicate + zeros. Returns None when
    ``every`` <= 0 (disabled) — callers splice the result into their
    metrics dict as ``metrics["grad_health"]``.

    ``phase`` is the optimizer count at RUN START (known when the step is
    built): the host reads the block on its own run-local 0-based sync
    cadence, so a checkpoint-resumed run whose absolute count is not a
    multiple of ``every`` would otherwise have its due steps land only on
    unsynced steps — zero records for the whole resumed run.

    The ``"due"`` scalar tells the host whether the values are real; the
    host additionally only fetches on synced steps, so the cadence that
    matters end-to-end is ``lcm(every, telemetry_sync_every)`` in the
    aligned (default) configuration where both are the same knob.
    """
    import jax
    import jax.numpy as jnp

    if not every or every <= 0:
        return None

    def compute():
        return grad_health(params, grads, updates, grad_scale=grad_scale)

    due = ((count - phase) % every) == 0
    if every == 1:
        stats = compute()
    else:
        shapes = jax.eval_shape(compute)
        zeros = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        stats = jax.lax.cond(due, compute, lambda: zeros)
    stats["due"] = jnp.asarray(due, jnp.float32)
    return stats


def finetune_grad_health(params, grads, updates, opt_state,
                         stats_every: int, fp16_scale=None):
    """The one grad-health splice shared by the finetune runners' inline
    train steps (run_squad/glue/ner/swag) — the cadence invariants live
    HERE, not in four copies:

    * gate on the PRE-update optimizer count (``opt_state`` BEFORE
      ``tx.update``): the host reads the block on its run-local 0-based
      sync cadence, and the post-update count is off by one;
    * fp16 (``fp16_scale`` = the live loss scale): skipped overflow
      steps don't advance the count, so a count gate drifts off the
      cadence after the first skip — compute every step instead and let
      the sync cadence sample; the reported grad norms are divided by
      the scale.

    Returns the block for ``metrics["grad_health"]`` or None (disabled).
    """
    from bert_pytorch_tpu.optim.transforms import opt_step_count

    if not stats_every or stats_every <= 0:
        return None
    return gated_grad_health(
        params, grads, updates, opt_step_count(opt_state),
        1 if fp16_scale is not None else stats_every,
        grad_scale=fp16_scale)


def health_record(step: int, stats) -> dict:
    """Host-side conversion of a fetched grad-health block into one
    ``kind="grad_health"`` JSONL record (floats/lists only). The caller
    has already synced, so the fetch does not block on compute — but it
    is still one host<->device transfer per array, so pull the WHOLE
    tree in a single device_get instead of ~50 scalar round trips
    (which through a remote-TPU tunnel each cost a full round trip)."""
    import jax

    stats = jax.device_get(stats)

    def f(x):
        return float(x)

    record = {
        "kind": "grad_health",
        "tag": "telemetry",
        "step": int(step),
        "grad_norm": f(stats["grad_norm"]),
        "param_norm": f(stats["param_norm"]),
        "update_ratio": f(stats["update_ratio"]),
        "groups": {
            name: {k: f(v) for k, v in vals.items()}
            for name, vals in stats["groups"].items()
        },
    }
    if "per_layer_grad_norm" in stats:
        record["per_layer_grad_norm"] = [
            round(float(v), 8) for v in stats["per_layer_grad_norm"]]
    return record


class DivergenceMonitor:
    """Host-side divergence early-warning over the grad-health stream.

    Two checks, both configurable and individually disabled by 0:

    * grad-norm spike — the observed global grad norm exceeds
      ``spike_factor`` x its own EMA (seeded over the first ``warmup``
      observations, during which no spike can fire: step-0 norms are
      legitimately wild);
    * update-ratio drift — the global update:weight ratio exceeds
      ``ratio_max`` (a per-step relative weight change of that size means
      the optimizer is rewriting the model, the signature of a blown
      learning rate or a mistuned K-FAC kl_clip).

    Warnings emit ``kind="divergence"`` records and follow the
    FailureSentinel policy: ``abort`` raises :class:`DivergenceError`
    after ``patience`` CONSECUTIVE warned observations (observations
    happen on the grad-health cadence, so real-step latency scales with
    it, same caveat as the sentinel's).
    """

    POLICIES = ("continue", "abort")

    def __init__(self, emit: Optional[Callable[[dict], None]] = None,
                 policy: str = "continue", patience: int = 3,
                 spike_factor: float = 10.0, ratio_max: float = 1.0,
                 warmup: int = 10, ema_decay: float = 0.9):
        if policy not in self.POLICIES:
            raise ValueError(
                f"divergence policy must be one of {self.POLICIES}, got "
                f"{policy!r}")
        self._emit = emit
        self.policy = policy
        self.patience = max(1, int(patience))
        self.spike_factor = float(spike_factor)
        self.ratio_max = float(ratio_max)
        self.warmup = max(1, int(warmup))
        self.ema_decay = float(ema_decay)
        self.ema = None
        self.observations = 0
        self.consecutive = 0
        self.total_warnings = 0

    def observe(self, step: int, grad_norm: float,
                update_ratio: Optional[float] = None) -> bool:
        """Feed one grad-health observation; True when healthy."""
        import math

        grad_norm = float(grad_norm)
        if not math.isfinite(grad_norm):
            return True  # the non-finite sentinel owns that signal
        warnings = []
        if (self.spike_factor and self.ema is not None
                and self.observations >= self.warmup
                and grad_norm > self.spike_factor * self.ema):
            warnings.append(("grad_norm_spike", grad_norm,
                             self.spike_factor * self.ema))
        if (self.ratio_max and update_ratio is not None
                and math.isfinite(float(update_ratio))
                and float(update_ratio) > self.ratio_max):
            warnings.append(("update_ratio_high", float(update_ratio),
                             self.ratio_max))
        if not warnings:
            # The EMA only absorbs HEALTHY observations: folding a
            # spiked norm in would raise the threshold under a
            # diverged-but-plateaued run, so it warns once and then the
            # abort policy's consecutive count can never accumulate.
            self.ema = (grad_norm if self.ema is None
                        else self.ema_decay * self.ema
                        + (1.0 - self.ema_decay) * grad_norm)
        self.observations += 1
        if not warnings:
            self.consecutive = 0
            return True
        self.consecutive += 1
        self.total_warnings += len(warnings)
        for reason, value, threshold in warnings:
            if self._emit is not None:
                self._emit({
                    "kind": "divergence",
                    "tag": "telemetry",
                    "step": int(step),
                    "reason": reason,
                    "value": round(value, 8),
                    "threshold": round(threshold, 8),
                    "consecutive": self.consecutive,
                    "policy": self.policy,
                })
        if self.policy == "abort" and self.consecutive >= self.patience:
            reason, value, threshold = warnings[0]
            raise DivergenceError(
                f"grad-health divergence warning ({reason}: {value:.4g} vs "
                f"threshold {threshold:.4g}) for {self.consecutive} "
                f"consecutive observations (last step {step}); aborting per "
                f"--sentinel_policy abort")
        return False
