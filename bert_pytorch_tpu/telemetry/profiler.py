"""Bounded ``jax.profiler`` trace windows for the training loop.

``--profile_steps`` accepts either ``"N"`` (legacy: N steady-state steps
starting after the compile step, i.e. the window ``[2, 2+N)`` in
step-in-run terms) or ``"N:M"`` (explicit half-open step range). The window
auto-stops: when the range's last step completes — or the run ends inside
the window — the trace is synced (``block_until_ready`` on the step's
outputs, so the trace holds the full device work) and written.

While a trace is active each step is wrapped in
``jax.profiler.StepTraceAnnotation``, which makes XLA's trace viewer group
events per training step.

On TPU the trace contains device (XLA op) timelines; on CPU it degrades to
host tracing only — both are readable with TensorBoard's profile plugin or
xprof. See docs/telemetry.md for the workflow.
"""

from __future__ import annotations

import contextlib
from typing import Optional, Tuple


def parse_profile_spec(spec) -> Optional[Tuple[int, int]]:
    """``"N"``/``N`` -> (2, 2+N) steady-state window; ``"N:M"`` -> (N, M);
    falsy / "0" -> None (disabled). Raises ValueError on malformed specs."""
    if spec is None:
        return None
    if isinstance(spec, int):
        return (2, 2 + spec) if spec > 0 else None
    text = str(spec).strip()
    if not text:
        return None
    if ":" in text:
        start_s, stop_s = text.split(":", 1)
        start, stop = int(start_s), int(stop_s)
        if start < 1 or stop <= start:
            raise ValueError(
                f"--profile_steps range must satisfy 1 <= N < M, got {text!r}")
        return (start, stop)
    n = int(text)
    return (2, 2 + n) if n > 0 else None


class ProfilerWindow:
    """Drives one bounded trace window from per-step calls.

    ``enabled`` gates everything (non-primary processes pass False: traces
    are per-host artifacts and rank 0's is the one the tooling reads).
    """

    def __init__(self, spec, trace_dir: Optional[str],
                 enabled: bool = True, annotate: bool = True):
        self.range = parse_profile_spec(spec) if enabled else None
        self.trace_dir = trace_dir
        self.annotate = annotate
        self.active = False
        self.done = False

    def maybe_start(self, step_in_run: int) -> bool:
        """Start the trace when ``step_in_run`` enters the window."""
        if (self.range is None or self.active or self.done
                or step_in_run < self.range[0]
                or step_in_run >= self.range[1]):
            return False
        import jax

        jax.profiler.start_trace(self.trace_dir)
        self.active = True
        return True

    def annotation(self, step_in_run: int):
        """Context manager wrapping one step's dispatch."""
        if self.active and self.annotate:
            import jax

            return jax.profiler.StepTraceAnnotation(
                "train", step_num=step_in_run)
        return contextlib.nullcontext()

    def maybe_stop(self, step_in_run: int, sync_target=None) -> bool:
        """Stop when the window's last step completed (auto-stop)."""
        if not self.active or step_in_run < self.range[1] - 1:
            return False
        return self.stop(sync_target)

    def stop(self, sync_target=None) -> bool:
        """Unconditional stop (end of run inside the window)."""
        if not self.active:
            return False
        import jax

        if sync_target is not None:
            # The trace must hold the device work of every step in the
            # window, not just their dispatches.
            jax.block_until_ready(sync_target)
        jax.profiler.stop_trace()
        self.active = False
        self.done = True
        return True
