"""Bounded ``jax.profiler`` trace windows for the training loop — and
the generalized ``begin``/``end`` facility the on-demand profiling plane
drives (telemetry/sampler.py, ``POST /profilez``).

``--profile_steps`` accepts either ``"N"`` (legacy: N steady-state steps
starting after the compile step, i.e. the window ``[2, 2+N)`` in
step-in-run terms) or ``"N:M"`` (explicit half-open step range). The window
auto-stops: when the range's last step completes — or the run ends inside
the window — the trace is synced (``block_until_ready`` on the step's
outputs, so the trace holds the full device work) and written.

While a trace is active each step is wrapped in
``jax.profiler.StepTraceAnnotation``, which makes XLA's trace viewer group
events per training step.

The startup window used to be this module's ONLY contract — one window
per process lifetime, latched by ``done``. :meth:`ProfilerWindow.begin`
and :meth:`ProfilerWindow.end` generalize past it: an on-demand capture
(``POST /profilez``) re-uses the same instance for any number of bounded
windows after the startup one, each to its own trace directory. What
does NOT generalize is concurrency — ``jax.profiler.start_trace`` is a
process-wide singleton and a second start while one is active raises —
so every start goes through the module-level exclusivity latch
(``_TRACE_ACTIVE``, concurrency registry): ``begin`` REFUSES (returns
False) instead of stacking traces, which is what lets two HTTP planes
and a startup window coexist on one process without coordinating.

On TPU the trace contains device (XLA op) timelines; on CPU it degrades to
host tracing only — both are readable with TensorBoard's profile plugin or
xprof. See docs/telemetry.md for the workflow.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional, Tuple

# Process-wide trace exclusivity (concurrency registry): jax.profiler
# allows one active trace per process; flipped by whichever thread's
# begin/end wins, checked by every other would-be starter.
_TRACE_LOCK = threading.Lock()
_TRACE_ACTIVE = False


def _acquire_trace() -> bool:
    global _TRACE_ACTIVE
    with _TRACE_LOCK:
        if _TRACE_ACTIVE:
            return False
        _TRACE_ACTIVE = True
        return True


def _release_trace() -> None:
    global _TRACE_ACTIVE
    with _TRACE_LOCK:
        _TRACE_ACTIVE = False


def trace_active() -> bool:
    """Whether ANY trace window is live in this process (status surface)."""
    with _TRACE_LOCK:
        return _TRACE_ACTIVE


def parse_profile_spec(spec) -> Optional[Tuple[int, int]]:
    """``"N"``/``N`` -> (2, 2+N) steady-state window; ``"N:M"`` -> (N, M);
    falsy / "0" -> None (disabled). Raises ValueError on malformed specs."""
    if spec is None:
        return None
    if isinstance(spec, int):
        return (2, 2 + spec) if spec > 0 else None
    text = str(spec).strip()
    if not text:
        return None
    if ":" in text:
        start_s, stop_s = text.split(":", 1)
        start, stop = int(start_s), int(stop_s)
        if start < 1 or stop <= start:
            raise ValueError(
                f"--profile_steps range must satisfy 1 <= N < M, got {text!r}")
        return (start, stop)
    n = int(text)
    return (2, 2 + n) if n > 0 else None


class ProfilerWindow:
    """Drives bounded trace windows from per-step calls.

    ``enabled`` gates everything (non-primary processes pass False: traces
    are per-host artifacts and rank 0's is the one the tooling reads).
    The spec-driven startup window remains one-shot (``done`` latches
    after it); ``begin``/``end`` windows are unlimited.
    """

    def __init__(self, spec, trace_dir: Optional[str],
                 enabled: bool = True, annotate: bool = True):
        self.range = parse_profile_spec(spec) if enabled else None
        self.trace_dir = trace_dir
        self.enabled = bool(enabled)
        self.annotate = annotate
        self.active = False
        self.done = False
        # True only while the SPEC-driven startup window is tracing:
        # maybe_stop's auto-stop rule applies to it alone — an on-demand
        # begin() window at step 50 must not be killed by the startup
        # range having ended at step 4.
        self._startup_active = False

    def begin(self, trace_dir: Optional[str] = None) -> bool:
        """Start a trace window outside the startup contract (on-demand
        captures). Returns False — never raises, never stacks — when
        this window is disabled, already tracing, or ANY other trace is
        active in the process (the startup window of this or another
        ProfilerWindow included)."""
        if not self.enabled or self.active:
            return False
        if not _acquire_trace():
            return False
        try:
            import jax

            jax.profiler.start_trace(trace_dir or self.trace_dir)
        except Exception:
            # A refused/failed start must release the latch or no trace
            # could ever start again in this process.
            _release_trace()
            return False
        self.active = True
        return True

    def end(self, sync_target=None) -> bool:
        """Stop the active trace window (on-demand counterpart of
        ``begin``; does NOT latch ``done`` — the startup contract's
        one-shot marker belongs to ``stop``)."""
        if not self.active:
            return False
        import jax

        if sync_target is not None:
            # The trace must hold the device work of every step in the
            # window, not just their dispatches.
            jax.block_until_ready(sync_target)
        try:
            jax.profiler.stop_trace()
        finally:
            self.active = False
            self._startup_active = False
            _release_trace()
        return True

    def maybe_start(self, step_in_run: int) -> bool:
        """Start the startup trace when ``step_in_run`` enters the
        spec's window (one-shot: ``done`` latches after it)."""
        if (self.range is None or self.active or self.done
                or step_in_run < self.range[0]
                or step_in_run >= self.range[1]):
            return False
        if not self.begin():
            return False
        self._startup_active = True
        return True

    def annotation(self, step_in_run: int):
        """Context manager wrapping one step's dispatch."""
        if self.active and self.annotate:
            import jax

            return jax.profiler.StepTraceAnnotation(
                "train", step_num=step_in_run)
        return contextlib.nullcontext()

    def maybe_stop(self, step_in_run: int, sync_target=None) -> bool:
        """Stop when the STARTUP window's last step completed
        (auto-stop; on-demand ``begin`` windows are bounded by their
        controller, not the spec range)."""
        if not self._startup_active or step_in_run < self.range[1] - 1:
            return False
        return self.stop(sync_target)

    def stop(self, sync_target=None) -> bool:
        """Unconditional stop (end of run inside the window); latches
        the startup one-shot ``done`` marker only when the startup
        window was the one tracing."""
        was_startup = self._startup_active
        if not self.end(sync_target=sync_target):
            return False
        if was_startup:
            self.done = True
        return True
