"""Offline telemetry reporting: human summary + baseline-diff regression
verdict over the JSONL artifacts the telemetry layer writes.

``summarize_file`` folds one artifact's records (``step_window``,
``compile``, ``sentinel``, ``grad_health``, ``divergence``, ``memory``,
``serve_*`` — including the request-tracing ``serve_phase``/
``serve_trace`` decomposition and its SLO verdict — the cross-tier
``router_trace``/``trace_stitch`` records with their per-tier latency
shares and the "router overhead share" / "orphan span share" gates, and
``run_summary``) into a flat summary; ``compare`` diffs two summaries
against relative tolerances and returns named regressions. The CLI
(`tools/telemetry_report.py`, console entry ``telemetry-report``) prints
the summary — and, given a baseline, the diff table — and exits nonzero
when any regression trips, which is what lets bench/CI gate on "did this
change make training slower, hungrier, or less healthy" instead of
eyeballing JSON.

Aggregation note: window records carry per-window percentiles, not raw
per-step samples, so the file-level ``step_p50_s`` is the
window-steps-weighted median of window p50s (robust to a cold-compile
first window) and ``step_p95_s`` is the max of window p95s (a tail
regression anywhere in the run must not average away). Throughput is the
harmonic aggregate — total steps over total window wall time.

This module imports stdlib only. The repo-root shim
(``tools/telemetry_report.py``) loads it by file path — bypassing the
package __init__ chain, which imports jax — so the checkout tool runs on
any machine, including CI boxes without the accelerator stack; the
installed ``telemetry-report`` console script goes through the package
import, where jax is a declared dependency anyway.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

# Relative tolerances (fraction of the baseline value) per check; chosen
# so real regressions (the ISSUE-2 gate injects +25% step time) trip
# clearly while window-to-window noise on a busy host does not.
DEFAULT_TOLERANCES = {
    "step": 0.10,    # step-time p50 / throughput / seq-per-sec
    "p95": 0.25,     # step-time p95 (noisier tail)
    "mfu": 0.10,     # MFU drop
    "mem": 0.05,     # peak device memory growth
    "grad": 1.00,    # grad-health envelope (2x the baseline max)
}


def _weighted_median(pairs):
    """Median of (value, weight) pairs; None when empty."""
    pairs = sorted((p for p in pairs if p[1] > 0), key=lambda p: p[0])
    total = sum(w for _, w in pairs)
    if not total:
        return None
    acc = 0.0
    for value, weight in pairs:
        acc += weight
        if acc >= total / 2.0:
            return value
    return pairs[-1][0]


def iter_records(path: str):
    """Decoded records of one JSONL artifact; silently skips blank and
    undecodable lines (the schema linter owns strictness)."""
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                yield rec


def last_run_records(records):
    """Trim an append-mode artifact to its FINAL run. Runs are terminated
    by ``run_summary`` records, so the final run is everything after the
    penultimate run_summary (including any trailing records of an
    unfinished newer run — those are the freshest evidence either way).
    With fewer than two run_summary records there is nothing to trim."""
    recs = list(records)
    ends = [i for i, rec in enumerate(recs)
            if rec.get("kind") == "run_summary"]
    if len(ends) >= 2:
        return recs[ends[-2] + 1:]
    return recs


def summarize_file(path: str, last_run: bool = False) -> dict:
    records = iter_records(path)
    if last_run:
        records = last_run_records(records)
    return summarize_records(records, name=os.path.basename(path))


def summarize_records(records, name: str = "") -> dict:
    windows = []
    compiles = []
    sentinels = []
    divergences = []
    grad_health = []
    memory = []
    serve_windows = []
    serve_cold_starts = []
    serve_phases = []
    serve_traces = []
    faults = []
    resumes = []
    router_windows = []
    router_traces = []
    trace_stitches = []
    fleet_events = []
    registry_events = []
    rollout_windows = []
    scale_events = []
    obs_scrapes = []
    obs_windows = []
    profile_windows = []
    compile_costs = []
    serve_summary: Optional[dict] = None
    router_summary: Optional[dict] = None
    run_summary: Optional[dict] = None
    n_records = 0
    for rec in records:
        n_records += 1
        kind = rec.get("kind")
        if kind == "step_window":
            windows.append(rec)
        elif kind == "compile":
            compiles.append(rec)
        elif kind == "sentinel":
            sentinels.append(rec)
        elif kind == "divergence":
            divergences.append(rec)
        elif kind == "grad_health":
            grad_health.append(rec)
        elif kind == "memory":
            memory.append(rec)
        elif kind == "serve_window":
            serve_windows.append(rec)
        elif kind == "serve_cold_start":
            serve_cold_starts.append(rec)
        elif kind == "serve_phase":
            serve_phases.append(rec)
        elif kind == "serve_trace":
            serve_traces.append(rec)
        elif kind == "serve_summary":
            serve_summary = rec
        elif kind == "fault":
            faults.append(rec)
        elif kind == "resume":
            resumes.append(rec)
        elif kind == "router_window":
            router_windows.append(rec)
        elif kind == "router_summary":
            router_summary = rec
        elif kind == "router_trace":
            router_traces.append(rec)
        elif kind == "trace_stitch":
            trace_stitches.append(rec)
        elif kind == "fleet_event":
            fleet_events.append(rec)
        elif kind == "registry_event":
            registry_events.append(rec)
        elif kind == "rollout_window":
            rollout_windows.append(rec)
        elif kind == "scale_event":
            scale_events.append(rec)
        elif kind == "obs_scrape":
            obs_scrapes.append(rec)
        elif kind == "obs_fleet_window":
            obs_windows.append(rec)
        elif kind == "profile_window":
            profile_windows.append(rec)
        elif kind == "compile_cost":
            compile_costs.append(rec)
        elif kind == "run_summary":
            run_summary = rec

    out: dict = {"name": name, "records": n_records}

    if windows:
        steps = sum(int(w.get("window_steps", 0)) for w in windows)
        wall = sum(
            int(w["window_steps"]) / float(w["steps_per_sec"])
            for w in windows
            if w.get("steps_per_sec") and float(w["steps_per_sec"]) > 0)
        out["steps"] = steps
        out["windows"] = len(windows)
        if wall > 0:
            out["wall_s"] = round(wall, 3)
            out["steps_per_sec"] = round(steps / wall, 4)
        for key in ("step_p50_s", "data_wait_p50_s", "h2d_wait_p50_s",
                    "host_p50_s", "device_p50_s"):
            med = _weighted_median(
                [(float(w[key]), int(w.get("window_steps", 1)))
                 for w in windows if key in w])
            if med is not None:
                out[key] = round(med, 6)
        # The step-0 compile lands in the FIRST window (its tail AND its
        # wall-basis MFU), so a cold run diffed against a warm baseline
        # would flag bogus p95/MFU regressions that are only cache
        # temperature; with more than one window the steady-state tail
        # is what the gate should compare.
        tail = windows[1:] if len(windows) > 1 else windows
        p95s = [float(w["step_p95_s"]) for w in tail if "step_p95_s" in w]
        if p95s:
            out["step_p95_s"] = round(max(p95s), 6)
        # Checkpoint-step accounting (step_timer.py note_ckpt_stall):
        # steps that carried a save, with the save's host stall folded in.
        # Aggregated over ALL windows — saves are sparse, and dropping the
        # first window could drop the only flagged one in a short run.
        # ``ckpt_step_p95_s`` vs ``step_p95_s`` is the async-checkpoint
        # acceptance comparison (docs/telemetry.md): blocking saves hold
        # it at a multiple of the steady-state tail; async saves collapse
        # it toward parity.
        ckpt_windows = [w for w in windows if w.get("ckpt_steps")]
        if ckpt_windows:
            out["ckpt_steps"] = sum(
                int(w["ckpt_steps"]) for w in ckpt_windows)
            vals = [float(w["ckpt_step_p95_s"]) for w in ckpt_windows
                    if "ckpt_step_p95_s" in w]
            if vals:
                out["ckpt_step_p95_s"] = round(max(vals), 6)
        mfus = [(float(w["mfu"]), int(w.get("window_steps", 1)))
                for w in tail
                if w.get("mfu") and w.get("mfu_basis") not in (None, "none")]
        if mfus:
            total_w = sum(w for _, w in mfus)
            out["mfu"] = round(
                sum(v * w for v, w in mfus) / total_w, 4)
        # Padding-aware accounting (step_timer.py): steady-state real-token
        # rate and the window-weighted padding efficiency it divides by.
        effs = [(float(w["padding_efficiency"]),
                 int(w.get("window_steps", 1)))
                for w in tail if w.get("padding_efficiency")]
        if effs:
            total_w = sum(w for _, w in effs)
            out["padding_efficiency"] = round(
                sum(v * w for v, w in effs) / total_w, 4)
        tok = _weighted_median(
            [(float(w["tokens_per_s"]), int(w.get("window_steps", 1)))
             for w in tail
             if w.get("tokens_per_s")
             and w.get("tokens_per_s_basis") == "real"])
        if tok is not None:
            out["tokens_per_s"] = round(tok, 2)

    if compiles:
        by_cache: dict = {}
        for rec in compiles:
            by_cache[rec.get("cache", "?")] = (
                by_cache.get(rec.get("cache", "?"), 0) + 1)
        out["compiles"] = len(compiles)
        out["compile_s"] = round(
            sum(float(rec.get("compile_s", 0.0)) for rec in compiles), 3)
        out["compile_cache"] = by_cache
        out["cold_start"] = bool(
            by_cache.get("miss", 0) + by_cache.get("uncached", 0))

    out["nonfinite_steps"] = len(sentinels)
    if sentinels:
        out["nonfinite_max_consecutive"] = max(
            int(rec.get("consecutive_nonfinite", 1)) for rec in sentinels)

    out["divergence_warnings"] = len(divergences)
    if divergences:
        out["divergence_reasons"] = sorted(
            {rec.get("reason", "?") for rec in divergences})

    if grad_health:
        norms = [float(rec["grad_norm"]) for rec in grad_health
                 if rec.get("grad_norm") is not None]
        ratios = [float(rec["update_ratio"]) for rec in grad_health
                  if rec.get("update_ratio") is not None]
        out["grad_health_records"] = len(grad_health)
        if norms:
            out["grad_norm_last"] = round(norms[-1], 6)
            out["grad_norm_max"] = round(max(norms), 6)
        if ratios:
            out["update_ratio_last"] = round(ratios[-1], 8)
            out["update_ratio_max"] = round(max(ratios), 8)

    supported = [rec for rec in memory if rec.get("memory_supported")]
    if memory:
        out["memory_supported"] = bool(supported)
    if supported:
        out["peak_bytes_in_use"] = max(
            int(rec.get("peak_bytes_in_use", 0)) for rec in supported)
        out["bytes_in_use_last"] = int(supported[-1].get("bytes_in_use", 0))
        limits = [int(rec.get("bytes_limit", 0)) for rec in supported]
        if any(limits):
            out["bytes_limit"] = max(limits)

    # -- recovery section (docs/fault_tolerance.md) ---------------------
    # Fault/resume records are operational history, not performance: the
    # report names what went wrong (split injected vs real — a chaos-run
    # artifact full of injected faults is healthy) and what every resume
    # skipped, so "did the run recover cleanly" is answerable offline.
    if faults:
        out["faults"] = len(faults)
        out["faults_injected"] = sum(
            1 for rec in faults if rec.get("injected"))
        out["fault_kinds"] = sorted(
            {str(rec.get("fault", "?")) for rec in faults})
    if resumes:
        out["resumes"] = len(resumes)
        out["resume_last_step"] = int(resumes[-1].get("step", 0))
        skipped = [entry for rec in resumes
                   for entry in (rec.get("skipped") or [])]
        out["resume_skipped_checkpoints"] = len(skipped)
        if skipped:
            out["resume_skipped_steps"] = sorted(
                {int(entry.get("step", -1)) for entry in skipped})

    # -- serve record family (serve/stats.py, docs/serving.md) ----------
    # The serve_summary record carries exact run-level percentiles; when a
    # run died before finish(), fall back to aggregating the windows with
    # the step-window conventions (weighted-median p50, max-of-window
    # tails — a latency spike anywhere in the run must not average away).
    if serve_summary is not None:
        for src, dst in (("requests", "serve_requests"),
                         ("requests_per_sec", "serve_rps"),
                         ("latency_p50_ms", "serve_latency_p50_ms"),
                         ("latency_p95_ms", "serve_latency_p95_ms"),
                         ("latency_p99_ms", "serve_latency_p99_ms"),
                         ("device_p50_ms", "serve_device_p50_ms"),
                         ("batch_occupancy", "serve_occupancy"),
                         ("compiles", "serve_compiles"),
                         ("errors", "serve_errors"),
                         # Continuous-batching gauges (docs/serving.md):
                         # the executor-gap share behind the "serve
                         # device idle share" gate, and the
                         # admission-window win count.
                         ("device_idle_share", "serve_device_idle_share"),
                         ("admitted_late", "serve_admitted_late")):
            if serve_summary.get(src) is not None:
                out[dst] = serve_summary[src]
    elif serve_windows:
        reqs = sum(int(w.get("window_requests", 0)) for w in serve_windows)
        out["serve_requests"] = reqs
        p50 = _weighted_median(
            [(float(w["latency_p50_ms"]), int(w.get("window_requests", 1)))
             for w in serve_windows if "latency_p50_ms" in w])
        if p50 is not None:
            out["serve_latency_p50_ms"] = round(p50, 3)
        for pct in ("p95", "p99"):
            vals = [float(w[f"latency_{pct}_ms"]) for w in serve_windows
                    if f"latency_{pct}_ms" in w]
            if vals:
                out[f"serve_latency_{pct}_ms"] = round(max(vals), 3)
        occs = [(float(w["batch_occupancy"]),
                 int(w.get("window_requests", 1)))
                for w in serve_windows if w.get("batch_occupancy")]
        if occs:
            total_w = sum(w for _, w in occs)
            out["serve_occupancy"] = round(
                sum(v * w for v, w in occs) / total_w, 4)
        out["serve_compiles"] = sum(
            int(w.get("compiles", 0)) for w in serve_windows)
        out["serve_admitted_late"] = sum(
            int(w.get("admitted_late", 0)) for w in serve_windows)
        # Window fallback for the executor-gap share: request-weighted
        # mean (each window's share already normalizes by its own busy
        # basis; a dead-air window anywhere must still pull the run's
        # number up, which a min/max would over- or under-state).
        idles = [(float(w["device_idle_share"]),
                  int(w.get("window_requests", 1)))
                 for w in serve_windows
                 if w.get("device_idle_share") is not None]
        if idles:
            total_w = sum(w for _, w in idles)
            out["serve_device_idle_share"] = round(
                sum(v * w for v, w in idles) / total_w, 4)

    # -- request-tracing section (serve/tracing.py, docs/serving.md) ----
    # serve_phase windows carry the latency DECOMPOSITION the coarse
    # serve_window records can't: where a request's time went (queue vs
    # execute vs postprocess), the queue-wait share a router balances
    # on, and the rolling-window SLO accounting. Aggregation follows the
    # step-window conventions: request-weighted means for shares, max
    # over windows for tails (a p99 breach anywhere in the run must not
    # average away).
    if serve_phases:
        reqs = sum(int(w.get("window_requests", 1)) for w in serve_phases)
        shares = [(float(w["queue_wait_share"]),
                   int(w.get("window_requests", 1)))
                  for w in serve_phases if "queue_wait_share" in w]
        if shares:
            total_w = sum(w for _, w in shares)
            out["serve_queue_wait_share"] = round(
                sum(v * w for v, w in shares) / total_w, 4)
        for phase in ("queue", "assembly", "execute", "postprocess"):
            vals = [float(w[f"{phase}_p95_ms"]) for w in serve_phases
                    if f"{phase}_p95_ms" in w]
            if vals:
                out[f"serve_{phase}_p95_ms"] = round(max(vals), 3)
        p99s = [float(w["total_p99_ms"]) for w in serve_phases
                if "total_p99_ms" in w]
        if p99s:
            # The metric behind the "serve SLO p99" gate: worst window
            # tail of the traced decomposition.
            out["serve_slo_p99_ms"] = round(max(p99s), 3)
        targets = [float(w["slo_target_ms"]) for w in serve_phases
                   if w.get("slo_target_ms")]
        if targets:
            target = targets[-1]
            over = sum(int(w.get("over_slo", 0)) for w in serve_phases)
            budgets = [float(w["slo_budget"]) for w in serve_phases
                       if w.get("slo_budget")]
            budget_frac = budgets[-1] if budgets else 0.01
            out["serve_slo_target_ms"] = target
            out["serve_slo_over"] = over
            allowed = budget_frac * reqs
            if allowed > 0:
                # >1 = the error budget for this run is spent.
                out["serve_slo_budget_burn"] = round(over / allowed, 4)
            p99 = out.get("serve_slo_p99_ms")
            out["serve_slo_verdict"] = (
                "breach" if (p99 is not None and p99 > target)
                or out.get("serve_slo_budget_burn", 0) > 1.0 else "ok")
    if serve_traces:
        out["serve_traces"] = len(serve_traces)
        out["serve_traces_slow"] = sum(
            1 for t in serve_traces if t.get("sample_reason") == "slow")
        # Critical path of the slowest decile: among the worst 10% of
        # sampled traces by total latency, which phase dominated each —
        # the "what do I fix first" summary ("The Tail at Scale").
        by_total = sorted(
            (t for t in serve_traces if t.get("spans")),
            key=lambda t: float(t.get("total_ms", 0.0)), reverse=True)
        decile = by_total[: max(1, len(by_total) // 10)] if by_total else []
        path: dict = {}
        for t in decile:
            spans = [s for s in t["spans"]
                     if isinstance(s, dict) and "dur_ms" in s]
            if not spans:
                continue
            worst = max(spans, key=lambda s: float(s["dur_ms"]))
            path[worst["name"]] = path.get(worst["name"], 0) + 1
        if path:
            out["serve_critical_path"] = dict(
                sorted(path.items(), key=lambda kv: -kv[1]))

    if serve_cold_starts:
        # A multi-start artifact (e.g. the BENCH_SERVE quant leg runs
        # fp32 then int8 engines) gates on the WORST start; the cold
        # compile count sums — the warm-restart acceptance is "zero cold
        # compiles", and any start that compiled breaks it.
        out["serve_cold_start_s"] = round(max(
            float(r.get("cold_start_s", 0.0)) for r in serve_cold_starts), 3)
        out["serve_compiles_cold"] = sum(
            int(r.get("compiles_cold", 0)) for r in serve_cold_starts)
        out["serve_compiles_warm"] = sum(
            int(r.get("compiles_warm", 0)) for r in serve_cold_starts)
        modes = sorted({str(r["quantize"]) for r in serve_cold_starts
                        if r.get("quantize")})
        if modes:
            out["serve_quantize"] = ",".join(modes)

    # -- fleet record family (serve/router.py, serve/supervisor.py) -----
    # Router traffic follows the serve conventions: the run-level
    # router_summary is exact when the router stopped cleanly; otherwise
    # aggregate the windows (sums for counters, weighted-median p50,
    # max for tails — a failover spike anywhere in the run must not
    # average away). ``router_failover_p95_ms`` is the metric behind the
    # "router failover" gate: the client-visible latency of requests
    # that needed a different replica than first chosen.
    if router_summary is not None:
        for src, dst in (("requests", "router_requests"),
                         ("ok", "router_ok"),
                         ("sheds", "router_sheds"),
                         ("errors", "router_errors"),
                         ("retries", "router_retries"),
                         ("hedges", "router_hedges"),
                         ("hedge_wins", "router_hedge_wins"),
                         ("hedge_wasted_ms", "router_hedge_wasted_ms"),
                         ("failovers", "router_failovers"),
                         ("latency_p50_ms", "router_latency_p50_ms"),
                         ("latency_p95_ms", "router_latency_p95_ms"),
                         ("failover_p95_ms", "router_failover_p95_ms")):
            if router_summary.get(src) is not None:
                out[dst] = router_summary[src]
    elif router_windows:
        for src, dst in (("window_requests", "router_requests"),
                         ("ok", "router_ok"),
                         ("sheds", "router_sheds"),
                         ("errors", "router_errors"),
                         ("retries", "router_retries"),
                         ("hedges", "router_hedges"),
                         ("hedge_wins", "router_hedge_wins"),
                         ("failovers", "router_failovers")):
            out[dst] = sum(int(w.get(src, 0)) for w in router_windows)
        wasted = sum(float(w.get("hedge_wasted_ms", 0.0))
                     for w in router_windows)
        if wasted or out.get("router_hedges"):
            out["router_hedge_wasted_ms"] = round(wasted, 3)
        p50 = _weighted_median(
            [(float(w["latency_p50_ms"]), int(w.get("window_requests", 1)))
             for w in router_windows if "latency_p50_ms" in w])
        if p50 is not None:
            out["router_latency_p50_ms"] = round(p50, 3)
        for key, dst in (("latency_p95_ms", "router_latency_p95_ms"),
                         ("failover_p95_ms", "router_failover_p95_ms")):
            vals = [float(w[key]) for w in router_windows if key in w]
            if vals:
                out[dst] = round(max(vals), 3)
    # -- end-to-end trace section (telemetry/collector.py stitching,
    # docs/observability.md "Trace propagation") ------------------------
    # trace_stitch records decompose each sampled client request into
    # router overhead + network gap + replica time. Shares are
    # aggregate ratios (sum of parts over sum of client totals), NOT
    # means of per-trace ratios — a 1 ms request with 50% overhead must
    # not outweigh a 100 ms request with 5%. ``trace_orphans`` counts
    # the stitches whose other tier never showed up: zero on a healthy
    # fleet, and any new one is the propagation or the collector
    # breaking (the "orphan span share" gate).
    if router_traces:
        out["router_traces"] = len(router_traces)
    if trace_stitches:
        out["trace_stitches"] = len(trace_stitches)
        orphans = sum(1 for s in trace_stitches if s.get("orphan"))
        out["trace_orphans"] = orphans
        out["trace_orphan_share"] = round(
            orphans / len(trace_stitches), 4)
        complete = [s for s in trace_stitches
                    if not s.get("orphan")
                    and s.get("client_total_ms") is not None
                    and s.get("router_overhead_ms") is not None
                    and s.get("replica_ms") is not None]
        total = sum(float(s["client_total_ms"]) for s in complete)
        if complete and total > 0:
            out["trace_router_overhead_share"] = round(
                sum(float(s["router_overhead_ms"]) for s in complete)
                / total, 4)
            out["trace_network_gap_share"] = round(
                sum(max(0.0, float(s.get("network_gap_ms", 0.0)))
                    for s in complete) / total, 4)
            out["trace_replica_share"] = round(
                sum(float(s["replica_ms"]) for s in complete) / total, 4)
        inconsistent = sum(1 for s in complete
                           if s.get("consistent") is False)
        if inconsistent:
            out["trace_inconsistent"] = inconsistent
        # Cross-tier critical path of the slowest decile: which TIER
        # dominated each of the worst 10% of stitched requests — and
        # when the replica did, its own dominant phase (carried on the
        # stitch record) names the hop, so "where do I look first" spans
        # tiers in one answer.
        by_total = sorted(complete,
                          key=lambda s: float(s["client_total_ms"]),
                          reverse=True)
        decile = by_total[: max(1, len(by_total) // 10)] if by_total \
            else []
        path: dict = {}
        for s in decile:
            parts = {
                "router_overhead": float(s["router_overhead_ms"]),
                "network_gap": max(0.0,
                                   float(s.get("network_gap_ms", 0.0))),
                "replica": float(s["replica_ms"]),
            }
            worst = max(parts, key=parts.get)
            if worst == "replica" and s.get("replica_critical_phase"):
                worst = f"replica:{s['replica_critical_phase']}"
            path[worst] = path.get(worst, 0) + 1
        if path:
            out["trace_critical_path"] = dict(
                sorted(path.items(), key=lambda kv: -kv[1]))

    # Supervisor history: operational counts by decision type — "how
    # often did something need restarting, and did anything get given up
    # on" is answerable offline from the artifact alone.
    if fleet_events:
        out["fleet_events"] = len(fleet_events)
        by_event: dict = {}
        for rec in fleet_events:
            name = str(rec.get("event", "?"))
            by_event[name] = by_event.get(name, 0) + 1
        out["fleet_event_kinds"] = dict(sorted(by_event.items()))
        out["fleet_spawns"] = by_event.get("spawn", 0)
        out["fleet_crash_restarts"] = sum(
            1 for rec in fleet_events
            if rec.get("event") == "restart_scheduled" and rec.get("crash"))
        out["fleet_wedged_kills"] = by_event.get("wedged_kill", 0)
        out["fleet_gave_up"] = by_event.get("gave_up", 0)
        out["fleet_swap_failures"] = by_event.get("swap_failed", 0)

    # -- deployment plane (serve/registry.py, serve/rollout.py, docs/
    # serving.md "Model registry & canary rollouts") ---------------------
    # rollout_window records are the canary's per-window SLO evidence;
    # the two counters behind the zero-tolerance gates are breaches
    # (slo_ok false anywhere) and torn serves (a request observed a
    # params flip mid-execution — structurally impossible under the
    # engine's atomic swap, which is exactly why telemetry counts it).
    if registry_events:
        out["registry_events"] = len(registry_events)
        by_ev: dict = {}
        for rec in registry_events:
            name = str(rec.get("event", "?"))
            by_ev[name] = by_ev.get(name, 0) + 1
        out["registry_event_kinds"] = dict(sorted(by_ev.items()))
        out["registry_rollbacks"] = sum(
            1 for rec in registry_events
            if rec.get("event") == "state_change"
            and rec.get("from_state") == "canary"
            and rec.get("state") == "staged")
    if rollout_windows:
        out["rollout_windows"] = len(rollout_windows)
        out["rollout_slo_breaches"] = sum(
            1 for w in rollout_windows if w.get("slo_ok") is False)
        out["rollout_rollbacks"] = sum(
            1 for w in rollout_windows if w.get("action") == "rollback")
        out["rollout_torn_serves"] = sum(
            int(w.get("torn_serves", 0)) for w in rollout_windows)
        out["rollout_max_share"] = max(
            float(w.get("canary_share", 0.0)) for w in rollout_windows)
        out["rollout_final_action"] = str(
            rollout_windows[-1].get("action", "?"))
        canary_reqs = sum(int(w.get("window_requests", 0))
                          for w in rollout_windows)
        out["rollout_canary_requests"] = canary_reqs
        p95s = [float(w["latency_p95_ms"]) for w in rollout_windows
                if w.get("latency_p95_ms") is not None]
        if p95s:
            out["rollout_canary_p95_ms"] = round(max(p95s), 3)
        burns = [float(w["budget_burn"]) for w in rollout_windows
                 if w.get("budget_burn") is not None]
        if burns:
            out["rollout_budget_burn"] = round(max(burns), 4)

    # -- elasticity plane section (serve/autoscaler.py, docs/serving.md
    # "Elastic fleet") ---------------------------------------------------
    # scale_event records are the autoscaler's decision stream. Two
    # zero-tolerance gates read it: "autoscaler thrash" (a direction
    # flip inside the cooldown window it is accountable to — the
    # controller's shared last-scale timestamp makes this structurally
    # impossible, so any occurrence is a control-loop bug) and "surge
    # client-visible errors" (elasticity must never burn a client
    # request; the controller's windows see every router error).
    if scale_events:
        out["scale_events"] = len(scale_events)
        by_dec: dict = {}
        for rec in scale_events:
            name = str(rec.get("decision", "?"))
            by_dec[name] = by_dec.get(name, 0) + 1
        out["scale_decision_kinds"] = dict(sorted(by_dec.items()))
        out["autoscaler_scale_ups"] = by_dec.get("scale_up", 0)
        out["autoscaler_scale_downs"] = by_dec.get("scale_down", 0)
        counts = [int(rec.get("replicas_after", 0))
                  for rec in scale_events]
        out["autoscaler_replicas_max"] = max(counts)
        out["autoscaler_replicas_last"] = counts[-1]
        thrash = 0
        last_dir = None
        for rec in scale_events:
            decision = rec.get("decision")
            if decision not in ("scale_up", "scale_down"):
                continue
            since = rec.get("since_last_scale_s")
            cool = rec.get("cooldown_s")
            if (last_dir is not None and decision != last_dir
                    and since is not None and cool is not None
                    and float(since) < float(cool)):
                thrash += 1
            last_dir = decision
        out["autoscaler_thrash"] = thrash
        out["surge_client_errors"] = sum(
            int(rec.get("window_errors", 0) or 0)
            for rec in scale_events)
        out["surge_sheds"] = sum(
            int(rec.get("window_sheds", 0) or 0)
            for rec in scale_events)

    # -- fleet observatory section (telemetry/collector.py, docs/
    # observability.md) --------------------------------------------------
    # The collector's timeline carries per-target scrape samples and
    # per-pass fleet aggregates. Aggregation follows the house
    # conventions: max over samples for staleness and worst-replica p99
    # (a dead scrape or a latency cliff anywhere in the run must not
    # average away), min over windows for the healthy count (the dip is
    # the signal), weighted medians for rates.
    if obs_scrapes:
        out["obs_scrapes"] = len(obs_scrapes)
        out["obs_targets"] = len({str(r.get("target")) for r in obs_scrapes})
        out["obs_scrape_failures"] = sum(
            1 for r in obs_scrapes if not r.get("ok"))
        stale = [float(r["staleness_s"]) for r in obs_scrapes
                 if r.get("staleness_s") is not None]
        if stale:
            # The metric behind the "fleet scrape staleness" gate.
            out["fleet_scrape_staleness_s"] = round(max(stale), 3)
    if obs_windows:
        out["fleet_windows"] = len(obs_windows)
        out["fleet_targets"] = max(
            int(w.get("targets_total", 0)) for w in obs_windows)
        out["fleet_healthy_min"] = min(
            int(w.get("targets_healthy", 0)) for w in obs_windows)
        p99s = [float(w["worst_replica_p99_ms"]) for w in obs_windows
                if w.get("worst_replica_p99_ms") is not None]
        if p99s:
            # The metric behind the "fleet worst-replica p99" gate.
            out["fleet_worst_replica_p99_ms"] = round(max(p99s), 3)
        rps = _weighted_median(
            [(float(w["fleet_rps"]), 1) for w in obs_windows
             if w.get("fleet_rps") is not None])
        if rps is not None:
            out["fleet_rps"] = round(rps, 3)
        rates = _weighted_median(
            [(float(w["trainer_steps_per_sec"]), 1) for w in obs_windows
             if w.get("trainer_steps_per_sec") is not None])
        if rates is not None:
            out["fleet_trainer_steps_per_sec"] = round(rates, 4)
        burns = [float(w["error_budget_burn"]) for w in obs_windows
                 if w.get("error_budget_burn") is not None]
        if burns:
            out["fleet_error_budget_burn"] = round(max(burns), 4)

    # -- profiling plane section (telemetry/sampler.py, docs/
    # observability.md "Profiling plane") -------------------------------
    # profile_window records carry the HOST view (thread-sampler self
    # time) of each on-demand capture; compile_cost records carry the
    # DEVICE view (static FLOP/byte attribution per jitted entry point).
    # The join names the dominant cost per phase in one place: the
    # hottest host frame across every capture, and the heaviest
    # compiled function it was feeding.
    if profile_windows:
        out["profile_windows"] = len(profile_windows)
        out["profile_samples"] = sum(
            int(w.get("samples", 0)) for w in profile_windows)
        out["profile_trace_bytes"] = sum(
            int(w.get("trace_bytes", 0)) for w in profile_windows)
        sources = sorted({str(w.get("source", "?"))
                          for w in profile_windows})
        out["profile_sources"] = ",".join(sources)
        covered: dict = {}
        for w in profile_windows:
            unit = str(w.get("covered_unit", "?"))
            covered[unit] = covered.get(unit, 0) + int(w.get("covered", 0))
        out["profile_covered"] = dict(sorted(covered.items()))
        # Aggregate host self time per leaf frame across every capture
        # (sample counts are comparable: all captures share the wall
        # clock, and a frame hot in two windows is hotter than one).
        frames: dict = {}
        for w in profile_windows:
            for row in w.get("top_frames") or []:
                if not isinstance(row, dict):
                    continue
                key = str(row.get("frame", "?"))
                frames[key] = frames.get(key, 0) + int(row.get("samples", 0))
        if frames:
            top = sorted(frames.items(), key=lambda kv: (-kv[1], kv[0]))
            out["profile_host_frames"] = dict(top[:5])
            out["profile_critical_host"] = top[0][0]
    if compile_costs:
        # The device side of the join: heaviest analyzed executable by
        # static FLOPs (bytes accessed breaks ties — a bandwidth-bound
        # fn can dominate at modest FLOPs).
        def _cost(rec):
            return (float(rec.get("flops", 0.0) or 0.0),
                    float(rec.get("bytes_accessed", 0.0) or 0.0))

        heaviest = max(compile_costs, key=_cost)
        if _cost(heaviest) > (0.0, 0.0):
            out["profile_critical_device"] = str(heaviest.get("fn", "?"))

    if run_summary:
        for key, value in run_summary.items():
            if key in ("schema", "ts", "kind", "tag"):
                continue
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                out.setdefault(key, value)
            elif key == "metric" and isinstance(value, str):
                # Bench runs stamp their config's metric name; consumers
                # (bench.py's regression gate) use it to refuse diffing
                # incomparable configurations.
                out.setdefault("metric", value)
    return out


# (summary key, pretty name, direction, tolerance key). Direction "up"
# means a larger NEW value is the regression.
_CHECKS = (
    ("step_p50_s", "step-time p50", "up", "step"),
    ("step_p95_s", "step-time p95", "up", "p95"),
    # Checkpoint-step tail: the number async checkpoint snapshots exist to
    # collapse — a revert to blocking saves trips this by name.
    ("ckpt_step_p95_s", "checkpoint-step p95", "up", "p95"),
    ("steps_per_sec", "throughput (steps/s)", "down", "step"),
    ("training_seq_per_sec", "training seq/s", "down", "step"),
    ("mfu", "MFU", "down", "mfu"),
    ("peak_bytes_in_use", "peak device memory", "up", "mem"),
    ("grad_norm_max", "grad-norm envelope", "up", "grad"),
    ("update_ratio_max", "update-ratio envelope", "up", "grad"),
    # serve record family (docs/serving.md): p95 is the tail gate; p50
    # is the INFERENCE-FAST-PATH gate — the quantized/fused-kernel work
    # targets the median forward, and a p50 regression there is the
    # optimization silently reverting even while the tail stays in tol.
    ("serve_latency_p50_ms", "serve p50 latency", "up", "p95"),
    ("serve_latency_p95_ms", "serve p95 latency", "up", "p95"),
    ("serve_rps", "serve throughput (req/s)", "down", "step"),
    ("serve_occupancy", "serve batch occupancy", "down", "step"),
    # Request-tracing gates (serve/tracing.py): the queue-wait share is
    # the admission-control signal — a dispatch/batching change that
    # parks requests in the queue moves it even when the device time is
    # unchanged; the SLO p99 is the worst traced-window tail, the number
    # the serving SLO is written against.
    ("serve_queue_wait_share", "serve queue-wait share", "up", "p95"),
    ("serve_slo_p99_ms", "serve SLO p99", "up", "p95"),
    # Continuous-batching gate (docs/serving.md "Continuous batching"):
    # the executor-gap (device idle) share between consecutive jitted
    # forwards. The pipelined dispatch plane exists to hold this down —
    # a regression means the device is idling through host-side
    # assembly/decode again (e.g. the pipeline silently serialized),
    # even when per-request latency still looks fine at low load.
    ("serve_device_idle_share", "serve device idle share", "up", "p95"),
    # Cold start: the persisted-AOT-cache win. A regression here means a
    # restarted replica is recompiling (cache key drift — e.g. a renamed
    # forward — or the persistence bar filtering serve executables).
    ("serve_cold_start_s", "serve cold start", "up", "p95"),
    # Fleet-tier gates (serve/router.py, docs/serving.md "Fleet tier"):
    # the "router failover" gate is the resilience acceptance — the
    # client-visible latency of requests that had to fail over to a
    # different replica. It growing past tolerance means recovery is
    # slipping (retry backoff too slow, health gate too stale, hedge not
    # firing) even while the healthy-path latency stays flat.
    ("router_failover_p95_ms", "router failover p95", "up", "p95"),
    ("router_latency_p95_ms", "router p95 latency", "up", "p95"),
    # Fleet observatory gates (telemetry/collector.py): staleness is
    # the collector's own health — a growing max means some endpoint
    # stopped answering (or the collector stopped keeping up), exactly
    # the blind spot the observatory exists to close; worst-replica p99
    # is the fleet-level tail the router's balancing is supposed to
    # hold down even while a replica dies and recovers.
    ("fleet_scrape_staleness_s", "fleet scrape staleness", "up", "p95"),
    ("fleet_worst_replica_p99_ms", "fleet worst-replica p99", "up", "p95"),
    # End-to-end trace gate (telemetry/collector.py stitching): the
    # router's share of each stitched request's client-observed total.
    # It growing means time moved INTO the routing tier — admission
    # queueing, retry backoff, hedge management — which per-tier p95s
    # can miss entirely when the replica got faster at the same time.
    ("trace_router_overhead_share", "router overhead share", "up", "p95"),
)


def compare(base: dict, new: dict, tolerances: Optional[dict] = None):
    """(regressions, checks): every comparable metric with a verdict.

    A check only runs when BOTH summaries carry the metric with a
    nonzero baseline — a metric appearing or disappearing (e.g. MFU on
    CPU) is reported as an ``"n/a"`` check, not a regression.
    """
    tol = dict(DEFAULT_TOLERANCES)
    tol.update(tolerances or {})
    checks = []
    regressions = []
    for key, label, direction, tol_key in _CHECKS:
        b, n = base.get(key), new.get(key)
        if b is None or n is None or not b:
            if b is not None or n is not None:
                checks.append({"metric": key, "label": label,
                               "verdict": "n/a", "base": b, "new": n})
            continue
        rel = (n - b) / abs(b)
        worse = rel > tol[tol_key] if direction == "up" \
            else rel < -tol[tol_key]
        entry = {
            "metric": key, "label": label, "base": b, "new": n,
            "change": round(rel, 4), "tolerance": tol[tol_key],
            "verdict": "regression" if worse else "ok",
        }
        checks.append(entry)
        if worse:
            regressions.append(entry)
    # Health counters: any NEW occurrence where the baseline had none is
    # a regression regardless of tolerance. serve_compiles_cold rides
    # here too: a warm-cache baseline (0 cold compiles) against a run
    # that recompiled is the cold-start acceptance breaking, no matter
    # how fast the recompiles happened to be.
    # router_errors (exhausted failover: a client saw a 5xx) and
    # fleet_gave_up (a replica crash-looped past the restart budget) are
    # zero in any healthy run, so any new occurrence is a regression.
    # trace_orphans rides the zero-tolerance loop (not the ratio
    # checks): a clean baseline has ZERO orphans, which the ratio path
    # would wave through as "n/a" — while a single new orphan means a
    # span went missing between tiers, which is exactly the regression
    # the "orphan span share" gate exists to name.
    # The deployment-plane pair rides here too: a canary window that
    # breached its SLO (rollout_slo_breaches) or a single torn-model
    # serve (rollout_torn_serves) is zero on any healthy rollout — the
    # breach gate is what the auto-rollback E2E proves fires, and the
    # torn gate is the atomic-swap invariant made falsifiable.
    # The elasticity-plane pair: a direction flip inside the cooldown
    # window (autoscaler_thrash) is structurally impossible under the
    # controller's shared last-scale timestamp, and a client-visible
    # error during elastic capacity change (surge_client_errors) means
    # scaling burned a request — both zero on any healthy surge, proven
    # live by tools/chaos_serve.py --surge.
    for key, label in (("nonfinite_steps", "non-finite steps"),
                       ("divergence_warnings", "divergence warnings"),
                       ("serve_compiles_cold", "serve cold compiles"),
                       ("router_errors", "router client-visible errors"),
                       ("fleet_gave_up", "fleet replicas given up"),
                       ("trace_orphans", "orphan span share"),
                       ("rollout_slo_breaches", "rollout canary SLO"),
                       ("rollout_torn_serves",
                        "rollout torn-model serves"),
                       ("autoscaler_thrash", "autoscaler thrash"),
                       ("surge_client_errors",
                        "surge client-visible errors")):
        b, n = int(base.get(key, 0)), int(new.get(key, 0))
        if n > b:
            entry = {"metric": key, "label": label, "base": b, "new": n,
                     "verdict": "regression"}
            checks.append(entry)
            regressions.append(entry)
        elif b or n:
            checks.append({"metric": key, "label": label, "base": b,
                           "new": n, "verdict": "ok"})
    return regressions, checks


def _fmt_value(key, value):
    if value is None:
        return "-"
    if key.endswith("bytes_in_use") or key in ("bytes_limit",):
        return f"{value / (1 << 20):.1f} MiB"
    if isinstance(value, float):
        return f"{value:.6g}"
    return str(value)


def format_summary(summary: dict) -> str:
    lines = [f"== {summary.get('name') or 'telemetry'} "
             f"({summary.get('records', 0)} records)"]
    order = ("steps", "wall_s", "steps_per_sec", "step_p50_s", "step_p95_s",
             "ckpt_steps", "ckpt_step_p95_s",
             "data_wait_p50_s", "h2d_wait_p50_s", "host_p50_s",
             "device_p50_s", "mfu",
             "training_seq_per_sec", "padding_efficiency", "tokens_per_s",
             "real_tokens_per_sec",
             "serve_requests", "serve_rps", "serve_latency_p50_ms",
             "serve_latency_p95_ms", "serve_latency_p99_ms",
             "serve_device_p50_ms", "serve_occupancy", "serve_compiles",
             "serve_errors", "serve_admitted_late",
             "serve_device_idle_share",
             "serve_cold_start_s", "serve_compiles_cold",
             "serve_compiles_warm", "serve_quantize",
             "serve_queue_wait_share", "serve_queue_p95_ms",
             "serve_assembly_p95_ms", "serve_execute_p95_ms",
             "serve_postprocess_p95_ms", "serve_traces",
             "serve_traces_slow", "serve_slo_target_ms", "serve_slo_p99_ms",
             "serve_slo_over", "serve_slo_budget_burn", "serve_slo_verdict",
             "router_requests", "router_ok", "router_sheds",
             "router_errors", "router_retries", "router_hedges",
             "router_hedge_wins", "router_hedge_wasted_ms",
             "router_failovers",
             "router_latency_p50_ms", "router_latency_p95_ms",
             "router_failover_p95_ms",
             "router_traces", "trace_stitches", "trace_orphans",
             "trace_orphan_share", "trace_inconsistent",
             "trace_router_overhead_share", "trace_network_gap_share",
             "trace_replica_share",
             "fleet_events", "fleet_spawns", "fleet_crash_restarts",
             "fleet_wedged_kills", "fleet_gave_up", "fleet_swap_failures",
             "registry_events", "registry_rollbacks",
             "rollout_windows", "rollout_canary_requests",
             "rollout_max_share", "rollout_canary_p95_ms",
             "rollout_budget_burn", "rollout_slo_breaches",
             "rollout_rollbacks", "rollout_torn_serves",
             "rollout_final_action",
             "scale_events", "autoscaler_scale_ups",
             "autoscaler_scale_downs", "autoscaler_replicas_max",
             "autoscaler_replicas_last", "autoscaler_thrash",
             "surge_client_errors", "surge_sheds",
             "obs_scrapes", "obs_targets", "obs_scrape_failures",
             "fleet_windows", "fleet_targets", "fleet_healthy_min",
             "fleet_scrape_staleness_s", "fleet_worst_replica_p99_ms",
             "fleet_rps", "fleet_trainer_steps_per_sec",
             "fleet_error_budget_burn",
             "profile_windows", "profile_samples", "profile_trace_bytes",
             "profile_sources", "profile_critical_host",
             "profile_critical_device",
             "compiles", "compile_s", "cold_start",
             "nonfinite_steps", "divergence_warnings", "grad_norm_last",
             "grad_norm_max", "update_ratio_max", "memory_supported",
             "peak_bytes_in_use", "bytes_in_use_last", "bytes_limit",
             "faults", "faults_injected", "resumes", "resume_last_step",
             "resume_skipped_checkpoints")
    for key in order:
        if key in summary:
            lines.append(f"  {key:>22}: {_fmt_value(key, summary[key])}")
    if summary.get("serve_critical_path"):
        lines.append(f"  {'serve_critical_path':>22}: "
                     + ", ".join(f"{k}={v}" for k, v
                                 in summary["serve_critical_path"].items())
                     + " (dominant phase, slowest decile)")
    if summary.get("trace_critical_path"):
        lines.append(f"  {'trace_critical_path':>22}: "
                     + ", ".join(f"{k}={v}" for k, v
                                 in summary["trace_critical_path"].items())
                     + " (dominant tier, slowest decile)")
    if summary.get("profile_host_frames"):
        lines.append(f"  {'profile_host_frames':>22}: "
                     + ", ".join(f"{k}={v}" for k, v
                                 in summary["profile_host_frames"].items())
                     + " (host self-time samples)")
    if summary.get("profile_covered"):
        lines.append(f"  {'profile_covered':>22}: "
                     + ", ".join(f"{v} {k}" for k, v
                                 in summary["profile_covered"].items()))
    if summary.get("fleet_event_kinds"):
        lines.append(f"  {'fleet_event_kinds':>22}: "
                     + ", ".join(f"{k}={v}" for k, v
                                 in summary["fleet_event_kinds"].items()))
    if summary.get("registry_event_kinds"):
        lines.append(f"  {'registry_event_kinds':>22}: "
                     + ", ".join(
                         f"{k}={v}" for k, v
                         in summary["registry_event_kinds"].items()))
    if summary.get("fault_kinds"):
        lines.append(f"  {'fault_kinds':>22}: "
                     + ", ".join(summary["fault_kinds"]))
    if summary.get("resume_skipped_steps"):
        lines.append(f"  {'resume_skipped_steps':>22}: "
                     + ", ".join(map(str, summary["resume_skipped_steps"])))
    if summary.get("compile_cache"):
        lines.append(f"  {'compile_cache':>22}: "
                     + ", ".join(f"{k}={v}" for k, v
                                 in sorted(summary["compile_cache"].items())))
    if summary.get("divergence_reasons"):
        lines.append(f"  {'divergence_reasons':>22}: "
                     + ", ".join(summary["divergence_reasons"]))
    return "\n".join(lines)


def format_checks(checks) -> str:
    lines = []
    for c in checks:
        mark = {"regression": "REGRESSION", "ok": "ok", "n/a": "n/a"}[
            c["verdict"]]
        if "change" in c:
            lines.append(
                f"  {mark:>10} {c['label']}: "
                f"{_fmt_value(c['metric'], c['base'])} -> "
                f"{_fmt_value(c['metric'], c['new'])} "
                f"({c['change']:+.1%}, tolerance {c['tolerance']:.0%})")
        else:
            lines.append(
                f"  {mark:>10} {c['label']}: "
                f"{_fmt_value(c['metric'], c.get('base'))} -> "
                f"{_fmt_value(c['metric'], c.get('new'))}")
    return "\n".join(lines)


def _load_ledger():
    """Ledger module both ways (the collector's _load_schema pattern):
    package import when report.py was imported normally, sibling
    file-path import when report.py was itself loaded by path
    (tools/telemetry_report.py on a jax-free box)."""
    if __package__:
        import importlib

        return importlib.import_module(
            "bert_pytorch_tpu.telemetry.ledger")
    import importlib.util

    module = sys.modules.get("_report_ledger")
    if module is not None:
        return module
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "ledger.py")
    spec = importlib.util.spec_from_file_location("_report_ledger", path)
    module = importlib.util.module_from_spec(spec)
    sys.modules["_report_ledger"] = module
    spec.loader.exec_module(module)
    return module


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="telemetry-report",
        description="Summarize a telemetry JSONL artifact; with a "
                    "baseline, diff the two and exit 1 on regression "
                    "(docs/telemetry.md).")
    parser.add_argument("run", nargs="?", default=None,
                        help="telemetry JSONL of the run under test "
                             "(optional with --ledger: a bare drift "
                             "check over the existing trajectory)")
    parser.add_argument("baseline", nargs="?", default=None,
                        help="baseline telemetry JSONL to diff against")
    parser.add_argument("--baseline", dest="baseline_flag", default=None,
                        help="alternative spelling of the baseline path")
    parser.add_argument("--json", action="store_true",
                        help="legacy machine-readable output (summaries + "
                             "checks + verdict) instead of the human "
                             "tables (bench.py's regression attachment "
                             "depends on its exact keys; --format json "
                             "is the stable-contract successor)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text", dest="out_format",
                        help="output format; 'json' emits one stable "
                             "versioned object ({\"version\": 1, ..., "
                             "\"rc\": N} — the tools/check_all.py "
                             "contract)")
    parser.add_argument("--ledger", default=None, metavar="PATH",
                        help="longitudinal perf ledger JSONL "
                             "(telemetry/ledger.py): append the run "
                             "under test as a ledger_entry, then gate "
                             "the newest entry of every (leg, config) "
                             "trajectory against its rolling median — "
                             "'perf ledger drift' by name, exit 1")
    parser.add_argument("--ledger-leg", default="report",
                        help="ledger leg name for the appended entry "
                             "(default %(default)s)")
    parser.add_argument("--ledger-window", type=int, default=None,
                        help="rolling-median history depth per "
                             "trajectory (default: the ledger module's)")
    parser.add_argument("--ledger-tol", type=float, default=None,
                        help="relative drift tolerance vs the rolling "
                             "median (default: the ledger module's)")
    parser.add_argument("--no-ledger-append", action="store_true",
                        help="gate the existing trajectory without "
                             "appending the run under test")
    parser.add_argument("--last-run", action="store_true",
                        help="summarize only each artifact's FINAL run "
                             "(append-mode artifacts accumulate runs, "
                             "delimited by run_summary records; blending "
                             "them poisons the medians/maxima the "
                             "regression checks compare)")
    parser.add_argument("--step-tol", type=float,
                        default=DEFAULT_TOLERANCES["step"],
                        help="relative tolerance for step-time p50 and "
                             "throughput (default %(default)s)")
    parser.add_argument("--p95-tol", type=float,
                        default=DEFAULT_TOLERANCES["p95"],
                        help="relative tolerance for step-time p95")
    parser.add_argument("--mfu-tol", type=float,
                        default=DEFAULT_TOLERANCES["mfu"],
                        help="relative tolerance for MFU drop")
    parser.add_argument("--mem-tol", type=float,
                        default=DEFAULT_TOLERANCES["mem"],
                        help="relative tolerance for peak-memory growth")
    parser.add_argument("--grad-tol", type=float,
                        default=DEFAULT_TOLERANCES["grad"],
                        help="relative tolerance for the grad-health "
                             "envelopes (1.0 = 2x the baseline max)")
    args = parser.parse_args(argv)
    baseline = args.baseline_flag or args.baseline
    if args.run is None and not args.ledger:
        parser.error("need a run artifact (or --ledger for a bare "
                     "drift check)")
    if args.run is None and baseline is not None:
        parser.error("a baseline needs a run artifact to diff against")

    for path in filter(None, (args.run, baseline)):
        if not os.path.exists(path):
            print(f"telemetry-report: {path}: no such file")
            return 2
    new = summarize_file(args.run, last_run=args.last_run) \
        if args.run else None
    base = summarize_file(baseline, last_run=args.last_run) \
        if baseline else None
    regressions: list = []
    checks: list = []
    if base is not None and new is not None:
        tolerances = {"step": args.step_tol, "p95": args.p95_tol,
                      "mfu": args.mfu_tol, "mem": args.mem_tol,
                      "grad": args.grad_tol}
        regressions, checks = compare(base, new, tolerances)

    # -- perf ledger gate (telemetry/ledger.py, docs/telemetry.md) ------
    # Append the run under test (one ledger_entry per report run — the
    # trajectory is the point), then gate the NEWEST entry of every
    # (leg, config) trajectory against its rolling median: the named
    # "perf ledger drift" regression a single hand-picked baseline can
    # never catch (a slow drift walks in one in-tolerance step at a
    # time).
    ledger_info = None
    if args.ledger:
        ledger = _load_ledger()
        window = args.ledger_window if args.ledger_window is not None \
            else ledger.DEFAULT_WINDOW
        tol = args.ledger_tol if args.ledger_tol is not None \
            else ledger.DEFAULT_TOLERANCE
        appended = None
        if new is not None and not args.no_ledger_append:
            metrics = ledger.metrics_from_summary(new)
            appended = ledger.append_entry(
                args.ledger, args.ledger_leg, metrics,
                extra={"source": new.get("name") or args.run})
        entries = ledger.read_entries(args.ledger)
        findings = ledger.check_drift(entries, window=window,
                                      tolerance=tol)
        ledger_info = {"path": args.ledger, "entries": len(entries),
                       "appended": appended is not None,
                       "findings": findings}
        for f in findings:
            entry = {
                "metric": f"ledger:{f['leg']}:{f['metric']}",
                "label": "perf ledger drift",
                "base": f["median"], "new": f["latest"],
                "change": f["change"], "tolerance": f["tolerance"],
                "verdict": "regression",
            }
            checks.append(entry)
            regressions.append(entry)

    verdict = "regression" if regressions else "ok"
    rc = 1 if regressions else 0

    if args.out_format == "json":
        # The stable machine contract (tools/check_all.py's shape): one
        # versioned object, rc mirrored inside so a pipe consumer never
        # needs the process exit code.
        combined: dict = {"version": 1, "verdict": verdict,
                          "regressions": regressions, "checks": checks}
        if new is not None:
            combined["run"] = new
        if base is not None:
            combined["baseline"] = base
        if ledger_info is not None:
            combined["ledger"] = ledger_info
        combined["rc"] = rc
        print(json.dumps(combined, indent=2))
        return rc
    if args.json:
        # Legacy shapes, preserved exactly (bench.py parses them); the
        # ledger verdict rides as extra keys only when requested.
        if base is not None:
            out = {"verdict": verdict, "regressions": regressions,
                   "checks": checks, "run": new, "baseline": base}
        else:
            out = {"run": new} if new is not None else {}
            if args.ledger:
                out["verdict"] = verdict
                out["regressions"] = regressions
        if ledger_info is not None:
            out["ledger"] = ledger_info
        print(json.dumps(out))
        return rc

    if base is not None and new is not None:
        print(format_summary(base))
        print(format_summary(new))
        print(f"== regression check (run vs baseline: {verdict})")
        print(format_checks(checks))
    elif new is not None:
        print(format_summary(new))
    if ledger_info is not None:
        state = "DRIFT" if ledger_info["findings"] else "ok"
        print(f"== perf ledger ({ledger_info['path']}: "
              f"{ledger_info['entries']} entries, {state})")
        for f in ledger_info["findings"]:
            print(f"  REGRESSION perf ledger drift: "
                  f"{f['leg']}/{f['metric']} [{f['digest']}]: "
                  f"median {f['median']:g} -> {f['latest']:g} "
                  f"({f['change']:+.1%}, tolerance {f['tolerance']:.0%}, "
                  f"window {f['window']})")
    if regressions:
        names = ", ".join(dict.fromkeys(r["label"] for r in regressions))
        print(f"telemetry-report: REGRESSION in: {names}")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
