"""TrainTelemetry — the facade every runner threads its training loop
through (run_pretraining, run_squad, run_glue, run_ner, run_swag, bench.py).

One object owns the telemetry pieces and their lifecycle:

* a JSONL sink (``utils/logging.py JSONLHandler``) — registered with the
  global logger by the runner so ordinary train records land there too,
  while telemetry records go ONLY there (the CSV/stream sinks stay clean);
* a :class:`~bert_pytorch_tpu.telemetry.step_timer.StepTimer` for the
  data-wait / host-dispatch / device-compute decomposition + MFU windows;
* a :class:`~bert_pytorch_tpu.telemetry.profiler.ProfilerWindow` for
  bounded ``jax.profiler`` traces with per-step annotations;
* a :class:`~bert_pytorch_tpu.telemetry.sampler.CaptureController` — the
  on-demand profiling plane: ``POST /profilez`` on the introspection hub
  arms it from an HTTP thread; :meth:`TrainTelemetry.step_done` ticks it
  at each step boundary, starting/collecting the bounded host-sampler +
  trace capture and emitting the ``profile_window`` record;
* a :class:`~bert_pytorch_tpu.telemetry.compile_events.CompileMonitor`
  (``instrument()``) attributing every XLA compile / cache hit to the
  jitted entry point and shapes digest that triggered it;
* a :class:`~bert_pytorch_tpu.telemetry.sentinels.FailureSentinel` and
  rank-0 :class:`~bert_pytorch_tpu.telemetry.sentinels.Heartbeat`;
* a :class:`~bert_pytorch_tpu.telemetry.memory.MemorySampler` reading
  ``device.memory_stats()`` watermarks on the sync cadence (one record
  per window; a single ``memory_supported: false`` note on CPU);
* a :class:`~bert_pytorch_tpu.telemetry.model_stats.DivergenceMonitor`
  consuming the in-jit grad-health block the train steps splice into
  ``metrics["grad_health"]`` (popped here, emitted as ``grad_health``
  records, checked for grad-norm spikes / update-ratio drift).

Minimal loop integration::

    tele = TrainTelemetry(jsonl_path=..., heartbeat_path=..., ...)
    train_step = tele.instrument(train_step, "train_step")
    for batch in tele.timed(iter(loader)):        # measures data_wait
        tele.profiler.maybe_start(step)
        with tele.profiler.annotation(step):
            state, metrics = train_step(state, batch)
        tele.dispatch_done()                      # measures host dispatch
        tele.step_done(step, metrics)             # sync + window + sentinel
                                                  # + heartbeat + auto-stop
    tele.finish(step)                             # flush partial window
"""

from __future__ import annotations

import contextlib
import math
import time
from typing import Callable, Iterator, Optional

from bert_pytorch_tpu.telemetry.compile_events import CompileMonitor
from bert_pytorch_tpu.telemetry.memory import MemorySampler
from bert_pytorch_tpu.telemetry.model_stats import (DivergenceMonitor,
                                                    health_record)
from bert_pytorch_tpu.telemetry.profiler import ProfilerWindow
from bert_pytorch_tpu.telemetry.sampler import CaptureController
from bert_pytorch_tpu.telemetry.sentinels import (FailureSentinel, Heartbeat,
                                                  HeartbeatWatchdog)
from bert_pytorch_tpu.telemetry.step_timer import StepTimer
from bert_pytorch_tpu.utils import logging as logging_util


class TrainTelemetry:
    def __init__(
        self,
        jsonl_path: Optional[str] = None,
        sink=None,
        is_primary: bool = True,
        window: int = 20,
        sync_every: int = 1,
        seq_per_step: Optional[int] = None,
        flops_per_seq: Optional[float] = None,
        tokens_per_step: Optional[int] = None,
        device_kind: str = "",
        n_devices: int = 1,
        profile_steps=None,
        profile_dir: Optional[str] = None,
        sentinel_policy: str = "continue",
        sentinel_patience: int = 3,
        heartbeat_path: Optional[str] = None,
        heartbeat_every: int = 1,
        watchdog_timeout_s: float = 0.0,
        grad_spike_factor: float = 10.0,
        update_ratio_max: float = 1.0,
        grad_warmup: int = 10,
        cost_analysis: str = "auto",
        introspect=None,
        flight_recorder=None,
        clock: Callable[[], float] = time.perf_counter,
    ):
        self.is_primary = is_primary
        self._clock = clock
        # Rank-0 writes the artifacts; other ranks keep a disabled sink so
        # the loop code is rank-agnostic. An already-open handler can be
        # shared in via ``sink`` (the runners register the same handler
        # with the global logger so train records land in the JSONL too).
        if sink is not None:
            self.sink = sink
        else:
            self.sink = logging_util.JSONLHandler(
                jsonl_path, is_primary=is_primary) if jsonl_path else None
        self.timer = StepTimer(
            window=window, sync_every=sync_every, clock=clock,
            seq_per_step=seq_per_step, flops_per_seq=flops_per_seq,
            device_kind=device_kind, n_devices=n_devices,
            tokens_per_step=tokens_per_step)
        self.profiler = ProfilerWindow(
            profile_steps, profile_dir, enabled=is_primary)
        self.compile_monitor = CompileMonitor(
            emit=self.emit, cost_analysis=cost_analysis)
        self.sentinel = FailureSentinel(
            policy=sentinel_policy, patience=sentinel_patience,
            emit=self.emit)
        # Grad-health early-warning shares the sentinel's policy/patience:
        # a sustained divergence warning is the same class of failure as a
        # sustained NaN, just caught earlier (model_stats.py).
        self.divergence = DivergenceMonitor(
            emit=self.emit, policy=sentinel_policy,
            patience=sentinel_patience, spike_factor=grad_spike_factor,
            ratio_max=update_ratio_max, warmup=grad_warmup)
        # Device-memory watermarks, sampled where the host already blocks
        # (the sync cadence) and emitted one record per window. Rank-0
        # only: every process sees the same allocator story under SPMD,
        # and per-rank duplicates would just bloat the artifact.
        self.memory = MemorySampler(emit=self.emit, enabled=is_primary)
        self.heartbeat = Heartbeat(heartbeat_path, is_primary=is_primary)
        self.heartbeat_every = max(1, int(heartbeat_every))
        # Hung-step watchdog (docs/fault_tolerance.md): fed a liveness
        # note per completed step; flags (fault record + warning, never a
        # kill) when none lands within the timeout. Rank-0 only — one
        # flag per job, and the collective hangs it exists to catch stall
        # every rank anyway. Started lazily at the first step so runner
        # setup (data/featurization, sometimes minutes) doesn't count.
        self.watchdog = (HeartbeatWatchdog(watchdog_timeout_s, emit=self.emit)
                        if watchdog_timeout_s and is_primary else None)
        # Live introspection hub (telemetry/introspect.py) and crash
        # flight recorder (telemetry/flightrec.py): both fed from emit()
        # — which background threads (watchdog) also call — so the
        # bindings are frozen after __init__ (concurrency registry);
        # each object does its own locking.
        self.introspect = introspect
        self.flight_recorder = flight_recorder
        # On-demand capture plane (telemetry/sampler.py): armed over
        # HTTP (POST /profilez on the hub), started/collected at the
        # step boundary in step_done. It shares the startup window's
        # ProfilerWindow — the process-wide trace latch (profiler.py
        # _TRACE_ACTIVE) is what keeps the two from stacking traces.
        # Frozen binding after __init__ like the hub itself.
        self.capture = CaptureController(
            source="trainer", covered_unit="steps", window=self.profiler,
            trace_dir=profile_dir, emit=self.emit)
        if self.introspect is not None:
            self.introspect.capture = self.capture
        # The debug HTTP server serving the hub, attached by
        # telemetry/cli.from_args (or tests); finish()/close() shut it
        # down so a runner that opened --debug_port never leaks the port.
        self.debug_server = None
        self._loader_stats: Optional[Callable[[], Optional[dict]]] = None
        self._prefetcher = None
        self._last_sync_target = None
        self.last_step_synced = False

    # -- wiring ---------------------------------------------------------

    def emit(self, record=None, **kwargs) -> None:
        """Write one telemetry record to the JSONL sink — teeing it into
        the live introspection hub and the flight-recorder ring first
        (both no-ops when not attached; an incident record — fault /
        divergence / sentinel — makes the recorder flush its
        postmortem)."""
        rec = dict(record or {})
        rec.update(kwargs)
        if self.introspect is not None:
            self.introspect.observe_record(rec)
        if self.flight_recorder is not None:
            self.flight_recorder.note_record(rec)
        if self.sink is not None:
            self.sink.write_record(rec)

    def instrument(self, fn, name: str):
        """Wrap a jitted callable for compile-event attribution."""
        return self.compile_monitor.instrument(fn, name)

    def attach_loader(self, loader) -> None:
        """Use ``loader.snapshot()`` gauges in each window record."""
        snapshot = getattr(loader, "snapshot", None)
        if callable(snapshot):
            self._loader_stats = snapshot

    def attach_prefetcher(self, prefetcher) -> None:
        """Attribute the H2D share of each step's data wait to the
        ``h2d_wait`` sub-phase (data/device_prefetch.py DevicePrefetcher),
        and fold the prefetcher's gauges into window records."""
        self._prefetcher = prefetcher

    @contextlib.contextmanager
    def checkpoint_stall(self):
        """Context manager timing a checkpoint save's host stall; the
        measured block lands on the step it rode on as a ``ckpt_step``
        sample (step_timer.py note_ckpt_stall). Wrap every IN-LOOP
        ``save_checkpoint`` call with it — async saves then show up as
        checkpoint-step p95 collapsing toward steady-state p95. Only
        meaningful before :meth:`finish` (the flush there is what emits a
        stall noted after the last full window)."""
        t0 = self._clock()
        try:
            yield
        finally:
            self.timer.note_ckpt_stall(self._clock() - t0)

    # -- per-step protocol ----------------------------------------------

    def timed(self, iterator: Iterator) -> Iterator:
        """Wrap the batch iterator so host time blocked on the input
        pipeline is measured as data_wait."""
        while True:
            self.timer.data_start()
            try:
                item = next(iterator)
            except StopIteration:
                return
            self.timer.data_end()
            if self._prefetcher is not None:
                # The batch just delivered came through the device
                # prefetcher; record how much of the wait was H2D staging
                # (0.0 when the batch was already resident).
                self.timer.note_h2d(self._prefetcher.pop_h2d_wait_s())
            yield item

    def dispatch_done(self) -> None:
        self.timer.dispatch_end()

    def step_done(self, step: int, metrics: Optional[dict] = None,
                  sync_target=None, force_sync: bool = False,
                  profile_step: Optional[int] = None) -> Optional[dict]:
        """Close out one step: device sync (per the cadence), sentinel
        check, heartbeat, profiler auto-stop, window emission.

        ``metrics`` is the step's device metrics dict (used as the sync
        target and the source of the ``finite``/``loss`` scalars);
        ``sync_target`` overrides it. ``profile_step`` is the step number in
        the SAME base the runner feeds ``profiler.maybe_start`` — pass it
        when that base differs from ``step`` (run_pretraining profiles in
        step-in-run terms while ``step`` is the checkpoint-resumed global
        step; without it a resumed run would close the trace window
        immediately). Returns the window record when one was emitted.
        """
        # The in-jit grad-health block rides in metrics but is telemetry's,
        # not the runner's: pop it unconditionally so runner-side
        # float(metrics[...]) loops never trip over the nested dict, and
        # read it only on synced steps (fetching it otherwise would BE a
        # sync and defeat the cadence). The real-token count
        # (padding-aware accounting, step_timer.py) follows the same
        # contract: popped always, fetched only when this step syncs.
        health = metrics.pop("grad_health", None) \
            if isinstance(metrics, dict) else None
        real_tokens = metrics.pop("real_tokens", None) \
            if isinstance(metrics, dict) else None
        target = sync_target if sync_target is not None else metrics
        self._last_sync_target = target
        synced = False
        if target is not None and (self.timer.should_sync() or force_sync):
            self.timer.device_sync(target)
            synced = True
        self.last_step_synced = synced
        if synced:
            if real_tokens is not None:
                self.timer.note_tokens(float(real_tokens))
            self.memory.sample(step)
            if health is not None and float(health.get("due", 0.0)):
                record = health_record(step, health)
                self.emit(record)
                # DivergenceError propagates under policy="abort", same
                # surface as the sentinel's NonFiniteError.
                self.divergence.observe(
                    step, record["grad_norm"], record["update_ratio"])
        if metrics is not None and synced:
            loss = metrics.get("loss")
            loss = None if loss is None else float(loss)
            finite = metrics.get("finite")
            if finite is not None:
                finite = float(finite)
            else:
                # No in-jit sentinel (the finetune runners): fall back to a
                # host-side isfinite on the fetched loss.
                finite = 1.0 if (loss is None or math.isfinite(loss)) else 0.0
            self.sentinel.observe(step, finite, loss)
            if self.timer._step_index % self.heartbeat_every == 0:
                self.heartbeat.beat(step, last_loss=loss)
        if self.introspect is not None:
            # Every step, synced or not: /healthz liveness must not
            # depend on the sync cadence (the loss rides only when this
            # step fetched it — reading it off-cadence would BE a sync).
            hub_loss = None
            if metrics is not None and synced and \
                    metrics.get("loss") is not None:
                hub_loss = float(metrics["loss"])
            self.introspect.note_step(step, loss=hub_loss)
        if self.watchdog is not None:
            self.watchdog.start().note(step)
        self.profiler.maybe_stop(
            step if profile_step is None else profile_step,
            sync_target=target)
        # On-demand capture boundary: starts an armed capture, collects
        # an expired one (the finished profile_window record rides the
        # normal emit tee into hub/recorder/sink).
        self.capture.tick(step, sync_target=target)
        window = self.timer.step_done(step)
        if window is not None:
            if self._loader_stats is not None:
                gauges = self._loader_stats()
                if gauges:
                    window["loader"] = gauges
            if self._prefetcher is not None:
                gauges = self._prefetcher.snapshot()
                if gauges:
                    window["prefetch"] = gauges
            self.emit(window)
            self.memory.flush(step)  # one memory record per window
        return window

    # -- teardown -------------------------------------------------------

    def finish(self, step: int, summary: Optional[dict] = None) -> None:
        """End of run: stop a still-open trace, flush the partial window,
        final heartbeat, optional run summary record."""
        if self.watchdog is not None:
            self.watchdog.stop()
        self.profiler.stop(sync_target=self._last_sync_target)
        window = self.timer.flush(step)
        if window is not None:
            self.emit(window)
        self.memory.flush(step)  # partial-window memory samples
        if summary is not None:
            rec = {"kind": "run_summary", "tag": "telemetry", "step": step,
                   "steps": step}
            rec.update(summary)
            self.emit(rec)
        self.heartbeat.beat(step)
        self._shutdown_observability()

    def _shutdown_observability(self) -> None:
        """Stop the debug server and mark the flight recorder's clean
        exit (a fault/divergence flush earlier in the run keeps its
        postmortem; a clean run removes it)."""
        server, self.debug_server = self.debug_server, None
        if server is not None:
            try:
                server.shutdown()
                server.server_close()
            except Exception:
                pass
        if self.flight_recorder is not None:
            self.flight_recorder.close(clean=True)

    def close(self) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()
        self._shutdown_observability()
        if self.sink is not None:
            self.sink.close()
