"""The host thread sampler + on-demand capture state machine behind
``POST /profilez`` (docs/observability.md "Profiling plane").

The jax profiler answers "where is DEVICE time going" — but every
background plane this repo grew (dispatch/assembler/completion stages,
the device prefetcher, the fleet collector, the flight-recorder flush
paths) is HOST threads, invisible to an XLA trace on exactly the runs
where they matter (a dispatch stage spinning on a lock shows up as
device idle, not as a named frame). :class:`ThreadSampler` closes that
gap with the stdlib alone: a periodic ``sys._current_frames()`` sweep
over the process's threads, attributing each sample's SELF time to the
leaf frame (collapsed-stack rendering kept per leaf for drill-down),
bounded in both duration and sample count so a capture can never grow
without limit.

:class:`CaptureController` is the arm/collect state machine both HTTP
planes share: ``POST /profilez`` (telemetry/introspect.py for trainers,
serve/http.py for replicas) calls :meth:`CaptureController.arm` from an
HTTP worker thread; the owning loop calls :meth:`CaptureController.tick`
at every step/dispatch boundary. The transition rules ARE the bugfix
this module ships with: a second arm while a capture is armed or active
is refused (the HTTP planes map that to 409) — ``jax.profiler`` traces
cannot nest, and before this guard two POSTs would stack two
``start_trace`` calls and crash the train loop from a scrape thread.

Deliberately stdlib-only and jax-free at import time: the jax trace
facility arrives by INJECTION (a :class:`telemetry.profiler.ProfilerWindow`
whose ``begin``/``end`` the controller drives), so this module loads by
file path in jax-free tools and works sampler-only on hosts without the
accelerator stack. Shared state is declared in the concurrency registry
(analysis/concurrency.py).
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

# Hard ceilings an arm request cannot exceed — a capture is a bounded
# measurement, not a resident profiler.
MAX_DURATION_S = 60.0
MAX_SAMPLES = 20000
MIN_INTERVAL_S = 0.001

DEFAULT_DURATION_S = 2.0
DEFAULT_INTERVAL_S = 0.01
DEFAULT_TOP_K = 10
_STACK_DEPTH = 12  # collapsed-stack rendering depth (leaf-most frames)


def _frame_key(frame) -> str:
    """Stable leaf-frame identity: ``file.py:function``. The basename
    (not the full path) so frames aggregate across installs, and the
    function name (not the line) so a hot function is one row, not one
    row per bytecode offset the sampler happened to land on."""
    code = frame.f_code
    return f"{os.path.basename(code.co_filename)}:{code.co_name}"


def _collapsed(frame) -> str:
    """Root->leaf collapsed stack (the flamegraph convention), bounded
    to the leaf-most ``_STACK_DEPTH`` frames."""
    parts: List[str] = []
    while frame is not None and len(parts) < _STACK_DEPTH:
        parts.append(_frame_key(frame))
        frame = frame.f_back
    return ";".join(reversed(parts))


class ThreadSampler:
    """Bounded periodic ``sys._current_frames`` sampler.

    ``include`` is an optional tuple of thread-name prefixes to sample
    (e.g. ``("serve-", "telemetry-")``); None samples every thread
    except the sampler's own. Self time is attributed per
    (thread, leaf frame); :meth:`result` folds the tallies into the
    ``top_frames`` table a ``profile_window`` record carries.
    """

    def __init__(self, interval_s: float = DEFAULT_INTERVAL_S,
                 max_samples: int = 2000,
                 max_duration_s: float = MAX_DURATION_S,
                 include: Optional[tuple] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.interval_s = max(MIN_INTERVAL_S, float(interval_s))
        self.max_samples = max(1, min(int(max_samples), MAX_SAMPLES))
        self.max_duration_s = max(0.0, min(float(max_duration_s),
                                           MAX_DURATION_S))
        self.include = tuple(include) if include else None
        self._clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Tallies (concurrency registry): written by the sampler thread
        # per tick, read by result() after stop() joins — but stop() may
        # race a final in-flight tick, so every touch takes the lock.
        self._lock = threading.Lock()
        self._samples = 0
        self._counts: Dict[tuple, int] = {}
        self._stacks: Dict[tuple, str] = {}

    def _sampled(self, name: str) -> bool:
        if self._thread is not None and name == self._thread.name:
            return False  # never profile the profiler
        if self.include is None:
            return True
        return any(name.startswith(p) for p in self.include)

    def _sample_once_locked(self) -> None:
        """One sweep (called with ``_lock`` held — the suffix contract):
        attribute this instant's self time to each sampled thread's leaf
        frame."""
        by_ident = {t.ident: t.name for t in threading.enumerate()
                    if t.ident is not None}
        for ident, frame in sys._current_frames().items():
            name = by_ident.get(ident)
            if name is None or not self._sampled(name):
                continue
            key = (name, _frame_key(frame))
            self._counts[key] = self._counts.get(key, 0) + 1
            if key not in self._stacks:
                self._stacks[key] = _collapsed(frame)
        self._samples += 1

    def _run(self) -> None:
        deadline = self._clock() + self.max_duration_s
        while not self._stop.is_set():
            with self._lock:
                if self._samples >= self.max_samples:
                    break
                self._sample_once_locked()
            if self._clock() >= deadline:
                break
            self._stop.wait(self.interval_s)

    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("sampler already started (one-shot)")
        self._thread = threading.Thread(
            target=self._run, name="telemetry-sampler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def result(self, top_k: int = DEFAULT_TOP_K) -> dict:
        """Fold the tallies: total sample count, the threads that ever
        appeared, and the top-K (thread, leaf-frame) self-time rows."""
        with self._lock:
            samples = self._samples
            counts = dict(self._counts)
            stacks = dict(self._stacks)
        rows = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
        # Share of ALL attributed self-time (not of sweep count): every
        # sweep tallies one hit per live thread, so dividing by sweeps
        # would sum to ~n_threads across frames — the shares must
        # decompose the capture to <= 1 (the schema invariant).
        total = sum(counts.values())
        top = []
        for (thread, frame), n in rows[:max(1, int(top_k))]:
            top.append({
                "frame": frame,
                "thread": thread,
                "samples": n,
                "share": round(n / total, 4) if total else 0.0,
                "stack": stacks.get((thread, frame), frame),
            })
        return {
            "samples": samples,
            "threads": sorted({t for (t, _f) in counts}),
            "top_frames": top,
        }


def _tree_bytes(path: Optional[str]) -> int:
    """On-disk size of a trace artifact directory (0 for absent/empty)."""
    if not path or not os.path.isdir(path):
        return 0
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                pass
    return total


class CaptureController:
    """Arm-at-boundary capture state machine (idle -> armed -> active).

    ``source`` labels the records (``"trainer"``/``"replica"``...);
    ``covered_unit`` is what boundaries count (``"steps"``/
    ``"requests"``). ``window`` is an optional
    :class:`telemetry.profiler.ProfilerWindow` driven via its
    ``begin``/``end`` generalization — None (or a ``begin`` that
    refuses because another trace is active) degrades the capture to
    sampler-only, recorded as an empty ``trace_path``. ``emit``
    receives the finished ``profile_window`` record (a JSONLHandler's
    ``write_record`` or TrainTelemetry.emit stamps schema/ts).

    Thread contract: :meth:`arm` and :meth:`status` may be called from
    any thread (HTTP workers); :meth:`tick` only by the owning boundary
    loop. All shared state lives under one lock; the trace begin/end and
    sampler start/stop run OUTSIDE it (``end`` may block in
    ``jax.block_until_ready``; holding the lock there would stall
    /statsz for the sync's duration).
    """

    def __init__(self, source: str, covered_unit: str = "steps",
                 window=None, trace_dir: Optional[str] = None,
                 include_threads: Optional[tuple] = None,
                 emit: Optional[Callable[[dict], None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.source = str(source)
        self.covered_unit = str(covered_unit)
        self.window = window
        self.trace_dir = trace_dir
        self.include_threads = include_threads
        self.emit = emit
        self._clock = clock
        self._lock = threading.Lock()
        # The one shared slot (concurrency registry): phase + the armed
        # request's parameters + capture bookkeeping + the last record.
        self._state: dict = {
            "phase": "idle",       # idle | armed | active
            "params": None,        # the armed request (dict)
            "trigger": None,
            "seq": 0,              # capture counter (trace subdir names)
            "captures": 0,         # completed captures
            "started_at": None,
            "start_position": None,
            "deadline": None,
            "last": None,          # last finished record (trimmed)
        }
        self._sampler: Optional[ThreadSampler] = None  # active-phase only

    # -- any thread (HTTP workers) ---------------------------------------

    def arm(self, duration_s: float = DEFAULT_DURATION_S,
            sample_interval_s: float = DEFAULT_INTERVAL_S,
            max_samples: int = 2000, top_k: int = DEFAULT_TOP_K,
            trigger: str = "ondemand"):
        """Request a capture at the next boundary. Returns
        ``(ok, payload)``; ``ok=False`` with the current phase when a
        capture is already armed or active — the HTTP planes answer 409
        (two overlapping ``jax.profiler.start_trace`` calls would
        crash the owning loop)."""
        try:
            duration_s = float(duration_s)
            sample_interval_s = float(sample_interval_s)
            max_samples = int(max_samples)
            top_k = int(top_k)
        except (TypeError, ValueError) as exc:
            return False, {"error": f"bad capture parameter: {exc}"}
        if not duration_s > 0:
            return False, {"error": "duration_s must be positive"}
        duration_s = min(duration_s, MAX_DURATION_S)
        with self._lock:
            if self._state["phase"] != "idle":
                return False, {
                    "error": "capture already in progress",
                    "phase": self._state["phase"],
                }
            self._state["phase"] = "armed"
            self._state["trigger"] = (trigger if trigger in
                                      ("ondemand", "fleet") else "ondemand")
            self._state["params"] = {
                "duration_s": duration_s,
                "sample_interval_s": max(MIN_INTERVAL_S, sample_interval_s),
                "max_samples": max(1, min(max_samples, MAX_SAMPLES)),
                "top_k": max(1, top_k),
            }
            payload = {"armed": True, "source": self.source,
                       "covered_unit": self.covered_unit}
            payload.update(self._state["params"])
        return True, payload

    def status(self) -> dict:
        """Live capture status for /statsz."""
        with self._lock:
            out = {
                "phase": self._state["phase"],
                "captures": self._state["captures"],
            }
            if self._state["phase"] == "active" and \
                    self._state["started_at"] is not None:
                out["active_for_s"] = round(
                    self._clock() - self._state["started_at"], 3)
            last = self._state["last"]
            if last is not None:
                out["last"] = dict(last)
        return out

    # -- owning boundary loop only ---------------------------------------

    def tick(self, position: int, sync_target=None) -> Optional[dict]:
        """One step/dispatch boundary. Starts an armed capture, finishes
        an expired one; returns the finished ``profile_window`` record
        (also emitted) or None. Must be called from the thread that owns
        the boundary — the trace begin/end and the sampler lifecycle are
        serialized by that ownership, only the phase state is shared."""
        with self._lock:
            phase = self._state["phase"]
            if phase == "armed":
                params = dict(self._state["params"])
                self._state["seq"] += 1
                seq = self._state["seq"]
            elif phase == "active":
                expired = self._clock() >= self._state["deadline"]
                if not expired:
                    return None
            else:
                return None

        if phase == "armed":
            sampler = ThreadSampler(
                interval_s=params["sample_interval_s"],
                max_samples=params["max_samples"],
                max_duration_s=params["duration_s"] + 5.0,
                include=self.include_threads)
            trace_path = ""
            if self.window is not None and self.trace_dir:
                sub = os.path.join(self.trace_dir, f"ondemand_{seq}")
                if self.window.begin(trace_dir=sub):
                    trace_path = sub
            sampler.start()
            now = self._clock()
            with self._lock:
                self._state["phase"] = "active"
                self._state["started_at"] = now
                self._state["start_position"] = int(position)
                self._state["deadline"] = now + params["duration_s"]
                self._state["params"] = params
                self._state["params"]["trace_path"] = trace_path
                self._sampler = sampler
            return None

        # active + expired: collect.
        with self._lock:
            sampler = self._sampler
            params = dict(self._state["params"])
            started = self._state["started_at"]
            start_pos = self._state["start_position"]
            trigger = self._state["trigger"]
        sampler.stop()
        trace_path = params.get("trace_path", "")
        if trace_path and self.window is not None:
            self.window.end(sync_target=sync_target)
        folded = sampler.result(top_k=params["top_k"])
        record = {
            "kind": "profile_window",
            "source": self.source,
            "trigger": trigger or "ondemand",
            "covered": max(0, int(position) - int(start_pos)),
            "covered_unit": self.covered_unit,
            "duration_s": round(self._clock() - started, 3),
            "sample_interval_s": params["sample_interval_s"],
            "samples": folded["samples"],
            "threads": folded["threads"],
            "top_frames": folded["top_frames"],
            "trace_path": trace_path,
            "trace_bytes": _tree_bytes(trace_path),
        }
        last = {k: record[k] for k in (
            "trigger", "covered", "covered_unit", "duration_s", "samples",
            "trace_path", "trace_bytes")}
        last["top_frame"] = (folded["top_frames"][0]["frame"]
                             if folded["top_frames"] else None)
        with self._lock:
            self._state["phase"] = "idle"
            self._state["params"] = None
            self._state["started_at"] = None
            self._state["start_position"] = None
            self._state["deadline"] = None
            self._state["captures"] += 1
            self._state["last"] = last
            self._sampler = None
        if self.emit is not None:
            self.emit(record)
        return record
