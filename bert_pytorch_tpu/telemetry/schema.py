"""Versioned record schema for the telemetry JSONL stream.

Every record the :class:`bert_pytorch_tpu.utils.logging.JSONLHandler` writes
carries ``schema`` (this module's ``SCHEMA_VERSION``) and ``ts`` (unix
seconds). Telemetry-layer records additionally carry ``kind``, which selects
the per-kind required-key set below; runner metric records (tag/step/loss…)
have no ``kind`` and only the universal rules apply.

Universal rules, lintable offline (``tools/check_telemetry_schema.py``):

* one JSON object per line — no arrays, no trailing prose;
* no NaN/Infinity spellings (non-finite floats are written as ``null``);
* a ``schema`` value other than a known version is an error (consumers
  must be able to dispatch on it).

Legacy artifacts (the ``*_r0*.jsonl`` bench files committed before this
schema existed) carry no ``schema`` key; the lint holds them to the
universal rules only, so history stays green while every NEW stream is
strictly validated. Bump ``SCHEMA_VERSION`` when a kind's required keys
change incompatibly; consumers dispatch on the per-record value.
"""

from __future__ import annotations

import json
import math

SCHEMA_VERSION = 1
KNOWN_VERSIONS = (1,)

# Per-kind required keys (beyond the universal schema/ts). Extra keys are
# always allowed — the schema pins the floor consumers can rely on, not the
# ceiling.
KIND_REQUIRED_KEYS = {
    # windowed step-time decomposition (telemetry/step_timer.py)
    "step_window": (
        "step", "window_steps",
        "data_wait_p50_s", "data_wait_p95_s", "data_wait_max_s",
        "host_p50_s", "host_p95_s", "host_max_s",
        "device_p50_s", "device_p95_s", "device_max_s",
        "step_p50_s", "steps_per_sec", "mfu",
    ),
    # one compile (or compile-cache lookup) of a jitted function
    # (telemetry/compile_events.py)
    "compile": ("fn", "shapes_digest", "compile_s", "cache"),
    # non-finite loss/grad-norm observation (telemetry/sentinels.py)
    "sentinel": ("step", "finite", "consecutive_nonfinite", "policy"),
    # in-jit model-internals statistics fetched on the sync cadence
    # (telemetry/model_stats.py): global + per-layer-group grad/param
    # norms and update:weight ratios
    "grad_health": ("step", "grad_norm", "param_norm", "update_ratio",
                    "groups"),
    # divergence early-warning from the grad-health monitor
    # (telemetry/model_stats.py DivergenceMonitor)
    "divergence": ("step", "reason", "value", "threshold", "policy"),
    # device-memory watermarks sampled on the sync cadence, or the
    # one-shot memory_supported:false note on backends without
    # allocator stats (telemetry/memory.py MemorySampler)
    "memory": ("step", "memory_supported"),
    # one-shot static cost/memory attribution of a jitted executable,
    # joined to the compile event by (fn, shapes_digest)
    # (telemetry/memory.py analyze_executable)
    "compile_cost": ("fn", "shapes_digest", "analysis"),
    # one Pallas block-geometry decision for one (kernel, seq, bh)
    # shape (ops/pallas/autotune.py, serve/engine.py _setup_autotune):
    # where the geometry came from — measured this start, loaded from
    # the persisted winners cache, or the heuristic fallback — plus the
    # winning (block_q, block_k, bh_block) when one exists
    "autotune": ("kernel", "seq", "bh", "source"),
    # end-of-run rollup
    "run_summary": ("steps",),
    # -- fault-tolerance record family (docs/fault_tolerance.md) -------
    # one fault observation: a preemption signal acted on, a shard-read
    # retry, a hung-step watchdog flag, or an armed injection
    # (testing/faults.py — those carry injected: true so chaos-run
    # artifacts are distinguishable from real incidents)
    "fault": ("fault", "injected"),
    # one resume decision (utils/checkpoint.py walk-back): the step
    # training resumed from, plus every newer retained checkpoint that
    # was skipped as corrupt/unreadable to get there
    "resume": ("step", "skipped"),
    # -- serve record family (serve/stats.py, docs/serving.md) ---------
    # one window of online-inference traffic: request count, e2e and
    # on-device latency percentiles (ms), batch occupancy (real tokens /
    # dispatched slot budget), recompile count
    "serve_window": (
        "window_requests", "batches",
        "latency_p50_ms", "latency_p95_ms", "latency_p99_ms",
        "device_p50_ms", "device_p95_ms", "device_p99_ms",
        "compiles",
    ),
    # end-of-run serving rollup (also the live /statsz shape)
    "serve_summary": (
        "requests", "batches",
        "latency_p50_ms", "latency_p95_ms", "latency_p99_ms",
    ),
    # one engine startup (serve/stats.py observe_cold_start): AOT warmup
    # wall time + its compiles split cold (real XLA compiles) vs warm
    # (persistent-cache hits, the counter-event authority) — a restarted
    # replica with a warm cache shows compiles_cold == 0
    "serve_cold_start": (
        "cold_start_s", "compiles", "compiles_cold", "compiles_warm",
    ),
    # one sampled request's span tree (serve/tracing.py): head-sampled
    # at --trace_sample_rate, or force-sampled by the always-sample-slow
    # rule when the request exceeded the SLO target
    "serve_trace": (
        "trace_id", "task", "total_ms", "queue_wait_ms", "sampled",
        "spans",
    ),
    # one per-task window of request-latency decomposition: per-phase
    # p50/p95, total percentiles, and the queue-wait share a router
    # balances on (serve/tracing.py)
    "serve_phase": (
        "task", "window_requests", "queue_wait_share",
        "queue_p50_ms", "queue_p95_ms",
        "assembly_p50_ms", "assembly_p95_ms",
        "execute_p50_ms", "execute_p95_ms",
        "postprocess_p50_ms", "postprocess_p95_ms",
        "total_p50_ms", "total_p95_ms", "total_p99_ms",
    ),
    # -- fleet record family (serve/supervisor.py, serve/router.py,
    # docs/serving.md "Fleet tier") ------------------------------------
    # one supervisor decision about one replica: spawn, exit (with rc
    # and graceful classification), restart_scheduled (with backoff),
    # wedged_kill/probe_kill (watchdog), gave_up, drain/drain_kill
    "fleet_event": ("event", "replica", "port"),
    # one window of routed traffic: the ok/shed/error decomposition plus
    # the tail-at-scale counters (retries, hedges, failovers) and the
    # failover-latency percentiles the "router failover" report gate
    # reads (serve/router.py)
    "router_window": (
        "window_requests", "ok", "sheds", "errors",
        "retries", "hedges", "hedge_wins", "failovers",
        "healthy_replicas", "replicas",
    ),
    # run-level router rollup (the router's /statsz shape)
    "router_summary": (
        "requests", "ok", "sheds", "errors",
        "retries", "hedges", "hedge_wins", "failovers",
        "healthy_replicas", "replicas",
    ),
    # one sampled client request's router-tier span tree
    # (serve/router.py): admission, per-attempt dispatch (attempt
    # index, target replica, outcome), backoff waits, hedge
    # launch/win/loss with loser-latency waste — the cross-tier parent
    # every replica serve_trace chains to via ``parent_trace_id``
    # (docs/observability.md "Trace propagation")
    "router_trace": (
        "trace_id", "task", "status", "total_ms", "sampled",
        "attempts", "spans",
    ),
    # one stitched end-to-end trace tree (telemetry/collector.py): the
    # join of a router_trace with the serve_trace records chained to it,
    # decomposing the client-observed total into router overhead +
    # network gap + winning-attempt replica time — or an orphan marker
    # when one side never arrived (counted, never dropped silently)
    "trace_stitch": (
        "trace_id", "orphan", "router_spans", "replica_spans",
    ),
    # -- fleet observatory family (telemetry/collector.py,
    # docs/observability.md) --------------------------------------------
    # one collector probe of one registered endpoint (trainer debug
    # plane, replica /metricsz, router /statsz): whether the scrape
    # succeeded, and how stale the target's last GOOD sample is — the
    # number the "fleet scrape staleness" report gate regresses on
    "obs_scrape": ("target", "target_kind", "ok", "staleness_s"),
    # one collector pass's fleet aggregate: healthy/total target counts
    # (the dip-and-recovery signal when a replica dies), worst-replica
    # p99, fleet request rate, trainer step rate, error-budget burn
    "obs_fleet_window": ("targets_total", "targets_healthy",
                         "max_staleness_s"),
    # -- profiling plane (telemetry/sampler.py, telemetry/profiler.py,
    # docs/observability.md "Profiling plane") --------------------------
    # one bounded on-demand capture (POST /profilez): the jax-profiler
    # trace artifact written (path + on-disk bytes; empty path when the
    # trace was skipped — e.g. another trace window was already active),
    # the steps/requests the window covered, and the host thread
    # sampler's top-K self-time frames
    "profile_window": (
        "source", "trigger", "covered", "covered_unit", "duration_s",
        "samples", "top_frames", "trace_path", "trace_bytes",
    ),
    # one point on the longitudinal perf trajectory (telemetry/ledger.py,
    # tools/perf_ledger.py): a named bench/report leg's headline numbers
    # plus the config digest that makes entries comparable — the
    # "perf ledger drift" gate regresses the newest entry against the
    # rolling median of its leg's history
    "ledger_entry": ("leg", "config_digest", "metrics"),
    # -- deployment plane (serve/registry.py, serve/rollout.py,
    # docs/serving.md "Model registry & canary rollouts") ---------------
    # one model-registry lifecycle event: a version published into the
    # registry, or one state-machine transition between the lifecycle
    # states below — transitions carry from_state, and a rollback
    # (canary -> staged) must carry the SLO-breach reason that forced it
    "registry_event": ("version", "event", "state"),
    # one canary observation window (serve/rollout.py RolloutController):
    # the canary cohort's ok/error decomposition and latency percentiles
    # at one traffic share, the SLO verdict + error-budget burn the
    # promotion gate read, the action taken (hold|advance|promote|
    # rollback), and the torn-serve count the zero-tolerance
    # "rollout torn-model serves" report gate regresses on
    "rollout_window": (
        "task", "version", "stage", "canary_share", "window_requests",
        "ok", "errors", "slo_ok", "action", "torn_serves",
    ),
    # -- elasticity plane (serve/autoscaler.py, docs/serving.md
    # "Elastic fleet") ---------------------------------------------------
    # one autoscaler control-loop verdict: the decision (scale_up|
    # scale_down|hold), the cooldown/hold reason, and the replica count
    # before/after — ``exogenous`` stamps any membership drift since the
    # previous event (a replica FAILed, an operator intervened) so the
    # cross-record lint can reconstruct fleet membership from the event
    # stream alone (see _check_scale_chain)
    "scale_event": (
        "decision", "reason", "replicas_before", "replicas_after",
        "exogenous",
    ),
}

# Target kinds the collector scrapes (telemetry/collector.py; mirrored
# here so the schema module stays stdlib-only/jax-free like TRACE_PHASES).
OBS_TARGET_KINDS = ("trainer", "replica", "router")

# How a profile_window came to be (telemetry/sampler.py): the startup
# --profile_steps window, an operator's POST /profilez, or the
# collector's coordinated fleet-wide capture (obs_collect --profile).
PROFILE_TRIGGERS = ("startup", "ondemand", "fleet")

# What a profile_window's ``covered`` counts: training steps (trainer
# captures) or completed dispatch batches' requests (replica captures).
PROFILE_COVERED_UNITS = ("steps", "requests")

# The ledger metrics the drift gate knows a direction for
# (telemetry/ledger.py): "up" metrics regress by growing (latencies,
# cold start), "down" metrics regress by shrinking (MFU, padding
# efficiency). Extra metric keys are allowed in entries — they are
# recorded but not drift-gated.
LEDGER_METRIC_DIRECTIONS = {
    "step_ms_p50": "up",
    "step_ms_p95": "up",
    "mfu": "down",
    "serve_p50_ms": "up",
    "serve_p99_ms": "up",
    "cold_start_s": "up",
    "padding_efficiency": "down",
}

# Model-registry version lifecycle (serve/registry.py; mirrored here so
# the schema lint stays stdlib-only/jax-free like TRACE_PHASES). A
# version enters the registry as ``staged``; only the edges below are
# legal, and the canary -> staged edge (a rollback) must name its breach
# reason — serve/registry.py imports THESE tuples, so the state machine
# the registry enforces and the one the lint checks cannot drift.
REGISTRY_STATES = ("staged", "canary", "live", "retired")
REGISTRY_TRANSITIONS = (
    ("staged", "canary"),    # rollout began (first traffic share)
    ("canary", "live"),      # promoted after green observation windows
    ("canary", "staged"),    # rolled back on SLO breach (reason required)
    ("staged", "retired"),   # abandoned without ever taking traffic
    ("live", "retired"),     # superseded by a promoted successor
)

# What a rollout_window decided (serve/rollout.py RolloutController):
# hold at the current share, advance to the next stage, promote to live,
# or roll back to the previous version.
ROLLOUT_ACTIONS = ("hold", "advance", "promote", "rollback")

# What a scale_event decided (serve/autoscaler.py AutoscalerController;
# the controller imports THIS tuple, so the runtime vocabulary and the
# offline lint cannot drift — the ROLLOUT_ACTIONS pattern).
SCALE_DECISIONS = ("scale_up", "scale_down", "hold")

# serve_trace span names (serve/tracing.py PHASES, mirrored here so the
# schema module stays stdlib-only/jax-free — tools/check_telemetry_schema
# loads it by file path).
TRACE_PHASES = ("queue", "assembly", "execute", "postprocess")

# Router-tier span names (serve/router.py, mirrored here so the schema
# module stays stdlib-only/jax-free like TRACE_PHASES). Unlike the
# replica phases, router spans may OVERLAP in time — a hedged race runs
# two attempt spans concurrently — so the additive sum rule does not
# apply; each span is individually bounded by the request interval.
ROUTER_TRACE_SPANS = ("admission", "attempt", "backoff")

# Rounding slack for the serve_trace additive invariants: spans and the
# total are independently rounded to 3 decimals at emission, so exact <=
# comparisons would flag sub-microsecond rounding noise as corruption.
_TRACE_EPS_MS = 0.01

# Rounding slack for the trace_stitch additive identity: the three
# components are independently rounded to 3 decimals, and the replica
# total is measured on a different process's clock than the router's
# attempt span.
_STITCH_EPS_MS = 0.05

# Serve-kind consistency rules (lintable offline): percentiles must be
# ordered, and occupancy is a ratio of real work to dispatched budget —
# the serving analog of padding_efficiency, with the same (0, 1] domain.
_SERVE_LATENCY_PREFIXES = ("latency", "device")

# Host input-pipeline gauges (data/loader.py snapshot) ride INSIDE a
# step_window record as its "loader" sub-object — they are not a standalone
# record kind.
LOADER_REQUIRED_KEYS = ("batches", "wait_s_total", "stalls", "depth_max")

# Padding-aware throughput fields (schema v1 addition; step_timer.py,
# sequence packing data/packing.py). Optional — pre-packing artifacts
# simply omit them — but internally consistent when present: a
# tokens_per_s without its basis, or a "real" basis without the
# padding_efficiency that defines it, would make artifacts incomparable
# across the packing transition (exactly what the basis field exists to
# prevent).
TOKENS_BASES = ("real", "all")

_NONFINITE_SPELLINGS = ("NaN", "Infinity", "-Infinity")


def validate_record(rec) -> list:
    """Schema errors for one decoded record (empty list = valid)."""
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not an object"]
    errors = []
    if "schema" in rec:
        if rec["schema"] not in KNOWN_VERSIONS:
            errors.append(f"unknown schema version {rec['schema']!r}")
        kind = rec.get("kind")
        if kind is not None:
            required = KIND_REQUIRED_KEYS.get(kind)
            if required is None:
                errors.append(f"unknown record kind {kind!r}")
            else:
                missing = [k for k in required if k not in rec]
                if missing:
                    errors.append(f"kind {kind!r} missing keys {missing}")
                if kind == "step_window" and isinstance(
                        rec.get("loader"), dict):
                    gauges = rec["loader"]
                    missing = [k for k in LOADER_REQUIRED_KEYS
                               if k not in gauges]
                    if missing:
                        errors.append(
                            f"loader gauges missing keys {missing}")
                if kind == "step_window":
                    _check_token_fields(rec, errors)
                    _check_async_fields(rec, errors)
                if kind in ("serve_window", "serve_summary"):
                    _check_serve_fields(rec, errors)
                if kind == "serve_cold_start":
                    _check_cold_start_fields(rec, errors)
                if kind == "serve_trace":
                    _check_trace_fields(rec, errors)
                if kind == "serve_phase":
                    _check_phase_fields(rec, errors)
                if kind == "fault":
                    _check_fault_fields(rec, errors)
                if kind == "resume":
                    _check_resume_fields(rec, errors)
                if kind == "fleet_event":
                    _check_fleet_fields(rec, errors)
                if kind in ("router_window", "router_summary"):
                    _check_router_fields(rec, errors)
                if kind == "router_trace":
                    _check_router_trace_fields(rec, errors)
                if kind == "trace_stitch":
                    _check_stitch_fields(rec, errors)
                if kind == "obs_scrape":
                    _check_obs_scrape_fields(rec, errors)
                if kind == "obs_fleet_window":
                    _check_obs_fleet_fields(rec, errors)
                if kind == "autotune":
                    _check_autotune_fields(rec, errors)
                if kind == "profile_window":
                    _check_profile_fields(rec, errors)
                if kind == "ledger_entry":
                    _check_ledger_fields(rec, errors)
                if kind == "registry_event":
                    _check_registry_event_fields(rec, errors)
                if kind == "rollout_window":
                    _check_rollout_window_fields(rec, errors)
                if kind == "scale_event":
                    _check_scale_event_fields(rec, errors)
    for key, value in rec.items():
        _check_finite(key, value, errors)
    return errors


def _check_token_fields(rec, errors) -> None:
    """Padding-aware throughput consistency (schema v1 addition)."""
    if "tokens_per_s" in rec:
        basis = rec.get("tokens_per_s_basis")
        if basis not in TOKENS_BASES:
            errors.append(
                f"tokens_per_s requires tokens_per_s_basis in "
                f"{TOKENS_BASES}, got {basis!r}")
        if basis == "real" and "padding_efficiency" not in rec:
            errors.append(
                "tokens_per_s_basis 'real' requires padding_efficiency")
    if "padding_efficiency" in rec:
        eff = rec["padding_efficiency"]
        if not isinstance(eff, (int, float)) or not 0 < eff <= 1:
            errors.append(
                f"padding_efficiency must be in (0, 1], got {eff!r}")
    if "mfu_real_tokens" in rec and "padding_efficiency" not in rec:
        errors.append("mfu_real_tokens requires padding_efficiency")


def _check_async_fields(rec, errors) -> None:
    """Async-hot-path consistency (schema v1 addition; step_timer.py,
    data/device_prefetch.py, utils/checkpoint.py async_write).

    ``h2d_wait_*`` is a SUB-phase of ``data_wait_*`` — an artifact where
    the host->device share exceeds the wait it is part of is mismeasured,
    not just noisy. ``ckpt_steps`` flags how many steps in the window
    carried a checkpoint save; the ``ckpt_step_*`` percentiles only mean
    anything over at least one such step."""
    for suffix in ("p50_s", "p95_s", "max_s"):
        h2d, data = rec.get(f"h2d_wait_{suffix}"), rec.get(
            f"data_wait_{suffix}")
        if h2d is None:
            continue
        if not isinstance(h2d, (int, float)) or isinstance(h2d, bool):
            errors.append(f"h2d_wait_{suffix} must be a number, got {h2d!r}")
        elif not isinstance(data, (int, float)) or isinstance(data, bool):
            errors.append(
                f"h2d_wait_{suffix} requires a numeric data_wait_{suffix}")
        elif h2d > data:
            errors.append(
                f"h2d_wait_{suffix} ({h2d}) exceeds data_wait_{suffix} "
                f"({data}): h2d_wait is a sub-phase of data_wait")
    ckpt_steps = rec.get("ckpt_steps")
    has_ckpt_stats = any(f"ckpt_step_{s}" in rec
                         for s in ("p50_s", "p95_s", "max_s"))
    if ckpt_steps is not None:
        if not isinstance(ckpt_steps, int) or isinstance(ckpt_steps, bool) \
                or ckpt_steps < 1:
            errors.append(
                f"ckpt_steps must be a positive integer, got {ckpt_steps!r}")
    elif has_ckpt_stats:
        errors.append("ckpt_step_* percentiles require ckpt_steps")


def _check_serve_fields(rec, errors) -> None:
    """Serve-kind consistency (schema v1 addition; serve/stats.py).
    Continuous-batching fields (docs/serving.md "Continuous batching"):
    ``device_idle_share`` is a ratio of idle to (idle + busy) executor
    time, so it lives in [0, 1]; ``admitted_late`` counts requests, so
    it is a non-negative integer bounded by the record's request
    count — a window claiming more late admissions than requests is the
    accounting bug this invariant exists to catch."""
    for prefix in _SERVE_LATENCY_PREFIXES:
        keys = [f"{prefix}_p50_ms", f"{prefix}_p95_ms", f"{prefix}_p99_ms"]
        vals = [rec.get(k) for k in keys]
        if not all(isinstance(v, (int, float)) and not isinstance(v, bool)
                   for v in vals if v is not None):
            continue  # type errors surface via the required-key check
        present = [v for v in vals if v is not None]
        if len(present) == 3 and not (vals[0] <= vals[1] <= vals[2]):
            errors.append(
                f"{prefix} percentiles not ordered "
                f"(p50 <= p95 <= p99): {vals}")
    if "batch_occupancy" in rec:
        occ = rec["batch_occupancy"]
        if not isinstance(occ, (int, float)) or isinstance(occ, bool) \
                or not 0 < occ <= 1:
            errors.append(
                f"batch_occupancy must be in (0, 1], got {occ!r}")
    if "device_idle_share" in rec:
        share = rec["device_idle_share"]
        if not _is_number(share) or not 0 <= share <= 1:
            errors.append(
                f"device_idle_share must be in [0, 1], got {share!r}")
    late = rec.get("admitted_late")
    if late is not None:
        total_key = ("window_requests" if rec.get("kind") == "serve_window"
                     else "requests")
        total = rec.get(total_key)
        if not isinstance(late, int) or isinstance(late, bool) or late < 0:
            errors.append(
                f"admitted_late must be a non-negative integer, got "
                f"{late!r}")
        elif isinstance(total, int) and not isinstance(total, bool) \
                and late > total:
            errors.append(
                f"admitted_late ({late}) exceeds {total_key} ({total})")


def _check_cold_start_fields(rec, errors) -> None:
    """Cold-start consistency (serve/stats.py observe_cold_start): the
    warm/cold split must add up — consumers assert "zero cold compiles"
    on the split, so a record where cold + warm exceeds the total would
    let a broken producer fake a warm start."""
    numbers = {}
    for key in ("cold_start_s", "compiles", "compiles_cold",
                "compiles_warm"):
        v = rec.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool) or v < 0:
            errors.append(f"{key} must be a non-negative number, got {v!r}")
        else:
            numbers[key] = v
    if {"compiles", "compiles_cold", "compiles_warm"} <= set(numbers) and \
            numbers["compiles_cold"] + numbers["compiles_warm"] \
            > numbers["compiles"]:
        errors.append(
            "compiles_cold + compiles_warm exceeds compiles "
            f"({rec.get('compiles_cold')} + {rec.get('compiles_warm')} > "
            f"{rec.get('compiles')})")


def _is_number(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _check_trace_fields(rec, errors) -> None:
    """serve_trace consistency (serve/tracing.py): the span tree must be
    a real decomposition of the request — non-negative durations summing
    to no more than the end-to-end total, a queue wait bounded by that
    total, and a genuine boolean ``sampled`` flag (consumers split
    head-sampled from slow-forced traces on it; the critical-path
    analysis in telemetry-report trusts the arithmetic)."""
    total = rec.get("total_ms")
    if not _is_number(total) or total < 0:
        errors.append(
            f"total_ms must be a non-negative number, got {total!r}")
        total = None
    queue = rec.get("queue_wait_ms")
    if not _is_number(queue) or queue < 0:
        errors.append(
            f"queue_wait_ms must be a non-negative number, got {queue!r}")
    elif total is not None and queue > total + _TRACE_EPS_MS:
        errors.append(
            f"queue_wait_ms ({queue}) exceeds total_ms ({total})")
    if not isinstance(rec.get("sampled"), bool):
        errors.append(
            f"serve_trace 'sampled' must be a boolean, got "
            f"{rec.get('sampled')!r}")
    reason = rec.get("sample_reason")
    if reason is not None and reason not in ("head", "slow"):
        errors.append(
            f"sample_reason must be 'head' or 'slow', got {reason!r}")
    parent = rec.get("parent_trace_id")
    if parent is not None and (not isinstance(parent, str) or not parent):
        # The cross-tier chain to the router's router_trace (ISSUE 16):
        # optional — direct-to-replica traffic has no parent — but the
        # stitcher joins on it, so a present-but-empty value is
        # corruption, not data.
        errors.append(
            f"parent_trace_id must be a non-empty string, got {parent!r}")
    attempt = rec.get("attempt")
    if attempt is not None and (not isinstance(attempt, int)
                                or isinstance(attempt, bool)
                                or attempt < 1):
        errors.append(
            f"serve_trace 'attempt' must be a positive integer, got "
            f"{attempt!r}")
    late = rec.get("admitted_late")
    if late is not None and not isinstance(late, bool):
        # The continuous-batching admission marker (serve/service.py
        # pipelined dispatch): consumers count admission-window wins on
        # it, so it must be a real boolean, like `sampled`.
        errors.append(
            f"serve_trace 'admitted_late' must be a boolean, got {late!r}")
    staged_wait = rec.get("staged_wait_ms")
    if staged_wait is not None and (
            not _is_number(staged_wait) or staged_wait < 0):
        errors.append(
            f"staged_wait_ms must be a non-negative number, got "
            f"{staged_wait!r}")
    spans = rec.get("spans")
    if not isinstance(spans, list) or not spans:
        errors.append(
            f"serve_trace 'spans' must be a non-empty list, got {spans!r}")
        return
    dur_sum = 0.0
    for i, span in enumerate(spans):
        if not isinstance(span, dict) or not {"name", "start_ms",
                                              "dur_ms"} <= set(span):
            errors.append(
                f"spans[{i}] must be an object with name/start_ms/dur_ms, "
                f"got {span!r}")
            continue
        if not isinstance(span["name"], str) or not span["name"]:
            errors.append(
                f"spans[{i}].name must be a non-empty string, got "
                f"{span['name']!r}")
        for key in ("start_ms", "dur_ms"):
            v = span[key]
            if not _is_number(v) or v < 0:
                errors.append(
                    f"spans[{i}].{key} must be a non-negative number, "
                    f"got {v!r}")
                break
        else:
            dur_sum += span["dur_ms"]
    if total is not None and dur_sum > total + _TRACE_EPS_MS:
        errors.append(
            f"sum of span durations ({round(dur_sum, 3)}) exceeds "
            f"total_ms ({total}): spans must be sub-intervals of the "
            "request")


def _check_phase_fields(rec, errors) -> None:
    """serve_phase consistency (serve/tracing.py window records)."""
    task = rec.get("task")
    if not isinstance(task, str) or not task:
        errors.append(f"task must be a non-empty string, got {task!r}")
    n = rec.get("window_requests")
    if not isinstance(n, int) or isinstance(n, bool) or n < 1:
        errors.append(
            f"window_requests must be a positive integer, got {n!r}")
    share = rec.get("queue_wait_share")
    if not _is_number(share) or not 0 <= share <= 1:
        errors.append(
            f"queue_wait_share must be in [0, 1], got {share!r}")
    for prefix in TRACE_PHASES:
        p50 = rec.get(f"{prefix}_p50_ms")
        p95 = rec.get(f"{prefix}_p95_ms")
        for key, v in ((f"{prefix}_p50_ms", p50), (f"{prefix}_p95_ms",
                                                   p95)):
            if v is not None and (not _is_number(v) or v < 0):
                errors.append(
                    f"{key} must be a non-negative number, got {v!r}")
        if _is_number(p50) and _is_number(p95) and p50 > p95:
            errors.append(
                f"{prefix} percentiles not ordered (p50 <= p95): "
                f"[{p50}, {p95}]")
    totals = [rec.get(f"total_{p}_ms") for p in ("p50", "p95", "p99")]
    if all(_is_number(v) for v in totals) and \
            not (totals[0] <= totals[1] <= totals[2]):
        errors.append(
            f"total percentiles not ordered (p50 <= p95 <= p99): {totals}")
    late = rec.get("admitted_late")
    if late is not None:
        if not isinstance(late, int) or isinstance(late, bool) or late < 0:
            errors.append(
                f"admitted_late must be a non-negative integer, got "
                f"{late!r}")
        elif isinstance(n, int) and not isinstance(n, bool) and late > n:
            errors.append(
                f"admitted_late ({late}) exceeds window_requests ({n})")
    over = rec.get("over_slo")
    if over is not None:
        if not isinstance(over, int) or isinstance(over, bool) or over < 0:
            errors.append(
                f"over_slo must be a non-negative integer, got {over!r}")
        elif isinstance(n, int) and not isinstance(n, bool) and over > n:
            errors.append(
                f"over_slo ({over}) exceeds window_requests ({n})")
        if not _is_number(rec.get("slo_target_ms")) or \
                rec.get("slo_target_ms") <= 0:
            errors.append(
                "over_slo requires a positive slo_target_ms, got "
                f"{rec.get('slo_target_ms')!r}")


def _check_fault_fields(rec, errors) -> None:
    """Fault-record consistency (schema v1 addition; docs/
    fault_tolerance.md): the fault name is a non-empty string and the
    injection marker is a real boolean — consumers filter chaos-run
    artifacts on ``injected`` and must be able to trust it."""
    fault = rec.get("fault")
    if not isinstance(fault, str) or not fault:
        errors.append(f"fault must be a non-empty string, got {fault!r}")
    if not isinstance(rec.get("injected"), bool):
        errors.append(
            f"fault record 'injected' must be a boolean, got "
            f"{rec.get('injected')!r}")


def _check_fleet_fields(rec, errors) -> None:
    """fleet_event consistency (serve/supervisor.py): the event is a
    non-empty string and the replica identity is a real non-negative
    index — the chaos harness reconstructs the supervisor's decision
    sequence from these and must be able to trust the join keys."""
    event = rec.get("event")
    if not isinstance(event, str) or not event:
        errors.append(f"event must be a non-empty string, got {event!r}")
    for key in ("replica", "port"):
        v = rec.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errors.append(
                f"{key} must be a non-negative integer, got {v!r}")
    backoff = rec.get("backoff_s")
    if backoff is not None and (not _is_number(backoff) or backoff < 0):
        errors.append(
            f"backoff_s must be a non-negative number, got {backoff!r}")


# Router counter keys whose values must be non-negative integers; the
# outcome triple additionally decomposes the window exactly (every
# routed request is ok, shed, or errored — a router that loses requests
# between the counters is the bug this invariant exists to catch).
_ROUTER_COUNTERS = ("ok", "sheds", "errors", "retries", "hedges",
                    "hedge_wins", "failovers")


def _check_router_fields(rec, errors) -> None:
    """router_window/router_summary consistency (serve/router.py)."""
    total_key = ("window_requests" if rec.get("kind") == "router_window"
                 else "requests")
    ints = {}
    for key in (total_key,) + _ROUTER_COUNTERS + (
            "healthy_replicas", "replicas"):
        v = rec.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errors.append(
                f"{key} must be a non-negative integer, got {v!r}")
        else:
            ints[key] = v
    if {total_key, "ok", "sheds", "errors"} <= set(ints) and \
            ints["ok"] + ints["sheds"] + ints["errors"] != ints[total_key]:
        errors.append(
            f"ok + sheds + errors must equal {total_key} "
            f"({ints['ok']} + {ints['sheds']} + {ints['errors']} != "
            f"{ints[total_key]}): every routed request is exactly one "
            "of the three")
    if {"hedges", "hedge_wins"} <= set(ints) and \
            ints["hedge_wins"] > ints["hedges"]:
        errors.append(
            f"hedge_wins ({ints['hedge_wins']}) exceeds hedges "
            f"({ints['hedges']})")
    if {"healthy_replicas", "replicas"} <= set(ints) and \
            ints["healthy_replicas"] > ints["replicas"]:
        errors.append(
            f"healthy_replicas ({ints['healthy_replicas']}) exceeds "
            f"replicas ({ints['replicas']})")
    wasted = rec.get("hedge_wasted_ms")
    if wasted is not None:
        # Hedge-loser waste (ISSUE 16): optional — pre-tracing windows
        # omit it — but non-negative, and zero whenever no hedge fired
        # (waste with no hedge would mean the counters were folded in
        # different lock acquisitions, the PR 11 race all over again).
        if not _is_number(wasted) or wasted < 0:
            errors.append(
                f"hedge_wasted_ms must be a non-negative number, got "
                f"{wasted!r}")
        elif wasted > 0 and ints.get("hedges") == 0:
            errors.append(
                f"hedge_wasted_ms ({wasted}) positive with zero hedges: "
                "waste is accounted per hedged race")
    for prefix, pcts in (("latency", ("p50", "p95", "p99")),
                         ("failover", ("p50", "p95"))):
        vals = [rec.get(f"{prefix}_{p}_ms") for p in pcts]
        for p, v in zip(pcts, vals):
            if v is not None and (not _is_number(v) or v < 0):
                errors.append(
                    f"{prefix}_{p}_ms must be a non-negative number, "
                    f"got {v!r}")
        present = [v for v in vals if _is_number(v)]
        if len(present) == len(pcts) and present != sorted(present):
            errors.append(
                f"{prefix} percentiles not ordered "
                f"({' <= '.join(pcts)}): {present}")


def _check_router_trace_fields(rec, errors) -> None:
    """router_trace consistency (serve/router.py): the router-tier span
    tree behind the end-to-end stitch. Every span is a sub-interval of
    the request (spans may overlap — a hedged race runs two attempts
    concurrently — so there is no additive sum rule), every attempt span
    names its target replica and outcome, and the ``attempts`` counter
    must equal the number of attempt spans — the stitcher joins the
    winning attempt by index and must be able to trust it."""
    for key in ("trace_id", "task"):
        v = rec.get(key)
        if not isinstance(v, str) or not v:
            errors.append(f"{key} must be a non-empty string, got {v!r}")
    status = rec.get("status")
    if not isinstance(status, int) or isinstance(status, bool) or \
            status < 0:
        errors.append(
            f"status must be a non-negative integer, got {status!r}")
    total = rec.get("total_ms")
    if not _is_number(total) or total < 0:
        errors.append(
            f"total_ms must be a non-negative number, got {total!r}")
        total = None
    if not isinstance(rec.get("sampled"), bool):
        errors.append(
            f"router_trace 'sampled' must be a boolean, got "
            f"{rec.get('sampled')!r}")
    attempts = rec.get("attempts")
    if not isinstance(attempts, int) or isinstance(attempts, bool) or \
            attempts < 0:
        errors.append(
            f"attempts must be a non-negative integer, got {attempts!r}")
        attempts = None
    for key in ("hedges",):
        v = rec.get(key)
        if v is not None and (not isinstance(v, int)
                              or isinstance(v, bool) or v < 0):
            errors.append(
                f"{key} must be a non-negative integer, got {v!r}")
    wasted = rec.get("hedge_wasted_ms")
    if wasted is not None and (not _is_number(wasted) or wasted < 0):
        errors.append(
            f"hedge_wasted_ms must be a non-negative number, got "
            f"{wasted!r}")
    winning = rec.get("winning_attempt")
    if winning is not None:
        if not isinstance(winning, int) or isinstance(winning, bool) or \
                winning < 1:
            errors.append(
                f"winning_attempt must be a positive integer, got "
                f"{winning!r}")
        elif attempts is not None and winning > attempts:
            errors.append(
                f"winning_attempt ({winning}) exceeds attempts "
                f"({attempts})")
    spans = rec.get("spans")
    if not isinstance(spans, list) or not spans:
        errors.append(
            f"router_trace 'spans' must be a non-empty list, got "
            f"{spans!r}")
        return
    attempt_spans = 0
    for i, span in enumerate(spans):
        if not isinstance(span, dict) or not {"name", "start_ms",
                                              "dur_ms"} <= set(span):
            errors.append(
                f"spans[{i}] must be an object with name/start_ms/dur_ms, "
                f"got {span!r}")
            continue
        name = span["name"]
        if name not in ROUTER_TRACE_SPANS:
            errors.append(
                f"spans[{i}].name must be one of {ROUTER_TRACE_SPANS}, "
                f"got {name!r}")
        bad_number = False
        for key in ("start_ms", "dur_ms"):
            v = span[key]
            if not _is_number(v) or v < 0:
                errors.append(
                    f"spans[{i}].{key} must be a non-negative number, "
                    f"got {v!r}")
                bad_number = True
        if not bad_number and total is not None and \
                span["start_ms"] + span["dur_ms"] > total + _TRACE_EPS_MS:
            errors.append(
                f"spans[{i}] ends past total_ms "
                f"({span['start_ms']} + {span['dur_ms']} > {total}): "
                "router spans must be sub-intervals of the request")
        if name == "attempt":
            attempt_spans += 1
            idx = span.get("attempt")
            if not isinstance(idx, int) or isinstance(idx, bool) or \
                    idx < 1:
                errors.append(
                    f"spans[{i}].attempt must be a positive integer, "
                    f"got {idx!r}")
            replica = span.get("replica")
            if not isinstance(replica, str) or not replica:
                errors.append(
                    f"spans[{i}].replica must be a non-empty string, "
                    f"got {replica!r}")
            outcome = span.get("outcome")
            if not isinstance(outcome, str) or not outcome:
                errors.append(
                    f"spans[{i}].outcome must be a non-empty string, "
                    f"got {outcome!r}")
    if attempts is not None and attempt_spans != attempts:
        errors.append(
            f"attempts ({attempts}) must equal the number of attempt "
            f"spans ({attempt_spans})")


def _check_stitch_fields(rec, errors) -> None:
    """trace_stitch consistency (telemetry/collector.py): the stitched
    tree's arithmetic must hold — client_total_ms decomposes exactly
    into router_overhead_ms + network_gap_ms + replica_ms (the
    acceptance invariant ``client_total >= router_overhead + winning
    replica span sum`` follows whenever the gap is non-negative, which
    is what ``consistent`` asserts) — and the orphan marker must be a
    real boolean consumers can count on: a replica span with no router
    parent is ALWAYS an orphan, never silently re-labeled."""
    v = rec.get("trace_id")
    if not isinstance(v, str) or not v:
        errors.append(f"trace_id must be a non-empty string, got {v!r}")
    orphan = rec.get("orphan")
    if not isinstance(orphan, bool):
        errors.append(
            f"trace_stitch 'orphan' must be a boolean, got {orphan!r}")
        orphan = None
    counts = {}
    for key in ("router_spans", "replica_spans"):
        n = rec.get(key)
        if not isinstance(n, int) or isinstance(n, bool) or n < 0:
            errors.append(
                f"{key} must be a non-negative integer, got {n!r}")
        else:
            counts[key] = n
    if len(counts) == 2:
        if counts["router_spans"] + counts["replica_spans"] == 0:
            errors.append(
                "trace_stitch must join at least one span "
                "(router_spans + replica_spans >= 1)")
        if orphan is False and counts["router_spans"] == 0:
            errors.append(
                "a stitch with no router_trace parent must be marked "
                "orphan (replica spans never lose their orphanhood "
                "silently)")
    parts = {}
    for key in ("client_total_ms", "router_overhead_ms", "replica_ms"):
        v = rec.get(key)
        if v is not None:
            if not _is_number(v) or v < 0:
                errors.append(
                    f"{key} must be a non-negative number, got {v!r}")
            else:
                parts[key] = v
    gap = rec.get("network_gap_ms")
    if gap is not None:
        # The gap alone may be slightly negative (replica and router
        # measure on different clocks); ``consistent`` flags that.
        if not _is_number(gap):
            errors.append(
                f"network_gap_ms must be a number, got {gap!r}")
        else:
            parts["network_gap_ms"] = gap
    consistent = rec.get("consistent")
    if consistent is not None and not isinstance(consistent, bool):
        errors.append(
            f"trace_stitch 'consistent' must be a boolean, got "
            f"{consistent!r}")
    if len(parts) == 4:
        lhs = parts["router_overhead_ms"] + parts["network_gap_ms"] + \
            parts["replica_ms"]
        if abs(lhs - parts["client_total_ms"]) > _STITCH_EPS_MS:
            errors.append(
                f"stitch decomposition must sum to client_total_ms "
                f"({round(lhs, 3)} != {parts['client_total_ms']}): "
                "router_overhead_ms + network_gap_ms + replica_ms is "
                "an exact decomposition, not an estimate")
        if consistent is True and \
                parts["network_gap_ms"] < -_STITCH_EPS_MS:
            errors.append(
                f"consistent stitch requires a non-negative "
                f"network_gap_ms, got {parts['network_gap_ms']}")
    winning = rec.get("winning_attempt")
    if winning is not None and (not isinstance(winning, int)
                                or isinstance(winning, bool)
                                or winning < 1):
        errors.append(
            f"winning_attempt must be a positive integer, got {winning!r}")


def _check_obs_scrape_fields(rec, errors) -> None:
    """obs_scrape consistency (telemetry/collector.py): the target
    identity is a non-empty string of a known kind, ``ok`` is a real
    boolean (the collector's health aggregation and the staleness gate
    both filter on it), and staleness/scrape cost are non-negative —
    a negative staleness would mean the collector's clocks ran
    backwards, which is corruption, not data."""
    target = rec.get("target")
    if not isinstance(target, str) or not target:
        errors.append(f"target must be a non-empty string, got {target!r}")
    kind = rec.get("target_kind")
    if kind not in OBS_TARGET_KINDS:
        errors.append(
            f"target_kind must be one of {OBS_TARGET_KINDS}, got {kind!r}")
    if not isinstance(rec.get("ok"), bool):
        errors.append(
            f"obs_scrape 'ok' must be a boolean, got {rec.get('ok')!r}")
    for key in ("staleness_s", "scrape_ms", "queue_depth",
                "latency_p99_ms", "requests", "errors", "over_slo"):
        v = rec.get(key)
        if v is not None and (not _is_number(v) or v < 0):
            errors.append(
                f"{key} must be a non-negative number, got {v!r}")


def _check_obs_fleet_fields(rec, errors) -> None:
    """obs_fleet_window consistency (telemetry/collector.py): the
    healthy/total pairs are non-negative integers with healthy bounded
    by total (a window claiming more healthy targets than targets is
    the aggregation bug this invariant exists to catch), and every
    rate/latency/burn aggregate is a non-negative number."""
    ints = {}
    for key in ("targets_total", "targets_healthy", "replicas_total",
                "replicas_healthy"):
        v = rec.get(key)
        if v is None and key in ("replicas_total", "replicas_healthy"):
            continue  # optional pair: a trainer-only fleet has none
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errors.append(
                f"{key} must be a non-negative integer, got {v!r}")
        else:
            ints[key] = v
    for healthy, total in (("targets_healthy", "targets_total"),
                           ("replicas_healthy", "replicas_total")):
        if {healthy, total} <= set(ints) and \
                ints[healthy] > ints[total]:
            errors.append(
                f"{healthy} ({ints[healthy]}) exceeds {total} "
                f"({ints[total]})")
    for key in ("max_staleness_s", "worst_replica_p99_ms", "fleet_rps",
                "trainer_steps_per_sec", "error_budget_burn"):
        v = rec.get(key)
        if key == "max_staleness_s" and v is None:
            continue  # required-key check already flagged it
        if v is not None and (not _is_number(v) or v < 0):
            errors.append(
                f"{key} must be a non-negative number, got {v!r}")


# Where an autotune record's geometry may come from
# (ops/pallas/autotune.py; serve/engine.py _setup_autotune).
AUTOTUNE_SOURCES = ("measured", "cached", "heuristic")


def _check_autotune_fields(rec, errors) -> None:
    """autotune-record consistency (ops/pallas/autotune.py): the kernel
    name is non-empty, seq/bh are positive integers, the source is one
    of the known provenances, and — when a winner is attached — its
    blocks tile the shape (a winner whose block does not divide seq
    would describe a grid the kernel cannot run; recording it would
    poison every consumer that replays geometry from artifacts)."""
    kernel = rec.get("kernel")
    if not isinstance(kernel, str) or not kernel:
        errors.append(f"kernel must be a non-empty string, got {kernel!r}")
    for key in ("seq", "bh"):
        v = rec.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 1:
            errors.append(
                f"{key} must be a positive integer, got {v!r}")
    source = rec.get("source")
    if source not in AUTOTUNE_SOURCES:
        errors.append(
            f"source must be one of {AUTOTUNE_SOURCES}, got {source!r}")
    winner = rec.get("winner")
    if winner is not None:
        if not isinstance(winner, dict):
            errors.append(f"winner must be an object, got {winner!r}")
        else:
            for field in ("block_q", "block_k", "bh_block"):
                v = winner.get(field)
                if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                    errors.append(
                        f"winner.{field} must be a positive integer, "
                        f"got {v!r}")
                    continue
                seq, bh = rec.get("seq"), rec.get("bh")
                if field.startswith("block") and isinstance(seq, int) \
                        and not isinstance(seq, bool) and seq >= 1 \
                        and seq % v != 0:
                    errors.append(
                        f"winner.{field}={v} does not divide seq {seq}")
                if field == "bh_block" and isinstance(bh, int) \
                        and not isinstance(bh, bool) and bh >= 1 \
                        and bh % v != 0:
                    errors.append(
                        f"winner.bh_block={v} does not divide bh {bh}")
    elif source in ("measured", "cached"):
        errors.append(f"source {source!r} requires a winner object")


def _check_profile_fields(rec, errors) -> None:
    """profile_window consistency (telemetry/sampler.py): the capture
    names its source and trigger, the covered count is a non-negative
    integer of a known unit, and the host-frame table is internally
    consistent — every frame's sample count is a positive integer
    bounded by the capture's total, and the self-time shares are in
    (0, 1] summing to no more than 1 (within rounding slack). A frame
    claiming more samples than the sampler took would mean the
    attribution folded two captures together — the double-arm race the
    409 guard exists to prevent."""
    source = rec.get("source")
    if not isinstance(source, str) or not source:
        errors.append(f"source must be a non-empty string, got {source!r}")
    trigger = rec.get("trigger")
    if trigger not in PROFILE_TRIGGERS:
        errors.append(
            f"trigger must be one of {PROFILE_TRIGGERS}, got {trigger!r}")
    unit = rec.get("covered_unit")
    if unit not in PROFILE_COVERED_UNITS:
        errors.append(
            f"covered_unit must be one of {PROFILE_COVERED_UNITS}, "
            f"got {unit!r}")
    covered = rec.get("covered")
    if not isinstance(covered, int) or isinstance(covered, bool) \
            or covered < 0:
        errors.append(
            f"covered must be a non-negative integer, got {covered!r}")
    samples = rec.get("samples")
    if not isinstance(samples, int) or isinstance(samples, bool) \
            or samples < 0:
        errors.append(
            f"samples must be a non-negative integer, got {samples!r}")
        samples = None
    for key in ("duration_s", "trace_bytes", "sample_interval_s"):
        v = rec.get(key)
        if key == "sample_interval_s" and v is None:
            continue  # optional: trace-only captures omit it
        if not _is_number(v) or v < 0:
            errors.append(
                f"{key} must be a non-negative number, got {v!r}")
    path = rec.get("trace_path")
    if not isinstance(path, str):
        # Empty is legal (trace skipped: another window active, or a
        # jax-free host); a non-string would break every path consumer.
        errors.append(f"trace_path must be a string, got {path!r}")
    frames = rec.get("top_frames")
    if not isinstance(frames, list):
        errors.append(
            f"top_frames must be a list, got {type(frames).__name__}")
        return
    share_sum = 0.0
    for i, frame in enumerate(frames):
        if not isinstance(frame, dict):
            errors.append(f"top_frames[{i}] must be an object, "
                          f"got {frame!r}")
            continue
        name = frame.get("frame")
        if not isinstance(name, str) or not name:
            errors.append(
                f"top_frames[{i}].frame must be a non-empty string, "
                f"got {name!r}")
        n = frame.get("samples")
        if not isinstance(n, int) or isinstance(n, bool) or n < 1:
            errors.append(
                f"top_frames[{i}].samples must be a positive integer, "
                f"got {n!r}")
        elif samples is not None and n > samples:
            errors.append(
                f"top_frames[{i}].samples ({n}) exceeds the capture's "
                f"total samples ({samples})")
        share = frame.get("share")
        if not _is_number(share) or share <= 0 or share > 1:
            errors.append(
                f"top_frames[{i}].share must be a number in (0, 1], "
                f"got {share!r}")
        else:
            share_sum += share
    if share_sum > 1.0 + 1e-6 + 0.005 * max(1, len(frames)):
        # Per-frame rounding slack: shares are rounded at emission.
        errors.append(
            f"top_frames shares sum to {share_sum:.4f} > 1: self-time "
            "attribution must decompose the capture, not exceed it")


def _check_ledger_fields(rec, errors) -> None:
    """ledger_entry consistency (telemetry/ledger.py): the trajectory
    point names its leg and config digest (the comparability join keys
    the drift gate filters on) and carries a non-empty metrics object of
    finite non-negative numbers, with the same percentile-ordering and
    ratio-domain rules the live record kinds obey — a ledger whose
    history is internally inconsistent cannot anchor a drift verdict."""
    for key in ("leg", "config_digest"):
        v = rec.get(key)
        if not isinstance(v, str) or not v:
            errors.append(f"{key} must be a non-empty string, got {v!r}")
    metrics = rec.get("metrics")
    if not isinstance(metrics, dict) or not metrics:
        errors.append(
            f"metrics must be a non-empty object, got {metrics!r}")
        return
    nums = {}
    for key, v in metrics.items():
        if not _is_number(v) or v < 0:
            errors.append(
                f"metrics.{key} must be a non-negative number, got {v!r}")
        else:
            nums[key] = v
    for lo, hi in (("step_ms_p50", "step_ms_p95"),
                   ("serve_p50_ms", "serve_p99_ms")):
        if {lo, hi} <= set(nums) and nums[lo] > nums[hi]:
            errors.append(
                f"metrics.{lo} ({nums[lo]}) exceeds metrics.{hi} "
                f"({nums[hi]}): percentiles must be ordered")
    for key in ("padding_efficiency", "mfu"):
        if key in nums and nums[key] > 1:
            errors.append(
                f"metrics.{key} must be a ratio in [0, 1], "
                f"got {nums[key]!r}")


def _check_registry_event_fields(rec, errors) -> None:
    """registry_event consistency (serve/registry.py): the version name
    is the join key across registry/rollout/fleet records, the resulting
    state must be a known lifecycle state, and a transition must be a
    legal state-machine edge — a rollback additionally names WHY (the
    breach reason is where the post-incident read starts)."""
    for key in ("version", "event"):
        v = rec.get(key)
        if not isinstance(v, str) or not v:
            errors.append(f"{key} must be a non-empty string, got {v!r}")
    state = rec.get("state")
    if state not in REGISTRY_STATES:
        errors.append(
            f"state must be one of {REGISTRY_STATES}, got {state!r}")
    from_state = rec.get("from_state")
    if from_state is not None:
        if (from_state, state) not in REGISTRY_TRANSITIONS:
            errors.append(
                f"illegal registry transition {from_state!r} -> "
                f"{state!r} (legal edges: {REGISTRY_TRANSITIONS})")
        if (from_state, state) == ("canary", "staged"):
            reason = rec.get("reason")
            if not isinstance(reason, str) or not reason:
                errors.append(
                    "a rollback (canary -> staged) must carry a "
                    f"non-empty 'reason', got {reason!r}")
    elif rec.get("event") == "state_change":
        errors.append("event 'state_change' requires from_state")
    digest = rec.get("digest")
    if digest is not None and (not isinstance(digest, str) or not digest):
        errors.append(f"digest must be a non-empty string, got {digest!r}")


def _check_rollout_window_fields(rec, errors) -> None:
    """rollout_window consistency (serve/rollout.py): the canary share
    is a traffic fraction, the cohort's ok/error split must fit inside
    its window, percentiles are ordered, the action is one of the
    controller's four decisions, and a rollback names its breach."""
    for key in ("task", "version"):
        v = rec.get(key)
        if not isinstance(v, str) or not v:
            errors.append(f"{key} must be a non-empty string, got {v!r}")
    stage = rec.get("stage")
    if not isinstance(stage, int) or isinstance(stage, bool) or stage < 0:
        errors.append(
            f"stage must be a non-negative integer, got {stage!r}")
    share = rec.get("canary_share")
    if not _is_number(share) or not 0 <= share <= 1:
        errors.append(f"canary_share must be in [0, 1], got {share!r}")
    counts = {}
    for key in ("window_requests", "ok", "errors", "torn_serves"):
        v = rec.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errors.append(
                f"{key} must be a non-negative integer, got {v!r}")
        else:
            counts[key] = v
    if {"window_requests", "ok", "errors"} <= set(counts) and \
            counts["ok"] + counts["errors"] > counts["window_requests"]:
        errors.append(
            "ok + errors exceeds window_requests "
            f"({counts['ok']} + {counts['errors']} > "
            f"{counts['window_requests']})")
    if not isinstance(rec.get("slo_ok"), bool):
        errors.append(
            f"slo_ok must be a boolean, got {rec.get('slo_ok')!r}")
    action = rec.get("action")
    if action not in ROLLOUT_ACTIONS:
        errors.append(
            f"action must be one of {ROLLOUT_ACTIONS}, got {action!r}")
    if action == "rollback":
        reason = rec.get("reason")
        if not isinstance(reason, str) or not reason:
            errors.append(
                "action 'rollback' must carry a non-empty 'reason', "
                f"got {reason!r}")
    vals = [rec.get(f"latency_{p}_ms") for p in ("p50", "p95", "p99")]
    nums = [v for v in vals if _is_number(v)]
    if len(nums) == 3 and not (nums[0] <= nums[1] <= nums[2]):
        errors.append(
            f"latency percentiles not ordered (p50 <= p95 <= p99): "
            f"{nums}")
    burn = rec.get("budget_burn")
    if burn is not None and (not _is_number(burn) or burn < 0):
        errors.append(
            f"budget_burn must be a non-negative number, got {burn!r}")


def _check_scale_event_fields(rec, errors) -> None:
    """scale_event consistency (serve/autoscaler.py): the decision is
    one of the controller's three verdicts, the before/after replica
    counts move by exactly the decision's delta (a hold holds, a
    scale_up adds ONE, a scale_down removes ONE), counts stay positive,
    and the signal values that justified the verdict are sane."""
    decision = rec.get("decision")
    if decision not in SCALE_DECISIONS:
        errors.append(
            f"decision must be one of {SCALE_DECISIONS}, got "
            f"{decision!r}")
    reason = rec.get("reason")
    if not isinstance(reason, str) or not reason:
        errors.append(
            f"reason must be a non-empty string, got {reason!r}")
    counts = {}
    for key in ("replicas_before", "replicas_after"):
        v = rec.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errors.append(
                f"{key} must be a non-negative integer, got {v!r}")
        else:
            counts[key] = v
    exo = rec.get("exogenous")
    if not isinstance(exo, int) or isinstance(exo, bool):
        errors.append(f"exogenous must be an integer, got {exo!r}")
    if len(counts) == 2 and decision in SCALE_DECISIONS:
        delta = {"scale_up": 1, "scale_down": -1, "hold": 0}[decision]
        if counts["replicas_after"] != counts["replicas_before"] + delta:
            errors.append(
                f"decision {decision!r} must move replicas by {delta:+d} "
                f"(got {counts['replicas_before']} -> "
                f"{counts['replicas_after']})")
    for key in ("window_requests", "window_errors", "window_sheds",
                "reds", "greens", "healthy", "unfinished", "replica"):
        v = rec.get(key)
        if v is not None and (not isinstance(v, int)
                              or isinstance(v, bool) or v < 0):
            errors.append(
                f"{key} must be a non-negative integer, got {v!r}")
    for key in ("queue_wait_share", "budget_burn", "cooldown_s",
                "since_last_scale_s"):
        v = rec.get(key)
        if v is not None and (not _is_number(v) or v < 0):
            errors.append(
                f"{key} must be a non-negative number, got {v!r}")
    share = rec.get("queue_wait_share")
    if _is_number(share) and share > 1:
        errors.append(
            f"queue_wait_share must be in [0, 1], got {share!r}")


def _check_resume_fields(rec, errors) -> None:
    """Resume-record consistency: ``skipped`` is a list of objects each
    naming what was passed over and why (utils/checkpoint.py walk-back)."""
    skipped = rec.get("skipped")
    if not isinstance(skipped, list):
        errors.append(f"resume 'skipped' must be a list, got "
                      f"{type(skipped).__name__}")
        return
    for i, entry in enumerate(skipped):
        if not isinstance(entry, dict) or not {"step", "path", "reason"} \
                <= set(entry):
            errors.append(
                f"resume skipped[{i}] must be an object with "
                f"step/path/reason, got {entry!r}")


def _check_finite(key, value, errors) -> None:
    """Non-finite floats anywhere in the record (grad_health nests its
    per-group stats; memory/compile_cost nest nothing today but may)."""
    if isinstance(value, float) and not math.isfinite(value):
        errors.append(f"non-finite value for {key!r}")
    elif isinstance(value, dict):
        for k, v in value.items():
            _check_finite(f"{key}.{k}", v, errors)
    elif isinstance(value, list):
        for i, v in enumerate(value):
            _check_finite(f"{key}[{i}]", v, errors)


def validate_line(line: str) -> list:
    """Schema errors for one raw JSONL line (empty list = valid)."""
    stripped = line.strip()
    if not stripped:
        return []  # blank lines tolerated (trailing newline etc.)
    for spelling in _NONFINITE_SPELLINGS:
        # json.loads accepts these non-standard spellings; downstream
        # strict parsers (jq, pandas with precise_float, other languages)
        # do not — reject them at the source.
        if spelling in stripped:
            try:
                json.loads(stripped, parse_constant=_reject_constant)
            except _NonFiniteConstant:
                return [f"non-finite JSON constant in line"]
            except ValueError:
                break  # fall through to the normal parse error below
            break
    try:
        rec = json.loads(stripped)
    except ValueError as exc:
        return [f"not valid JSON: {exc}"]
    return validate_record(rec)


class _NonFiniteConstant(ValueError):
    pass


def _reject_constant(name):
    raise _NonFiniteConstant(name)


def validate_file(path: str) -> list:
    """(line_number, error) pairs for a JSONL file; empty list = valid.

    Beyond the per-line rules this applies the one CROSS-record lint the
    stream carries: within one (task, version) rollout, ``canary_share``
    may only advance (the controller holds or grows the cohort) until an
    explicit ``rollback`` record resets the ramp — a share that shrinks
    without a rollback means two controllers fought over the split,
    which no single emitter produces.

    ``scale_event`` streams carry a second cross-record lint: fleet
    membership must be RECONSTRUCTIBLE from the event stream — each
    event's ``replicas_before`` must equal the previous event's
    ``replicas_after`` plus its declared ``exogenous`` drift. A count
    that jumps without a declaration means the autoscaler lost track of
    the fleet it manages (a SIGKILLed replica double-counted as
    capacity, exactly the drift the surge chaos run forbids)."""
    errors = []
    shares: dict = {}
    chain: dict = {}
    with open(path, "r", encoding="utf-8") as f:
        for lineno, line in enumerate(f, start=1):
            line_errors = validate_line(line)
            for err in line_errors:
                errors.append((lineno, err))
            stripped = line.strip()
            if line_errors or not stripped:
                continue
            rec = json.loads(stripped)
            if isinstance(rec, dict) and "schema" in rec \
                    and rec.get("kind") == "rollout_window":
                for err in _check_rollout_sequence(rec, shares):
                    errors.append((lineno, err))
            if isinstance(rec, dict) and "schema" in rec \
                    and rec.get("kind") == "scale_event":
                for err in _check_scale_chain(rec, chain):
                    errors.append((lineno, err))
    return errors


def _check_rollout_sequence(rec, shares: dict) -> list:
    """The cross-record monotone-share rule (see validate_file)."""
    key = (rec.get("task"), rec.get("version"))
    share = rec.get("canary_share")
    if not _is_number(share):
        return []
    if rec.get("action") == "rollback":
        shares.pop(key, None)  # a re-attempt starts the ramp over
        return []
    last = shares.get(key)
    shares[key] = max(share, last) if last is not None else share
    if last is not None and share < last:
        return [
            f"canary_share regressed without a rollback for task "
            f"{rec.get('task')!r} version {rec.get('version')!r}: "
            f"{share} < {last} (shares advance monotonically per stage)"]
    return []


def _check_scale_chain(rec, chain: dict) -> list:
    """The cross-record membership-reconstruction rule (see
    validate_file): replicas_before == previous replicas_after +
    exogenous, per tag (one chain per autoscaler instance)."""
    before = rec.get("replicas_before")
    after = rec.get("replicas_after")
    exo = rec.get("exogenous")
    if not isinstance(before, int) or not isinstance(after, int) \
            or not isinstance(exo, int):
        return []  # field-level errors already reported per record
    key = rec.get("tag")
    last = chain.get(key)
    chain[key] = after
    if last is not None and before != last + exo:
        return [
            f"fleet membership not reconstructible: replicas_before="
            f"{before} but previous replicas_after={last} with declared "
            f"exogenous drift {exo:+d} (expected "
            f"{last + exo})"]
    return []
