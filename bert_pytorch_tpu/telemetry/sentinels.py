"""Failure sentinels: non-finite detection policy + a liveness heartbeat.

The in-jit half lives in the train step (pretrain.make_train_step emits
``metrics["finite"]`` — an ``isfinite`` reduction over the step's losses and
global grad-norm, one scalar, free to fetch alongside the loss). This module
is the host half: the policy applied to that scalar, and the heartbeat file
the capture harness reads instead of guessing liveness from checkpoint
mtimes (scripts/retry_capture_r04.sh).

K-FAC HBM overflows and fp16 overflows in rounds 2-4 surfaced as NaN losses
that kept training silently for hundreds of steps before anyone looked at
the CSV. ``policy="abort"`` turns that into a loud, bounded failure:
``patience`` CONSECUTIVE observed non-finite steps raise
:class:`NonFiniteError` (one bad step recovered by the fp16 loss-scaler
backoff does not kill the run; a divergence does). ``policy="continue"``
(default) logs a sentinel record per observed bad step and keeps going —
the reference's implicit behavior, now at least visible in the artifacts.

Observation cadence: fetching the finite scalar is a device sync, so the
sentinel sees a step only when the runner synced it — every
``--telemetry_sync_every``-th step plus every log step. A sampled cadence
stretches detection accordingly (patience 3 at cadence 4 aborts within
~12 steps of a hard divergence, not 3); runs that want step-exact abort
pass ``--telemetry_sync_every 1``. A NaN burst shorter than the cadence
that the loss-scaler recovers in between can go entirely unobserved —
which is also why patience counts OBSERVED consecutive bad steps.
"""

from __future__ import annotations

import json
import os
import threading
import time
import warnings
from typing import Callable, Optional


class NonFiniteError(RuntimeError):
    """Raised by the abort policy after ``patience`` consecutive bad steps."""


class FailureSentinel:
    POLICIES = ("continue", "abort")

    def __init__(self, policy: str = "continue", patience: int = 3,
                 emit: Optional[Callable[[dict], None]] = None):
        if policy not in self.POLICIES:
            raise ValueError(
                f"sentinel policy must be one of {self.POLICIES}, got "
                f"{policy!r}")
        self.policy = policy
        self.patience = max(1, int(patience))
        self._emit = emit
        self.consecutive = 0
        self.total_nonfinite = 0

    def observe(self, step: int, finite, loss=None) -> bool:
        """Feed one step's finite flag (truthy = healthy). Returns True when
        healthy; emits a sentinel record and applies the policy otherwise."""
        if bool(finite):
            self.consecutive = 0
            return True
        self.consecutive += 1
        self.total_nonfinite += 1
        record = {
            "kind": "sentinel",
            "tag": "telemetry",
            "step": int(step),
            "finite": 0,
            "loss": None if loss is None else float(loss),
            "consecutive_nonfinite": self.consecutive,
            "policy": self.policy,
        }
        if self._emit is not None:
            self._emit(record)
        if self.policy == "abort" and self.consecutive >= self.patience:
            raise NonFiniteError(
                f"non-finite loss/grad-norm for {self.consecutive} "
                f"consecutive steps (last step {step}); aborting per "
                f"--sentinel_policy abort")
        return False


class Heartbeat:
    """Rank-0 liveness file: ``{"step", "wallclock", "last_loss",
    "counter"}``, written atomically (tmp + rename) so a reader never sees
    a torn record. ``counter`` increments monotonically per beat — a
    restarted run resumes it from the file, so "is this process alive"
    is simply "did counter advance between two reads"."""

    def __init__(self, path: Optional[str], is_primary: bool = True,
                 clock: Callable[[], float] = time.time):
        self.path = path if is_primary else None
        self._clock = clock
        self.counter = 0
        self._last_loss = None
        if self.path:
            previous = self.read(self.path)
            if previous:
                self.counter = int(previous.get("counter", 0))
            os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                        exist_ok=True)

    def beat(self, step: int, last_loss=None) -> None:
        if not self.path:
            return
        self.counter += 1
        if last_loss is not None:
            self._last_loss = float(last_loss)
        payload = {
            "step": int(step),
            "wallclock": round(self._clock(), 3),
            "last_loss": self._last_loss,
            "counter": self.counter,
        }
        tmp = f"{self.path}.tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, self.path)

    @staticmethod
    def read(path: str) -> Optional[dict]:
        """Parse a heartbeat file; None when absent/torn (callers treat
        both as 'no evidence of liveness')."""
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None


class HeartbeatWatchdog:
    """Hung-step detector (docs/fault_tolerance.md).

    A wedged collective, a deadlocked input queue, or a hung storage
    mount stalls training WITHOUT crashing it — the loop just never
    reaches the next step boundary, and nothing in-process says so (the
    round-1 capture harness could only infer this from checkpoint mtimes
    going stale). The watchdog is a daemon thread fed a liveness note at
    every completed step (``TrainTelemetry.step_done``); when the age of
    the newest note exceeds ``max_age_s`` it emits one schema-v1
    ``fault`` record (``fault: "hung_step"``) and a warning, then
    re-arms only after progress resumes (one flag per stall, never a
    storm).

    Arming starts at the FIRST note, so the step-0 compile (minutes at
    BERT-large) never counts as a hang; size ``max_age_s`` generously —
    it bounds detection, and a false positive is only a log line (the
    watchdog flags, it never kills: the process may be seconds from
    recovering, and killing is the scheduler's call).
    """

    def __init__(self, max_age_s: float, emit: Optional[Callable] = None,
                 clock: Callable[[], float] = time.monotonic,
                 poll_s: Optional[float] = None):
        if max_age_s <= 0:
            raise ValueError(f"max_age_s must be > 0, got {max_age_s}")
        self.max_age_s = float(max_age_s)
        self._emit = emit
        self._clock = clock
        self._poll_s = poll_s if poll_s is not None else max(
            0.05, self.max_age_s / 4.0)
        self._lock = threading.Lock()
        self._last: Optional[tuple] = None  # (clock(), step)
        self._flagged = False
        self.stalls_flagged = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def note(self, step: int) -> None:
        """One completed step: refresh the liveness timestamp and re-arm."""
        with self._lock:
            self._last = (self._clock(), int(step))
            self._flagged = False

    def check(self) -> Optional[dict]:
        """The ``fault`` record if the run is stalled and unflagged, else
        None. Pure of the thread machinery so tests drive it with a fake
        clock instead of sleeping."""
        with self._lock:
            if self._last is None or self._flagged:
                return None
            noted_at, step = self._last
            age = self._clock() - noted_at
            if age < self.max_age_s:
                return None
            self._flagged = True
            self.stalls_flagged += 1
        return {
            "kind": "fault", "tag": "telemetry", "fault": "hung_step",
            "injected": False, "step": step,
            "age_s": round(age, 3), "max_age_s": self.max_age_s,
        }

    def _loop(self) -> None:
        while not self._stop.wait(self._poll_s):
            record = self.check()
            if record is not None:
                warnings.warn(
                    f"watchdog: no step completed for {record['age_s']:.1f}s "
                    f"(> {self.max_age_s:.1f}s) after step "
                    f"{record['step']}; the run may be hung")
                if self._emit is not None:
                    self._emit(record)

    def start(self) -> "HeartbeatWatchdog":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="telemetry-watchdog", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
