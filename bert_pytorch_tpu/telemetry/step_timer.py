"""Step-time decomposition with correct device-sync discipline.

Under JAX's async dispatch the wall time of ``train_step(...)`` is only the
HOST cost of building and enqueueing the program; the device executes in the
background and the next blocking operation (a metrics fetch, the next
``device_put``) absorbs the device time. Naive timing therefore conflates
three very different bottlenecks. The :class:`StepTimer` splits each step:

* ``data_wait`` — host blocked on the input pipeline (loader + prefetch);
* ``host`` — dispatch: trace/lower lookup + enqueue (compile lands here on
  step 0, which is why window records also carry ``max``, not just p50);
* ``device`` — dispatch-return until ``jax.block_until_ready`` on the
  step's metrics completes. Correct only when the caller syncs, so the
  timer owns the sync (:meth:`device_sync`) and records device time ONLY
  for synced steps.

Per-step syncing costs one host<->device round trip (measured ~35% reported
throughput loss through a remote-TPU tunnel — bench.py docstring), so the
sync cadence is a knob: ``sync_every=1`` gives the full decomposition,
``sync_every=N`` samples every Nth step and the unsynced steps contribute
data/host times only (``synced_steps`` in the record says how many device
samples a window holds). At ``N>1`` each device sample is the residual
BACKLOG at the sync point — the device work of the unsynced steps queued
since the previous sync, minus whatever overlapped host time — so the
``device_*`` percentiles then characterise sync tails, not single steps.

Every ``window`` steps :meth:`step_done` returns one ``kind="step_window"``
record (schema.py) with p50/p95/max per component and MFU. ``mfu_basis``
says how MFU was computed: ``"device"`` (from measured device seconds — the
hardware-normalised number that does not move when the input pipeline
stalls) when every step in the window was synced, ``"wall"`` (window FLOPs
over window wall time, the conventional definition) otherwise — dividing
per-step FLOPs by a multi-step backlog interval would deflate MFU by
roughly the sync cadence.

Padding-aware accounting (sequence packing, data/packing.py): given
``tokens_per_step`` (the step's token budget, pad included) and per-step
real-token counts (``note_tokens``, fed from the train step's
``real_tokens`` metric on the sync cadence), windows additionally report
``padding_efficiency`` (real/budget over the sampled steps),
``tokens_per_s`` with an explicit ``tokens_per_s_basis`` ("real" — pad
divided out; "all" — raw budget rate, the pre-packing convention), and
``mfu_real_tokens`` (MFU scaled to count only real-token FLOPs as useful
work, while ``mfu`` keeps reporting hardware occupancy).

Async-hot-path accounting (docs/telemetry.md): with a device prefetcher
attached, :meth:`note_h2d` records the host->device share of each step's
data wait and windows carry ``h2d_wait_*`` percentiles (clamped so
``h2d_wait <= data_wait`` always holds — it is a sub-phase);
:meth:`note_ckpt_stall` folds a checkpoint save's host stall into the step
it rode on, and windows with such steps carry ``ckpt_steps`` +
``ckpt_step_*`` percentiles — the checkpoint-step vs steady-state
comparison that async checkpointing (utils/checkpoint.py) collapses.

The clock is injectable for tests (``clock=fake``); the timer never calls
into JAX except through the ``sync`` callable handed to it.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from bert_pytorch_tpu.utils import flops as flops_util


def _percentile(sorted_vals: list, frac: float) -> float:
    """Nearest-rank percentile of an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(frac * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[idx]


def _stats(vals: list, prefix: str) -> dict:
    s = sorted(vals)
    return {
        f"{prefix}_p50_s": round(_percentile(s, 0.50), 6),
        f"{prefix}_p95_s": round(_percentile(s, 0.95), 6),
        f"{prefix}_max_s": round(s[-1] if s else 0.0, 6),
    }


class StepTimer:
    def __init__(
        self,
        window: int = 20,
        sync_every: int = 1,
        clock: Callable[[], float] = time.perf_counter,
        seq_per_step: Optional[int] = None,
        flops_per_seq: Optional[float] = None,
        device_kind: str = "",
        n_devices: int = 1,
        tokens_per_step: Optional[int] = None,
    ):
        self.window = max(1, int(window))
        self.sync_every = max(0, int(sync_every))  # 0 = never sync
        self._clock = clock
        self.seq_per_step = seq_per_step
        self.flops_per_seq = flops_per_seq
        self.device_kind = device_kind
        self.n_devices = max(1, int(n_devices))
        # Padding-aware accounting (docs/telemetry.md): tokens_per_step is
        # the step's token BUDGET (rows x seq_len, pad included); the train
        # step reports the real (non-pad) count via note_tokens on the sync
        # cadence. Their ratio is padding_efficiency — what sequence
        # packing (data/packing.py) exists to raise.
        self.tokens_per_step = tokens_per_step
        self.run_real_tokens = 0.0
        self.run_token_steps = 0
        self._step_index = 0
        self._reset_window()
        self._t_data0 = self._t_data1 = self._t_dispatch1 = None
        self._t_device1 = None
        self._pending_h2d = None
        self._h2d_attached = False
        self._last_step_s = 0.0

    def _reset_window(self):
        self._data_waits: list = []
        self._hosts: list = []
        self._devices: list = []
        self._steps: list = []
        self._real_tokens: list = []
        self._h2ds: list = []
        self._ckpt_steps_s: list = []
        self._window_t0 = None

    # -- per-step marks, in order --------------------------------------

    def data_start(self) -> None:
        self._t_data0 = self._clock()
        if self._window_t0 is None:
            self._window_t0 = self._t_data0

    def data_end(self) -> None:
        self._t_data1 = self._clock()

    def dispatch_end(self) -> None:
        self._t_dispatch1 = self._clock()

    def should_sync(self) -> bool:
        if self.sync_every == 0:
            return False
        return self._step_index % self.sync_every == 0

    def note_h2d(self, h2d_wait_s: float) -> None:
        """Record the host->device share of THIS step's data wait (the
        device-prefetch stage's attribution, data/device_prefetch.py).
        Called by the telemetry facade right after ``data_end``; clamped
        to the step's measured data_wait at :meth:`step_done`, so the
        ``h2d_wait_* <= data_wait_*`` invariant holds by construction."""
        self._pending_h2d = max(0.0, float(h2d_wait_s))
        self._h2d_attached = True

    def note_ckpt_stall(self, stall_s: float) -> None:
        """Record a checkpoint save's host stall, attributed to the step
        it rode on (the one that just finished). Window records then carry
        ``ckpt_steps`` and ``ckpt_step_*`` percentiles over step+stall
        durations — the number async checkpointing exists to collapse
        toward the steady-state step time (docs/telemetry.md)."""
        base = self._steps[-1] if self._steps else self._last_step_s
        self._ckpt_steps_s.append(base + max(0.0, float(stall_s)))

    def note_tokens(self, real_tokens: float) -> None:
        """Record one step's REAL (non-pad) token count. Called by the
        telemetry facade on synced steps only — the count rides in the
        step metrics, so reading it off-cadence would itself be a sync.
        Window records then report padding_efficiency and real-token
        throughput from the sampled steps."""
        self._real_tokens.append(float(real_tokens))
        self.run_real_tokens += float(real_tokens)
        self.run_token_steps += 1

    def run_padding_efficiency(self) -> Optional[float]:
        """Run-level real/budget token ratio over the sampled steps (None
        when no counts were observed or the budget is unknown)."""
        if not self.run_token_steps or not self.tokens_per_step:
            return None
        return self.run_real_tokens / (
            self.run_token_steps * self.tokens_per_step)

    def device_sync(self, sync_target) -> bool:
        """Block until the step's outputs are ready and record the device
        tail. Call after :meth:`dispatch_end`, only when :meth:`should_sync`
        (the caller may also force a sync, e.g. on log steps)."""
        import jax

        jax.block_until_ready(sync_target)
        self._t_device1 = self._clock()
        return True

    def step_done(self, step: int) -> Optional[dict]:
        """Finish the step; every ``window`` steps return the window record.

        Monotonic by construction: each component is a difference of
        successive clock reads, so components are non-negative and their
        sum never exceeds the step's total wall time.
        """
        if self._t_data0 is None or self._t_data1 is None:
            return None  # marks were skipped (e.g. epoch boundary)
        self._data_waits.append(max(0.0, self._t_data1 - self._t_data0))
        if self._h2d_attached:
            # Clamp to the step's own data_wait: h2d is a SUB-phase of it
            # (steps with no note contribute 0 — the prefetcher reported
            # nothing to attribute).
            self._h2ds.append(min(self._pending_h2d or 0.0,
                                  self._data_waits[-1]))
            self._pending_h2d = None
        if self._t_dispatch1 is not None:
            self._hosts.append(max(0.0, self._t_dispatch1 - self._t_data1))
            if self._t_device1 is not None and \
                    self._t_device1 >= self._t_dispatch1:
                self._devices.append(self._t_device1 - self._t_dispatch1)
        end = self._t_device1 if self._t_device1 is not None \
            else (self._t_dispatch1 if self._t_dispatch1 is not None
                  else self._t_data1)
        self._steps.append(max(0.0, end - self._t_data0))
        self._last_step_s = self._steps[-1]
        self._t_data0 = self._t_data1 = self._t_dispatch1 = None
        self._t_device1 = None
        self._step_index += 1

        if len(self._steps) < self.window:
            return None
        record = self._window_record(step, end)
        self._reset_window()
        return record

    def flush(self, step: int) -> Optional[dict]:
        """Emit a final partial-window record (end of run)."""
        if not self._steps and not self._ckpt_steps_s:
            # A checkpoint stall noted after the last full window rolled
            # (the end-of-run save) must still land in a record.
            return None
        record = self._window_record(step, None)
        self._reset_window()
        return record

    # -- window rollup --------------------------------------------------

    def _window_record(self, step: int, window_end) -> dict:
        n = len(self._steps)
        wall = ((window_end - self._window_t0)
                if (window_end is not None and self._window_t0 is not None)
                else sum(self._steps)) or 1e-9
        record = {
            "kind": "step_window",
            "tag": "telemetry",
            "step": step,
            "window_steps": n,
            "synced_steps": len(self._devices),
            "steps_per_sec": round(n / wall, 4),
        }
        record.update(_stats(self._data_waits, "data_wait"))
        if self._h2d_attached:
            # H2D sub-phase of data_wait (device prefetch attribution).
            # Per-step samples are clamped to that step's data_wait, and the
            # emitted percentiles are clamped pairwise again so the
            # h2d_wait <= data_wait invariant survives rounding and
            # unequal sample counts (schema.py lints it).
            h2d = _stats(self._h2ds, "h2d_wait")
            for suffix in ("p50_s", "p95_s", "max_s"):
                h2d[f"h2d_wait_{suffix}"] = min(
                    h2d[f"h2d_wait_{suffix}"], record[f"data_wait_{suffix}"])
            record.update(h2d)
        record.update(_stats(self._hosts, "host"))
        record.update(_stats(self._devices, "device"))
        record.update(_stats(self._steps, "step"))
        if self._ckpt_steps_s:
            # Steps a checkpoint save rode on, with the save's host stall
            # folded in: the checkpoint-step vs steady-state comparison
            # telemetry-report aggregates (async saves collapse these
            # toward step_p95_s).
            record["ckpt_steps"] = len(self._ckpt_steps_s)
            record.update(_stats(self._ckpt_steps_s, "ckpt_step"))
        record["mfu"], record["mfu_basis"] = self._window_mfu(wall, n)
        if self.seq_per_step:
            record["seq_per_sec"] = round(self.seq_per_step * n / wall, 2)
        if self.tokens_per_step:
            # Padding-aware throughput: tokens_per_s with an explicit basis
            # so pre-packing artifacts stay comparable. "real" divides out
            # the pad tokens (sampled from the steps the sync cadence
            # observed); "all" is the raw token budget rate (the only
            # number available when no step in the window was sampled).
            if self._real_tokens:
                eff = (sum(self._real_tokens)
                       / (len(self._real_tokens) * self.tokens_per_step))
                eff = min(1.0, eff)
                record["padding_efficiency"] = round(eff, 4)
                record["tokens_per_s"] = round(
                    self.tokens_per_step * n / wall * eff, 2)
                record["tokens_per_s_basis"] = "real"
                if record["mfu"]:
                    # Tokens-basis MFU: counts only real-token FLOPs as
                    # useful work (pad FLOPs ARE executed — "mfu" keeps
                    # reporting hardware occupancy; this reports how much
                    # of it trained the model).
                    record["mfu_real_tokens"] = round(
                        record["mfu"] * eff, 4)
            else:
                record["tokens_per_s"] = round(
                    self.tokens_per_step * n / wall, 2)
                record["tokens_per_s_basis"] = "all"
        return record

    def _window_mfu(self, wall: float, n_steps: int):
        """(mfu, basis). Device basis — window FLOPs over the peak FLOPs
        the chips could have delivered in the measured DEVICE seconds —
        only when EVERY step was synced; with a sampled cadence each device
        interval is a multi-step backlog, which would deflate device-basis
        MFU by ~the cadence, so the window falls back to wall basis (FLOPs
        over window wall time, the conventional definition). 0.0 when the
        device kind has no known peak (CPU)."""
        if not self.seq_per_step or not self.flops_per_seq:
            return 0.0, "none"
        if self._devices and len(self._devices) == n_steps:
            device_s = sum(self._devices)
            if device_s <= 0:
                return 0.0, "device"
            per_chip = (self.seq_per_step * n_steps / device_s
                        / self.n_devices)
            basis = "device"
        else:
            if wall <= 0:
                return 0.0, "wall"
            per_chip = self.seq_per_step * n_steps / wall / self.n_devices
            basis = "wall"
        return round(flops_util.mfu(
            per_chip, self.flops_per_seq, self.device_kind), 4), basis
