"""Deterministic fault injection for the fault-tolerance test harness
(docs/fault_tolerance.md; armed via ``--fault_spec`` / ``BERT_FAULTS``,
driven end to end by ``tools/chaos_run.py``)."""

from bert_pytorch_tpu.testing.faults import (  # noqa: F401
    FAULTS_ENV,
    FaultPlan,
    arm,
    corrupt_checkpoint,
    get_plan,
)
