"""Deterministic fault-injection points (docs/fault_tolerance.md).

The ROADMAP's "handles as many scenarios as you can imagine" gap was
never the recovery code — it was the PROOF: none of the recovery paths
(resume after a kill, checkpoint walk-back past corruption, transient
shard-read retry, sentinel abort, hung-step detection) were exercised by
anything. This module is the injection half of that proof: a small,
deterministic, explicitly-armed set of fault points that
``tools/chaos_run.py`` and the tier-1 suite drive end to end.

Arming. Faults are OFF unless a spec is armed via ``--fault_spec`` (the
pretraining runner) or the ``BERT_FAULTS`` env var (any process,
including DataLoader workers — the env survives ``fork``/``spawn``).
The spec is a comma-separated list of points::

    die@N            SIGKILL this process at step N (after the step's
                     checkpoint block) — the hard-preemption model
    term@N           SIGTERM this process at step N — exercises the
                     graceful stop + emergency checkpoint path
    nonfinite@N      poison step N's fetched metrics with NaN loss /
    nonfinite@NxK    finite=0 (K consecutive steps) — exercises the
                     sentinel continue/abort policies host-side
    hang@N           sleep S seconds inside step N (default 3600) —
    hang@NxS         exercises the heartbeat-age watchdog
    shard_error      first K (default 1) HDF5 shard loads raise OSError,
    shard_errorxK    then reads are healthy — exercises the data-path
                     retry/backoff (transient-then-healthy)
    wedge@N          wedge the SERVING dispatch thread once N requests
    wedge@NxS        have been served: the dispatch loop's fault check
                     sleeps S seconds (default 3600) while /healthz keeps
                     answering 200 (the thread is alive, just stuck) —
                     the failure mode only the supervisor's heartbeat
                     watchdog can catch (serve/supervisor.py,
                     tools/chaos_serve.py)
    admit_hold@N     hold the serving ASSEMBLER inside the admission
    admit_hold@NxS   window on its Nth formed batch: the fault check
                     emits its injection record (the chaos harness's
                     kill cue) then sleeps S seconds (default 3) with
                     the forming batch open — so a SIGKILL lands with
                     requests provably inside the admission window
                     (serve/service.py pipelined dispatch,
                     tools/chaos_serve.py)
    swap_hold@N      hold a hot-swap OPEN on the Nth swap attempt,
    swap_hold@NxS    between the new params finishing their load and
                     the atomic flip (serve/engine.py swap_params): the
                     fault check emits its injection record (the chaos
                     harness's cue to SIGKILL the replica mid-swap)
                     then sleeps S seconds (default 5) — so a kill
                     lands with two complete param trees in memory and
                     the flip not yet taken, proving in-flight batches
                     only ever see the OLD consistent version
                     (tools/chaos_serve.py swap phase)

Everything is keyed on explicit step numbers / call counts — rerunning
the same spec on the same data reproduces the same failure, which is
what lets the chaos harness assert exact resumed-loss trajectories.

Stdlib-only (the jax-free chaos parent imports this by file path), and
every injection emits a schema-v1 ``fault`` telemetry record
(``injected: true``) when the caller passes its emit hook, so injected
faults are distinguishable from real ones in the artifacts.
"""

from __future__ import annotations

import os
import re
import signal
import threading
import time
from typing import Callable, Dict, Optional

FAULTS_ENV = "BERT_FAULTS"

_STEP_POINTS = ("die", "term", "nonfinite", "hang")
_SPEC_RE = re.compile(
    r"^(?P<point>[a-z_]+)(?:@(?P<step>\d+))?(?:x(?P<count>\d+))?$")


class FaultSpecError(ValueError):
    """Malformed ``--fault_spec`` / ``BERT_FAULTS`` string."""


class FaultPlan:
    """Parsed, stateful fault plan for one process.

    State (the shard-error countdown, one-shot step points) is per-plan;
    the module-level singleton (:func:`arm` / :func:`get_plan`) is what
    the dataset layer consults so the runner's CLI arming reaches code
    that never sees args.
    """

    def __init__(self, spec: str = ""):
        self.spec = (spec or "").strip()
        # point -> {"step": N, "count": K}; shard_error keeps a live
        # countdown under a lock (loads happen on the prefetch thread).
        self._points: Dict[str, dict] = {}
        self._lock = threading.Lock()
        self._shard_errors_left = 0
        self._fired: set = set()
        for part in filter(None, (p.strip()
                                  for p in self.spec.split(","))):
            m = _SPEC_RE.match(part)
            if m is None:
                raise FaultSpecError(
                    f"bad fault spec element {part!r} (expected "
                    f"point[@step][xcount], e.g. die@7 or shard_errorx2)")
            point = m.group("point")
            step = m.group("step")
            count = int(m.group("count") or 0)
            if point in _STEP_POINTS or point in ("wedge", "admit_hold",
                                                  "swap_hold"):
                if step is None:
                    raise FaultSpecError(
                        f"fault point {point!r} needs @step (e.g. "
                        f"{point}@7)")
                self._points[point] = {"step": int(step), "count": count}
            elif point == "shard_error":
                self._shard_errors_left = count or 1
                self._points[point] = {"count": self._shard_errors_left}
            else:
                raise FaultSpecError(
                    f"unknown fault point {point!r} (known: "
                    f"{', '.join(_STEP_POINTS)}, shard_error, wedge, "
                    f"admit_hold, swap_hold)")

    @classmethod
    def from_env(cls) -> "FaultPlan":
        return cls(os.environ.get(FAULTS_ENV, ""))

    @property
    def active(self) -> bool:
        return bool(self._points)

    # -- injection hooks --------------------------------------------------

    def _record(self, fault: str, step: Optional[int] = None, **extra
                ) -> dict:
        rec = {"kind": "fault", "tag": "telemetry", "fault": fault,
               "injected": True}
        if step is not None:
            rec["step"] = int(step)
        rec.update(extra)
        return rec

    def poison_metrics(self, step: int, metrics,
                       emit: Optional[Callable] = None):
        """Host-side NaN injection: replace the fetched loss/finite scalars
        for an armed step (the in-jit finite reduction itself is
        unit-tested; this exercises the host policy end to end)."""
        point = self._points.get("nonfinite")
        if (point is None or not isinstance(metrics, dict)
                or not (point["step"] <= step
                        < point["step"] + max(1, point["count"]))):
            return metrics
        if emit is not None:
            emit(self._record("injected_nonfinite", step))
        poisoned = dict(metrics)
        poisoned["loss"] = float("nan")
        poisoned["finite"] = 0.0
        return poisoned

    def fire_process_faults(self, step: int,
                            emit: Optional[Callable] = None) -> None:
        """die/term/hang points for ``step``; called once per step from
        the training loop (after the checkpoint block, so ``die@N`` tests
        resume from whatever N's cadence had durably written)."""
        for point, action in (("hang", self._hang), ("term", self._term),
                              ("die", self._die)):
            cfg = self._points.get(point)
            key = (point, step)
            if cfg is None or cfg["step"] != step or key in self._fired:
                continue
            self._fired.add(key)
            if emit is not None:
                emit(self._record(f"injected_{point}", step,
                                  **({"hang_s": cfg["count"] or 3600}
                                     if point == "hang" else {})))
            action(cfg)

    def _hang(self, cfg) -> None:
        time.sleep(cfg["count"] or 3600)

    def _term(self, cfg) -> None:
        os.kill(os.getpid(), signal.SIGTERM)

    def _die(self, cfg) -> None:
        # SIGKILL: no handlers, no atexit, no flushing — the honest
        # hard-preemption model. Telemetry written so far survives
        # because the JSONL sink flushes per record.
        os.kill(os.getpid(), signal.SIGKILL)

    def serve_wedge_check(self, requests_served: int,
                          emit: Optional[Callable] = None) -> None:
        """Wedge the calling (dispatch) thread once ``requests_served``
        reaches the armed ``wedge@N`` threshold: emit the injection
        record, then sleep S seconds (default 3600). Called by the
        serving dispatch loop after each processed batch
        (serve/service.py); fires at most once per plan."""
        cfg = self._points.get("wedge")
        if (cfg is None or requests_served < cfg["step"]
                or "wedge" in self._fired):
            return
        self._fired.add("wedge")
        hang_s = cfg["count"] or 3600
        if emit is not None:
            emit(self._record("injected_wedge", None,
                              requests_served=int(requests_served),
                              hang_s=hang_s))
        time.sleep(hang_s)

    def serve_admit_check(self, batches_assembled: int,
                          emit: Optional[Callable] = None) -> None:
        """Hold the calling (assembler) thread inside the admission
        window once ``batches_assembled`` reaches the armed
        ``admit_hold@N`` threshold: emit the injection record FIRST (it
        is the chaos harness's cue to SIGKILL this replica with
        requests captive in the forming batch), then sleep S seconds
        (default 3 — a hold, not a wedge: an unkilled replica resumes
        and serves the batch late). Called by the pipelined assembler
        per formed batch (serve/service.py); fires at most once per
        plan."""
        cfg = self._points.get("admit_hold")
        if (cfg is None or batches_assembled < cfg["step"]
                or "admit_hold" in self._fired):
            return
        self._fired.add("admit_hold")
        hold_s = cfg["count"] or 3
        if emit is not None:
            emit(self._record("injected_admit_hold", None,
                              batches_assembled=int(batches_assembled),
                              hold_s=hold_s))
        time.sleep(hold_s)

    def serve_swap_check(self, swaps_attempted: int,
                         emit: Optional[Callable] = None) -> None:
        """Hold the calling (control) thread inside the swap window on
        the armed ``swap_hold@N``-th swap attempt — AFTER the new params
        finished loading, BEFORE the atomic flip (serve/engine.py
        swap_params): emit the injection record FIRST (the chaos
        harness's cue to SIGKILL this replica mid-swap), then sleep S
        seconds (default 5 — a hold, not a wedge: an unkilled replica
        resumes and completes the flip late). Fires at most once per
        plan."""
        cfg = self._points.get("swap_hold")
        if (cfg is None or swaps_attempted < cfg["step"]
                or "swap_hold" in self._fired):
            return
        self._fired.add("swap_hold")
        hold_s = cfg["count"] or 5
        if emit is not None:
            emit(self._record("injected_swap_hold", None,
                              swaps_attempted=int(swaps_attempted),
                              hold_s=hold_s))
        time.sleep(hold_s)

    def shard_read_check(self, path: str,
                         emit: Optional[Callable] = None) -> None:
        """Raise a transient OSError for the first armed K shard loads
        (then healthy). Called by the dataset layer inside its retry
        wrapper; thread-safe (loads run on the prefetch thread)."""
        if "shard_error" not in self._points:
            return
        with self._lock:
            if self._shard_errors_left <= 0:
                return
            self._shard_errors_left -= 1
            remaining = self._shard_errors_left
        if emit is not None:
            emit(self._record("injected_shard_error", None, path=path,
                              remaining=remaining))
        raise OSError(
            f"injected transient shard read error for {path} "
            f"({remaining} more armed)")


# -- module-level plan (CLI/env arming reaches the data layer) -----------

_plan = FaultPlan()


def arm(spec: str) -> FaultPlan:
    """Install the process-wide plan (runner ``--fault_spec``); also
    exports it to ``BERT_FAULTS`` so forked/spawned DataLoader workers
    inherit the arming. ``arm("")`` fully disarms (and clears the env
    var) — what in-process tests call in their finally blocks."""
    global _plan
    _plan = FaultPlan(spec)
    if _plan.active:
        os.environ[FAULTS_ENV] = _plan.spec
    else:
        os.environ.pop(FAULTS_ENV, None)
    return _plan


def get_plan() -> FaultPlan:
    """The process-wide plan; lazily picks up ``BERT_FAULTS`` so worker
    processes (which never run the runner CLI) arm themselves."""
    global _plan
    if not _plan.active and os.environ.get(FAULTS_ENV):
        _plan = FaultPlan.from_env()
    return _plan


# -- harness-side corruption (chaos_run.py) ------------------------------

def corrupt_checkpoint(path: str, mode: str = "truncate") -> None:
    """Deterministically damage a checkpoint file IN PLACE (the manifest
    sidecar is left alone, so verification must catch the damage):

    * ``truncate`` — cut the file to half its size (the torn-copy shape);
    * ``flip``     — XOR one byte in the middle (bit rot; size-preserving,
      so only the sha256 check can catch it).
    """
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"{path} is empty; nothing to corrupt")
    if mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(max(1, size // 2))
    elif mode == "flip":
        with open(path, "r+b") as f:
            f.seek(size // 2)
            byte = f.read(1)
            f.seek(size // 2)
            f.write(bytes([byte[0] ^ 0xFF]))
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
