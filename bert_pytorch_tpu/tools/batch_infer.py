"""Offline batch inference: file-in / file-out scoring through the same
engine the online server runs (docs/serving.md).

Input is a JSONL request file — the shape ``make_synthetic_data
--requests`` emits, minus ``arrival_s`` which is ignored offline::

    {"id": 0, "task": "fill_mask", "payload": {"text": "... [MASK] ..."}}

Output is one JSONL line per request: ``{"id", "task", "result"}`` (or
``"error"``), in input order. Requests are grouped per task and run
through the SAME bucket-compiled, optionally packed batched path as the
server (serve/engine.py ``plan_batch``/``execute``), so offline scores
are bit-identical to served ones — this tool is the regression harness
for the serving path as much as a utility. The engine flags are
``run_server.py``'s, including the inference fast path's
``--quantize {none,bf16,int8}`` / ``--attention_backend``
(serve/cli.py; docs/serving.md "Inference fast path") — scoring a file
under int8 vs fp32 is the offline parity check.

::

    python -m bert_pytorch_tpu.tools.batch_infer \
        --model_config_file configs/bert_base_config.json \
        --vocab_file vocab.txt --input requests.jsonl --output scored.jsonl
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def run_offline(service, lines, out_stream) -> dict:
    """Score decoded request dicts through the service's engine; returns
    summary stats. ``service`` is a ServingService (its batcher/dispatch
    thread are NOT used — offline scoring drives the engine directly in
    submission order, grouping consecutive same-task requests)."""
    from bert_pytorch_tpu.serve.batcher import Request

    engine = service.engine
    results = {}
    errors = 0
    pending: list = []

    def flush():
        nonlocal errors
        group, pending[:] = list(pending), []
        if not group:
            return
        task = group[0][1]["task"]
        spec = engine.tasks[task]
        line_of = {}
        todo = []
        for idx, line in group:
            payload = line.get("payload", {})
            try:
                features = spec.handler.prepare(payload, engine.max_len())
            except Exception as exc:
                results[idx] = {"id": line.get("id", idx), "task": task,
                                "error": f"{type(exc).__name__}: {exc}"}
                errors += 1
                continue
            req = Request(task, features, payload)
            line_of[req.id] = (idx, line)
            todo.append(req)
        while todo:
            plan = engine.plan_batch(todo)
            outputs, _ = engine.execute(task, plan)
            for req, out in zip(plan.requests, outputs):
                idx, line = line_of[req.id]
                try:
                    results[idx] = {
                        "id": line.get("id", idx), "task": task,
                        "result": spec.handler.postprocess(
                            req.features, out, req.payload)}
                except Exception as exc:
                    results[idx] = {
                        "id": line.get("id", idx), "task": task,
                        "error": f"{type(exc).__name__}: {exc}"}
                    errors += 1
            done = {r.id for r in plan.requests}
            todo = [r for r in todo if r.id not in done]

    for idx, line in enumerate(lines):
        task = line.get("task")
        if task not in engine.tasks:
            results[idx] = {"id": line.get("id", idx), "task": task,
                            "error": f"unknown task {task!r}"}
            errors += 1
            continue
        if pending and pending[-1][1]["task"] != task:
            flush()
        pending.append((idx, line))
    flush()

    for idx in sorted(results):
        out_stream.write(json.dumps(results[idx]) + "\n")
    return {"requests": len(results), "errors": errors}


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--input", required=True,
                        help="JSONL request file ({task, payload} lines)")
    parser.add_argument("--output", required=True,
                        help="JSONL results file (- for stdout)")
    # The engine knobs reuse run_server's surface.
    import run_server

    server_args, _ = parser.parse_known_args(argv)
    engine_argv = []
    skip_value = False
    for arg in (argv if argv is not None else sys.argv[1:]):
        if skip_value:
            skip_value = False
            continue
        if arg in ("--input", "--output"):
            skip_value = True
            continue
        if arg.startswith("--input=") or arg.startswith("--output="):
            continue
        engine_argv.append(arg)
    args = run_server.parse_arguments(engine_argv)

    service, sink = run_server.build_service(args)
    service.engine.warmup()
    with open(server_args.input, "r", encoding="utf-8") as f:
        lines = [json.loads(line) for line in f if line.strip()]
    t0 = time.perf_counter()
    out = (sys.stdout if server_args.output == "-"
           else open(server_args.output, "w", encoding="utf-8"))
    try:
        stats = run_offline(service, lines, out)
    finally:
        if out is not sys.stdout:
            out.close()
        if sink is not None:
            sink.close()
        if getattr(service, "flight_recorder", None) is not None:
            exc = sys.exc_info()[1]
            if exc is not None:
                # Crashing out of the batch: flush the forensics WITH
                # the traceback now (a clean close here would delete
                # the file and disarm the excepthook — zero forensics
                # for the exact case the recorder exists for).
                service.flight_recorder.flush("crash", exc=exc)
            else:
                # Clean close, like run_server's teardown: a healthy
                # batch run must not leave a stale postmortem.json for
                # a later harvest to misread (the atexit hook would
                # otherwise flush one at interpreter exit).
                service.flight_recorder.close(clean=True)
    stats["wall_s"] = round(time.perf_counter() - t0, 3)
    startup = service.engine.startup or {}
    stats["quantize"] = startup.get("quantize", args.quantize)
    if startup.get("cold_start_s") is not None:
        stats["cold_start_s"] = startup["cold_start_s"]
    print(json.dumps({"batch_infer": stats}), file=sys.stderr)
    return stats


if __name__ == "__main__":
    main()
