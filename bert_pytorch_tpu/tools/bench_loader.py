"""Host input-pipeline throughput bench: is the loader fast enough to feed
the chips?

The reference feeds GPUs with 4 DataLoader worker processes
(run_pretraining.py:394-395). The TPU-side question is concrete: a host
with N chips needs ``N x per-chip seq/s`` sustained from the loader
(e.g. ~400 seq/s/chip for BERT-large phase-1 on v5e, BENCH numbers).
This tool measures the real pipeline — ShardedPretrainingDataset streaming
+ dynamic masking + collate through DataLoader — on synthetic shards and
prints one JSON line per worker setting, so headroom claims are
reproducible instead of asserted.

Usage:
  python -m bert_pytorch_tpu.tools.bench_loader [--seq_len 128]
      [--batch_size 64] [--workers 0 1 2 4] [--samples 16384]
      [--input_dir DIR]       # measure real shards instead of synthetic
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import time
from pathlib import Path


def bench_one(files, num_workers: int, batch_size: int, vocab: int,
              warmup_batches: int = 4) -> dict:
    from bert_pytorch_tpu.data import (
        DataLoader,
        DistributedSampler,
        ShardedPretrainingDataset,
    )

    ds = ShardedPretrainingDataset(
        files, 4, max_pred_per_seq=76, masked_lm_prob=0.15,
        vocab_size=vocab, seed=0)
    sampler = DistributedSampler(ds, 1, 0)
    loader = DataLoader(ds, sampler, batch_size=batch_size,
                        num_workers=num_workers)
    total_batches = len(loader)
    if total_batches < warmup_batches + 2:
        raise ValueError(
            f"need at least {warmup_batches + 2} batches to measure "
            f"(warmup {warmup_batches} + a timing window), got "
            f"{total_batches}; lower --batch_size or raise --samples")
    n, start = 0, None
    for i, batch in enumerate(loader):
        if i == warmup_batches:  # spawn/prefetch startup out of the window
            start = time.perf_counter()
        elif i > warmup_batches:
            n += batch["input_ids"].shape[0]
    elapsed = time.perf_counter() - start
    return {
        "metric": "loader_seq_per_sec",
        "num_workers": num_workers,
        "batch_size": batch_size,
        "seq_len": int(batch["input_ids"].shape[1]),
        "value": round(n / elapsed, 1),
        "unit": "seq/s/host",
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seq_len", type=int, default=128)
    p.add_argument("--batch_size", type=int, default=64)
    p.add_argument("--samples", type=int, default=16384)
    p.add_argument("--vocab_size", type=int, default=30528)
    p.add_argument("--workers", type=int, nargs="+", default=[0, 1, 2, 4])
    p.add_argument("--input_dir", default=None,
                   help="existing HDF5 shard dir (default: synthesize)")
    args = p.parse_args()

    if args.input_dir:
        files = sorted(
            str(f) for f in Path(args.input_dir).rglob("*.hdf5"))
    else:
        from bert_pytorch_tpu.tools.make_synthetic_data import make_shard

        d = tempfile.mkdtemp(prefix="bench_loader_")
        per_shard = args.samples // 4
        files = [
            make_shard(os.path.join(d, f"s{i}.hdf5"), per_shard,
                       args.seq_len, args.vocab_size, seed=i)
            for i in range(4)
        ]
    for w in args.workers:
        print(json.dumps(bench_one(
            files, w, args.batch_size, args.vocab_size)), flush=True)


if __name__ == "__main__":
    main()
