"""Tokenizer throughput: the in-repo C++ core vs HF's Rust tokenizers.

The reference's entire offline pipeline throughput rests on the external
Rust `tokenizers` crate (SURVEY.md §2.3: src/tokenization.py:42-57,
utils/encode_data.py:280-293). This framework replaces it with the
in-repo C++ core (`native/tokenizer.cpp`, ctypes-bound); bit-parity is
pinned by tests/test_tokenizer.py — this harness measures whether the
replacement also holds up on THROUGHPUT, the property the reference
outsourced to Rust for. Prints one JSON line per backend:

  {"metric": "wordpiece_encode_tokens_per_sec", "backend": ..., ...}

  python -m bert_pytorch_tpu.tools.bench_tokenizer [--lines 20000]
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import time


def _build_corpus(n_lines: int, seed: int):
    from bert_pytorch_tpu.tools.make_synthetic_text import write_corpus

    d = tempfile.mkdtemp(prefix="bench_tok_")
    paths = write_corpus(d, n_files=1,
                         articles_per_file=max(1, n_lines // 10), seed=seed)
    lines = []
    with open(paths[0]) as f:
        for ln in f:
            ln = ln.strip()
            if ln:
                lines.append(ln)
            if len(lines) >= n_lines:
                break
    return d, lines


def _train_vocab(corpus_dir: str, out: str):
    from bert_pytorch_tpu.tools.tokenizer_cpp import train_wordpiece_vocab

    train_wordpiece_vocab(
        [os.path.join(corpus_dir, f) for f in os.listdir(corpus_dir)
         if f.endswith(".txt")],
        4096, out, min_frequency=1)


def bench_cpp(vocab_file: str, lines, repeat: int):
    from bert_pytorch_tpu.tools.tokenizer_cpp import CppWordPieceTokenizer

    tok = CppWordPieceTokenizer(vocab_file, lowercase=True)
    # warmup + token count
    n_tokens = sum(len(e.ids) for e in tok.encode_batch(lines))
    t0 = time.perf_counter()
    for _ in range(repeat):
        tok.encode_batch(lines)
    dt = (time.perf_counter() - t0) / repeat
    return n_tokens, dt


def bench_hf(vocab_file: str, lines, repeat: int):
    try:
        from tokenizers import BertWordPieceTokenizer
    except ImportError:
        return None
    tok = BertWordPieceTokenizer(vocab_file, lowercase=True)
    # no [CLS]/[SEP] so both backends do identical token work
    n_tokens = sum(len(e.ids)
                   for e in tok.encode_batch(lines, add_special_tokens=False))
    t0 = time.perf_counter()
    for _ in range(repeat):
        tok.encode_batch(lines, add_special_tokens=False)
    dt = (time.perf_counter() - t0) / repeat
    return n_tokens, dt


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--lines", type=int, default=20000)
    p.add_argument("--repeat", type=int, default=3)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    corpus_dir, lines = _build_corpus(args.lines, args.seed)
    vocab = os.path.join(corpus_dir, "vocab.txt")
    _train_vocab(corpus_dir, vocab)

    results = {}
    for backend, fn in (("cpp", bench_cpp), ("hf_rust", bench_hf)):
        got = fn(vocab, lines, args.repeat)
        if got is None:
            print(json.dumps({"backend": backend, "skipped": "not installed"}))
            continue
        n_tokens, dt = got
        results[backend] = n_tokens / dt
        print(json.dumps({
            "metric": "wordpiece_encode_tokens_per_sec",
            "backend": backend,
            "lines": len(lines),
            "tokens": n_tokens,
            "value": round(n_tokens / dt, 0),
            "unit": "tokens/s",
        }))
    if "cpp" in results and "hf_rust" in results:
        print(json.dumps({
            "metric": "cpp_vs_hf_rust_ratio",
            "value": round(results["cpp"] / results["hf_rust"], 3),
            "note": ("identical token work (no specials), same vocab; cpp "
                     "side is a SEQUENTIAL python loop over ctypes calls, "
                     "hf_rust side is tokenizers' default encode_batch "
                     "(rayon-parallel unless TOKENIZERS_PARALLELISM "
                     "disables it); sentence-length synthetic English"),
        }))


if __name__ == "__main__":
    main()
