"""Train a WordPiece or byte-level-BPE vocabulary from formatted text.

Parity with reference utils/build_vocab.py: trains on the corpus with the
standard special tokens, then reorders so the specials sit at the front with
[PAD] at index 0 (:53-75). The WordPiece path uses the in-repo C++ trainer
(native/tokenizer.cpp) instead of the Rust `tokenizers` trainer; the BPE
path uses the `tokenizers` package when available.
"""

from __future__ import annotations

import argparse
import glob
import os

SPECIAL_TOKENS = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]


def build_wordpiece_vocab(input_files, output_file: str, vocab_size: int,
                          lowercase: bool = True, min_frequency: int = 2) -> str:
    from bert_pytorch_tpu.tools.tokenizer_cpp import train_wordpiece_vocab

    parent = os.path.dirname(os.path.abspath(output_file))
    os.makedirs(parent, exist_ok=True)
    return train_wordpiece_vocab(
        list(input_files), vocab_size, output_file,
        special_tokens=tuple(SPECIAL_TOKENS),
        min_frequency=min_frequency, lowercase=lowercase)


def build_bpe_vocab(input_files, output_dir: str, vocab_size: int,
                    lowercase: bool = True, min_frequency: int = 2,
                    backend: str = "auto") -> str:
    """'auto' prefers the HF trainer when installed — its incremental pair
    bookkeeping trains a 30k vocab in minutes where the in-repo C++
    trainer's per-merge rescan (native/tokenizer.cpp bpe_train_impl, a
    reference implementation like the WordPiece trainer beside it) is only
    suitable for small/test vocabs — and falls back to C++ without it.
    backend='cpp' forces the native trainer."""
    use_cpp = backend == "cpp"
    if not use_cpp:
        try:
            from tokenizers import ByteLevelBPETokenizer
        except ImportError:
            use_cpp = True
    if use_cpp:
        from bert_pytorch_tpu.tools.tokenizer_cpp import train_bpe_vocab

        return train_bpe_vocab(
            list(input_files), vocab_size, output_dir,
            special_tokens=tuple(SPECIAL_TOKENS),
            min_frequency=min_frequency, lowercase=lowercase)

    tok = ByteLevelBPETokenizer(lowercase=lowercase)
    tok.train(files=list(input_files), vocab_size=vocab_size,
              min_frequency=min_frequency, special_tokens=SPECIAL_TOKENS)
    os.makedirs(output_dir, exist_ok=True)
    tok.save_model(output_dir)
    return os.path.join(output_dir, "vocab.json")


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--input_glob", type=str, required=True)
    parser.add_argument("--output", type=str, required=True,
                        help="vocab .txt path (wordpiece) or directory (bpe)")
    parser.add_argument("--tokenizer", choices=["wordpiece", "bpe"],
                        default="wordpiece")
    parser.add_argument("--vocab_size", type=int, default=30522)
    parser.add_argument("--min_frequency", type=int, default=2)
    parser.add_argument("--uppercase", action="store_true")
    args = parser.parse_args(argv)
    files = glob.glob(args.input_glob, recursive=True)
    if not files:
        raise ValueError(f"no files match {args.input_glob}")
    if args.tokenizer == "wordpiece":
        out = build_wordpiece_vocab(files, args.output, args.vocab_size,
                                    not args.uppercase, args.min_frequency)
    else:
        out = build_bpe_vocab(files, args.output, args.vocab_size,
                              not args.uppercase, args.min_frequency)
    print(f"[vocab] wrote {out}")


if __name__ == "__main__":
    main()
