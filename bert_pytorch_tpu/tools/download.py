"""Corpus / benchmark / pretrained-weight downloaders.

Parity with reference utils/download.py: Wikipedia dump (+bz2 extraction,
:219-255), BooksCorpus (:59-79), SQuAD v1.1+v2.0 with the official eval
scripts (:103-121), GLUE (:81-101), and Google BERT TF weights with SHA256
verification (:123-216). Structured as one downloader class per dataset
keyed by name.

This environment has zero egress; downloads fail fast with a clear error,
but checksum verification and archive extraction are fully functional and
unit-tested against local files.
"""

from __future__ import annotations

import argparse
import bz2
import hashlib
import os
import shutil
import urllib.request
import zipfile

SQUAD_URLS = {
    "train-v1.1.json": "https://rajpurkar.github.io/SQuAD-explorer/dataset/train-v1.1.json",
    "dev-v1.1.json": "https://rajpurkar.github.io/SQuAD-explorer/dataset/dev-v1.1.json",
    "evaluate-v1.1.py": "https://worksheets.codalab.org/rest/bundles/0xbcd57bee090b421c982906709c8c27e1/contents/blob/",
    "train-v2.0.json": "https://rajpurkar.github.io/SQuAD-explorer/dataset/train-v2.0.json",
    "dev-v2.0.json": "https://rajpurkar.github.io/SQuAD-explorer/dataset/dev-v2.0.json",
    "evaluate-v2.0.py": "https://worksheets.codalab.org/rest/bundles/0x6b567e1cf2e041ec80d7098f031c5c9e/contents/blob/",
}

WIKI_DUMP_URL = (
    "https://dumps.wikimedia.org/enwiki/latest/"
    "enwiki-latest-pages-articles.xml.bz2"
)

# Google BERT TF weight archives + SHA256 (the verification pattern of
# reference utils/download.py:137-216; hashes verified at download time).
WEIGHTS = {
    "bert-large-uncased": (
        "https://storage.googleapis.com/bert_models/2019_05_30/"
        "wwm_uncased_L-24_H-1024_A-16.zip"
    ),
    "bert-base-uncased": (
        "https://storage.googleapis.com/bert_models/2018_10_18/"
        "uncased_L-12_H-768_A-12.zip"
    ),
    "bert-large-cased": (
        "https://storage.googleapis.com/bert_models/2019_05_30/"
        "wwm_cased_L-24_H-1024_A-16.zip"
    ),
}


def sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def verify_sha256(path: str, expected: str) -> None:
    actual = sha256_file(path)
    if actual != expected:
        raise ValueError(
            f"SHA256 mismatch for {path}: expected {expected}, got {actual}")


def fetch(url: str, dest: str, expected_sha256: str | None = None) -> str:
    os.makedirs(os.path.dirname(os.path.abspath(dest)), exist_ok=True)
    if not os.path.exists(dest):
        print(f"[download] {url} -> {dest}")
        tmp = dest + ".part"
        with urllib.request.urlopen(url) as resp, open(tmp, "wb") as out:
            shutil.copyfileobj(resp, out)
        os.replace(tmp, dest)
    if expected_sha256:
        verify_sha256(dest, expected_sha256)
    return dest


def extract_bz2(src: str, dest: str) -> str:
    """Streamed bz2 extraction (reference :227-235)."""
    with bz2.open(src, "rb") as fin, open(dest, "wb") as fout:
        shutil.copyfileobj(fin, fout)
    return dest


def extract_zip(src: str, dest_dir: str) -> str:
    with zipfile.ZipFile(src) as z:
        z.extractall(dest_dir)
    return dest_dir


class Downloader:
    def __init__(self, output_dir: str):
        self.output_dir = output_dir

    def download(self) -> None:  # pragma: no cover
        raise NotImplementedError


class SquadDownloader(Downloader):
    def download(self) -> None:
        out = os.path.join(self.output_dir, "squad")
        for name, url in SQUAD_URLS.items():
            version = "v2.0" if "2.0" in name else "v1.1"
            fetch(url, os.path.join(out, version, name))


class WikiCorpusDownloader(Downloader):
    def download(self) -> None:
        out = os.path.join(self.output_dir, "wikicorpus")
        archive = fetch(WIKI_DUMP_URL, os.path.join(out, "wikicorpus.xml.bz2"))
        extract_bz2(archive, os.path.join(out, "wikicorpus.xml"))


class WeightsDownloader(Downloader):
    def download(self, model: str = "bert-large-uncased") -> None:
        out = os.path.join(self.output_dir, "weights")
        archive = fetch(WEIGHTS[model], os.path.join(out, f"{model}.zip"))
        extract_zip(archive, os.path.join(out, model))


DOWNLOADERS = {
    "squad": SquadDownloader,
    "wikicorpus": WikiCorpusDownloader,
    "weights": WeightsDownloader,
}


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--dataset", choices=sorted(DOWNLOADERS), required=True)
    parser.add_argument("--output_dir", type=str, required=True)
    args = parser.parse_args(argv)
    DOWNLOADERS[args.dataset](args.output_dir).download()


if __name__ == "__main__":
    main()
