"""Corpus / benchmark / pretrained-weight downloaders.

Parity with reference utils/download.py: Wikipedia dump (+bz2 extraction,
:219-255), BooksCorpus (:59-79), SQuAD v1.1+v2.0 with the official eval
scripts (:103-121), GLUE (:81-101), and Google BERT TF weights with SHA256
verification (:123-216). Structured as one downloader class per dataset
keyed by name.

This environment has zero egress; downloads fail fast with a clear error,
but checksum verification and archive extraction are fully functional and
unit-tested against local files.
"""

from __future__ import annotations

import argparse
import bz2
import hashlib
import os
import shutil
import urllib.request
import zipfile

SQUAD_URLS = {
    "train-v1.1.json": "https://rajpurkar.github.io/SQuAD-explorer/dataset/train-v1.1.json",
    "dev-v1.1.json": "https://rajpurkar.github.io/SQuAD-explorer/dataset/dev-v1.1.json",
    "evaluate-v1.1.py": "https://worksheets.codalab.org/rest/bundles/0xbcd57bee090b421c982906709c8c27e1/contents/blob/",
    "train-v2.0.json": "https://rajpurkar.github.io/SQuAD-explorer/dataset/train-v2.0.json",
    "dev-v2.0.json": "https://rajpurkar.github.io/SQuAD-explorer/dataset/dev-v2.0.json",
    "evaluate-v2.0.py": "https://worksheets.codalab.org/rest/bundles/0x6b567e1cf2e041ec80d7098f031c5c9e/contents/blob/",
}

WIKI_DUMP_URL = (
    "https://dumps.wikimedia.org/enwiki/latest/"
    "enwiki-latest-pages-articles.xml.bz2"
)

# Google BERT TF weight archives (reference utils/download.py:123-135) and
# per-extracted-file SHA256 tables (:137-175) checked after extraction.
WEIGHTS = {
    "bert-base-uncased": (
        "https://storage.googleapis.com/bert_models/2018_10_18/"
        "uncased_L-12_H-768_A-12.zip"
    ),
    "bert-large-uncased": (
        "https://storage.googleapis.com/bert_models/2018_10_18/"
        "uncased_L-24_H-1024_A-16.zip"
    ),
    "bert-base-cased": (
        "https://storage.googleapis.com/bert_models/2018_10_18/"
        "cased_L-12_H-768_A-12.zip"
    ),
    "bert-large-cased": (
        "https://storage.googleapis.com/bert_models/2018_10_18/"
        "cased_L-24_H-1024_A-16.zip"
    ),
}

_UNCASED_VOCAB_SHA = (
    "07eced375cec144d27c900241f3e339478dec958f92fddbc551f295c992038a3")
_CASED_VOCAB_SHA = (
    "eeaa9875b23b04b4c54ef759d03db9d1ba1554838f8fb26c5d96fa551df93d02")

WEIGHTS_SHA = {
    "bert-base-uncased": {
        "bert_config.json": "7b4e5f53efbd058c67cda0aacfafb340113ea1b5797d9ce6ee411704ba21fcbc",
        "bert_model.ckpt.data-00000-of-00001": "58580dc5e0bf0ae0d2efd51d0e8272b2f808857f0a43a88aaf7549da6d7a8a84",
        "bert_model.ckpt.index": "04c1323086e2f1c5b7c0759d8d3e484afbb0ab45f51793daab9f647113a0117b",
        "bert_model.ckpt.meta": "dd5682170a10c3ea0280c2e9b9a45fee894eb62da649bbdea37b38b0ded5f60e",
        "vocab.txt": _UNCASED_VOCAB_SHA,
    },
    "bert-large-uncased": {
        "bert_config.json": "bfa42236d269e2aeb3a6d30412a33d15dbe8ea597e2b01dc9518c63cc6efafcb",
        "bert_model.ckpt.data-00000-of-00001": "bc6b3363e3be458c99ecf64b7f472d2b7c67534fd8f564c0556a678f90f4eea1",
        "bert_model.ckpt.index": "68b52f2205ffc64dc627d1120cf399c1ef1cbc35ea5021d1afc889ffe2ce2093",
        "bert_model.ckpt.meta": "6fcce8ff7628f229a885a593625e3d5ff9687542d5ef128d9beb1b0c05edc4a1",
        "vocab.txt": _UNCASED_VOCAB_SHA,
    },
    "bert-base-cased": {
        "bert_config.json": "f11dfb757bea16339a33e1bf327b0aade6e57fd9c29dc6b84f7ddb20682f48bc",
        "bert_model.ckpt.data-00000-of-00001": "734d5a1b68bf98d4e9cb6b6692725d00842a1937af73902e51776905d8f760ea",
        "bert_model.ckpt.index": "517d6ef5c41fc2ca1f595276d6fccf5521810d57f5a74e32616151557790f7b1",
        "bert_model.ckpt.meta": "5f8a9771ff25dadd61582abb4e3a748215a10a6b55947cbb66d0f0ba1694be98",
        "vocab.txt": _CASED_VOCAB_SHA,
    },
    "bert-large-cased": {
        "bert_config.json": "7adb2125c8225da495656c982fd1c5f64ba8f20ad020838571a3f8a954c2df57",
        "bert_model.ckpt.data-00000-of-00001": "6ff33640f40d472f7a16af0c17b1179ca9dcc0373155fb05335b6a4dd1657ef0",
        "bert_model.ckpt.index": "ef42a53f577fbe07381f4161b13c7cab4f4fc3b167cec6a9ae382c53d18049cf",
        "bert_model.ckpt.meta": "d2ddff3ed33b80091eac95171e94149736ea74eb645e575d942ec4a5e01a40a1",
        "vocab.txt": _CASED_VOCAB_SHA,
    },
}


def sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def verify_sha256(path: str, expected: str) -> None:
    actual = sha256_file(path)
    if actual != expected:
        raise ValueError(
            f"SHA256 mismatch for {path}: expected {expected}, got {actual}")


def fetch(url: str, dest: str, expected_sha256: str | None = None) -> str:
    os.makedirs(os.path.dirname(os.path.abspath(dest)), exist_ok=True)
    if not os.path.exists(dest):
        print(f"[download] {url} -> {dest}")
        tmp = dest + ".part"
        with urllib.request.urlopen(url) as resp, open(tmp, "wb") as out:
            shutil.copyfileobj(resp, out)
        os.replace(tmp, dest)
    if expected_sha256:
        verify_sha256(dest, expected_sha256)
    return dest


def extract_bz2(src: str, dest: str) -> str:
    """Streamed bz2 extraction (reference :227-235)."""
    with bz2.open(src, "rb") as fin, open(dest, "wb") as fout:
        shutil.copyfileobj(fin, fout)
    return dest


def extract_zip(src: str, dest_dir: str) -> str:
    with zipfile.ZipFile(src) as z:
        z.extractall(dest_dir)
    return dest_dir


class Downloader:
    def __init__(self, output_dir: str):
        self.output_dir = output_dir

    def download(self) -> None:  # pragma: no cover
        raise NotImplementedError


class SquadDownloader(Downloader):
    def download(self) -> None:
        out = os.path.join(self.output_dir, "squad")
        for name, url in SQUAD_URLS.items():
            version = "v2.0" if "2.0" in name else "v1.1"
            fetch(url, os.path.join(out, version, name))


class WikiCorpusDownloader(Downloader):
    def download(self) -> None:
        out = os.path.join(self.output_dir, "wikicorpus")
        archive = fetch(WIKI_DUMP_URL, os.path.join(out, "wikicorpus.xml.bz2"))
        extract_bz2(archive, os.path.join(out, "wikicorpus.xml"))


class BooksCorpusDownloader(Downloader):
    """Clone soskek/bookcorpus and drive its downloader (reference
    utils/download.py:59-79). Needs git + network."""

    def download(self) -> None:
        import subprocess
        import sys

        out = os.path.join(self.output_dir, "bookscorpus")
        repo = os.path.join(out, "bookcorpus")
        if not os.path.exists(repo):
            subprocess.run(
                ["git", "clone",
                 "https://github.com/soskek/bookcorpus.git", repo],
                check=True)
        subprocess.run(
            [sys.executable, os.path.join(repo, "download_files.py"),
             "--list", os.path.join(repo, "url_list.jsonl"),
             "--out", os.path.join(out, "data"), "--trash-bad-count"],
            check=True)


class GLUEDownloader(Downloader):
    """Fetch the community GLUE download script and run it per task
    (reference utils/download.py:81-101)."""

    SCRIPT_URL = (
        "https://gist.githubusercontent.com/W4ngatang/"
        "60c2bdb54d156a41194446737ce03e2e/raw/"
        "17b8dd0d724281ed7c3b2aeeda662b92809aadd5/download_glue_data.py"
    )
    DEFAULT_TASKS = ("MRPC", "SST")

    def download(self, tasks=DEFAULT_TASKS) -> None:
        import importlib
        import sys

        out = os.path.join(self.output_dir, "glue")
        fetch(self.SCRIPT_URL, os.path.join(out, "download_glue_data.py"))
        sys.path.insert(0, out)
        try:
            download_glue_data = importlib.import_module("download_glue_data")
            for task in tasks:
                download_glue_data.main(
                    ["--data_dir", out, "--tasks", task])
        finally:
            sys.path.remove(out)


class WeightsDownloader(Downloader):
    def download(self, model: str = "bert-large-uncased") -> None:
        out = os.path.join(self.output_dir, "weights")
        archive = fetch(WEIGHTS[model], os.path.join(out, f"{model}.zip"))
        dest = extract_zip(archive, os.path.join(out, model))
        self.verify(dest, model)

    @staticmethod
    def verify(extracted_dir: str, model: str) -> None:
        """Per-extracted-file SHA256 check (reference :203-216). The archive
        nests files under its own top-level directory; search for each."""
        for name, expected in WEIGHTS_SHA.get(model, {}).items():
            matches = [
                os.path.join(root, name)
                for root, _, files in os.walk(extracted_dir)
                if name in files
            ]
            if not matches:
                raise FileNotFoundError(
                    f"{name} missing from extracted archive {extracted_dir}")
            verify_sha256(matches[0], expected)
            print(f"[download] {matches[0]} verified")


class SwagDownloader(Downloader):
    """SWAG multiple-choice CSVs (rowanz/swagaf) for run_swag.py —
    beyond-reference: the reference's BertForMultipleChoice has no data
    source at all."""

    BASE = "https://raw.githubusercontent.com/rowanz/swagaf/master/data"

    def download(self) -> None:
        out = os.path.join(self.output_dir, "swag")
        for name in ("train.csv", "val.csv", "test.csv"):
            fetch(f"{self.BASE}/{name}", os.path.join(out, name))


DOWNLOADERS = {
    "squad": SquadDownloader,
    "wikicorpus": WikiCorpusDownloader,
    "bookscorpus": BooksCorpusDownloader,
    "glue": GLUEDownloader,
    "swag": SwagDownloader,
    "weights": WeightsDownloader,
}


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--dataset", choices=sorted(DOWNLOADERS), required=True)
    parser.add_argument("--output_dir", type=str, required=True)
    args = parser.parse_args(argv)
    DOWNLOADERS[args.dataset](args.output_dir).download()


if __name__ == "__main__":
    main()
