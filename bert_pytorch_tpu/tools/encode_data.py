"""Encode formatted text into pretraining HDF5 shards.

Parity with reference utils/encode_data.py: documents delimited by blank
lines (:48-62), chunk sentences up to a (possibly short_seq_prob-reduced)
target length (:65-167), optional NSP pair construction with a random next
segment drawn from another document and index rewind (:112-130), in-file
shuffle (:179), and gzip HDF5 output with ``input_ids`` i4,
``special_token_positions`` i4 and ``next_sentence_labels`` i1 (:204-210).

Sample layout (consumed by data/dataset.py):
  NSP:    [CLS] seq [SEP] next_seq [SEP] pad   specials = [0, p1, p2]
  no NSP: [CLS] seq [SEP] pad                  specials = [0, p1]
"""

from __future__ import annotations

import argparse
import dataclasses
import multiprocessing as mp
import os
import random
import time
from pathlib import Path
from typing import List, Optional

import h5py
import numpy as np


@dataclasses.dataclass
class TrainingSample:
    """[CLS]/[SEP]-wrapped token sequence with special-token bookkeeping
    (reference utils/encode_data.py:12-35)."""

    seq_tokens: List[str]
    next_seq_tokens: Optional[List[str]] = None
    is_random_next: bool = False

    def __post_init__(self):
        self.sequence = ["[CLS]"] + list(self.seq_tokens)
        self.special_token_positions = [0]
        if self.next_seq_tokens is not None:
            self.special_token_positions.append(len(self.sequence))
            self.sequence.append("[SEP]")
            self.sequence.extend(self.next_seq_tokens)
        self.special_token_positions.append(len(self.sequence))
        self.sequence.append("[SEP]")


def documents_from_file(input_file: str, tokenizer) -> List[List[List[str]]]:
    """Blank-line-delimited documents -> lists of tokenized sentences
    (reference :48-62)."""
    documents: List[List[List[str]]] = [[]]
    with open(input_file, "r", encoding="utf-8", errors="ignore") as reader:
        for line in reader:
            line = line.strip()
            if not line:
                documents.append([])
                continue
            tokens = tokenizer.encode(line, add_special_tokens=False).tokens
            if tokens:
                documents[-1].append(tokens)
    return [d for d in documents if d]


def _target_length(max_num_tokens: int, short_seq_prob: float, rng) -> int:
    if rng.random() < short_seq_prob:
        return rng.randint(2, max_num_tokens)
    return max_num_tokens


def _truncate_pair(seq_tokens: List[str], next_seq_tokens: List[str],
                   budget: int) -> None:
    """Trim the longer segment from the back until the pair fits (the
    canonical-BERT truncate_seq_pair role; the chunk may overshoot the
    target because the closing sentence is included before the flush)."""
    while len(seq_tokens) + len(next_seq_tokens) > budget:
        longer = (seq_tokens if len(seq_tokens) >= len(next_seq_tokens)
                  else next_seq_tokens)
        longer.pop()


def create_samples_from_document(
    document_idx: int,
    documents: List[List[List[str]]],
    max_seq_len: int,
    next_seq_prob: float,
    short_seq_prob: float,
    rng=random,
) -> List[TrainingSample]:
    """Chunk one document into samples (reference :65-167).

    Two deliberate fixes over the reference's loop (which checks the
    flush condition *before* appending the current sentence,
    encode_data.py:92-96):
      - the final sentence of every document is included in the last sample
        instead of being silently dropped (and 1-sentence documents yield a
        sample at all);
      - a flushed chunk holding a single segment forces ``is_random_next``
        (canonical BERT behavior) instead of emitting a degenerate pair with
        an empty second segment labeled "actual next".
    """
    nsp = next_seq_prob > 0
    max_num_tokens = max_seq_len - (3 if nsp else 2)
    target_len = _target_length(max_num_tokens, short_seq_prob, rng)

    document = documents[document_idx]
    samples: List[TrainingSample] = []
    chunk: List[List[str]] = []
    chunk_length = 0
    i = 0
    while i < len(document):
        current = document[i][:target_len]
        chunk.append(current)
        chunk_length += len(current)
        if i + 1 == len(document) or chunk_length >= target_len:
            if nsp:
                if len(documents) <= 1:
                    raise ValueError(
                        "File only contained one document; unable to draw a "
                        "random next sequence."
                    )
                seq_end = rng.randint(1, len(chunk) - 1) if len(chunk) >= 2 else 1
                seq_tokens = [t for seg in chunk[:seq_end] for t in seg]
                if len(chunk) == 1 or rng.random() < next_seq_prob:
                    # Random next: fill from a random position in another
                    # document, and rewind i to reuse the displaced segments.
                    is_random_next = True
                    rand_idx = rng.randint(0, len(documents) - 1)
                    while rand_idx == document_idx:
                        rand_idx = rng.randint(0, len(documents) - 1)
                    rand_doc = documents[rand_idx]
                    rand_start = rng.randint(0, len(rand_doc) - 1)
                    budget = max(1, target_len - len(seq_tokens))
                    next_seq_tokens: List[str] = []
                    for j in range(rand_start, len(rand_doc)):
                        next_seq_tokens.extend(rand_doc[j])
                        if len(next_seq_tokens) >= budget:
                            next_seq_tokens = next_seq_tokens[:budget]
                            break
                    i -= len(chunk) - seq_end
                else:
                    is_random_next = False
                    next_seq_tokens = [
                        t for seg in chunk[seq_end:] for t in seg
                    ]
                _truncate_pair(seq_tokens, next_seq_tokens, target_len)
                samples.append(
                    TrainingSample(seq_tokens, next_seq_tokens, is_random_next)
                )
            else:
                seq_tokens = [t for seg in chunk for t in seg][:target_len]
                samples.append(TrainingSample(seq_tokens))
            target_len = _target_length(max_num_tokens, short_seq_prob, rng)
            chunk = []
            chunk_length = 0
        i += 1
    return samples


def create_samples(
    input_file: str, tokenizer, max_seq_len: int, next_seq_prob: float,
    short_seq_prob: float, rng=random,
) -> List[TrainingSample]:
    documents = documents_from_file(input_file, tokenizer)
    samples: List[TrainingSample] = []
    for i in range(len(documents)):
        samples.extend(
            create_samples_from_document(
                i, documents, max_seq_len, next_seq_prob, short_seq_prob, rng
            )
        )
    rng.shuffle(samples)
    return samples


def write_packed_samples_to_hdf5(output_file, samples, tokenizer,
                                 max_seq_len, max_sequences_per_pack) -> int:
    """Offline sequence packing (docs/packing.md): greedy
    first-fit-decreasing over the encoded samples, written in the packed
    shard layout data/packing.py owns. Dynamic masking still happens in
    the runtime dataset — the shard stores raw token ids plus per-member
    lengths/special positions; returns the packed row count."""
    from bert_pytorch_tpu.data.packing import (first_fit_decreasing,
                                               write_packed_shard)

    encoded = []
    for sample in samples:
        ids = [tokenizer.token_to_id(t) for t in sample.sequence]
        assert None not in ids, "token missing from vocab"
        assert len(ids) <= max_seq_len
        encoded.append((np.asarray(ids, np.int32),
                        sample.special_token_positions,
                        1 if sample.is_random_next else 0))
    packs = first_fit_decreasing(
        [len(e[0]) for e in encoded], max_seq_len, max_sequences_per_pack)
    rows = [[encoded[i] for i in pack] for pack in packs]
    n = write_packed_shard(output_file, rows, max_seq_len,
                           max_sequences_per_pack)
    total = sum(len(e[0]) for e in encoded)
    print(f"[encoder] packed {len(encoded)} samples into {n} rows "
          f"(occupancy {total / max(1, n * max_seq_len):.3f})")
    return n


def write_samples_to_hdf5(output_file, samples, tokenizer, max_seq_len) -> int:
    """Gzip HDF5 in the runtime dataset's format (reference :183-210);
    special_token_positions is a ragged (vlen) i4 dataset since samples mix
    2- and 3-entry position lists."""
    n = len(samples)
    input_ids = np.zeros((n, max_seq_len), np.int32)
    next_labels = np.zeros((n,), np.int8)
    specials = []
    for row, sample in enumerate(samples):
        ids = [tokenizer.token_to_id(t) for t in sample.sequence]
        assert None not in ids, "token missing from vocab"
        assert len(ids) <= max_seq_len
        input_ids[row, : len(ids)] = ids
        specials.append(np.asarray(sample.special_token_positions, np.int32))
        next_labels[row] = 1 if sample.is_random_next else 0

    with h5py.File(output_file, "w") as f:
        f.create_dataset("input_ids", data=input_ids, dtype="i4",
                         compression="gzip")
        dt = h5py.vlen_dtype(np.dtype("i4"))
        ds = f.create_dataset("special_token_positions", (n,), dtype=dt,
                              compression="gzip")
        for row, sp in enumerate(specials):
            ds[row] = sp
        f.create_dataset("next_sentence_labels", data=next_labels, dtype="i1",
                         compression="gzip")
    return n


def _make_tokenizer(args):
    from bert_pytorch_tpu.data.tokenization import (
        get_bpe_tokenizer, get_wordpiece_tokenizer)

    if args.tokenizer == "wordpiece":
        return get_wordpiece_tokenizer(args.vocab_file,
                                       uppercase=args.uppercase)
    return get_bpe_tokenizer(args.vocab_file, uppercase=args.uppercase)


def encode_file(args, input_file: str, output_file: str) -> None:
    print(f"[encoder] Creating instances from {input_file}")
    start = time.time()
    tokenizer = _make_tokenizer(args)
    samples = create_samples(
        input_file, tokenizer, args.max_seq_len, args.next_seq_prob,
        args.short_seq_prob)
    if getattr(args, "pack_sequences", False):
        n = write_packed_samples_to_hdf5(
            output_file, samples, tokenizer, args.max_seq_len,
            args.max_sequences_per_pack)
    else:
        n = write_samples_to_hdf5(output_file, samples, tokenizer,
                                  args.max_seq_len)
    print(f"[encoder] Encoded {output_file} ({n} samples, "
          f"time={time.time() - start:.0f}s)")


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--input_dir", type=str, required=True)
    parser.add_argument("--output_dir", type=str, required=True)
    parser.add_argument("--vocab_file", type=str, required=True)
    parser.add_argument("--max_seq_len", type=int, default=512)
    parser.add_argument("--short_seq_prob", type=float, default=0.1)
    parser.add_argument("--next_seq_prob", type=float, default=0.0,
                        help="probability of a random next segment; 0 "
                             "disables the NSP task entirely")
    parser.add_argument("--uppercase", action="store_true")
    parser.add_argument("--tokenizer", type=str, default="wordpiece",
                        choices=["wordpiece", "bpe"])
    parser.add_argument("--processes", type=int, default=4)
    parser.add_argument("--pack_sequences", action="store_true",
                        help="emit offline-PACKED shards (greedy first-fit-"
                             "decreasing, data/packing.py layout): several "
                             "sequences share one max_seq_len row; the "
                             "runtime derives block-diagonal attention "
                             "masks from it (docs/packing.md)")
    parser.add_argument("--max_sequences_per_pack", type=int, default=8,
                        help="cap on sequences per packed row")
    args = parser.parse_args(argv)

    input_files = []
    if os.path.isfile(args.input_dir):
        input_files.append(args.input_dir)
    elif os.path.isdir(args.input_dir):
        input_files = sorted(
            str(p) for p in Path(args.input_dir).rglob("*.txt") if p.is_file())
    else:
        raise ValueError(f"{args.input_dir} is not a valid path")
    print(f"[encoder] Found {len(input_files)} input files")

    prefix = (
        f"sequences_{'uppercase' if args.uppercase else 'lowercase'}"
        f"_max_seq_len_{args.max_seq_len}"
        f"_next_seq_task_{str(args.next_seq_prob > 0).lower()}"
        # Packed and unpacked shards cannot share a dataset directory
        # (data/dataset.py refuses the mix), so the prefix keeps them apart.
        + ("_packed" if args.pack_sequences else "")
    )
    out_dir = os.path.join(args.output_dir, prefix)
    os.makedirs(out_dir, exist_ok=True)

    jobs = [
        (args, ifile, os.path.join(out_dir, f"train_{i}.hdf5"))
        for i, ifile in enumerate(input_files)
    ]
    start = time.time()
    if args.processes <= 1 or len(jobs) <= 1:
        for job in jobs:
            encode_file(*job)
    else:
        with mp.Pool(processes=args.processes) as pool:
            pool.starmap(encode_file, jobs)
    print(f"[encoder] Finished processing (time={time.time() - start:.0f}s)")


if __name__ == "__main__":
    main()
