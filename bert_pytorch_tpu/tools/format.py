"""Format raw corpus dumps into one-sentence-per-line text.

Parity with reference utils/format.py: wikiextractor JSON/text output or
BooksCorpus .txt files -> files with one sentence per line and a blank line
between articles/documents (:28-63, :97-176), processed with an mp.Pool and
round-robin assignment of articles to output shards.

Sentence splitting uses nltk's punkt when importable (reference :13-25) and
a regex splitter otherwise (zero-download environments).
"""

from __future__ import annotations

import argparse
import glob
import json
import multiprocessing as mp
import os
import re


def get_sentences(text: str) -> list[str]:
    try:
        import nltk

        try:
            return nltk.tokenize.sent_tokenize(text)
        except LookupError:
            pass
    except ImportError:
        pass
    # Regex fallback: split on sentence-final punctuation + whitespace.
    parts = re.split(r"(?<=[.!?])\s+", text.strip())
    return [p.strip() for p in parts if p.strip()]


def _iter_wiki_articles(path: str):
    """wikiextractor output: either --json lines or <doc> ... </doc> blocks."""
    with open(path, "r", encoding="utf-8", errors="ignore") as f:
        first = f.read(1)
        f.seek(0)
        if first == "{":
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line).get("text", "")
                except json.JSONDecodeError:
                    continue
        else:
            article: list[str] = []
            for line in f:
                if line.startswith("<doc"):
                    article = []
                elif line.startswith("</doc"):
                    yield "\n".join(article)
                else:
                    article.append(line.strip())


def _format_wiki(input_path: str, output_path: str) -> None:
    with open(output_path, "a", encoding="utf-8") as out:
        for article in _iter_wiki_articles(input_path):
            wrote = False
            for paragraph in article.split("\n"):
                for sentence in get_sentences(paragraph):
                    out.write(sentence + "\n")
                    wrote = True
            if wrote:
                out.write("\n")


def _format_books(input_path: str, output_path: str) -> None:
    with open(input_path, "r", encoding="utf-8", errors="ignore") as f:
        text = f.read()
    with open(output_path, "a", encoding="utf-8") as out:
        wrote = False
        for paragraph in text.split("\n"):
            for sentence in get_sentences(paragraph):
                out.write(sentence + "\n")
                wrote = True
        if wrote:
            out.write("\n")


FORMATTERS = {"wiki": _format_wiki, "books": _format_books}


def _run_job(dataset: str, output: str, inputs: list[str]) -> None:
    """Module-level so mp.Pool can pickle it (workers look the formatter up
    by dataset name)."""
    fmt = FORMATTERS[dataset]
    for ifile in inputs:
        fmt(ifile, output)


def format_corpus(input_files, output_dir: str, dataset: str,
                  num_outputs: int = 16, processes: int = 4) -> list[str]:
    os.makedirs(output_dir, exist_ok=True)
    outputs = [
        os.path.join(output_dir, f"{dataset}_{i:03d}.txt")
        for i in range(num_outputs)
    ]
    for path in outputs:
        open(path, "w").close()
    # Round-robin input->output assignment; one worker per output file so
    # appends never interleave.
    assignment: dict[str, list[str]] = {o: [] for o in outputs}
    for i, f in enumerate(sorted(input_files)):
        assignment[outputs[i % num_outputs]].append(f)

    jobs = [(dataset, o, ins) for o, ins in assignment.items() if ins]
    if processes <= 1:
        for job in jobs:
            _run_job(*job)
    else:
        with mp.Pool(processes=processes) as pool:
            pool.starmap(_run_job, jobs)
    return [o for o, ins in assignment.items() if ins]


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--input_glob", type=str, required=True)
    parser.add_argument("--output_dir", type=str, required=True)
    parser.add_argument("--dataset", choices=sorted(FORMATTERS), required=True)
    parser.add_argument("--num_outputs", type=int, default=16)
    parser.add_argument("--processes", type=int, default=4)
    args = parser.parse_args(argv)
    files = glob.glob(args.input_glob, recursive=True)
    print(f"[formatter] {len(files)} input files")
    outs = format_corpus(files, args.output_dir, args.dataset,
                         args.num_outputs, args.processes)
    print(f"[formatter] wrote {len(outs)} formatted files")


if __name__ == "__main__":
    main()
