"""Generate synthetic pretraining shards for smoke tests and benchmarks.

Writes HDF5 shards in the same formats the real pipeline produces
(reference utils/encode_data.py:183-210 for the new
``special_token_positions`` format; NVIDIA DeepLearningExamples layout for
the legacy pre-masked format, reference dataset.py:184-192) so the data
runtime and runners can be exercised end-to-end without the real corpus.

``--requests N`` switches to REQUEST-TRACE mode (docs/serving.md): a JSONL
trace of N online-inference requests — mixed task heads, short-biased
text lengths (the same u^2 draw as ``--mixed_lengths``, which is what
makes request packing worth testing), Poisson arrival offsets — plus a
``vocab.txt`` covering the trace's word list, consumed by bench.py's
``BENCH_SERVE`` leg and the serving smoke test (tests/test_serve.py).
"""

from __future__ import annotations

import argparse
import json
import os

import h5py
import numpy as np

# Word list for synthetic request text; ``write_trace_vocab`` derives a
# WordPiece vocab covering exactly these, so any trace line tokenizes
# without [UNK] under either the C++ or the pure-Python tokenizer.
TRACE_WORDS = (
    "the capital of france is paris what who wrote hamlet shakespeare "
    "william city big a in was by play london england river runs through "
    "where mountain tall old new house red blue green").split()
TRACE_TASKS = ("fill_mask", "classify", "squad", "ner")


def write_trace_vocab(path: str) -> str:
    """WordPiece vocab covering :data:`TRACE_WORDS` + the BERT specials."""
    tokens = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + list(TRACE_WORDS)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(tokens) + "\n")
    return path


def _trace_text(rng, n_words: int) -> str:
    return " ".join(
        TRACE_WORDS[i]
        for i in rng.integers(0, len(TRACE_WORDS), max(1, n_words)))


def make_request_trace(
    path: str,
    num_requests: int,
    seed: int = 0,
    tasks=TRACE_TASKS,
    max_words: int = 48,
    rate_rps: float = 100.0,
) -> str:
    """Write a JSONL request trace for the serving engine.

    Each line: ``{"id", "arrival_s", "task", "payload"}``. Lengths are
    short-biased (``lo + (max-lo) * u^2`` words — the Wikipedia-style
    spread of ``--mixed_lengths``, so packing has headroom); arrivals are
    Poisson (exponential inter-arrival at ``rate_rps``; 0 = all at t=0,
    the closed-loop saturation replay bench.py uses by default).
    """
    rng = np.random.default_rng(seed)
    lines = []
    t = 0.0
    for i in range(num_requests):
        if rate_rps > 0:
            t += float(rng.exponential(1.0 / rate_rps))
        task = str(tasks[int(rng.integers(0, len(tasks)))])
        n_words = 3 + int((max_words - 3) * float(rng.random()) ** 2)
        if task == "fill_mask":
            words = _trace_text(rng, n_words).split()
            words[int(rng.integers(0, len(words)))] = "[MASK]"
            payload = {"text": " ".join(words)}
        elif task == "classify":
            payload = {"text": _trace_text(rng, n_words)}
            if rng.random() < 0.3:
                payload["text_pair"] = _trace_text(
                    rng, max(1, n_words // 2))
        elif task == "squad":
            payload = {
                "question": _trace_text(rng, min(8, max(3, n_words // 4))),
                "context": _trace_text(rng, n_words),
            }
        else:  # ner
            payload = {"text": _trace_text(rng, n_words)}
        lines.append(json.dumps({
            "id": i, "arrival_s": round(t, 6), "task": task,
            "payload": payload}))
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")
    return path


def make_shard(
    path: str,
    num_samples: int,
    seq_len: int,
    vocab_size: int,
    seed: int = 0,
    nsp: bool = True,
    legacy: bool = False,
    max_pred_per_seq: int = 20,
    mixed_lengths: bool = False,
    packed: bool = False,
    max_sequences_per_pack: int = 8,
):
    """``mixed_lengths`` draws content lengths uniformly from nearly the
    whole range (instead of the [S/2, S) default) — a stand-in for the
    Wikipedia-style length distribution that makes sequence packing
    (docs/packing.md) worth ~2x, so packing is exercisable in tests and
    bench.py. ``packed`` additionally packs the generated samples
    first-fit-decreasing and writes an OFFLINE-PACKED shard
    (data/packing.py layout) instead of the unpacked one."""
    if packed and legacy:
        raise ValueError("packed shards use the new format only")
    rng = np.random.default_rng(seed)
    input_ids = np.zeros((num_samples, seq_len), np.int32)
    specials = []
    next_sentence = rng.integers(0, 2 if nsp else 1, num_samples).astype(np.int8)

    cls_id, sep_id = 2, 3  # arbitrary special ids clear of 0 ([PAD])
    for i in range(num_samples):
        # Random content length; two segments when NSP.
        if mixed_lengths:
            # Short-biased draw (u^2 over the full range): mean occupancy
            # ~0.4 like real Wikipedia-style corpora (Krell 2021 fig. 1),
            # with occasional near-full rows so truncation paths are hit.
            lo = min(6, seq_len - 4)
            content = lo + int((seq_len - 2 - lo) * rng.random() ** 2)
        else:
            content = int(rng.integers(seq_len // 2, seq_len - 1))
        ids = rng.integers(5, vocab_size, size=content).astype(np.int32)
        if nsp:
            split = int(rng.integers(1, content - 1)) if content > 2 else 1
            row = np.concatenate(
                [[cls_id], ids[:split], [sep_id], ids[split:], [sep_id]]
            )
            special = [0, split + 1, len(row) - 1]
        else:
            row = np.concatenate([[cls_id], ids, [sep_id]])
            special = [0, len(row) - 1]
        row = row[:seq_len]
        special = [min(p, seq_len - 1) for p in special]
        input_ids[i, : len(row)] = row
        specials.append(special)

    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    if packed:
        from bert_pytorch_tpu.data.packing import (first_fit_decreasing,
                                                   write_packed_shard)

        lengths = [sp[-1] + 1 for sp in specials]
        packs = first_fit_decreasing(lengths, seq_len, max_sequences_per_pack)
        rows = [
            [(input_ids[i, :lengths[i]], specials[i], int(next_sentence[i]))
             for i in pack]
            for pack in packs
        ]
        write_packed_shard(path, rows, seq_len, max_sequences_per_pack)
        return path
    with h5py.File(path, "w") as f:
        f.create_dataset("input_ids", data=input_ids, dtype="i4", compression="gzip")
        if legacy:
            segment_ids = np.zeros_like(input_ids)
            input_mask = np.zeros_like(input_ids)
            positions = np.zeros((num_samples, max_pred_per_seq), np.int32)
            label_ids = np.zeros((num_samples, max_pred_per_seq), np.int32)
            for i, special in enumerate(specials):
                input_mask[i, : special[-1] + 1] = 1
                if len(special) == 3:
                    segment_ids[i, special[1] + 1 : special[2] + 1] = 1
                n_mask = int(rng.integers(1, max_pred_per_seq))
                cand = [
                    p for p in range(1, special[-1]) if p not in special
                ][:n_mask]
                positions[i, : len(cand)] = cand
                label_ids[i, : len(cand)] = input_ids[i, cand]
            f.create_dataset("segment_ids", data=segment_ids, dtype="i4")
            f.create_dataset("input_mask", data=input_mask, dtype="i4")
            f.create_dataset("masked_lm_positions", data=positions, dtype="i4")
            f.create_dataset("masked_lm_ids", data=label_ids, dtype="i4")
        else:
            # Ragged special_token_positions (2 or 3 entries per sample).
            dt = h5py.vlen_dtype(np.dtype("i4"))
            ds = f.create_dataset("special_token_positions", (num_samples,), dtype=dt)
            for i, special in enumerate(specials):
                ds[i] = np.asarray(special, np.int32)
        f.create_dataset(
            "next_sentence_labels", data=next_sentence, dtype="i1", compression="gzip"
        )
    return path


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--output_dir", required=True)
    p.add_argument("--num_shards", type=int, default=2)
    p.add_argument("--samples_per_shard", type=int, default=64)
    p.add_argument("--seq_len", type=int, default=128)
    p.add_argument("--vocab_size", type=int, default=30522)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--no_nsp", action="store_true")
    p.add_argument("--legacy", action="store_true")
    p.add_argument("--mixed_lengths", action="store_true",
                   help="draw content lengths from (6, seq_len) instead of "
                        "[seq_len/2, seq_len) — the length spread that makes "
                        "sequence packing (docs/packing.md) worth testing")
    p.add_argument("--packed", action="store_true",
                   help="write offline-PACKED shards (data/packing.py "
                        "layout); combine with --mixed_lengths")
    p.add_argument("--max_sequences_per_pack", type=int, default=8)
    p.add_argument("--requests", type=int, default=0,
                   help="REQUEST-TRACE mode: write a JSONL trace of N "
                        "online-inference requests (mixed tasks, short-"
                        "biased lengths, Poisson arrivals) plus a "
                        "covering vocab.txt into --output_dir, for "
                        "BENCH_SERVE and the serving smoke test "
                        "(docs/serving.md)")
    p.add_argument("--request_rate", type=float, default=100.0,
                   help="Poisson arrival rate (req/s) for --requests; "
                        "0 = all arrivals at t=0 (saturation replay)")
    p.add_argument("--max_words", type=int, default=48,
                   help="--requests: max words per request text (short-"
                        "biased draw below this)")
    args = p.parse_args(argv)

    if args.requests:
        trace = make_request_trace(
            os.path.join(args.output_dir, "requests.jsonl"),
            args.requests, seed=args.seed, max_words=args.max_words,
            rate_rps=args.request_rate)
        vocab = write_trace_vocab(
            os.path.join(args.output_dir, "vocab.txt"))
        print(f"wrote {trace}")
        print(f"wrote {vocab}")
        return

    for s in range(args.num_shards):
        path = os.path.join(args.output_dir, f"shard_{s:04d}.hdf5")
        make_shard(
            path,
            args.samples_per_shard,
            args.seq_len,
            args.vocab_size,
            seed=args.seed + s,
            nsp=not args.no_nsp,
            legacy=args.legacy,
            mixed_lengths=args.mixed_lengths,
            packed=args.packed,
            max_sequences_per_pack=args.max_sequences_per_pack,
        )
        print(f"wrote {path}")


if __name__ == "__main__":
    main()
