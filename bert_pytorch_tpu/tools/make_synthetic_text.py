"""Synthesize a learnable text corpus + SQuAD-format QA data, egress-free.

The offline pipeline (scripts/create_datasets.sh ≙ reference
scripts/create_datasets.sh:85-141) normally starts from a Wikipedia dump
and SQuAD downloads; this container has zero egress, so the end-to-end
capability chain (format -> shard -> vocab -> encode -> pretrain ->
finetune -> official eval) is proven on locally generated data instead
(scripts/e2e_offline.sh).

The corpus is templated English over a closed entity/fact world with a
Zipf-ish word distribution — structured enough that a WordPiece vocab
trained on it is non-degenerate and a small model can learn the
fact patterns. The SQuAD generator emits v1.1-format train/dev JSON whose
answers are literal spans of the generated contexts, so the sliding-window
featurization, answer realignment (get_final_text), n-best decode, and the
official EM/F1 metric all exercise their real code paths.
"""

from __future__ import annotations

import argparse
import json
import os
import random

ENTITIES = [
    "arveth", "brimlor", "caldus", "dorvane", "elmira", "fenwick",
    "garlan", "hestia", "ilmar", "jorund", "kelvar", "lorath",
    "mirren", "norvik", "ostara", "pellam", "quorin", "ravenna",
    "selwyn", "tormund", "ulfric", "vexley", "wendel", "ystral",
]
RELATIONS = [
    ("capital", "the capital of {a} is {b}.",
     "what is the capital of {a}?"),
    ("river", "the longest river in {a} is called {b}.",
     "what is the longest river in {a}?"),
    ("founder", "{b} founded the city of {a} long ago.",
     "who founded the city of {a}?"),
    ("export", "the main export of {a} is {b}.",
     "what is the main export of {a}?"),
    ("ruler", "during the old age {b} ruled over {a}.",
     "who ruled over {a} during the old age?"),
]
FILLER = [
    "the merchants travelled far across the plains.",
    "many scholars wrote about these lands in heavy books.",
    "winter in the north lasts for several long months.",
    "trade along the coast grew quickly in those years.",
    "the old roads connect every town to the harbour.",
    "farmers in the valley grow wheat and barley.",
    "sailors tell stories about the storms of the east.",
    "the great library holds maps of every province.",
]


def _facts(rng):
    """A consistent fact world: each (entity, relation) maps to one value."""
    facts = {}
    for a in ENTITIES:
        for rel, stmt, q in RELATIONS:
            facts[(a, rel)] = rng.choice([e for e in ENTITIES if e != a])
    return facts


def write_corpus(out_dir, n_files, articles_per_file, seed):
    """Formatted one-sentence-per-line text, blank line between articles
    (the contract of tools/shard.py's iter_articles)."""
    rng = random.Random(seed)
    facts = _facts(rng)
    os.makedirs(out_dir, exist_ok=True)
    paths = []
    for fi in range(n_files):
        path = os.path.join(out_dir, f"corpus_{fi:03d}.txt")
        with open(path, "w") as f:
            for _ in range(articles_per_file):
                a = rng.choice(ENTITIES)
                n_sent = rng.randint(6, 14)
                for _ in range(n_sent):
                    if rng.random() < 0.55:
                        rel, stmt, _q = rng.choice(RELATIONS)
                        f.write(stmt.format(a=a, b=facts[(a, rel)]) + "\n")
                    else:
                        f.write(rng.choice(FILLER) + "\n")
                f.write("\n")
        paths.append(path)
    return paths


def write_squad(out_path, n_paragraphs, qas_per_paragraph, seed,
                fact_seed, impossible_frac=0.0):
    """SQuAD-format JSON; answers are literal context spans.

    With impossible_frac > 0 the output is v2.0-format: that fraction of
    questions ask about a relation whose fact sentence is NOT in the
    paragraph (``is_impossible: true``, empty answers — the official
    evaluate-v2.0 semantics; reference consumes the real v2.0 dev set the
    same way, run_squad.py:131-206)."""
    rng = random.Random(seed)
    facts = _facts(random.Random(fact_seed))  # same world as the corpus
    v2 = impossible_frac > 0
    data = []
    qid = 0
    for pi in range(n_paragraphs):
        a = rng.choice(ENTITIES)
        # keep at least one relation OUT of the context so impossible
        # questions (about the held-out relations) exist to ask
        k = min(qas_per_paragraph, len(RELATIONS) - 1 if v2 else len(RELATIONS))
        rels = rng.sample(RELATIONS, k=k)
        held_out = [r for r in RELATIONS if r not in rels]
        sentences, qas = [], []
        for rel, stmt, question in rels:
            b = facts[(a, rel)]
            sentences.append(stmt.format(a=a, b=b))
            sentences.append(rng.choice(FILLER))
        context = " ".join(sentences)
        for rel, stmt, question in rels:
            if v2 and rng.random() < impossible_frac:
                # Ask about a fact the paragraph does not state.
                mrel, _mstmt, mquestion = rng.choice(held_out)
                qas.append({
                    "id": f"q{qid}",
                    "question": mquestion.format(a=a),
                    "answers": [],
                    "is_impossible": True,
                })
                qid += 1
                continue
            b = facts[(a, rel)]
            # the answer span is b's occurrence inside its own fact
            # sentence (b may also appear elsewhere in the context)
            sent = stmt.format(a=a, b=b)
            sent_start = context.find(sent)
            start = sent_start + sent.find(b)
            assert context[start:start + len(b)] == b
            qa = {
                "id": f"q{qid}",
                "question": question.format(a=a),
                "answers": [{"text": b, "answer_start": start}],
            }
            if v2:
                qa["is_impossible"] = False
            qas.append(qa)
            qid += 1
        data.append({
            "title": f"article_{pi}",
            "paragraphs": [{"context": context, "qas": qas}],
        })
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump({"version": "v2.0" if v2 else "1.1", "data": data}, f)
    return out_path


def main(argv=None):
    p = argparse.ArgumentParser()
    sub = p.add_subparsers(dest="mode", required=True)
    c = sub.add_parser("corpus")
    c.add_argument("--output_dir", required=True)
    c.add_argument("--num_files", type=int, default=4)
    c.add_argument("--articles_per_file", type=int, default=200)
    c.add_argument("--seed", type=int, default=0)
    s = sub.add_parser("squad")
    s.add_argument("--output", required=True)
    s.add_argument("--paragraphs", type=int, default=200)
    s.add_argument("--qas_per_paragraph", type=int, default=3)
    s.add_argument("--seed", type=int, default=1)
    s.add_argument("--fact_seed", type=int, default=0,
                   help="must match the corpus --seed for a shared world")
    s.add_argument("--impossible_frac", type=float, default=0.0,
                   help=">0 emits SQuAD v2.0 format with this fraction of "
                        "unanswerable questions")
    args = p.parse_args(argv)
    if args.mode == "corpus":
        paths = write_corpus(args.output_dir, args.num_files,
                             args.articles_per_file, args.seed)
        print(f"wrote {len(paths)} corpus files to {args.output_dir}")
    else:
        path = write_squad(args.output, args.paragraphs,
                           args.qas_per_paragraph, args.seed, args.fact_seed,
                           args.impossible_frac)
        print(f"wrote {path}")


if __name__ == "__main__":
    main()
