"""Split formatted one-sentence-per-line text into ~N-byte shards on article
boundaries.

Parity with reference utils/shard.py (:6-27: greedy fill to max_bytes,
never splitting inside an article) and utils/sample_and_shard.py (the
``--sample_sentences`` variant that uniformly subsamples sentences before
sharding, :83-121). Size strings accept k/M/G suffixes
(reference shard.py:30-43).
"""

from __future__ import annotations

import argparse
import glob
import os
import random


def parse_value_as_int(value) -> int:
    """'250M' -> 250_000_000 (reference shard.py:30-43)."""
    if isinstance(value, int):
        return value
    value = value.strip()
    suffixes = {"k": 10**3, "K": 10**3, "m": 10**6, "M": 10**6,
                "g": 10**9, "G": 10**9}
    if value and value[-1] in suffixes:
        return int(float(value[:-1]) * suffixes[value[-1]])
    return int(value)


def iter_articles(paths):
    """Yield articles (lists of sentences) across files."""
    for path in sorted(paths):
        article: list[str] = []
        with open(path, "r", encoding="utf-8", errors="ignore") as f:
            for line in f:
                line = line.rstrip("\n")
                if not line:
                    if article:
                        yield article
                        article = []
                    continue
                article.append(line)
        if article:
            yield article


def shard(input_files, output_dir: str, max_bytes: int,
          prefix: str = "shard", sample_sentences: int | None = None,
          seed: int = 0) -> list[str]:
    os.makedirs(output_dir, exist_ok=True)
    articles = list(iter_articles(input_files))

    if sample_sentences is not None:
        # Uniform sentence subsample, preserving article grouping
        # (sample_and_shard.py:83-121).
        rng = random.Random(seed)
        flat = [(ai, s) for ai, art in enumerate(articles) for s in art]
        keep = set(
            rng.sample(range(len(flat)), min(sample_sentences, len(flat))))
        regrouped: dict[int, list[str]] = {}
        for i, (ai, s) in enumerate(flat):
            if i in keep:
                regrouped.setdefault(ai, []).append(s)
        articles = [regrouped[k] for k in sorted(regrouped)]

    outputs = []
    shard_idx = 0
    current_bytes = 0
    out = None
    for article in articles:
        if out is None or current_bytes >= max_bytes:
            if out is not None:
                out.close()
            path = os.path.join(output_dir, f"{prefix}_{shard_idx:04d}.txt")
            out = open(path, "w", encoding="utf-8")
            outputs.append(path)
            shard_idx += 1
            current_bytes = 0
        for sentence in article:
            out.write(sentence + "\n")
            current_bytes += len(sentence) + 1
        out.write("\n")
        current_bytes += 1
    if out is not None:
        out.close()
    return outputs


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--input_glob", type=str, required=True)
    parser.add_argument("--output_dir", type=str, required=True)
    parser.add_argument("--max_bytes_per_shard", type=str, default="250M")
    parser.add_argument("--prefix", type=str, default="shard")
    parser.add_argument("--sample_sentences", type=int, default=None)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)
    files = glob.glob(args.input_glob, recursive=True)
    outs = shard(files, args.output_dir,
                 parse_value_as_int(args.max_bytes_per_shard), args.prefix,
                 args.sample_sentences, args.seed)
    print(f"[shard] wrote {len(outs)} shards from {len(files)} files")


if __name__ == "__main__":
    main()
