"""ctypes bindings for the C++ tokenizer core (native/tokenizer.cpp).

The in-repo native replacement for the HuggingFace Rust `tokenizers`
dependency (SURVEY.md §2.3). API shape mirrors the fast-tokenizer surface
the reference code uses: ``encode(text).ids/.tokens``, ``token_to_id``,
``id_to_token`` (reference src/tokenization.py:42-49,
src/dataset.py mask-token lookup, run_squad.py:292).

The library is built on demand with ``make -C native`` (g++ only, no
external deps); when neither the prebuilt .so nor a compiler is available,
callers fall back to the HF tokenizers package or the pure-Python
implementation (bert_pytorch_tpu/data/tokenization.py).

Thread-safety: the C++ core keeps the LAST encode's ids/tokens in
per-handle buffers (``wp_encode`` fills, ``wp_get_ids``/``wp_get_tokens``
read), so an unguarded concurrent encode would hand one thread another
thread's result. Every tokenizer instance therefore serializes
``encode`` behind its own ``_encode_lock`` — shared instances are safe
under the serving engine's worker threads (docs/serving.md), at the cost
of one-encode-at-a-time per instance; ``token_to_id``/``id_to_token``
are read-only lookups and take no lock.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from dataclasses import dataclass
from typing import List, Optional

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libbert_tokenizer.so")
_lib = None
_lib_lock = threading.Lock()


def _load_library() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        # Always invoke make: it is a timestamp-checked no-op when the .so
        # is current, and it rebuilds a STALE one (a prebuilt library from
        # an older source would be missing newer symbols and poison every
        # ctypes prototype below). If make itself is unavailable, fall
        # through to loading whatever .so exists.
        try:
            subprocess.run(
                ["make", "-C", _NATIVE_DIR, "-s"],
                check=True,
                capture_output=True,
            )
        except (OSError, subprocess.CalledProcessError):
            if not os.path.exists(_LIB_PATH):
                raise
        lib = ctypes.CDLL(_LIB_PATH)
        lib.wp_create.restype = ctypes.c_void_p
        lib.wp_create.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.wp_free.argtypes = [ctypes.c_void_p]
        lib.wp_vocab_size.argtypes = [ctypes.c_void_p]
        lib.wp_vocab_size.restype = ctypes.c_int
        lib.wp_token_to_id.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.wp_token_to_id.restype = ctypes.c_int
        lib.wp_id_to_token.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.wp_id_to_token.restype = ctypes.c_char_p
        lib.wp_encode.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_int]
        lib.wp_encode.restype = ctypes.c_int
        lib.wp_get_ids.argtypes = [ctypes.c_void_p]
        lib.wp_get_ids.restype = ctypes.POINTER(ctypes.c_int)
        lib.wp_get_tokens.argtypes = [ctypes.c_void_p]
        lib.wp_get_tokens.restype = ctypes.c_char_p
        lib.wp_train.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_char_p,
        ]
        lib.wp_train.restype = ctypes.c_int
        lib.bpe_create.restype = ctypes.c_void_p
        lib.bpe_create.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                   ctypes.c_int]
        lib.bpe_free.argtypes = [ctypes.c_void_p]
        lib.bpe_vocab_size.argtypes = [ctypes.c_void_p]
        lib.bpe_vocab_size.restype = ctypes.c_int
        lib.bpe_token_to_id.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.bpe_token_to_id.restype = ctypes.c_int
        lib.bpe_id_to_token.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.bpe_id_to_token.restype = ctypes.c_char_p
        lib.bpe_encode.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_int]
        lib.bpe_encode.restype = ctypes.c_int
        lib.bpe_get_ids.argtypes = [ctypes.c_void_p]
        lib.bpe_get_ids.restype = ctypes.POINTER(ctypes.c_int)
        lib.bpe_get_tokens.argtypes = [ctypes.c_void_p]
        lib.bpe_get_tokens.restype = ctypes.c_char_p
        lib.bpe_train.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_char_p,
        ]
        lib.bpe_train.restype = ctypes.c_int
        _lib = lib
        return lib


@dataclass
class Encoding:
    ids: List[int]
    tokens: List[str]


class CppWordPieceTokenizer:
    """BERT WordPiece tokenizer backed by the C++ core."""

    def __init__(self, vocab_file: str, lowercase: bool = True):
        self._lib = _load_library()
        self._handle = self._lib.wp_create(
            vocab_file.encode("utf-8"), 1 if lowercase else 0
        )
        if not self._handle:
            raise OSError(f"could not load vocab from {vocab_file}")
        self.lowercase = lowercase
        # Encoding is stateful per handle; serialize access.
        self._encode_lock = threading.Lock()

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.wp_free(handle)
            self._handle = None

    def get_vocab_size(self) -> int:
        return self._lib.wp_vocab_size(self._handle)

    def token_to_id(self, token: str) -> Optional[int]:
        tid = self._lib.wp_token_to_id(self._handle, token.encode("utf-8"))
        return None if tid < 0 else tid

    def id_to_token(self, token_id: int) -> str:
        return self._lib.wp_id_to_token(self._handle, token_id).decode("utf-8")

    def encode(self, text: str, add_special_tokens: bool = False) -> Encoding:
        with self._encode_lock:
            raw_text = text.encode("utf-8")
            n = self._lib.wp_encode(self._handle, raw_text, len(raw_text))
            ids = list(self._lib.wp_get_ids(self._handle)[:n])
            raw = self._lib.wp_get_tokens(self._handle).decode("utf-8")
        tokens = raw.split("\n") if raw else []
        if add_special_tokens:
            cls_id, sep_id = self.token_to_id("[CLS]"), self.token_to_id("[SEP]")
            ids = [cls_id] + ids + [sep_id]
            tokens = ["[CLS]"] + tokens + ["[SEP]"]
        return Encoding(ids=ids, tokens=tokens)

    def encode_batch(self, texts: List[str]) -> List[Encoding]:
        return [self.encode(t) for t in texts]


class CppByteLevelBPETokenizer:
    """Byte-level BPE tokenizer (GPT-2/RoBERTa) backed by the C++ core.

    Mirrors HF ``ByteLevelBPETokenizer(vocab.json, merges.txt)``'s encode
    surface (reference src/tokenization.py:51-57): GPT-2 byte-to-unicode
    mapping + pre-tokenizer regex + ranked merge loop. ``vocab_file`` is
    the vocab.json (token -> id); ``merges_file`` the merges.txt.
    """

    def __init__(self, vocab_file: str, merges_file: str,
                 lowercase: bool = False):
        import json

        self._lib = _load_library()
        with open(vocab_file, encoding="utf-8") as f:
            vocab = json.load(f)
        by_id = sorted(vocab.items(), key=lambda kv: kv[1])
        n = by_id[-1][1] + 1 if by_id else 0
        tokens = [""] * n
        for tok, tid in by_id:
            tokens[tid] = tok
        with open(merges_file, encoding="utf-8") as f:
            merges = f.read()
        self._handle = self._lib.bpe_create(
            "\n".join(tokens).encode("utf-8"), merges.encode("utf-8"),
            1 if lowercase else 0)
        if not self._handle:
            raise OSError(f"could not build BPE from {vocab_file}")
        self.lowercase = lowercase
        self._encode_lock = threading.Lock()

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.bpe_free(handle)
            self._handle = None

    def get_vocab_size(self) -> int:
        return self._lib.bpe_vocab_size(self._handle)

    def token_to_id(self, token: str) -> Optional[int]:
        tid = self._lib.bpe_token_to_id(self._handle, token.encode("utf-8"))
        return None if tid < 0 else tid

    def id_to_token(self, token_id: int) -> str:
        return self._lib.bpe_id_to_token(self._handle, token_id).decode("utf-8")

    def encode(self, text: str, add_special_tokens: bool = False) -> Encoding:
        # ``add_special_tokens`` is accepted for HF signature compatibility
        # (callers like tools/encode_data.py pass it); like HF's
        # ByteLevelBPETokenizer — which has no post-processor template —
        # it is a no-op here.
        del add_special_tokens
        with self._encode_lock:
            raw_text = text.encode("utf-8")
            n = self._lib.bpe_encode(self._handle, raw_text, len(raw_text))
            ids = list(self._lib.bpe_get_ids(self._handle)[:n])
            raw = self._lib.bpe_get_tokens(self._handle).decode("utf-8")
        return Encoding(ids=ids, tokens=raw.split("\n") if raw else [])

    def encode_batch(self, texts: List[str]) -> List[Encoding]:
        return [self.encode(t) for t in texts]


def train_bpe_vocab(
    files: List[str],
    vocab_size: int,
    out_dir: str,
    special_tokens=("[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"),
    min_frequency: int = 2,
    lowercase: bool = False,
) -> str:
    """Train a byte-level BPE (vocab.json + merges.txt into ``out_dir``) —
    the ByteLevelBPETokenizer.train role of reference
    utils/build_vocab.py:39-58. The output files load interchangeably into
    HF's ByteLevelBPETokenizer and :class:`CppByteLevelBPETokenizer`."""
    lib = _load_library()
    os.makedirs(out_dir, exist_ok=True)
    rc = lib.bpe_train(
        "\n".join(files).encode("utf-8"),
        "\n".join(special_tokens).encode("utf-8"),
        vocab_size,
        min_frequency,
        1 if lowercase else 0,
        out_dir.encode("utf-8"),
    )
    if rc != 0:
        raise RuntimeError(f"bpe_train failed with code {rc}")
    return os.path.join(out_dir, "vocab.json")


def train_wordpiece_vocab(
    files: List[str],
    vocab_size: int,
    out_path: str,
    special_tokens=("[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"),
    min_frequency: int = 2,
    lowercase: bool = True,
) -> str:
    """Train a WordPiece vocab (reference utils/build_vocab.py:39-75 role:
    specials forced to the front, [PAD] at index 0)."""
    lib = _load_library()
    rc = lib.wp_train(
        "\n".join(files).encode("utf-8"),
        "\n".join(special_tokens).encode("utf-8"),
        vocab_size,
        min_frequency,
        1 if lowercase else 0,
        out_path.encode("utf-8"),
    )
    if rc != 0:
        raise RuntimeError(f"wp_train failed with code {rc}")
    return out_path
