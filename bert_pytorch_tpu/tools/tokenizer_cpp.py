"""ctypes bindings for the C++ tokenizer core (native/tokenizer.cpp).

The in-repo native replacement for the HuggingFace Rust `tokenizers`
dependency (SURVEY.md §2.3). API shape mirrors the fast-tokenizer surface
the reference code uses: ``encode(text).ids/.tokens``, ``token_to_id``,
``id_to_token`` (reference src/tokenization.py:42-49,
src/dataset.py mask-token lookup, run_squad.py:292).

The library is built on demand with ``make -C native`` (g++ only, no
external deps); when neither the prebuilt .so nor a compiler is available,
callers fall back to the HF tokenizers package or the pure-Python
implementation (bert_pytorch_tpu/data/tokenization.py).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from dataclasses import dataclass
from typing import List, Optional

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libbert_tokenizer.so")
_lib = None
_lib_lock = threading.Lock()


def _load_library() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB_PATH):
            subprocess.run(
                ["make", "-C", _NATIVE_DIR, "-s"],
                check=True,
                capture_output=True,
            )
        lib = ctypes.CDLL(_LIB_PATH)
        lib.wp_create.restype = ctypes.c_void_p
        lib.wp_create.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.wp_free.argtypes = [ctypes.c_void_p]
        lib.wp_vocab_size.argtypes = [ctypes.c_void_p]
        lib.wp_vocab_size.restype = ctypes.c_int
        lib.wp_token_to_id.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.wp_token_to_id.restype = ctypes.c_int
        lib.wp_id_to_token.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.wp_id_to_token.restype = ctypes.c_char_p
        lib.wp_encode.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.wp_encode.restype = ctypes.c_int
        lib.wp_get_ids.argtypes = [ctypes.c_void_p]
        lib.wp_get_ids.restype = ctypes.POINTER(ctypes.c_int)
        lib.wp_get_tokens.argtypes = [ctypes.c_void_p]
        lib.wp_get_tokens.restype = ctypes.c_char_p
        lib.wp_train.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_char_p,
        ]
        lib.wp_train.restype = ctypes.c_int
        _lib = lib
        return lib


@dataclass
class Encoding:
    ids: List[int]
    tokens: List[str]


class CppWordPieceTokenizer:
    """BERT WordPiece tokenizer backed by the C++ core."""

    def __init__(self, vocab_file: str, lowercase: bool = True):
        self._lib = _load_library()
        self._handle = self._lib.wp_create(
            vocab_file.encode("utf-8"), 1 if lowercase else 0
        )
        if not self._handle:
            raise OSError(f"could not load vocab from {vocab_file}")
        self.lowercase = lowercase
        # Encoding is stateful per handle; serialize access.
        self._encode_lock = threading.Lock()

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.wp_free(handle)
            self._handle = None

    def get_vocab_size(self) -> int:
        return self._lib.wp_vocab_size(self._handle)

    def token_to_id(self, token: str) -> Optional[int]:
        tid = self._lib.wp_token_to_id(self._handle, token.encode("utf-8"))
        return None if tid < 0 else tid

    def id_to_token(self, token_id: int) -> str:
        return self._lib.wp_id_to_token(self._handle, token_id).decode("utf-8")

    def encode(self, text: str, add_special_tokens: bool = False) -> Encoding:
        with self._encode_lock:
            n = self._lib.wp_encode(self._handle, text.encode("utf-8"))
            ids = list(self._lib.wp_get_ids(self._handle)[:n])
            raw = self._lib.wp_get_tokens(self._handle).decode("utf-8")
        tokens = raw.split("\n") if raw else []
        if add_special_tokens:
            cls_id, sep_id = self.token_to_id("[CLS]"), self.token_to_id("[SEP]")
            ids = [cls_id] + ids + [sep_id]
            tokens = ["[CLS]"] + tokens + ["[SEP]"]
        return Encoding(ids=ids, tokens=tokens)

    def encode_batch(self, texts: List[str]) -> List[Encoding]:
        return [self.encode(t) for t in texts]


def train_wordpiece_vocab(
    files: List[str],
    vocab_size: int,
    out_path: str,
    special_tokens=("[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"),
    min_frequency: int = 2,
    lowercase: bool = True,
) -> str:
    """Train a WordPiece vocab (reference utils/build_vocab.py:39-75 role:
    specials forced to the front, [PAD] at index 0)."""
    lib = _load_library()
    rc = lib.wp_train(
        "\n".join(files).encode("utf-8"),
        "\n".join(special_tokens).encode("utf-8"),
        vocab_size,
        min_frequency,
        1 if lowercase else 0,
        out_path.encode("utf-8"),
    )
    if rc != 0:
        raise RuntimeError(f"wp_train failed with code {rc}")
    return out_path
