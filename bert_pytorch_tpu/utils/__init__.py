"""Cross-cutting utilities: dist helpers, logging, checkpointing."""

from bert_pytorch_tpu.utils.compile_cache import enable_compile_cache
from bert_pytorch_tpu.utils.dist import (
    barrier,
    get_rank,
    get_world_size,
    is_main_process,
    format_step,
)

__all__ = [
    "enable_compile_cache",
    "barrier",
    "get_rank",
    "get_world_size",
    "is_main_process",
    "format_step",
]
