"""Checkpoint / resume subsystem.

Parity with the reference's most-developed subsystem (SURVEY.md §5.4): one
checkpoint per optimizer-step cadence holding
``{model, optimizer, sampler, epoch[, preconditioner][, scaler]}``
(run_pretraining.py:513-523), written by the main process only, last-3
retention (:525-528), resume by scanning the output dir for the max step
(:246-253), and the phase-2 optimizer surgery hook
(see :func:`bert_pytorch_tpu.optim.reset_count`).

Storage is msgpack via flax.serialization: param/optimizer pytrees are
fetched to host (fully materialized — fine at BERT scale) and restored with
``from_state_dict`` onto the target tree, so the same checkpoint loads under
any mesh/sharding layout. Multi-host sharded state (fsdp/tp across
processes) is gathered with ``multihost_utils.process_allgather`` — a
collective all processes join — before rank 0 writes; restore reads the
full file on every process and re-shards via the caller's device_put.
Writes are atomic (tmp + rename), and every checkpoint gets a sidecar
integrity manifest (step, sha256, size; ``utils/integrity.py``) written
in the same tmp+rename discipline — the load/resume paths verify it and
walk back across ALL retained checkpoints past corrupt files
(docs/fault_tolerance.md). ``async_write=True`` saves snapshot the state
on device and stream + write from a background thread (one in flight per
output directory, blob committed before manifest), so a periodic save
stalls training for the device-side copy only.

``layout="sharded"`` swaps the gather for a per-process slice-record
layout: each process writes ``ckpt_{step}.shard{p}of{n}.msgpack`` holding
only its addressable shards, and the index file replaces array leaves
with shape/dtype stubs. Loads reassemble full host arrays from the slice
records and re-shard under the CALLER's mesh, so a run saved on one
topology resumes on another (elastic resume, docs/parallelism.md) — and
because no collective is involved, ``async_write`` covers sharded state
with the same device-snapshot path.
"""

from __future__ import annotations

import os
import re
import tempfile
import threading
import warnings
from typing import Any, Callable, Optional

import jax
import numpy as np
from flax import serialization

from bert_pytorch_tpu.utils import integrity
from bert_pytorch_tpu.utils.dist import is_main_process

CKPT_RE = re.compile(r"ckpt_(\d+)\.msgpack$")
# Sharded-layout shard files (``ckpt_{step}.shard{p}of{n}.msgpack``)
# deliberately do NOT match CKPT_RE: the resume scan, retention count,
# and walk-back see only the index file.
SHARD_RE = re.compile(r"ckpt_(\d+)\.shard(\d+)of(\d+)\.msgpack$")

# Index-file marker of the sharded layout: the top-level msgpack map
# carries this key with {version, n_shards, shard_files, mesh_spec};
# array leaves are replaced by {_LEAF_KEY: 1, shape, dtype} stubs whose
# bytes live in the shard files as slice records. Load reassembles full
# host arrays, so the checkpoint restores under ANY topology — elastic
# resume (save on 8 ways, resume on 4) falls out of the layout.
SHARDED_KEY = "__sharded__"
_LEAF_KEY = "__elastic_leaf__"


class CheckpointCorruptError(RuntimeError):
    """Raised by :func:`load_checkpoint` when the sidecar manifest exists
    and the file fails verification (size or sha256 mismatch)."""


class CheckpointShapeError(ValueError):
    """Raised by the streaming params load when a checkpoint leaf's shape
    does not match the target template — distinct from the layout
    oddities that fall back to a full restore."""

# Pending async writes, KEYED BY OUTPUT DIRECTORY: at most one background
# write in flight per save target — a second save to the same directory
# joins the first, so that directory's checkpoints land in order and memory
# holds at most one extra copy of its state. Distinct targets (chaos
# harness reference + child runs, serve+train in one process, parallel
# tests) are independent: one slot per directory, never shared.
_pending_saves: dict = {}   # abspath(output_dir) -> threading.Thread
_pending_errors: dict = {}  # abspath(output_dir) -> [BaseException]
_pending_lock = threading.Lock()


def _pending_key(output_dir: str) -> str:
    return os.path.abspath(output_dir)


def _join_pending_save(key: Optional[str] = None) -> Optional[BaseException]:
    """Join in-flight async writes — all of them, or one directory's —
    and return the first recorded error instead of raising (the collective
    save path must delay the raise until after the gather — see
    :func:`save_checkpoint`)."""
    with _pending_lock:
        if key is None:
            threads = list(_pending_saves.values())
            _pending_saves.clear()
        else:
            thread = _pending_saves.pop(key, None)
            threads = [thread] if thread is not None else []
    for thread in threads:
        thread.join()
    with _pending_lock:
        if key is None:
            errors = [(k, e) for k in list(_pending_errors)
                      for e in _pending_errors.pop(k)]
        else:
            errors = [(key, e) for e in _pending_errors.pop(key, [])]
    for where, extra in errors[1:]:
        # Only the first error propagates as the raise; the per-directory
        # registry can genuinely hold several — name the rest instead of
        # silently dropping a second target's lost checkpoint.
        warnings.warn(
            f"additional async checkpoint write failure for {where}: "
            f"{type(extra).__name__}: {extra}")
    return errors[0][1] if errors else None


def _start_pending_save(key: str, step: int, work: Callable[[], None]) -> None:
    def run():
        try:
            work()
        except BaseException as e:  # surfaced by wait_for_pending_save
            with _pending_lock:
                _pending_errors.setdefault(key, []).append(e)

    thread = threading.Thread(target=run, name=f"ckpt-write-{step}",
                              daemon=False)
    with _pending_lock:
        _pending_saves[key] = thread
    thread.start()


def wait_for_pending_save(output_dir: Optional[str] = None) -> None:
    """Block until in-flight async checkpoint writes have finished; raise
    if any failed. With ``output_dir`` joins only that save target's write;
    the default joins ALL of them (what every pre-exit guard wants).

    Call before reading checkpoints back, at end of training, and before
    process exit — an unjoined write may otherwise be truncated by
    interpreter teardown (the write itself is atomic, so a killed process
    loses only the newest checkpoint, never corrupts one). A failed write
    (disk full, permissions) re-raises here / at the next save to the same
    directory rather than letting training run on while no checkpoints
    land.
    """
    key = None if output_dir is None else _pending_key(output_dir)
    error = _join_pending_save(key)
    if error is not None:
        raise RuntimeError("async checkpoint write failed") from error


def checkpoint_path(output_dir: str, step: int) -> str:
    return os.path.join(output_dir, f"ckpt_{step}.msgpack")


def _ckpt_steps(output_dir: str) -> list[int]:
    """Ascending steps of the ckpt_*.msgpack files in ``output_dir``."""
    if not os.path.isdir(output_dir):
        return []
    return sorted(
        int(m.group(1))
        for name in os.listdir(output_dir)
        if (m := CKPT_RE.search(name))
    )


def find_resume_step(output_dir: str, verify: bool = False) -> Optional[int]:
    """Max step among ckpt_*.msgpack files (reference run_pretraining.py:246-253).

    ``verify=True`` walks newest-first past checkpoints whose integrity
    manifest fails verification (docs/fault_tolerance.md) — the step
    returned is the newest one a resume could actually load. Manifestless
    (legacy) checkpoints are accepted: unverifiable is not corrupt.
    """
    steps = _ckpt_steps(output_dir)
    if not verify:
        return steps[-1] if steps else None
    for step in reversed(steps):
        status, _ = integrity.verify_checkpoint(
            checkpoint_path(output_dir, step))
        if status != integrity.CORRUPT:
            return step
    return None


def latest_checkpoint(output_dir: str) -> Optional[str]:
    """Path of the newest ``ckpt_*.msgpack`` in ``output_dir``, or None.

    Safe when the directory does not exist yet (a serving process pointed
    at a training run's output dir may start before the first checkpoint
    lands) — ``_ckpt_steps`` already treats a missing dir as empty.
    """
    step = find_resume_step(output_dir)
    return None if step is None else checkpoint_path(output_dir, step)


def load_params_only(path: str, target: Any, key: str = "model",
                     quantize: Optional[str] = None) -> Any:
    """Restore ONLY the ``key`` (model-params) subtree of a checkpoint onto
    ``target``, without materializing the optimizer/preconditioner pytrees.

    A pretraining checkpoint holds ``{model, optimizer, sampler, epoch
    [, preconditioner][, scaler]}``; for LAMB the optimizer subtree is 2x
    the params, and K-FAC adds per-layer factor/inverse stacks on top —
    state a serving process (serve/engine.py) must never pay host memory
    for. The top-level msgpack map is walked with a streaming unpacker:
    every subtree except ``key`` is skipped byte-wise (``Unpacker.skip``
    decodes nothing), and the ``key`` subtree itself is decoded LEAF BY
    LEAF with dtype conversion applied as each tensor's bytes arrive —
    the transient cost is one fp32 tensor, never a second full fp32
    model tree:

    * ``quantize=None`` — each decoded leaf casts to the dtype of the
      matching ``target`` leaf inside the decode (a bf16-param target
      never materializes the fp32 tree), then the state restores onto
      ``target`` via flax ``from_state_dict``;
    * ``quantize="bf16" | "int8"`` — each dense module converts per the
      rules in :mod:`bert_pytorch_tpu.ops.quant` (int8 kernels +
      per-tensor symmetric scales / bf16 storage) and the QUANTIZED
      tree is returned as a plain dict for the quant model's ``apply``;
      ``target`` is the fp32-layout template used for shape checking.

    Falls back to a full restore (plus host-side
    :func:`~bert_pytorch_tpu.ops.quant.quantize_params`) if the file is
    not the expected top-level map (e.g. a hand-rolled artifact).

    The integrity manifest is verified first when present (a serving
    process loading a torn checkpoint should fail loudly at startup, not
    serve a half-restored head) — :class:`CheckpointCorruptError`. The
    bytes just read are what gets verified: one pass of IO.
    """
    if quantize is not None:
        from bert_pytorch_tpu.ops import quant as quant_ops

        quant_ops.check_mode(quantize)
    with open(path, "rb") as f:
        blob = f.read()
    status, detail = integrity.verify_blob(path, blob)
    if status == integrity.CORRUPT:
        raise CheckpointCorruptError(f"{path}: {detail}")
    marker = _extract_toplevel_subtree(blob, SHARDED_KEY)
    if marker is not None:
        # Sharded-layout index: the key subtree holds elastic-leaf stubs,
        # not tensors, so the streaming extract cannot apply. Reassemble
        # ONLY that subtree's slice records from the shard files (the
        # optimizer/preconditioner leaves are filtered out before any
        # bytes decode — the memory contract holds).
        index = serialization.msgpack_restore(blob)
        if key not in index:
            raise KeyError(
                f"checkpoint {path} has no top-level {key!r} subtree "
                f"(keys: {sorted(k for k in index if k != SHARDED_KEY)})")
        trimmed = {key: index[key], SHARDED_KEY: index[SHARDED_KEY]}
        state = _assemble_sharded(path, trimmed, only_prefix=key)[key]
        if quantize is not None:
            from bert_pytorch_tpu.ops import quant as quant_ops

            return quant_ops.quantize_params(state, quantize)
        return serialization.from_state_dict(target, state)
    convert = _make_module_converter(
        serialization.to_state_dict(target), quantize)
    state = _extract_toplevel_subtree(blob, key, convert=convert)
    if state is None:
        full = serialization.msgpack_restore(blob)
        if not isinstance(full, dict) or key not in full:
            raise KeyError(
                f"checkpoint {path} has no top-level {key!r} subtree "
                f"(keys: {sorted(full) if isinstance(full, dict) else type(full).__name__})")
        state = full[key]
        if quantize is not None:
            from bert_pytorch_tpu.ops import quant as quant_ops

            return quant_ops.quantize_params(state, quantize)
    if quantize is not None:
        return state
    return serialization.from_state_dict(target, state)


def _make_module_converter(target_sd: Any, quantize: Optional[str]):
    """Per-module conversion hook for the streaming decode: receives each
    innermost decoded dict (a flax module's array leaves) with its path,
    returns the dict to keep. With ``quantize`` set, dense modules
    convert through :func:`bert_pytorch_tpu.ops.quant.convert_module`;
    without it, each leaf casts to the matching ``target`` leaf's dtype.
    Shapes are checked against the target template either way — a
    mismatched checkpoint must fail loudly, not quantize garbage."""

    def target_leaf(path, name):
        node = target_sd
        for part in path + (name,):
            if not isinstance(node, dict) or part not in node:
                return None
            node = node[part]
        return node

    def convert(path, module):
        checked = {}
        for name, leaf in module.items():
            want = target_leaf(path, name)
            if hasattr(leaf, "shape") and hasattr(want, "shape") \
                    and tuple(want.shape) != tuple(leaf.shape):
                raise CheckpointShapeError(
                    f"checkpoint leaf {'/'.join(map(str, path + (name,)))} "
                    f"has shape {tuple(leaf.shape)}, target expects "
                    f"{tuple(want.shape)}")
            if quantize is None and hasattr(leaf, "dtype") \
                    and hasattr(want, "dtype") and want.dtype != leaf.dtype:
                leaf = leaf.astype(want.dtype)
            checked[name] = leaf
        if quantize is None:
            return checked
        from bert_pytorch_tpu.ops import quant as quant_ops

        return quant_ops.convert_module(path, checked, quantize)

    return convert


# msgpack type tags that open a map: fixmap (0x80-0x8f), map16, map32.
# Used to distinguish nested state-dict dicts (recurse) from array/scalar
# leaves (decode the span) without decoding anything first.
_MSGPACK_MAP_TAGS = frozenset(range(0x80, 0x90)) | {0xDE, 0xDF}


def _extract_toplevel_subtree(blob: bytes, key: str,
                              convert=None) -> Optional[Any]:
    """Decode one value of the checkpoint's top-level msgpack map,
    byte-skipping the others; None when the layout is unexpected (the
    caller then falls back to a full restore).

    The ``key`` subtree is decoded by recursive map-walk, one LEAF at a
    time: each leaf's span is located with ``Unpacker.skip`` (which
    decodes nothing) and handed to flax's ``msgpack_restore``
    individually, and ``convert(path, module_dict)`` — when given — runs
    on every innermost dict as soon as its leaves decode, so dtype
    conversion/quantization happens while streaming and the peak
    transient is one fp32 tensor, not the whole subtree.
    """
    import msgpack

    def walk(unpacker, path):
        n = unpacker.read_map_header()
        out = {}
        any_leaves = False
        for _ in range(n):
            name = unpacker.unpack()
            start = unpacker.tell()
            if blob[start] in _MSGPACK_MAP_TAGS:
                out[name] = walk(unpacker, path + (name,))
            else:
                unpacker.skip()
                out[name] = serialization.msgpack_restore(
                    blob[start:unpacker.tell()])
                any_leaves = True
        if convert is not None and any_leaves:
            leaves = {k: v for k, v in out.items()
                      if not isinstance(v, dict)}
            for k in leaves:
                del out[k]
            out.update(convert(path, leaves))
        return out

    try:
        unpacker = msgpack.Unpacker(max_buffer_size=len(blob) or 1,
                                    raw=False)
        unpacker.feed(blob)
        n_items = unpacker.read_map_header()
        for _ in range(n_items):
            name = unpacker.unpack()
            if name == key:
                start = unpacker.tell()
                if blob[start] not in _MSGPACK_MAP_TAGS:
                    # A non-dict model subtree (hand-rolled artifact):
                    # decode the span whole, no per-leaf conversion.
                    unpacker.skip()
                    return serialization.msgpack_restore(
                        blob[start:unpacker.tell()])
                return walk(unpacker, ())
            unpacker.skip()
    except CheckpointShapeError:
        raise  # a real target/checkpoint mismatch, not a layout oddity
    except Exception:
        return None
    return None


def load_latest_checkpoint(output_dir: str,
                           on_skip: Optional[Callable[[dict], None]] = None):
    """(step, state) of the newest VERIFIED-loadable checkpoint, or None.

    Writes are atomic (tmp + rename in :func:`_write_and_prune`), but a
    checkpoint can still arrive corrupt — a torn filesystem, a partial copy
    from another machine, bit rot. The reference would crash on it
    (torch.load of the max-step file, run_pretraining.py:246-257); here a
    bad newest file costs the training between it and the previous retained
    checkpoint, not the run: we walk steps newest-first across ALL retained
    checkpoints, verifying each against its integrity manifest
    (``utils/integrity.py``) before decoding, and warn-and-skip failures
    (the dataset layer's warn-and-skip stance, SURVEY §4). Each skip also
    calls ``on_skip({"step", "path", "reason"})`` so the runner can emit a
    telemetry ``resume`` record naming exactly what was passed over.
    """
    def skip(step: int, path: str, reason: str) -> None:
        warnings.warn(
            f"Skipping unreadable checkpoint {path} ({reason}); "
            "falling back to the previous retained one")
        if on_skip is not None:
            on_skip({"step": step, "path": path, "reason": reason})

    for step in reversed(_ckpt_steps(output_dir)):
        path = checkpoint_path(output_dir, step)
        try:
            # load_checkpoint reads once and verifies those bytes; a
            # manifestless legacy file gets the decode as its only net.
            return step, load_checkpoint(path)
        except CheckpointCorruptError as e:
            skip(step, path, f"integrity: {e}")
        except Exception as e:  # corrupt/truncated/unreadable pre-manifest
            skip(step, path, f"{type(e).__name__}: {e}")
    return None


def _leaf_needs_collective(x: Any) -> bool:
    """True when ``x``'s full value is NOT locally readable: shards live on
    devices this process can't address AND the array isn't fully replicated.

    That is the multi-host fsdp/tp case: ``jax.device_get`` raises on such
    arrays, so the save path must run a cross-process gather — which is a
    collective every process has to join (reference behavior being replaced:
    rank-0 ``torch.save`` of replicated DDP state, run_pretraining.py:513-523;
    with sharded state the TPU-native analog is an all-gather first).
    Multi-host dp-REPLICATED state stays on the cheap path: every process
    already holds the full value, so rank 0 reads it locally with no
    collective and no host copies on the other ranks.
    """
    if getattr(x, "is_fully_addressable", True):
        return False
    sharding = getattr(x, "sharding", None)
    return not (sharding is not None and sharding.is_fully_replicated)


def _needs_collective_gather(tree: Any) -> bool:
    return any(map(_leaf_needs_collective, jax.tree_util.tree_leaves(tree)))


def _to_host(tree: Any) -> Any:
    """Device arrays -> host numpy (gathering sharded arrays).

    Locally-readable arrays (single-host meshes; multi-host dp-REPLICATED
    state, where every process holds the full value) fetch with
    ``jax.device_get``. Arrays whose shards this process cannot read
    (multi-host fsdp/tp) go through ``multihost_utils.process_allgather`` — a
    collective, so when any such leaf exists EVERY process must call
    ``_to_host`` with an identically-structured tree (``save_checkpoint``
    arranges this; tree_map traversal order is deterministic, so the
    per-leaf collectives line up across processes).

    Always returns buffers the caller owns: async writes serialize after this
    function returns, so a view into a host array (or a CPU-backend jax
    array's buffer) would let the next train step's buffer reuse corrupt the
    snapshot. TPU device_get already copies; the owndata check makes the
    host/CPU cases copy too without double-copying the TPU path.
    """

    def get(x):
        if not hasattr(x, "dtype"):
            return x
        if _leaf_needs_collective(x):
            from jax.experimental import multihost_utils
            out = np.asarray(multihost_utils.process_allgather(x, tiled=True))
            return out if out.flags.owndata else out.copy()
        out = np.asarray(jax.device_get(x))
        # A plain-numpy leaf comes back as the caller's own object (owndata
        # True but still aliased) — copy it; a view copies too. Only a fresh
        # device_get transfer is returned as-is.
        return out.copy() if (out is x or not out.flags.owndata) else out

    return jax.tree_util.tree_map(get, tree)


# Jitted identity: the device-side snapshot primitive. jit never aliases an
# un-donated input into an output, so every leaf comes back as a FRESH
# buffer with its sharding preserved — the next train step can donate the
# live state without invalidating the snapshot. One dispatch for the whole
# tree; returns before the copies complete (the background fetch blocks).
_snapshot_identity = None


def _device_snapshot(tree: Any) -> Any:
    """Donation-safe copy of ``tree``: jax.Array leaves are copied ON
    DEVICE (cheap D2D, async dispatch) and their device->host streams are
    kicked off immediately (``copy_to_host_async``); numpy leaves are
    host-copied (the caller may reuse those buffers too); everything else
    passes through by value. The returned tree is owned by the caller —
    safe to fetch, serialize, and write from a background thread while
    training overwrites the source state."""
    global _snapshot_identity
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    device_idx = [i for i, x in enumerate(leaves) if isinstance(x, jax.Array)]
    if device_idx:
        if _snapshot_identity is None:
            _snapshot_identity = jax.jit(lambda xs: xs)
        copies = _snapshot_identity([leaves[i] for i in device_idx])
        for i, copy in zip(device_idx, copies):
            leaves[i] = copy
            try:
                copy.copy_to_host_async()  # start D2H behind the dispatch
            except Exception:
                pass  # backend without async host copies: device_get later
    leaves = [x.copy() if isinstance(x, np.ndarray) else x for x in leaves]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _atomic_write(path: str, blob: bytes) -> None:
    directory = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _prune_old(output_dir: str, keep: int) -> None:
    steps = _ckpt_steps(output_dir)
    for old in steps[:-keep] if keep > 0 else []:
        old_path = checkpoint_path(output_dir, old)
        stale_names = [old_path, integrity.manifest_path(old_path)]
        for name in os.listdir(output_dir):
            m = SHARD_RE.search(name)
            if m and int(m.group(1)) == old:
                shard = os.path.join(output_dir, name)
                stale_names += [shard, integrity.manifest_path(shard)]
        for stale in stale_names:
            try:
                os.unlink(stale)
            except OSError:
                pass


def _write_and_prune(state: Any, output_dir: str, step: int, keep: int,
                     mesh_spec: Optional[dict] = None) -> None:
    blob = serialization.msgpack_serialize(state)
    path = checkpoint_path(output_dir, step)
    _atomic_write(path, blob)
    # Integrity sidecar, hashed from the in-memory blob (no re-read) and
    # itself tmp+renamed. Blob first, manifest second: a crash in the gap
    # leaves a manifestless blob — reported as unverifiable, like any
    # legacy checkpoint, never as corruption (the reverse order would
    # leave a manifest whose blob is missing: indistinguishable from a
    # deleted checkpoint).
    integrity.write_manifest(
        path, integrity.build_manifest(
            step, blob, keys=state.keys() if isinstance(state, dict) else (),
            mesh_spec=mesh_spec))
    _prune_old(output_dir, keep)


def _shard_name(step: int, proc: int, n_procs: int) -> str:
    return f"ckpt_{step}.shard{proc}of{n_procs}.msgpack"


def _np_dtype(name: str):
    """np.dtype from its string name, including the ml_dtypes extension
    types (bfloat16) numpy alone cannot spell."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _slice_records(x) -> list[dict]:
    """This process's unique {start, limit, data} slice records of a
    jax.Array — one record per distinct shard index (replicated shards
    dedup), data fetched per-shard, so nothing here is a collective even
    when the full value spans processes."""
    shape = tuple(x.shape)
    records, seen = [], set()
    for shard in x.addressable_shards:
        bounds = tuple(
            (idx.start or 0, dim if idx.stop is None else idx.stop)
            for idx, dim in zip(shard.index, shape))
        if bounds in seen:
            continue
        seen.add(bounds)
        records.append({
            "start": [int(b[0]) for b in bounds],
            "limit": [int(b[1]) for b in bounds],
            "data": np.asarray(shard.data),
        })
    return records


def _build_sharded(state_sd: Any, records: dict, path=()) -> Any:
    """Walk a state dict, replacing jax.Array leaves with elastic-leaf
    stubs and collecting their slice records into ``records`` (flat-path
    keyed). Host-side leaves (numpy, scalars, strings — sampler state,
    epoch) stay inline in the index: they are small and replicated."""
    if isinstance(state_sd, dict):
        return {k: _build_sharded(v, records, path + (str(k),))
                for k, v in state_sd.items()}
    if isinstance(state_sd, jax.Array):
        key = "/".join(path)
        records[key] = _slice_records(state_sd)
        return {_LEAF_KEY: 1, "shape": [int(d) for d in state_sd.shape],
                "dtype": str(state_sd.dtype)}
    return state_sd


def _write_sharded(contents: Any, output_dir: str, step: int, keep: int,
                   mesh_spec: Optional[dict]) -> None:
    """Sharded-layout write: every process writes ONE shard file of its
    addressable slice records (own sidecar manifest — no process can
    hash another's shard), then the main process writes the index +
    manifest naming them all. Shards first, index last: a torn write
    leaves orphan shard files but no visible step. No collective
    anywhere — this is why ``async_write`` covers sharded state."""
    state_sd = serialization.to_state_dict(contents)
    records: dict = {}
    index = _build_sharded(state_sd, records)
    proc, n_procs = jax.process_index(), jax.process_count()
    os.makedirs(output_dir, exist_ok=True)
    shard_files = [_shard_name(step, p, n_procs) for p in range(n_procs)]
    shard_path = os.path.join(output_dir, _shard_name(step, proc, n_procs))
    shard_blob = serialization.msgpack_serialize({"leaves": records})
    _atomic_write(shard_path, shard_blob)
    integrity.write_manifest(
        shard_path, integrity.build_manifest(step, shard_blob,
                                             mesh_spec=mesh_spec))
    if not is_main_process():
        return
    index[SHARDED_KEY] = {
        "version": 1,
        "n_shards": n_procs,
        "shard_files": shard_files,
        "mesh_spec": dict(mesh_spec) if mesh_spec else {},
    }
    index_blob = serialization.msgpack_serialize(index)
    path = checkpoint_path(output_dir, step)
    _atomic_write(path, index_blob)
    integrity.write_manifest(
        path, integrity.build_manifest(
            step, index_blob,
            keys=[k for k in index if k != SHARDED_KEY],
            mesh_spec=mesh_spec, layout="sharded",
            shard_files=shard_files))
    _prune_old(output_dir, keep)


def _assemble_sharded(path: str, index: dict, verify: bool = True,
                      only_prefix: Optional[str] = None) -> dict:
    """Reassemble full host arrays from a sharded checkpoint's index +
    shard files. Every elastic leaf allocates its global shape and fills
    from the slice records of ALL shards, with a coverage mask so a
    missing slice fails loudly instead of restoring zeros. The result is
    a plain state dict of full numpy arrays — restore re-shards it via
    the caller's device_put, so the saving and resuming topologies are
    completely decoupled (elastic resume)."""
    meta = index.pop(SHARDED_KEY)
    directory = os.path.dirname(os.path.abspath(path))
    leaves: dict = {}
    for name in meta.get("shard_files", ()):
        shard_path = os.path.join(directory, os.path.basename(str(name)))
        with open(shard_path, "rb") as f:
            blob = f.read()
        if verify:
            status, detail = integrity.verify_blob(shard_path, blob)
            if status == integrity.CORRUPT:
                raise CheckpointCorruptError(f"{shard_path}: {detail}")
        shard = serialization.msgpack_restore(blob)
        for key, records in shard.get("leaves", {}).items():
            if only_prefix is not None and key != only_prefix \
                    and not key.startswith(only_prefix + "/"):
                continue  # params-only load: never materialize optimizer
            leaves.setdefault(key, []).extend(records)

    def fill(node, path_parts):
        if not (isinstance(node, dict) and node.get(_LEAF_KEY)):
            if isinstance(node, dict):
                return {k: fill(v, path_parts + (str(k),))
                        for k, v in node.items()}
            return node
        key = "/".join(path_parts)
        shape = tuple(int(d) for d in node["shape"])
        arr = np.zeros(shape, _np_dtype(node["dtype"]))
        covered = np.zeros(shape, bool)
        for rec in leaves.get(key, ()):
            window = tuple(slice(int(s), int(l))
                           for s, l in zip(rec["start"], rec["limit"]))
            arr[window] = rec["data"]
            covered[window] = True
        if not covered.all():
            raise CheckpointCorruptError(
                f"{path}: sharded leaf {key} has uncovered elements "
                "(missing shard slices)")
        return arr

    return fill(index, ())


def save_checkpoint(
    output_dir: str,
    step: int,
    contents: dict,
    keep: int = 3,
    async_write: bool = False,
    layout: str = "gathered",
    mesh_spec: Optional[dict] = None,
) -> Optional[str]:
    """Serialize ``contents`` (a dict of pytrees/plain values) to
    ``ckpt_{step}.msgpack``. Main-process-only; prunes to the newest ``keep``
    checkpoints (reference cadence + retention, run_pretraining.py:496-528).

    ``async_write=True`` snapshots the live state ON DEVICE (a jitted
    identity copy — cheap, donation-safe, sharding-preserving) and returns
    as soon as that dispatch and the device->host streams are enqueued; a
    background thread then fetches the snapshot to host, serializes, and
    writes blob-then-manifest. The train loop pays only the device-side
    copy, not the D2H fetch or the multi-second msgpack+disk write of a
    BERT-large state. Errors surface at the next save to the same
    directory or at :func:`wait_for_pending_save`. At most one write per
    output directory is in flight; a newer save joins it first.

    ``layout`` picks the on-disk shape:

    * ``"gathered"`` (default) — one full-state file. Multi-host SHARDED
      state (non-addressable leaves) gathers synchronously first — the
      gather is a collective every process must join at the same point —
      and only the serialize+write goes to the background under
      ``async_write``.
    * ``"sharded"`` — every process writes its own shard of slice records
      plus a main-process index (:func:`_write_sharded`). No collective
      at all, so ``async_write`` covers sharded state too: the device
      snapshot is donation-safe and the whole fetch+write runs in the
      background — closing the PR 6 gap where sharded async saves fell
      back to a synchronous gather. Loads reassemble full arrays and
      re-shard under the CALLER's mesh: elastic resume.

    ``mesh_spec`` (a plain ``{axis: size}`` dict, ``MeshSpec.as_dict()``)
    is recorded in the integrity manifest either way, labeling the saving
    topology for ``tools/verify_checkpoint.py --strict`` and audits.
    """
    if layout not in ("gathered", "sharded"):
        raise ValueError(
            f"unknown checkpoint layout {layout!r}; options: gathered, sharded")
    # Forwarded to _write_and_prune only when set: tests (and any caller)
    # that stub the writer with the pre-one-mesh 4-arg signature keep
    # working for spec-less saves.
    _spec_kw = {} if mesh_spec is None else {"mesh_spec": mesh_spec}
    if layout == "sharded":
        key = _pending_key(output_dir)
        pending_error = _join_pending_save(key)
        path = checkpoint_path(output_dir, step)
        if async_write:
            box = [_device_snapshot(contents)]

            def write_snapshot():
                snapshot = box.pop()
                _write_sharded(snapshot, output_dir, step, keep, mesh_spec)

            _start_pending_save(key, step, write_snapshot)
        else:
            _write_sharded(contents, output_dir, step, keep, mesh_spec)
        if pending_error is not None:
            raise RuntimeError(
                "async checkpoint write failed") from pending_error
        return path if is_main_process() else None
    # Multi-host sharded state: the gather below is a COLLECTIVE, so every
    # process must run it (with the same tree) before non-main processes
    # bail out. Single-host / replicated state skips straight to rank 0.
    collective = _needs_collective_gather(contents)
    if not collective and not is_main_process():
        return None
    key = _pending_key(output_dir)
    # Join any in-flight write to THIS directory before snapshotting the
    # next state — so its checkpoints land in order and memory holds at
    # most one extra copy per save target. A failed previous write
    # re-raises only AFTER this save's own work: the CURRENT state is the
    # one worth persisting (an emergency checkpoint must not be
    # sacrificed to report a stale periodic-write error — the disk may
    # have recovered), and on the collective path raising rank-0-only
    # before the gather would abandon a collective the other ranks have
    # already entered, turning a clean disk error into a whole-job
    # rendezvous hang.
    pending_error = _join_pending_save(key)

    def raise_pending():
        if pending_error is not None:
            raise RuntimeError(
                "async checkpoint write failed") from pending_error

    if async_write and not collective:
        # Device-side snapshot; the background thread owns the only
        # reference, so the device copies free as soon as their host
        # fetch lands (the box.pop() below drops the closure's handle).
        box = [_device_snapshot(contents)]
        os.makedirs(output_dir, exist_ok=True)
        path = checkpoint_path(output_dir, step)

        def fetch_and_write():
            snapshot = box.pop()
            state = serialization.to_state_dict(_to_host(snapshot))
            del snapshot
            _write_and_prune(state, output_dir, step, keep, **_spec_kw)

        _start_pending_save(key, step, fetch_and_write)
        raise_pending()
        return path

    state = serialization.to_state_dict(_to_host(contents))
    if not is_main_process():
        return None
    os.makedirs(output_dir, exist_ok=True)
    path = checkpoint_path(output_dir, step)
    if not async_write:
        _write_and_prune(state, output_dir, step, keep, **_spec_kw)
        raise_pending()
        return path
    _start_pending_save(
        key, step,
        lambda: _write_and_prune(state, output_dir, step, keep, **_spec_kw))
    raise_pending()
    return path


def load_checkpoint(path: str, verify: bool = True) -> dict:
    """Raw state dict (nested dicts of numpy arrays / scalars).

    ``verify=True`` (default) checks the integrity manifest first and
    raises :class:`CheckpointCorruptError` on a mismatch — decoding a
    damaged msgpack can otherwise "succeed" into a silently-truncated
    pytree. A checkpoint with no manifest (legacy, or a torn write that
    lost the sidecar) loads with only the decode as its net.
    """
    with open(path, "rb") as f:
        blob = f.read()
    if verify:
        # Verify the bytes just read — one pass of IO, not a separate
        # hashing read of a multi-GB state (integrity.verify_blob).
        status, detail = integrity.verify_blob(path, blob)
        if status == integrity.CORRUPT:
            raise CheckpointCorruptError(f"{path}: {detail}")
    state = serialization.msgpack_restore(blob)
    if isinstance(state, dict) and SHARDED_KEY in state:
        # Sharded-layout index: reassemble the full arrays from the shard
        # files next to it. The result is topology-free host state —
        # restore re-shards it under the caller's mesh (elastic resume).
        return _assemble_sharded(path, state, verify=verify)
    return state


def restore_tree(target: Any, state: Any) -> Any:
    """Restore a loaded state dict onto a target pytree (shape/type-checked
    by flax). The analog of ``load_state_dict`` (non-strict loading is the
    caller's concern: pass the matching subtree)."""
    return serialization.from_state_dict(target, state)
