"""Checkpoint / resume subsystem.

Parity with the reference's most-developed subsystem (SURVEY.md §5.4): one
checkpoint per optimizer-step cadence holding
``{model, optimizer, sampler, epoch[, preconditioner][, scaler]}``
(run_pretraining.py:513-523), written by the main process only, last-3
retention (:525-528), resume by scanning the output dir for the max step
(:246-253), and the phase-2 optimizer surgery hook
(see :func:`bert_pytorch_tpu.optim.reset_count`).

Storage is msgpack via flax.serialization: param/optimizer pytrees are
fetched to host (fully materialized — fine at BERT scale) and restored with
``from_state_dict`` onto the target tree, so the same checkpoint loads under
any mesh/sharding layout. Writes are atomic (tmp + rename).
"""

from __future__ import annotations

import os
import re
import tempfile
from typing import Any, Optional

import jax
import numpy as np
from flax import serialization

from bert_pytorch_tpu.utils.dist import is_main_process

CKPT_RE = re.compile(r"ckpt_(\d+)\.msgpack$")


def checkpoint_path(output_dir: str, step: int) -> str:
    return os.path.join(output_dir, f"ckpt_{step}.msgpack")


def find_resume_step(output_dir: str) -> Optional[int]:
    """Max step among ckpt_*.msgpack files (reference run_pretraining.py:246-253)."""
    if not os.path.isdir(output_dir):
        return None
    steps = [
        int(m.group(1))
        for name in os.listdir(output_dir)
        if (m := CKPT_RE.search(name))
    ]
    return max(steps) if steps else None


def _to_host(tree: Any) -> Any:
    """Device arrays -> host numpy (gathering sharded arrays)."""
    return jax.tree_util.tree_map(
        lambda x: np.asarray(jax.device_get(x)) if hasattr(x, "dtype") else x, tree
    )


def save_checkpoint(
    output_dir: str,
    step: int,
    contents: dict,
    keep: int = 3,
) -> Optional[str]:
    """Serialize ``contents`` (a dict of pytrees/plain values) to
    ``ckpt_{step}.msgpack``. Main-process-only; prunes to the newest ``keep``
    checkpoints (reference cadence + retention, run_pretraining.py:496-528).
    """
    if not is_main_process():
        return None
    os.makedirs(output_dir, exist_ok=True)
    state = serialization.to_state_dict(_to_host(contents))
    blob = serialization.msgpack_serialize(state)
    path = checkpoint_path(output_dir, step)
    fd, tmp = tempfile.mkstemp(dir=output_dir, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)

    steps = sorted(
        int(m.group(1))
        for name in os.listdir(output_dir)
        if (m := CKPT_RE.search(name))
    )
    for old in steps[:-keep] if keep > 0 else []:
        try:
            os.unlink(checkpoint_path(output_dir, old))
        except OSError:
            pass
    return path


def load_checkpoint(path: str) -> dict:
    """Raw state dict (nested dicts of numpy arrays / scalars)."""
    with open(path, "rb") as f:
        return serialization.msgpack_restore(f.read())


def restore_tree(target: Any, state: Any) -> Any:
    """Restore a loaded state dict onto a target pytree (shape/type-checked
    by flax). The analog of ``load_state_dict`` (non-strict loading is the
    caller's concern: pass the matching subtree)."""
    return serialization.from_state_dict(target, state)
