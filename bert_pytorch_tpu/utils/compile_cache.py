"""Persistent XLA compilation cache setup shared by the entry points.

A restarted/resumed job (or a bench retry after a TPU-tunnel drop mid-compile)
reuses the cached executables instead of recompiling — minutes for BERT-large.
``jax.config.update`` itself only raises for unknown flag names; real cache
failures (unwritable directory, unsupported backend) surface later as buried
warnings, so the directory is validated up front to make failures visible at
startup.

This module is also the tap point for compile OBSERVABILITY
(:mod:`bert_pytorch_tpu.telemetry.compile_events`):
:func:`install_compile_listeners` registers ``jax.monitoring`` listeners so
every backend compile duration and persistent-cache hit/miss event reaches
the telemetry layer, which attributes them to the jitted function and shape
signature that triggered them — cold-vs-warm is always distinguishable in
the artifacts.
"""

from __future__ import annotations

import os
import tempfile

# Compiles cheaper than this are faster to redo than to round-trip through
# the cache; only the big train-step executables are worth persisting.
MIN_COMPILE_TIME_SECS = 10.0


def enable_compile_cache(cache_dir: str,
                         min_compile_secs: float = MIN_COMPILE_TIME_SECS
                         ) -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Returns True if enabled; prints a diagnostic and returns False when the
    directory cannot be created or written (the caller runs uncached).

    ``min_compile_secs`` sets the persistence bar. Training keeps the
    default (only the multi-minute train-step executables are worth the
    round trip); SERVING passes 0.0 — a replica's per-(task, bucket)
    forwards each compile in seconds, but a fresh replica compiles dozens
    of them, and the cold-start acceptance ("second start performs zero
    cold compiles", docs/serving.md) needs every one persisted. Below-bar
    compiles fire no cache-miss counter (they are never written), so they
    would read as "uncached" forever and the warm-start proof could never
    hold.
    """
    if not cache_dir:
        return False
    try:
        os.makedirs(cache_dir, exist_ok=True)
        probe = tempfile.NamedTemporaryFile(dir=cache_dir, delete=True)
        probe.close()
    except OSError as exc:
        print(f"compile cache disabled ({cache_dir} not writable): {exc}")
        return False
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", float(min_compile_secs))
    # jax latches cache-enablement at the first compile of the process
    # (_cache_used): if anything compiled before this call — a warmup probe,
    # an eager op that triggered jit — the new cache dir would be silently
    # ignored for the rest of the process. Reset the latch so it re-reads
    # the config.
    from jax._src import compilation_cache as _cc

    _cc.reset_cache()
    return True


def cache_enabled() -> bool:
    """True when a persistent compilation cache directory is configured."""
    import jax

    return bool(jax.config.jax_compilation_cache_dir)


def install_compile_listeners(event_cb, duration_cb) -> None:
    """Register ``jax.monitoring`` listeners for compile observability.

    ``event_cb(event, **kw)`` receives counter events (persistent-cache
    hits/misses: ``/jax/compilation_cache/cache_hits`` / ``cache_misses``);
    ``duration_cb(event, duration_secs, **kw)`` receives durations (real XLA
    compiles: ``/jax/core/compile/backend_compile_duration``). Registration
    is permanent — jax.monitoring has no unregister — so callers install
    once and route internally (telemetry/compile_events.py does)."""
    import jax.monitoring as monitoring

    monitoring.register_event_listener(event_cb)
    monitoring.register_event_duration_secs_listener(duration_cb)
