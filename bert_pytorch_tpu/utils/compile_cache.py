"""Persistent XLA compilation cache setup shared by the entry points.

A restarted/resumed job (or a bench retry after a TPU-tunnel drop mid-compile)
reuses the cached executables instead of recompiling — minutes for BERT-large.
``jax.config.update`` itself only raises for unknown flag names; real cache
failures (unwritable directory, unsupported backend) surface later as buried
warnings, so the directory is validated up front to make failures visible at
startup.
"""

from __future__ import annotations

import os
import tempfile

# Compiles cheaper than this are faster to redo than to round-trip through
# the cache; only the big train-step executables are worth persisting.
MIN_COMPILE_TIME_SECS = 10.0


def enable_compile_cache(cache_dir: str) -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Returns True if enabled; prints a diagnostic and returns False when the
    directory cannot be created or written (the caller runs uncached).
    """
    if not cache_dir:
        return False
    try:
        os.makedirs(cache_dir, exist_ok=True)
        probe = tempfile.NamedTemporaryFile(dir=cache_dir, delete=True)
        probe.close()
    except OSError as exc:
        print(f"compile cache disabled ({cache_dir} not writable): {exc}")
        return False
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", MIN_COMPILE_TIME_SECS)
    return True
