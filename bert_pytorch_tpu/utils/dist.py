"""Distributed helpers — parity with reference src/utils.py:22-74.

Rank/world-size map to JAX process index/count; the reference's
``dist.barrier()`` (utils.py:49-51) has no direct analog in JAX's SPMD model —
host synchronization happens implicitly at blocking device ops — so
``barrier()`` here performs a tiny cross-process psum, which is both a real
barrier and cheap.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def get_rank() -> int:
    """Host (process) rank; reference utils.py:29-34."""
    return jax.process_index()


def get_world_size() -> int:
    """Number of host processes; reference utils.py:37-42."""
    return jax.process_count()


def is_main_process() -> bool:
    """reference utils.py:45-46."""
    return get_rank() == 0


def barrier() -> None:
    """Block until all processes arrive; reference utils.py:49-51."""
    if jax.process_count() > 1:
        # A tiny global psum forces a cross-host synchronization point.
        x = jnp.ones((jax.local_device_count(),))
        jax.block_until_ready(
            jax.pmap(lambda v: jax.lax.psum(v, "i"), axis_name="i")(x)
        )


def _multihost_utils():
    """Deferred multihost_utils accessor — the explicit seam the resume-
    agreement unit test replaces (patching ``sys.modules`` is unreliable:
    once the real module was imported anywhere, ``from jax.experimental
    import multihost_utils`` binds the package ATTRIBUTE and ignores the
    sys.modules entry)."""
    from jax.experimental import multihost_utils

    return multihost_utils


def agree_on_resume_step(step: int | None) -> int | None:
    """Cross-process agreement on which checkpoint step to resume from.

    Every process proposes the newest step it could LOAD (or None). On a
    single process this is the identity. Multi-host, the processes share a
    checkpoint directory but can observe it differently (NFS/GCS propagation
    lag after an async write, partial copies): resuming from different steps
    would silently diverge the run. Policy: if all propose the same step,
    proceed; if they differ but all have one, everyone resumes from the
    MINIMUM (the newest checkpoint every process can see); if any process
    has none while others do, fail fast — the shared storage is
    inconsistent and no silent choice is safe.
    """
    if jax.process_count() == 1:
        return step
    # Host numpy proposal, not jnp: process_allgather accepts host-local
    # values, and building a jax array here would dispatch a device op
    # before the gather — under a faked two-process setup (the unit test)
    # that dispatch explodes inside jax before the fake gather is reached.
    proposals = np.asarray(
        _multihost_utils().process_allgather(
            np.int32(-1 if step is None else step)
        )
    )
    lo, hi = int(proposals.min()), int(proposals.max())
    if lo == hi:
        return None if lo == -1 else lo
    if lo == -1:
        raise RuntimeError(
            f"checkpoint directory inconsistent across hosts: some processes "
            f"see no loadable checkpoint while others see step {hi} "
            f"(proposals per process: {proposals.tolist()})"
        )
    return lo


def format_step(epoch, step, split: str = "") -> str:
    """Human-readable step tag; reference utils.py:54-64."""
    parts = []
    if epoch is not None:
        parts.append(f"Epoch: {epoch}")
    if step is not None:
        parts.append(f"Step: {step}")
    if split:
        parts.append(f"Split: {split}")
    return " ".join(parts)


def seed_for_worker(seed: int, rank: int | None = None) -> np.random.Generator:
    """Seeded numpy generator per (seed, rank) — the WorkerInitObj analog
    (reference utils.py:22-26, run_pretraining.py:583-586 seeds with
    seed + local_rank)."""
    rank = get_rank() if rank is None else rank
    return np.random.default_rng(seed + rank)
