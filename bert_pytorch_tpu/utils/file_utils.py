"""URL/S3 cached download with ETag-hashed filenames.

Parity with reference src/file_utils.py:97-263 (the HF-style model cache):
``cached_path`` resolves a URL or local path, downloading remote files once
into a cache directory keyed by ``sha256(url).sha256(etag)`` with a sidecar
``.json`` holding the original url/etag. S3 support is gated on boto3
(reference :159-186). One behavior added for air-gapped hosts: if the ETag
probe fails but a cached copy of the url exists, the newest cached copy is
served instead of erroring.

Cache location: ``$BERT_TPU_CACHE`` or ``~/.cache/bert_pytorch_tpu``
(the ``PYTORCH_PRETRAINED_BERT_CACHE`` analog, reference :35-44).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import tempfile
import urllib.request
from pathlib import Path
from typing import Optional, Tuple
from urllib.parse import urlparse

CACHE_DIR = os.getenv(
    "BERT_TPU_CACHE",
    os.path.join(os.path.expanduser("~"), ".cache", "bert_pytorch_tpu"),
)


def url_to_filename(url: str, etag: Optional[str] = None) -> str:
    """sha256(url)[.sha256(etag)] (reference :52-66)."""
    filename = hashlib.sha256(url.encode()).hexdigest()
    if etag:
        filename += "." + hashlib.sha256(etag.encode()).hexdigest()
    return filename


def filename_to_url(filename: str, cache_dir: Optional[str] = None) -> Tuple[str, Optional[str]]:
    """Recover (url, etag) from a cache entry's sidecar (reference :69-94)."""
    cache_dir = cache_dir or CACHE_DIR
    cache_path = os.path.join(cache_dir, filename)
    if not os.path.exists(cache_path):
        raise EnvironmentError(f"file {cache_path} not found")
    meta_path = cache_path + ".json"
    if not os.path.exists(meta_path):
        raise EnvironmentError(f"file {meta_path} not found")
    with open(meta_path, encoding="utf-8") as f:
        metadata = json.load(f)
    return metadata["url"], metadata["etag"]


def cached_path(url_or_filename, cache_dir: Optional[str] = None) -> str:
    """URL -> cached local path (downloading once); local path -> itself
    (reference :97-125)."""
    if isinstance(url_or_filename, Path):
        url_or_filename = str(url_or_filename)
    cache_dir = str(cache_dir) if cache_dir is not None else CACHE_DIR
    parsed = urlparse(url_or_filename)
    if parsed.scheme in ("http", "https", "s3"):
        return get_from_cache(url_or_filename, cache_dir)
    if os.path.exists(url_or_filename):
        return url_or_filename
    if parsed.scheme == "":
        raise EnvironmentError(f"file {url_or_filename} not found")
    raise ValueError(
        f"unable to parse {url_or_filename} as a URL or as a local path")


def split_s3_path(url: str) -> Tuple[str, str]:
    parsed = urlparse(url)
    if not parsed.netloc or not parsed.path:
        raise ValueError(f"bad s3 path {url}")
    return parsed.netloc, parsed.path.lstrip("/")


def _s3_resource():
    try:
        import boto3
    except ImportError as exc:  # pragma: no cover
        raise ImportError(
            "s3:// paths require boto3, which is not installed") from exc
    return boto3.resource("s3")


def s3_etag(url: str) -> Optional[str]:
    bucket, path = split_s3_path(url)
    return _s3_resource().Object(bucket, path).e_tag


def s3_get(url: str, temp_file) -> None:
    bucket, path = split_s3_path(url)
    _s3_resource().Bucket(bucket).download_fileobj(path, temp_file)


def _http_etag(url: str) -> Optional[str]:
    request = urllib.request.Request(url, method="HEAD")
    with urllib.request.urlopen(request) as response:
        if response.status != 200:
            raise IOError(
                f"HEAD request failed for url {url} with status "
                f"{response.status}")
        return response.headers.get("ETag")


def _newest_cached(url: str, cache_dir: str) -> Optional[str]:
    prefix = url_to_filename(url)
    candidates = [
        os.path.join(cache_dir, name)
        for name in os.listdir(cache_dir)
        if name.startswith(prefix) and not name.endswith(".json")
    ] if os.path.isdir(cache_dir) else []
    return max(candidates, key=os.path.getmtime) if candidates else None


def get_from_cache(url: str, cache_dir: Optional[str] = None) -> str:
    """Download-once semantics keyed by (url, etag) (reference :189-240)."""
    cache_dir = cache_dir or CACHE_DIR
    os.makedirs(cache_dir, exist_ok=True)

    try:
        etag = s3_etag(url) if url.startswith("s3://") else _http_etag(url)
    except (OSError, ImportError):
        # Offline / probe failure: serve the newest cached copy if any.
        cached = _newest_cached(url, cache_dir)
        if cached is not None:
            return cached
        raise

    cache_path = os.path.join(cache_dir, url_to_filename(url, etag))
    if os.path.exists(cache_path):
        return cache_path

    fd, temp_path = tempfile.mkstemp(dir=cache_dir, suffix=".part")
    try:
        with os.fdopen(fd, "wb") as temp_file:
            if url.startswith("s3://"):
                s3_get(url, temp_file)
            else:
                with urllib.request.urlopen(url) as response:
                    shutil.copyfileobj(response, temp_file)
        os.replace(temp_path, cache_path)
    finally:
        if os.path.exists(temp_path):
            os.unlink(temp_path)

    with open(cache_path + ".json", "w", encoding="utf-8") as meta_file:
        json.dump({"url": url, "etag": etag}, meta_file)
    return cache_path
