"""Analytic model-FLOP accounting for MFU reporting.

The reference repo reports raw sequences/second only
(run_pretraining.py:597-599); judging a TPU number against an A100 anchor
then needs a hardware-normalised metric. Model FLOPs Utilisation (MFU)
divides the *model* FLOPs actually required per step (forward + backward,
NOT counting rematerialisation recompute) by the chip's peak matmul
throughput — the convention from the PaLM appendix.

Matmul FLOP accounting per sequence of length S, hidden H, layers L,
intermediate F, masked positions M, vocab V (a matmul of (m,k)x(k,n)
costs 2mkn FLOPs):

  per layer, forward:
    QKV + output projections:  4 * 2*S*H*H
    attention scores QK^T:     2 * S*S*H
    attention context AV:      2 * S*S*H
    FFN (two mats):            2 * 2*S*H*F
  encoder forward  = L * (8*S*H^2 + 4*S^2*H + 4*S*H*F)
  heads forward:
    pooler:                    2*H*H
    NSP classifier:            2*H*2
    MLM transform:             M * 2*H*H
    MLM decoder (tied vocab):  M * 2*H*V
  training multiplier: 3x forward (one backward pass costs ~2x forward
  in matmul FLOPs — dL/dW and dL/dx per matmul).

Embedding lookups, layernorms, biases, softmax and activations are
omitted (sub-1% and not MXU work).
"""

from __future__ import annotations

# Peak dense bf16 matmul TFLOP/s per chip, by PJRT ``device_kind``
# substring (lowercased). Public numbers from cloud.google.com/tpu/docs.
_PEAK_TFLOPS_BY_KIND = (
    # Order matters: the "lite" spellings must match before the generic
    # generation entries (libtpu reports e.g. "TPU v5 lite" for v5e but
    # plain "TPU v5" for v5p, and "TPU v6 lite" for v6e/Trillium).
    ("v6e", 918.0),
    ("v6 lite", 918.0),
    ("trillium", 918.0),
    ("v5p", 459.0),
    ("v5e", 197.0),
    ("v5 lite", 197.0),
    ("v5litepod", 197.0),
    ("v5", 459.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
    # CPU fallback: no meaningful peak; callers should treat 0 as "unknown".
)


def peak_tflops(device_kind: str) -> float:
    """Peak bf16 TFLOP/s for a device kind string, or 0.0 if unknown."""
    kind = device_kind.lower()
    for sub, tf in _PEAK_TFLOPS_BY_KIND:
        if sub in kind:
            return tf
    return 0.0


def bert_encoder_flops_per_seq(config, seq_len: int) -> float:
    """Forward matmul FLOPs of the encoder stack for ONE sequence."""
    h = config.hidden_size
    f = config.intermediate_size
    ll = config.num_hidden_layers
    s = seq_len
    # Pure host math: every operand is a Python int off the config / CLI
    # (never a device array), so this float() is not a device fetch.
    # jaxlint: disable=HS101
    return float(ll * (8 * s * h * h + 4 * s * s * h + 4 * s * h * f))


def bert_train_flops_per_seq(config, seq_len: int, max_pred_per_seq: int,
                             next_sentence: bool = True) -> float:
    """Model FLOPs (fwd+bwd) for ONE sequence of the pretraining objective."""
    h = config.hidden_size
    v = config.vocab_size
    m = max_pred_per_seq
    heads = m * (2 * h * h + 2 * h * v)
    if next_sentence:
        heads += 2 * h * h + 2 * h * 2  # pooler + NSP classifier
    return 3.0 * (bert_encoder_flops_per_seq(config, seq_len) + heads)


def bert_finetune_flops_per_seq(config, seq_len: int, head_outputs: int = 2,
                                per_token_head: bool = True,
                                pooled: bool = False) -> float:
    """Model FLOPs (fwd+bwd) for ONE sequence of a finetuning objective.

    The task head is one linear: H -> ``head_outputs`` applied per token
    (``per_token_head``, e.g. QA span / NER logits) or once on the pooled
    [CLS] vector (``pooled`` adds the H x H pooler matmul first, e.g.
    GLUE / SWAG classification)."""
    h = config.hidden_size
    head = 2.0 * h * head_outputs
    if per_token_head:
        head *= seq_len
    if pooled:
        head += 2.0 * h * h  # pooler
    return 3.0 * (bert_encoder_flops_per_seq(config, seq_len) + head)


def mfu(seq_per_sec_per_chip: float, flops_per_seq: float,
        device_kind: str) -> float:
    """Fraction of the chip's peak used by model FLOPs; 0.0 if peak unknown."""
    peak = peak_tflops(device_kind)
    if peak <= 0:
        return 0.0
    return seq_per_sec_per_chip * flops_per_seq / (peak * 1e12)
