"""Checkpoint integrity manifests (docs/fault_tolerance.md).

A checkpoint write is atomic (tmp + rename, ``utils/checkpoint.py``), but
atomicity only protects against the writing process dying — not against a
torn filesystem, a partial copy from another machine, or bit rot between
runs. A 40-layer K-FAC state is slow to rebuild (arXiv:2107.01739), so a
silently-corrupt checkpoint that crashes the resume path — or worse, loads
garbage — costs real wallclock. This module gives every checkpoint a
sidecar manifest:

    ckpt_200.msgpack            # the flax msgpack state
    ckpt_200.msgpack.manifest.json
        {"schema": "ckpt-manifest-v1", "step": 200,
         "sha256": "...", "size_bytes": N, "keys": ["epoch", "model", ...]}

written tmp+rename immediately after the blob's own rename (a crash in
the gap leaves a blob with no manifest — reported as ``no_manifest``,
the same status pre-manifest checkpoints get, never as corruption).

Verification statuses (:func:`verify_checkpoint`):

* ``verified``    — manifest present, size and sha256 match;
* ``no_manifest`` — blob present, no sidecar (legacy checkpoint or a
  crash between the two renames). Loadable, but unverifiable;
* ``corrupt``     — size/sha mismatch, unreadable manifest, or missing
  blob. Never loaded; the resume walk-back skips it.

Stdlib-only by design: ``tools/verify_checkpoint.py`` and the chaos
harness load this by file path (``tools/_bootstrap.py``) on machines
without jax.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Optional, Tuple

MANIFEST_SCHEMA = "ckpt-manifest-v1"
MANIFEST_SUFFIX = ".manifest.json"

# verify_checkpoint statuses, strongest first.
VERIFIED = "verified"
NO_MANIFEST = "no_manifest"
CORRUPT = "corrupt"


def manifest_path(ckpt_path: str) -> str:
    return ckpt_path + MANIFEST_SUFFIX


def sha256_file(path: str, chunk_bytes: int = 1 << 20) -> str:
    digest = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            chunk = f.read(chunk_bytes)
            if not chunk:
                break
            digest.update(chunk)
    return digest.hexdigest()


def build_manifest(step: int, blob: bytes, keys=(), mesh_spec=None,
                   layout=None, shard_files=None) -> dict:
    """Manifest dict for an in-memory serialized checkpoint (the save path
    has the bytes in hand — hashing them costs no extra IO).

    ``mesh_spec`` (a plain dict of axis sizes, ``MeshSpec.as_dict()``)
    labels the topology the checkpoint was saved under — what elastic
    resume and ``tools/verify_checkpoint.py --strict`` read. Sharded-save
    layouts pass ``layout='sharded'`` plus the shard file NAMES; each
    shard carries its own sidecar manifest (multi-host saves cannot hash
    another process's shard), and :func:`verify_checkpoint` chases them.
    """
    manifest = {
        "schema": MANIFEST_SCHEMA,
        "step": int(step),
        "sha256": hashlib.sha256(blob).hexdigest(),
        "size_bytes": len(blob),
        "keys": sorted(keys),
    }
    if mesh_spec is not None:
        manifest["mesh_spec"] = {str(k): int(v)
                                 for k, v in dict(mesh_spec).items()}
    if layout is not None:
        manifest["layout"] = str(layout)
    if shard_files is not None:
        manifest["shard_files"] = sorted(str(n) for n in shard_files)
    return manifest


def write_manifest(ckpt_path: str, manifest: dict) -> str:
    """Atomically (tmp + rename) write the sidecar next to ``ckpt_path``."""
    path = manifest_path(ckpt_path)
    directory = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(manifest, f, sort_keys=True)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def read_manifest(ckpt_path: str) -> Optional[dict]:
    """The sidecar manifest dict, or None when absent/unreadable."""
    try:
        with open(manifest_path(ckpt_path)) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return None
    return manifest if isinstance(manifest, dict) else None


def _verify_against_manifest(ckpt_path: str, actual_size: int,
                             sha_fn) -> Tuple[str, str]:
    """Shared core of the file-path and in-memory verifiers: manifest
    presence/schema, cheap size check first (truncation — the common
    torn-copy shape — is caught without hashing a multi-GB state), then
    ``sha_fn()`` only when the size matches."""
    if not os.path.exists(manifest_path(ckpt_path)):
        return NO_MANIFEST, "no manifest sidecar (legacy or torn write)"
    manifest = read_manifest(ckpt_path)
    if manifest is None:
        return CORRUPT, "manifest unreadable (not a JSON object)"
    if manifest.get("schema") != MANIFEST_SCHEMA:
        return CORRUPT, (f"unknown manifest schema "
                         f"{manifest.get('schema')!r}")
    expected_size = manifest.get("size_bytes")
    if expected_size != actual_size:
        return CORRUPT, (f"size mismatch: manifest says {expected_size} "
                         f"bytes, file is {actual_size}")
    actual_sha = sha_fn()
    if manifest.get("sha256") != actual_sha:
        return CORRUPT, (f"sha256 mismatch: manifest "
                         f"{str(manifest.get('sha256'))[:12]}..., file "
                         f"{actual_sha[:12]}...")
    return VERIFIED, "sha256 verified"


def verify_checkpoint(ckpt_path: str) -> Tuple[str, str]:
    """(status, detail) for one checkpoint file — see the module docstring
    for the status vocabulary. Detail is a human-readable reason string.

    A sharded-layout INDEX whose manifest lists ``shard_files`` chases
    every shard: a missing or corrupt shard corrupts the whole
    checkpoint (the resume walk-back must not half-load it), and an
    unverifiable shard caps the status at ``no_manifest``.
    """
    if not os.path.isfile(ckpt_path):
        return CORRUPT, "checkpoint file missing"
    status, detail = _verify_against_manifest(
        ckpt_path, os.path.getsize(ckpt_path),
        lambda: sha256_file(ckpt_path))
    if status != VERIFIED:
        return status, detail
    manifest = read_manifest(ckpt_path)
    directory = os.path.dirname(os.path.abspath(ckpt_path))
    for name in (manifest or {}).get("shard_files", ()):
        shard = os.path.join(directory, os.path.basename(str(name)))
        if not os.path.isfile(shard):
            return CORRUPT, f"shard file missing: {name}"
        shard_status, shard_detail = _verify_against_manifest(
            shard, os.path.getsize(shard), lambda s=shard: sha256_file(s))
        if shard_status == CORRUPT:
            return CORRUPT, f"shard {name}: {shard_detail}"
        if shard_status == NO_MANIFEST:
            status, detail = NO_MANIFEST, f"shard {name}: {shard_detail}"
    return status, detail


def validate_mesh_spec(manifest: dict) -> Tuple[bool, str]:
    """Jax-free consistency check of a manifest's mesh-spec vs its shard
    layout (``tools/verify_checkpoint.py --strict``): axis sizes must be
    concrete positives, and a sharded layout's device product must be
    divisible by its process-shard count (each process wrote one shard
    of an evenly-distributed mesh). Returns (ok, reason)."""
    spec = manifest.get("mesh_spec")
    if spec is None:
        return True, "no mesh_spec recorded (pre-one-mesh checkpoint)"
    if not isinstance(spec, dict) or not spec:
        return False, "mesh_spec is not a non-empty object"
    product = 1
    for key, size in spec.items():
        if not isinstance(size, int) or size < 1:
            return False, (f"mesh_spec axis '{key}' must be a concrete "
                           f"positive size, got {size!r}")
        product *= size
    shards = manifest.get("shard_files")
    if manifest.get("layout") == "sharded":
        if not shards:
            return False, "layout=sharded but no shard_files listed"
        if product % len(shards) != 0:
            return False, (f"device product {product} not divisible by "
                           f"{len(shards)} process shards")
    return True, f"mesh_spec consistent ({product} devices)"


def verify_blob(ckpt_path: str, blob: bytes) -> Tuple[str, str]:
    """(status, detail) for checkpoint bytes already in memory — the load
    paths read the file ONCE and verify that buffer instead of paying a
    second multi-GB read just to hash (utils/checkpoint.py)."""
    return _verify_against_manifest(
        ckpt_path, len(blob),
        lambda: hashlib.sha256(blob).hexdigest())
