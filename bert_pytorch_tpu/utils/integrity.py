"""Checkpoint integrity manifests (docs/fault_tolerance.md).

A checkpoint write is atomic (tmp + rename, ``utils/checkpoint.py``), but
atomicity only protects against the writing process dying — not against a
torn filesystem, a partial copy from another machine, or bit rot between
runs. A 40-layer K-FAC state is slow to rebuild (arXiv:2107.01739), so a
silently-corrupt checkpoint that crashes the resume path — or worse, loads
garbage — costs real wallclock. This module gives every checkpoint a
sidecar manifest:

    ckpt_200.msgpack            # the flax msgpack state
    ckpt_200.msgpack.manifest.json
        {"schema": "ckpt-manifest-v1", "step": 200,
         "sha256": "...", "size_bytes": N, "keys": ["epoch", "model", ...]}

written tmp+rename immediately after the blob's own rename (a crash in
the gap leaves a blob with no manifest — reported as ``no_manifest``,
the same status pre-manifest checkpoints get, never as corruption).

Verification statuses (:func:`verify_checkpoint`):

* ``verified``    — manifest present, size and sha256 match;
* ``no_manifest`` — blob present, no sidecar (legacy checkpoint or a
  crash between the two renames). Loadable, but unverifiable;
* ``corrupt``     — size/sha mismatch, unreadable manifest, or missing
  blob. Never loaded; the resume walk-back skips it.

Stdlib-only by design: ``tools/verify_checkpoint.py`` and the chaos
harness load this by file path (``tools/_bootstrap.py``) on machines
without jax.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Optional, Tuple

MANIFEST_SCHEMA = "ckpt-manifest-v1"
MANIFEST_SUFFIX = ".manifest.json"

# verify_checkpoint statuses, strongest first.
VERIFIED = "verified"
NO_MANIFEST = "no_manifest"
CORRUPT = "corrupt"


def manifest_path(ckpt_path: str) -> str:
    return ckpt_path + MANIFEST_SUFFIX


def sha256_file(path: str, chunk_bytes: int = 1 << 20) -> str:
    digest = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            chunk = f.read(chunk_bytes)
            if not chunk:
                break
            digest.update(chunk)
    return digest.hexdigest()


def build_manifest(step: int, blob: bytes, keys=()) -> dict:
    """Manifest dict for an in-memory serialized checkpoint (the save path
    has the bytes in hand — hashing them costs no extra IO)."""
    return {
        "schema": MANIFEST_SCHEMA,
        "step": int(step),
        "sha256": hashlib.sha256(blob).hexdigest(),
        "size_bytes": len(blob),
        "keys": sorted(keys),
    }


def write_manifest(ckpt_path: str, manifest: dict) -> str:
    """Atomically (tmp + rename) write the sidecar next to ``ckpt_path``."""
    path = manifest_path(ckpt_path)
    directory = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(manifest, f, sort_keys=True)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def read_manifest(ckpt_path: str) -> Optional[dict]:
    """The sidecar manifest dict, or None when absent/unreadable."""
    try:
        with open(manifest_path(ckpt_path)) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return None
    return manifest if isinstance(manifest, dict) else None


def _verify_against_manifest(ckpt_path: str, actual_size: int,
                             sha_fn) -> Tuple[str, str]:
    """Shared core of the file-path and in-memory verifiers: manifest
    presence/schema, cheap size check first (truncation — the common
    torn-copy shape — is caught without hashing a multi-GB state), then
    ``sha_fn()`` only when the size matches."""
    if not os.path.exists(manifest_path(ckpt_path)):
        return NO_MANIFEST, "no manifest sidecar (legacy or torn write)"
    manifest = read_manifest(ckpt_path)
    if manifest is None:
        return CORRUPT, "manifest unreadable (not a JSON object)"
    if manifest.get("schema") != MANIFEST_SCHEMA:
        return CORRUPT, (f"unknown manifest schema "
                         f"{manifest.get('schema')!r}")
    expected_size = manifest.get("size_bytes")
    if expected_size != actual_size:
        return CORRUPT, (f"size mismatch: manifest says {expected_size} "
                         f"bytes, file is {actual_size}")
    actual_sha = sha_fn()
    if manifest.get("sha256") != actual_sha:
        return CORRUPT, (f"sha256 mismatch: manifest "
                         f"{str(manifest.get('sha256'))[:12]}..., file "
                         f"{actual_sha[:12]}...")
    return VERIFIED, "sha256 verified"


def verify_checkpoint(ckpt_path: str) -> Tuple[str, str]:
    """(status, detail) for one checkpoint file — see the module docstring
    for the status vocabulary. Detail is a human-readable reason string.
    """
    if not os.path.isfile(ckpt_path):
        return CORRUPT, "checkpoint file missing"
    return _verify_against_manifest(
        ckpt_path, os.path.getsize(ckpt_path),
        lambda: sha256_file(ckpt_path))


def verify_blob(ckpt_path: str, blob: bytes) -> Tuple[str, str]:
    """(status, detail) for checkpoint bytes already in memory — the load
    paths read the file ONCE and verify that buffer instead of paying a
    second multi-GB read just to hash (utils/checkpoint.py)."""
    return _verify_against_manifest(
        ckpt_path, len(blob),
        lambda: hashlib.sha256(blob).hexdigest())
