"""Multi-sink structured logging — the in-repo replacement for the external
``loggerplus`` the reference drives (run_pretraining.py:21,191-204).

Four handler types, all rank-0-gated via ``verbose``: stream, append-mode
text file, CSV, and TensorBoard (skipped with a warning if no tensorboard
backend is importable). ``log(tag=..., step=..., **metrics)`` writes one
structured record to every sink (the reference's record shape:
tag/step/epoch/average_loss/step_loss/learning_rate/samples_per_second,
run_pretraining.py:554-564).
"""

from __future__ import annotations

import csv
import os
import sys
import time
import warnings
from typing import Iterable, Optional


class Handler:
    def __init__(self, verbose: bool = True):
        self.verbose = verbose

    def write_message(self, message: str) -> None:  # pragma: no cover
        raise NotImplementedError

    def write_record(self, record: dict) -> None:
        self.write_message(
            " | ".join(f"{k}: {_fmt(v)}" for k, v in record.items())
        )

    def close(self) -> None:
        pass


def _fmt(v):
    if isinstance(v, float):
        return f"{v:.6g}"
    return v


class StreamHandler(Handler):
    def __init__(self, verbose: bool = True, stream=None):
        super().__init__(verbose)
        self.stream = stream or sys.stdout

    def write_message(self, message: str) -> None:
        if self.verbose:
            self.stream.write(message + "\n")
            self.stream.flush()


class FileHandler(Handler):
    def __init__(self, path: str, overwrite: bool = False, verbose: bool = True):
        super().__init__(verbose)
        self.path = path
        if verbose:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._f = open(path, "w" if overwrite else "a")
        else:
            self._f = None

    def write_message(self, message: str) -> None:
        if self._f is not None:
            self._f.write(message + "\n")
            self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class CSVHandler(Handler):
    """One CSV row per structured record; columns fixed by the first record
    (extra keys in later records are dropped, missing keys are blank)."""

    def __init__(self, path: str, overwrite: bool = False, verbose: bool = True):
        super().__init__(verbose)
        self.path = path
        self._fieldnames: Optional[list] = None
        if verbose:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._f = open(path, "w" if overwrite else "a", newline="")
        else:
            self._f = None

    def write_message(self, message: str) -> None:
        pass  # CSV carries records only

    def write_record(self, record: dict) -> None:
        if self._f is None:
            return
        if self._fieldnames is None:
            self._fieldnames = list(record.keys())
            self._writer = csv.DictWriter(
                self._f, fieldnames=self._fieldnames, extrasaction="ignore"
            )
            if self._f.tell() == 0:
                self._writer.writeheader()
        self._writer.writerow({k: record.get(k, "") for k in self._fieldnames})
        self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class TensorBoardHandler(Handler):
    """Scalar metrics to TensorBoard via any importable writer backend."""

    def __init__(self, log_dir: str, verbose: bool = True):
        super().__init__(verbose)
        self._writer = None
        if not verbose:
            return
        try:
            from torch.utils.tensorboard import SummaryWriter  # type: ignore

            self._writer = SummaryWriter(log_dir)
        except Exception:
            try:
                from tensorboardX import SummaryWriter  # type: ignore

                self._writer = SummaryWriter(log_dir)
            except Exception:
                warnings.warn(
                    "No tensorboard backend available; TensorBoardHandler disabled"
                )

    def write_message(self, message: str) -> None:
        pass

    def write_record(self, record: dict) -> None:
        if self._writer is None:
            return
        step = record.get("step", 0)
        tag = record.get("tag", "train")
        for key, value in record.items():
            if key in ("tag", "step"):
                continue
            if isinstance(value, (int, float)):
                self._writer.add_scalar(f"{tag}/{key}", value, int(step))
        self._writer.flush()

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()


class Logger:
    def __init__(self):
        self.handlers: list[Handler] = [StreamHandler()]

    def init(self, handlers: Iterable[Handler]) -> None:
        self.close()
        self.handlers = list(handlers)

    def info(self, message: str) -> None:
        stamp = time.strftime("%Y-%m-%d %H:%M:%S")
        for h in self.handlers:
            h.write_message(f"[{stamp}] {message}")

    def log(self, **record) -> None:
        for h in self.handlers:
            h.write_record(record)

    def close(self) -> None:
        for h in self.handlers:
            h.close()


# Module-level singleton, loggerplus-style.
logger = Logger()
init = logger.init
info = logger.info
log = logger.log
close = logger.close
