"""Multi-sink structured logging — the in-repo replacement for the external
``loggerplus`` the reference drives (run_pretraining.py:21,191-204).

Five handler types: stream, append-mode text file, CSV, JSONL (the
machine-readable telemetry sink, schema-versioned — see
``bert_pytorch_tpu/telemetry/schema.py`` and docs/telemetry.md), and
TensorBoard (skipped with a warning if no tensorboard backend is
importable). ``log(tag=..., step=..., **metrics)`` writes one structured
record to every sink (the reference's record shape:
tag/step/epoch/average_loss/step_loss/learning_rate/samples_per_second,
run_pretraining.py:554-564).

Two orthogonal gates, deliberately separate:

* ``is_primary`` — is this process rank 0? Non-primary processes write no
  file artifacts at all (file/CSV/JSONL/TensorBoard handlers stay closed).
* ``verbose`` — purely cosmetic: does the STREAM handler echo to the
  terminal? A quiet (``verbose=False``) rank-0 run still produces every
  file artifact.

``is_primary`` defaults to the value of ``verbose`` so pre-existing call
sites that passed only ``verbose=is_main_process()`` keep their behavior;
new call sites should pass both explicitly.
"""

from __future__ import annotations

import csv
import json
import math
import os
import sys
import threading
import time
import warnings
from typing import Iterable, Optional


class Handler:
    def __init__(self, verbose: bool = True, is_primary: Optional[bool] = None):
        self.verbose = verbose
        self.is_primary = verbose if is_primary is None else is_primary

    def write_message(self, message: str) -> None:  # pragma: no cover
        raise NotImplementedError

    def write_record(self, record: dict) -> None:
        self.write_message(
            " | ".join(f"{k}: {_fmt(v)}" for k, v in record.items())
        )

    def close(self) -> None:
        pass


def _fmt(v):
    if isinstance(v, float):
        return f"{v:.6g}"
    return v


class StreamHandler(Handler):
    def __init__(self, verbose: bool = True, stream=None,
                 is_primary: Optional[bool] = None):
        super().__init__(verbose, is_primary)
        self.stream = stream or sys.stdout

    def write_message(self, message: str) -> None:
        # Stream output is the one place ``verbose`` applies: quiet runs
        # keep their file artifacts but stop echoing to the terminal.
        if self.verbose and self.is_primary:
            self.stream.write(message + "\n")
            self.stream.flush()


class FileHandler(Handler):
    def __init__(self, path: str, overwrite: bool = False, verbose: bool = True,
                 is_primary: Optional[bool] = None):
        super().__init__(verbose, is_primary)
        self.path = path
        if self.is_primary:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._f = open(path, "w" if overwrite else "a")
        else:
            self._f = None

    def write_message(self, message: str) -> None:
        if self._f is not None:
            self._f.write(message + "\n")
            self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class CSVHandler(Handler):
    """One CSV row per structured record. The column set WIDENS when a later
    record brings new keys (e.g. eval metrics or telemetry gauges appearing
    mid-run): the file is rewritten once with the union header and old rows
    blank-filled — nothing is silently dropped. Missing keys stay blank."""

    def __init__(self, path: str, overwrite: bool = False, verbose: bool = True,
                 is_primary: Optional[bool] = None):
        super().__init__(verbose, is_primary)
        self.path = path
        self._fieldnames: Optional[list] = None
        if self.is_primary:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._f = open(path, "w" if overwrite else "a", newline="")
        else:
            self._f = None

    def write_message(self, message: str) -> None:
        pass  # CSV carries records only

    def _open_writer(self, write_header: bool) -> None:
        self._writer = csv.DictWriter(
            self._f, fieldnames=self._fieldnames, extrasaction="ignore"
        )
        if write_header:
            self._writer.writeheader()

    def _existing_header(self) -> Optional[list]:
        """First row of the file being appended to (None when empty) — the
        prior run's column set, which seeds ``_fieldnames`` so a resumed
        run widens relative to the FILE's header, not this session's first
        record (else the old header would be misread as a data row)."""
        if self._f.tell() == 0:
            return None
        with open(self.path, newline="") as f:
            return next(csv.reader(f), None)

    def _widen(self, novel: list) -> None:
        """Rewrite the file with the union header; existing rows get blanks
        for the new columns. Metric CSVs are small (one row per log step),
        and new keys appear a handful of times per run, so the rewrite is
        cheap — and strictly better than dropping the new metrics."""
        old_fields = self._fieldnames
        self._fieldnames = old_fields + novel
        self._f.close()
        rows = []
        with open(self.path, newline="") as f:
            reader = csv.reader(f)
            for i, row in enumerate(reader):
                if i == 0 and row == old_fields:
                    continue  # old header; replaced below
                rows.append(dict(zip(old_fields, row)))
        self._f = open(self.path, "w", newline="")
        self._open_writer(write_header=True)
        for row in rows:
            self._writer.writerow(row)

    def write_record(self, record: dict) -> None:
        if self._f is None:
            return
        if self._fieldnames is None:
            existing = self._existing_header()
            if existing:
                self._fieldnames = existing
                self._open_writer(write_header=False)
            else:
                self._fieldnames = list(record.keys())
                self._open_writer(write_header=True)
        novel = [k for k in record if k not in self._fieldnames]
        if novel:
            self._widen(novel)
        self._writer.writerow({k: record.get(k, "") for k in self._fieldnames})
        self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class JSONLHandler(Handler):
    """One JSON object per line — the machine-readable sink the telemetry
    layer, bench.py, and the NOTES/PARITY tooling parse.

    Every line carries ``schema`` (the telemetry record schema version,
    ``telemetry/schema.py``) and ``ts`` (unix seconds) in addition to the
    record's own fields; non-finite floats are serialized as JSON ``null``
    (NaN is not valid JSON and would poison downstream parsers — the
    sentinel record's ``finite`` flag carries the signal instead).
    ``tools/check_telemetry_schema.py`` lints committed artifacts against
    the schema.

    Thread-safe: background threads also emit here (the hung-step
    watchdog, the data path's shard-retry fault records — PR 5,
    docs/fault_tolerance.md), and interleaved ``TextIOWrapper.write``
    calls could otherwise tear two records into one invalid line. One
    lock serializes each record's write+flush (and close).
    """

    def __init__(self, path: str, overwrite: bool = False, verbose: bool = True,
                 is_primary: Optional[bool] = None):
        super().__init__(verbose, is_primary)
        self.path = path
        self._lock = threading.Lock()
        if self.is_primary:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._f = open(path, "w" if overwrite else "a")
        else:
            self._f = None

    def write_message(self, message: str) -> None:
        pass  # JSONL carries records only; prose goes to the text sink

    def write_record(self, record: dict) -> None:
        # Cheap unlocked fast-path for non-primary ranks: the only None
        # transition is close(), and the locked re-check below covers
        # that race — but serializing every hot-path record just to drop
        # it would be per-step waste on every rank. The deliberate
        # lock-free read is suppressed, not baselined: the justification
        # lives here, next to the code it licenses.
        if self._f is None:  # jaxlint: disable=LK501
            return
        from bert_pytorch_tpu.telemetry.schema import SCHEMA_VERSION

        rec = {"schema": SCHEMA_VERSION, "ts": round(time.time(), 3)}
        rec.update(record)
        line = json.dumps(rec, default=str, allow_nan=False,
                          cls=_FiniteEncoder) + "\n"
        with self._lock:
            if self._f is None:
                return
            self._f.write(line)
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


class _FiniteEncoder(json.JSONEncoder):
    """Serialize non-finite floats as null instead of raising (allow_nan
    only controls the invalid-JSON NaN/Infinity spellings)."""

    def iterencode(self, o, _one_shot=False):
        return super().iterencode(_sanitize_nonfinite(o), _one_shot)


def _sanitize_nonfinite(obj):
    if isinstance(obj, float) and not math.isfinite(obj):
        return None
    if isinstance(obj, dict):
        return {k: _sanitize_nonfinite(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_sanitize_nonfinite(v) for v in obj]
    return obj


class TensorBoardHandler(Handler):
    """Scalar metrics to TensorBoard via any importable writer backend."""

    def __init__(self, log_dir: str, verbose: bool = True,
                 is_primary: Optional[bool] = None):
        super().__init__(verbose, is_primary)
        self._writer = None
        self._warned_stepless = False
        if not self.is_primary:
            return
        try:
            from torch.utils.tensorboard import SummaryWriter  # type: ignore

            self._writer = SummaryWriter(log_dir)
        except Exception:
            try:
                from tensorboardX import SummaryWriter  # type: ignore

                self._writer = SummaryWriter(log_dir)
            except Exception:
                warnings.warn(
                    "No tensorboard backend available; TensorBoardHandler disabled"
                )

    def write_message(self, message: str) -> None:
        pass

    def write_record(self, record: dict) -> None:
        if self._writer is None:
            return
        step = record.get("step")
        if step is None:
            # A stepless record has no x-axis position; writing it at step 0
            # would alias it onto the real step-0 scalars. Skip it (the
            # file/CSV/JSONL sinks still carry it).
            if not self._warned_stepless:
                self._warned_stepless = True
                warnings.warn(
                    "TensorBoardHandler: record without 'step' skipped "
                    "(scalars need an x-axis position)")
            return
        tag = record.get("tag", "train")
        for key, value in record.items():
            if key in ("tag", "step"):
                continue
            if isinstance(value, (int, float)):
                self._writer.add_scalar(f"{tag}/{key}", value, int(step))
        self._writer.flush()

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()


class Logger:
    def __init__(self):
        self.handlers: list[Handler] = [StreamHandler()]

    def init(self, handlers: Iterable[Handler]) -> None:
        # Close the handlers being replaced (including the default
        # StreamHandler) so re-init never leaks open files or TB writers.
        self.close()
        self.handlers = list(handlers)

    def add_handler(self, handler: Handler) -> None:
        """Append one handler to an already-initialized logger (the
        flight recorder's log tee attaches this way — after init, which
        would otherwise close and replace it)."""
        self.handlers.append(handler)

    def info(self, message: str) -> None:
        stamp = time.strftime("%Y-%m-%d %H:%M:%S")
        for h in self.handlers:
            h.write_message(f"[{stamp}] {message}")

    def log(self, **record) -> None:
        for h in self.handlers:
            h.write_record(record)

    def close(self) -> None:
        for h in self.handlers:
            h.close()


# Module-level singleton, loggerplus-style.
logger = Logger()
init = logger.init
add_handler = logger.add_handler
info = logger.info
log = logger.log
close = logger.close
