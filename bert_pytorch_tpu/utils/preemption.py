"""Graceful-preemption handling shared by all five runners
(docs/fault_tolerance.md).

TPU-VM maintenance events, SLURM preemption, and Kubernetes pod eviction
all deliver SIGTERM with a short grace period; an interactive operator
delivers SIGINT. Dying mid-step loses the training since the last
checkpoint — PaLM (arXiv:2204.02311) reports preemption-driven restarts
as routine at scale, so the stop path must be a tested code path, not an
accident. :class:`GracefulStop` is the one shared implementation:

* the handler only sets a flag — nothing async-unsafe runs in signal
  context, and the training loop acts on the flag at a STEP BOUNDARY
  (multi-host jobs additionally agree collectively on the stop step —
  run_pretraining.py's allgather);
* the runner then writes an emergency checkpoint (joining any in-flight
  async save), flushes telemetry, and exits with :data:`EXIT_PREEMPTED`
  so the scheduler/driver can distinguish "checkpointed and ready to
  resume" (resubmit) from success (0) and from crashes (anything else);
* handlers stay installed through the checkpoint write — the grace
  period may re-deliver the signal, and the default disposition would
  kill the write mid-file — and are restored on exit even on exceptions
  (in-process callers, like the test suite, must not inherit a handler
  over a dead flag).
"""

from __future__ import annotations

import signal
from typing import Optional

# 75 = EX_TEMPFAIL ("temporary failure; user is invited to retry") — the
# closest sysexits.h code to "preempted cleanly, resubmit me". Distinct
# from 0 (done), from 1/2 (crash/config error), and from 128+N (killed by
# an unhandled signal N — the path this module exists to avoid).
EXIT_PREEMPTED = 75

_DEFAULT_SIGNALS = ("SIGTERM", "SIGINT", "SIGUSR1")


class GracefulStop:
    """Install flag-setting handlers for the preemption signals; use as a
    context manager (restores previous handlers on exit)::

        with GracefulStop() as stop:
            for batch in loader:
                ...
                if stop.requested:
                    break   # runner writes the emergency checkpoint
        sys.exit(EXIT_PREEMPTED if stop.requested else 0)

    ``signals`` are names resolved against the platform (``SIGUSR1`` is
    skipped where absent). Installation failures (non-main thread — the
    in-process test suite; restricted platforms) are silently tolerated:
    the loop then simply never sees ``requested``, which is the
    pre-existing behavior, not a new failure mode.
    """

    def __init__(self, signals=_DEFAULT_SIGNALS, on_signal=None):
        self._names = tuple(signals)
        self._on_signal = on_signal
        self._old: dict = {}
        self.requested = False
        self.signum: Optional[int] = None

    @property
    def signal_name(self) -> Optional[str]:
        if self.signum is None:
            return None
        try:
            return signal.Signals(self.signum).name
        except ValueError:
            return str(self.signum)

    def _handler(self, signum, frame):
        # First delivery wins; repeats during the grace period are absorbed
        # (the default disposition coming back would kill the checkpoint
        # write this machinery exists to protect) — EXCEPT a second
        # SIGINT: the interactive convention is first Ctrl-C = graceful,
        # second = abort now. A wedged loop (the watchdog's stall modes)
        # stays interruptible without SIGKILL; automation signals
        # (SIGTERM/SIGUSR1, re-delivered by schedulers during the grace
        # period) never escalate.
        if self.requested:
            if signum == signal.SIGINT:
                raise KeyboardInterrupt
            return
        self.requested = True
        self.signum = signum
        if self._on_signal is not None:
            try:
                self._on_signal(signum)
            except Exception:
                pass  # never raise from signal context

    def install(self) -> "GracefulStop":
        for name in self._names:
            sig = getattr(signal, name, None)
            if sig is None:
                continue
            try:
                self._old[sig] = signal.signal(sig, self._handler)
            except (ValueError, OSError):
                pass  # non-main thread or platform restriction
        return self

    def restore(self) -> None:
        for sig, handler in self._old.items():
            try:
                signal.signal(sig, handler)
            except (ValueError, OSError):
                pass
        self._old = {}

    def __enter__(self) -> "GracefulStop":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.restore()


def preemption_record(step: int, stop: GracefulStop) -> dict:
    """The telemetry ``fault`` record a runner emits when it acts on a
    graceful-stop request (schema v1; docs/telemetry.md)."""
    return {
        "kind": "fault",
        "tag": "telemetry",
        "fault": "preemption",
        "step": int(step),
        "signal": stop.signal_name,
        "injected": False,
    }
