"""Shared retry/backoff policy (docs/fault_tolerance.md).

One place for the backoff math every resilient path uses — the HDF5
shard reads in ``data/dataset.py``, the bench harness's attempt loop
(bench.py), and any future network/storage client — instead of each
call site hand-rolling its own sleep loop with slightly different
semantics (the pre-PR-5 state: bench.py capped flat sleeps, the capture
scripts re-invented theirs in shell).

Design constraints, all test-driven:

* **stdlib-only** — the bench parent and the repo-root tools import this
  by file path on machines without the accelerator stack (the
  ``tools/_bootstrap.py`` property), so nothing here may import jax,
  numpy, or the package ``__init__`` chain;
* **deterministic under test** — the jitter source, sleep function, and
  clock are injectable, so unit tests assert exact delay sequences with
  a fake clock instead of sleeping;
* **bounded** — attempts are finite and the per-delay cap is explicit;
  an exhausted policy re-raises the LAST error (with context), never
  swallows it.

Jitter is "full jitter" scaled: ``delay = backoff * (1 - jitter + jitter
* u)`` with ``u ~ U[0, 1)`` — at the default ``jitter=0.5`` delays land
in ``[0.5, 1.0) * backoff``, decorrelating retry herds (every host of a
multi-host job hitting the same flaky filer) while keeping the expected
wait predictable.

Two opt-in extensions (PR 11, the serving router's requirements — both
OFF by default so every existing call site keeps byte-identical delay
sequences, pinned by ``tests/test_fleet.py``):

* ``full_jitter=True`` — the AWS "full jitter" scheme: ``delay = backoff
  * u`` with ``u ~ U[0, 1)``. A router retrying a failed replica wants
  maximal decorrelation (many concurrent requests fail over at the same
  instant when a replica dies) and a LOW expected wait, not a
  predictable one — half the raw backoff on average, spread over the
  whole interval;
* ``max_elapsed_s`` — a wall-clock budget over the WHOLE retry loop
  (measured by the injectable ``clock``): once the next sleep would
  land past the budget, :func:`retry_call` stops retrying and raises.
  Per-request deadlines make "attempts" the wrong unit alone — a
  deadline-bound caller needs the loop bounded in seconds too.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Iterator, Optional, Tuple, Type


class RetryError(RuntimeError):
    """All attempts exhausted; ``__cause__`` is the last underlying error."""


class RetryPolicy:
    """Exponential backoff with jitter, bounded attempts.

    ``attempts`` counts TOTAL calls (1 = no retries). ``base_delay_s`` is
    the pre-jitter delay before the first retry, doubling (``multiplier``)
    per retry up to ``max_delay_s``. ``jitter`` in [0, 1] is the fraction
    of each delay that is randomized (0 = deterministic, for tests and
    for callers that already decorrelate externally).

    ``full_jitter=True`` switches to ``delay = raw * u`` (``jitter`` is
    then ignored); ``max_elapsed_s`` bounds the whole retry loop in
    wall-clock seconds (:func:`retry_call` checks it against ``clock``
    before every sleep). Both default off — see the module docstring.
    """

    def __init__(
        self,
        attempts: int = 3,
        base_delay_s: float = 0.5,
        max_delay_s: float = 30.0,
        multiplier: float = 2.0,
        jitter: float = 0.5,
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
        full_jitter: bool = False,
        max_elapsed_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if attempts < 1:
            raise ValueError(f"attempts must be >= 1, got {attempts}")
        if base_delay_s < 0 or max_delay_s < 0:
            raise ValueError("delays must be >= 0")
        if multiplier < 1:
            raise ValueError(f"multiplier must be >= 1, got {multiplier}")
        if not 0 <= jitter <= 1:
            raise ValueError(f"jitter must be in [0, 1], got {jitter}")
        if max_elapsed_s is not None and max_elapsed_s < 0:
            raise ValueError(
                f"max_elapsed_s must be >= 0, got {max_elapsed_s}")
        self.attempts = int(attempts)
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.sleep = sleep
        self.rng = rng if rng is not None else random.Random()
        self.full_jitter = bool(full_jitter)
        self.max_elapsed_s = (None if max_elapsed_s is None
                              else float(max_elapsed_s))
        self.clock = clock

    def backoff_s(self, retry_index: int) -> float:
        """Jittered delay before retry ``retry_index`` (0-based: the delay
        after the first failed attempt is ``backoff_s(0)``)."""
        raw = min(self.max_delay_s,
                  self.base_delay_s * self.multiplier ** retry_index)
        if self.full_jitter:
            return raw * self.rng.random()
        if self.jitter == 0:
            return raw
        return raw * (1.0 - self.jitter + self.jitter * self.rng.random())

    def delays(self) -> Iterator[float]:
        """The policy's ``attempts - 1`` jittered retry delays, in order."""
        for i in range(self.attempts - 1):
            yield self.backoff_s(i)


def retry_call(
    fn: Callable,
    *args,
    policy: Optional[RetryPolicy] = None,
    retry_on: Tuple[Type[BaseException], ...] = (OSError,),
    on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
    description: str = "",
    **kwargs,
):
    """Call ``fn(*args, **kwargs)``, retrying ``retry_on`` errors per
    ``policy``.

    ``on_retry(attempt, error, delay_s)`` fires before each backoff sleep
    (attempt is 1-based) — the hook call sites use to emit ``fault``
    telemetry records / warnings without this module knowing about either.
    Exhausted attempts raise :class:`RetryError` from the last error;
    non-``retry_on`` errors propagate immediately (a genuine bug must not
    burn the retry budget looking transient). With ``policy.max_elapsed_s``
    set, a retry whose backoff sleep would end past the budget (measured
    by ``policy.clock`` from this call's entry) is abandoned the same way
    an exhausted attempt count is.
    """
    policy = policy or RetryPolicy()
    t0 = policy.clock() if policy.max_elapsed_s is not None else 0.0
    last: Optional[BaseException] = None
    exhausted_by = ""
    for attempt in range(1, policy.attempts + 1):
        try:
            return fn(*args, **kwargs)
        except retry_on as exc:
            last = exc
            if attempt >= policy.attempts:
                exhausted_by = f"after {policy.attempts} attempt(s)"
                break
            delay = policy.backoff_s(attempt - 1)
            if policy.max_elapsed_s is not None and (
                    policy.clock() - t0 + delay > policy.max_elapsed_s):
                exhausted_by = (
                    f"after {attempt} attempt(s): next {delay:.3f}s "
                    f"backoff exceeds the {policy.max_elapsed_s:g}s "
                    "elapsed budget")
                break
            if on_retry is not None:
                on_retry(attempt, exc, delay)
            policy.sleep(delay)
    what = description or getattr(fn, "__name__", "call")
    raise RetryError(
        f"{what} failed {exhausted_by}: "
        f"{type(last).__name__}: {last}") from last
