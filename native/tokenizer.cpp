// bert_pytorch_tpu native tokenizer core.
//
// C++ replacement for the HuggingFace Rust `tokenizers` dependency the
// reference drives for its entire offline pipeline and runtime data path
// (reference src/tokenization.py:42-57, utils/build_vocab.py:39-58,
// utils/encode_data.py:280-293; SURVEY.md §2.3). The behavioral
// specification is the pure-Python BasicTokenizer/WordpieceTokenizer
// (src/tokenization.py:60-229 ≙ bert_pytorch_tpu/data/tokenization.py).
//
// Pipeline: UTF-8 decode -> clean (drop control/NUL/replacement chars,
// canonicalize whitespace) -> CJK isolation -> never_split passthrough for
// special tokens -> optional lowercase + accent strip (full-Unicode
// lower()+NFD+drop-Mn fold tables generated from Python unicodedata by
// gen_unicode_tables.py, plus algorithmic Hangul decomposition and the
// Final_Sigma rule) -> punctuation split -> greedy longest-match WordPiece
// against a prefix-keyed hash vocab.
//
// Exposed as a C ABI for ctypes (see tools/tokenizer_cpp.py). A WordPiece
// vocab trainer (pair-merge algorithm over word counts) lives here too,
// replacing BertWordPieceTokenizer.train.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <fstream>
#include <map>
#include <sstream>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// UTF-8
// ---------------------------------------------------------------------------

// Decode one UTF-8 codepoint starting at s[i]; advances i. Invalid bytes
// decode as U+FFFD and advance by one.
uint32_t decode_utf8(const std::string& s, size_t& i) {
  unsigned char c = s[i];
  if (c < 0x80) { i += 1; return c; }
  if ((c >> 5) == 0x6 && i + 1 < s.size()) {
    uint32_t cp = ((c & 0x1F) << 6) | (s[i + 1] & 0x3F);
    i += 2; return cp;
  }
  if ((c >> 4) == 0xE && i + 2 < s.size()) {
    uint32_t cp = ((c & 0x0F) << 12) | ((s[i + 1] & 0x3F) << 6) |
                  (s[i + 2] & 0x3F);
    i += 3; return cp;
  }
  if ((c >> 3) == 0x1E && i + 3 < s.size()) {
    uint32_t cp = ((c & 0x07) << 18) | ((s[i + 1] & 0x3F) << 12) |
                  ((s[i + 2] & 0x3F) << 6) | (s[i + 3] & 0x3F);
    i += 4; return cp;
  }
  i += 1;
  return 0xFFFD;
}

void encode_utf8(uint32_t cp, std::string& out) {
  if (cp < 0x80) {
    out.push_back(static_cast<char>(cp));
  } else if (cp < 0x800) {
    out.push_back(static_cast<char>(0xC0 | (cp >> 6)));
    out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  } else if (cp < 0x10000) {
    out.push_back(static_cast<char>(0xE0 | (cp >> 12)));
    out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
    out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  } else {
    out.push_back(static_cast<char>(0xF0 | (cp >> 18)));
    out.push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
    out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
    out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  }
}

// ---------------------------------------------------------------------------
// Character classes — range tables generated from Python unicodedata by
// gen_unicode_tables.py (the behavioral spec is the pure-Python
// BasicTokenizer's unicodedata.category calls, reference
// src/tokenization.py:120-173). Full Unicode coverage, no ICU dependency.
// ---------------------------------------------------------------------------

struct CpRange { uint32_t lo, hi; };
#include "unicode_tables.inc"

bool in_ranges(uint32_t cp, const CpRange* table, size_t count) {
  size_t lo = 0, hi = count;
  while (lo < hi) {
    size_t mid = (lo + hi) / 2;
    if (cp < table[mid].lo) hi = mid;
    else if (cp > table[mid].hi) lo = mid + 1;
    else return true;
  }
  return false;
}

bool is_whitespace(uint32_t cp) {
  if (cp == ' ' || cp == '\t' || cp == '\n' || cp == '\r') return true;
  if (cp < 0x80) return false;
  return in_ranges(cp, kWhitespace, kWhitespaceCount);
}

bool is_control(uint32_t cp) {
  if (cp == '\t' || cp == '\n' || cp == '\r') return false;
  if (cp < 0x80) return cp < 0x20 || cp == 0x7F;
  return in_ranges(cp, kControl, kControlCount);
}

bool is_ascii_punct(uint32_t cp) {
  return (cp >= 33 && cp <= 47) || (cp >= 58 && cp <= 64) ||
         (cp >= 91 && cp <= 96) || (cp >= 123 && cp <= 126);
}

bool is_punct(uint32_t cp) {
  // ASCII non-alphanumerics count as punctuation even where Unicode
  // disagrees ('$', '`'), matching the spec's explicit override.
  if (cp < 0x80) return is_ascii_punct(cp);
  return in_ranges(cp, kPunct, kPunctCount);
}

bool is_cased_cp(uint32_t cp) {
  return in_ranges(cp, kCased, kCasedCount);
}

bool is_case_ignorable_cp(uint32_t cp) {
  return in_ranges(cp, kCaseIgnorable, kCaseIgnorableCount);
}

// Final_Sigma per CPython str.lower() (whose Cased/Case_Ignorable sets the
// generated tables reproduce exactly — probed, not approximated): the
// capital sigma at cps[j] takes the final form iff a cased character
// precedes it (skipping case-ignorables) and no cased character follows it
// (skipping case-ignorables). The scan is bounded to the word because the
// spec lower()s one whitespace token at a time.
bool sigma_is_final(const std::vector<uint32_t>& cps, size_t j) {
  bool preceded = false;
  for (size_t k = j; k > 0;) {
    uint32_t c = cps[--k];
    if (is_case_ignorable_cp(c)) continue;
    preceded = is_cased_cp(c);
    break;
  }
  if (!preceded) return false;
  for (size_t k = j + 1; k < cps.size(); k++) {
    uint32_t c = cps[k];
    if (is_case_ignorable_cp(c)) continue;
    return !is_cased_cp(c);
  }
  return true;
}

bool is_cjk(uint32_t cp) {
  return (cp >= 0x4E00 && cp <= 0x9FFF) || (cp >= 0x3400 && cp <= 0x4DBF) ||
         (cp >= 0x20000 && cp <= 0x2A6DF) || (cp >= 0x2A700 && cp <= 0x2B73F) ||
         (cp >= 0x2B740 && cp <= 0x2B81F) || (cp >= 0x2B820 && cp <= 0x2CEAF) ||
         (cp >= 0xF900 && cp <= 0xFAFF) || (cp >= 0x2F800 && cp <= 0x2FA1F);
}

// Per-codepoint fold: lower() + NFD + drop category-Mn, the spec's
// do_lower_case normalization (reference tokenization.py:94-102). Appends
// the 0..3 output codepoints to `out`. ``sigma_final`` is the Final_Sigma
// context for U+03A3 (sigma_is_final, computed by the caller which holds
// the whole word).
void fold_cp(uint32_t cp, bool sigma_final, std::vector<uint32_t>& out) {
  if (cp < 0x80) {
    out.push_back(cp >= 'A' && cp <= 'Z' ? cp + 32 : cp);
    return;
  }
  if (cp == 0x03A3) {  // GREEK CAPITAL SIGMA: context-sensitive lower()
    out.push_back(sigma_final ? 0x03C2 : 0x03C3);
    return;
  }
  if (cp >= 0xAC00 && cp <= 0xD7A3) {  // Hangul syllable: algorithmic NFD
    uint32_t s = cp - 0xAC00;
    out.push_back(0x1100 + s / 588);
    out.push_back(0x1161 + (s % 588) / 28);
    if (s % 28) out.push_back(0x11A7 + s % 28);
    return;
  }
  size_t lo = 0, hi = kFoldCount;
  while (lo < hi) {
    size_t mid = (lo + hi) / 2;
    if (kFoldKeys[mid] < cp) lo = mid + 1; else hi = mid;
  }
  if (lo < kFoldCount && kFoldKeys[lo] == cp) {
    // An all-zero entry means "drop" (standalone combining marks).
    for (int j = 0; j < 3 && kFoldVals[lo][j]; j++)
      out.push_back(kFoldVals[lo][j]);
    return;
  }
  out.push_back(cp);
}

// ---------------------------------------------------------------------------
// WordPiece tokenizer
// ---------------------------------------------------------------------------

struct Tokenizer {
  std::unordered_map<std::string, int> vocab;
  std::vector<std::string> id_to_token;
  bool lowercase = true;
  int unk_id = 0;
  // Spec: reference tokenization.py:181 (chars = CODEPOINTS, not bytes).
  size_t max_chars_per_word = 100;
  size_t max_token_len = 0;  // longest vocab entry (bytes), bounds matching
  // Special tokens pass through basic_tokenize verbatim — no lowercase,
  // no accent strip, no punctuation split (reference tokenization.py:64-75).
  std::unordered_set<std::string> never_split{
      "[UNK]", "[SEP]", "[PAD]", "[CLS]", "[MASK]"};

  std::vector<int> last_ids;           // result buffers for the C API
  std::string last_tokens_joined;      // '\n'-joined token strings
};

// Normalize + split into word/punct chunks (BasicTokenizer semantics:
// clean -> CJK isolation -> whitespace split -> per-token never_split
// passthrough OR lower+NFD-strip -> punctuation split).
std::vector<std::string> basic_tokenize(const Tokenizer& t,
                                        const std::string& text) {
  // Pass 1: clean + CJK isolation + whitespace split. No case folding yet:
  // never_split matching and the Final_Sigma rule need the whole raw token.
  std::vector<std::string> words;
  std::string current;
  auto flush_word = [&]() {
    if (!current.empty()) { words.push_back(current); current.clear(); }
  };
  size_t i = 0;
  while (i < text.size()) {
    uint32_t cp = decode_utf8(text, i);
    if (cp == 0 || cp == 0xFFFD || is_control(cp)) continue;
    if (is_whitespace(cp)) { flush_word(); continue; }
    if (is_cjk(cp)) {  // CJK chars become standalone tokens
      flush_word();
      std::string c; encode_utf8(cp, c); words.push_back(c);
      continue;
    }
    encode_utf8(cp, current);
  }
  flush_word();

  // Pass 2: per whitespace token, fold + punctuation split.
  std::vector<std::string> out;
  std::vector<uint32_t> cps, folded;
  for (const auto& word : words) {
    if (t.never_split.count(word)) { out.push_back(word); continue; }
    cps.clear();
    for (size_t j = 0; j < word.size();) cps.push_back(decode_utf8(word, j));
    folded.clear();
    if (t.lowercase) {
      for (size_t j = 0; j < cps.size(); j++)
        fold_cp(cps[j],
                cps[j] == 0x03A3 && sigma_is_final(cps, j), folded);
    } else {
      folded = cps;
    }
    std::string chunk;
    auto flush_chunk = [&]() {
      if (!chunk.empty()) { out.push_back(chunk); chunk.clear(); }
    };
    for (uint32_t cp : folded) {
      if (is_punct(cp)) {
        flush_chunk();
        std::string c; encode_utf8(cp, c); out.push_back(c);
      } else {
        encode_utf8(cp, chunk);
      }
    }
    flush_chunk();
  }
  return out;
}

// Greedy longest-match WordPiece on one word (already normalized).
void wordpiece(const Tokenizer& t, const std::string& word,
               std::vector<int>& ids, std::vector<std::string>& tokens) {
  size_t n_chars = 0;
  for (size_t i = 0; i < word.size();) {
    decode_utf8(word, i);
    n_chars++;
  }
  if (n_chars > t.max_chars_per_word) {
    ids.push_back(t.unk_id);
    tokens.push_back(t.id_to_token[t.unk_id]);
    return;
  }
  std::vector<int> piece_ids;
  std::vector<std::string> piece_tokens;
  size_t start = 0;
  while (start < word.size()) {
    size_t end = word.size();
    int found = -1;
    std::string found_tok;
    while (start < end) {
      std::string sub = word.substr(start, end - start);
      if (start > 0) sub = "##" + sub;
      if (sub.size() <= t.max_token_len) {
        auto it = t.vocab.find(sub);
        if (it != t.vocab.end()) { found = it->second; found_tok = sub; break; }
      }
      // step back one UTF-8 codepoint
      do { end--; } while (end > start && (word[end] & 0xC0) == 0x80);
    }
    if (found < 0) {
      ids.push_back(t.unk_id);
      tokens.push_back(t.id_to_token[t.unk_id]);
      return;
    }
    piece_ids.push_back(found);
    piece_tokens.push_back(found_tok);
    start = end;
  }
  ids.insert(ids.end(), piece_ids.begin(), piece_ids.end());
  tokens.insert(tokens.end(), piece_tokens.begin(), piece_tokens.end());
}

// ---------------------------------------------------------------------------
// WordPiece vocab trainer (pair-merge over word counts)
// ---------------------------------------------------------------------------

struct TrainerState {
  // Global word counts across all input files (one entry per distinct word
  // — counting per-file and appending would duplicate frequent words N×
  // and inflate every merge iteration's scan by the same factor).
  std::unordered_map<std::string, long> counts;
  // Built from `counts` once at train time: each word as a sequence of
  // symbols; continuation symbols carry "##".
  std::vector<std::pair<std::vector<std::string>, long>> words;
};

void trainer_count_file(TrainerState& st, Tokenizer& norm,
                        const std::string& path) {
  std::ifstream in(path);
  std::string line;
  while (std::getline(in, line)) {
    for (const auto& w : basic_tokenize(norm, line)) st.counts[w] += 1;
  }
}

void trainer_build_words(TrainerState& st) {
  st.words.clear();
  st.words.reserve(st.counts.size());
  for (auto& kv : st.counts) {
    std::vector<std::string> symbols;
    size_t i = 0;
    bool first = true;
    while (i < kv.first.size()) {
      size_t j = i;
      decode_utf8(kv.first, j);
      std::string sym = kv.first.substr(i, j - i);
      symbols.push_back(first ? sym : "##" + sym);
      first = false;
      i = j;
    }
    st.words.emplace_back(std::move(symbols), kv.second);
  }
}

std::vector<std::string> trainer_run(TrainerState& st, size_t vocab_size,
                                     const std::vector<std::string>& specials,
                                     long min_frequency) {
  trainer_build_words(st);
  // Alphabet first.
  std::map<std::string, long> alphabet;
  for (auto& [symbols, count] : st.words)
    for (auto& s : symbols) alphabet[s] += count;

  std::vector<std::string> vocab(specials);
  for (auto& kv : alphabet) vocab.push_back(kv.first);

  auto merged_symbol = [](const std::string& a, const std::string& b) {
    // "fo" + "##o" -> "foo"; "##f" + "##oo" -> "##foo"
    return a + (b.rfind("##", 0) == 0 ? b.substr(2) : b);
  };

  while (vocab.size() < vocab_size) {
    std::map<std::pair<std::string, std::string>, long> pair_counts;
    for (auto& [symbols, count] : st.words)
      for (size_t i = 0; i + 1 < symbols.size(); i++)
        pair_counts[{symbols[i], symbols[i + 1]}] += count;
    if (pair_counts.empty()) break;
    auto best = std::max_element(
        pair_counts.begin(), pair_counts.end(),
        [](const auto& a, const auto& b) { return a.second < b.second; });
    if (best->second < min_frequency) break;
    const auto [left, right] = best->first;
    std::string merged = merged_symbol(left, right);
    vocab.push_back(merged);
    for (auto& [symbols, count] : st.words) {
      std::vector<std::string> out;
      out.reserve(symbols.size());
      size_t i = 0;
      while (i < symbols.size()) {
        if (i + 1 < symbols.size() && symbols[i] == left &&
            symbols[i + 1] == right) {
          out.push_back(merged);
          i += 2;
        } else {
          out.push_back(symbols[i]);
          i += 1;
        }
      }
      symbols = std::move(out);
    }
  }
  return vocab;
}

// ---------------------------------------------------------------------------
// Byte-level BPE (GPT-2/RoBERTa; reference src/tokenization.py:51-57 drives
// HF ByteLevelBPETokenizer — this is the C++ equivalent of its encode path)
// ---------------------------------------------------------------------------

// Unicode letter/number classes for the GPT-2 pre-tokenizer regex
// (\p{L}/\p{N}). Covers ASCII, Latin-1/Extended, Greek, Cyrillic, CJK,
// kana, and Hangul — the scripts in BERT/RoBERTa's corpora; exotic scripts
// degrade to the punctuation branch, mirroring the fold-table stance above.
bool is_letter(uint32_t cp) {
  if ((cp >= 'a' && cp <= 'z') || (cp >= 'A' && cp <= 'Z')) return true;
  if (cp == 0x00AA || cp == 0x00B5 || cp == 0x00BA) return true;
  if (cp >= 0x00C0 && cp <= 0x02AF && cp != 0x00D7 && cp != 0x00F7) return true;
  if (cp >= 0x0386 && cp <= 0x03FF && cp != 0x0387) return true;  // Greek
  if (cp >= 0x0400 && cp <= 0x04FF) return true;                  // Cyrillic
  // Kana LETTERS only: the block also holds combining sound marks
  // (U+3099-U+309C), the interpunct U+30FB, and U+30A0 (punctuation),
  // which \p{L} excludes.
  if ((cp >= 0x3041 && cp <= 0x3096) || (cp >= 0x309D && cp <= 0x309F) ||
      (cp >= 0x30A1 && cp <= 0x30FA) || (cp >= 0x30FC && cp <= 0x30FF))
    return true;
  if (cp >= 0xAC00 && cp <= 0xD7A3) return true;                  // Hangul
  return is_cjk(cp);
}

bool is_number(uint32_t cp) {
  if (cp >= '0' && cp <= '9') return true;
  return cp == 0x00B2 || cp == 0x00B3 || cp == 0x00B9 ||
         (cp >= 0x00BC && cp <= 0x00BE) || (cp >= 0x0660 && cp <= 0x0669);
}

// \s of the GPT-2 regex (Unicode whitespace).
bool is_bpe_space(uint32_t cp) {
  return is_whitespace(cp) || cp == 0x0B || cp == 0x0C || cp == 0x85 ||
         cp == 0x2028 || cp == 0x2029;
}

uint32_t simple_lower(uint32_t cp) {
  // HF Lowercase normalizer (no accent strip). ASCII + Latin-1 + Greek +
  // Cyrillic. Latin Extended-A pairs upper/lower adjacently but the parity
  // FLIPS at U+0138 (and Ÿ lives at U+0178 with its lowercase back in
  // Latin-1), so the ranges are spelled out.
  if (cp >= 'A' && cp <= 'Z') return cp + 32;
  if (cp >= 0x00C0 && cp <= 0x00DE && cp != 0x00D7) return cp + 32;
  if (cp >= 0x0100 && cp <= 0x0137 && cp % 2 == 0) return cp + 1;
  if (cp >= 0x0139 && cp <= 0x0148 && cp % 2 == 1) return cp + 1;
  if (cp >= 0x014A && cp <= 0x0177 && cp % 2 == 0) return cp + 1;
  if (cp == 0x0178) return 0x00FF;
  if (cp >= 0x0179 && cp <= 0x017E && cp % 2 == 1) return cp + 1;
  if (cp == 0x0386) return 0x03AC;                       // accented Greek
  if (cp >= 0x0388 && cp <= 0x038A) return cp + 0x25;
  if (cp == 0x038C) return 0x03CC;
  if (cp == 0x038E || cp == 0x038F) return cp + 0x3F;
  if (cp == 0x03AA || cp == 0x03AB) return cp + 0x20;
  if (cp >= 0x0391 && cp <= 0x03A9 && cp != 0x03A2) return cp + 32;
  if (cp >= 0x0400 && cp <= 0x040F) return cp + 0x50;    // Ѐ-Џ -> ѐ-џ
  if (cp >= 0x0410 && cp <= 0x042F) return cp + 32;
  return cp;
}

// Lowercase a UTF-8 string via simple_lower (shared by bpe_encode and the
// trainer so training and encoding segment words identically).
std::string lower_utf8(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  size_t i = 0;
  while (i < s.size()) encode_utf8(simple_lower(decode_utf8(s, i)), out);
  return out;
}

// Merge every adjacent (left, right) occurrence left-to-right — THE merge
// semantics; bpe_word and the trainer must agree on it exactly.
std::vector<std::string> apply_merge(const std::vector<std::string>& symbols,
                                     const std::string& left,
                                     const std::string& right) {
  std::vector<std::string> out;
  out.reserve(symbols.size());
  for (size_t i = 0; i < symbols.size();) {
    if (i + 1 < symbols.size() && symbols[i] == left &&
        symbols[i + 1] == right) {
      out.push_back(left + right);
      i += 2;
    } else {
      out.push_back(symbols[i]);
      i += 1;
    }
  }
  return out;
}

struct BpeTokenizer {
  std::unordered_map<std::string, int> vocab;  // byte-mapped token -> id
  std::vector<std::string> id_to_token;
  // merge pair "left\x01right" -> rank (lower merges first)
  std::unordered_map<std::string, int> merges;
  bool lowercase = false;
  std::string byte_to_uni[256];  // UTF-8 of each byte's mapped codepoint
  std::unordered_map<std::string, std::vector<int>> cache;  // pretoken -> ids

  std::vector<int> last_ids;
  std::string last_tokens_joined;
};

void init_byte_map(BpeTokenizer& t) {
  // GPT-2 bytes_to_unicode: printable bytes keep their codepoint, the rest
  // are assigned 256, 257, ... in byte order.
  int next = 0;
  for (int b = 0; b < 256; b++) {
    bool printable = (b >= 33 && b <= 126) || (b >= 161 && b <= 172) ||
                     (b >= 174 && b <= 255);
    uint32_t cp = printable ? static_cast<uint32_t>(b)
                            : static_cast<uint32_t>(256 + next++);
    encode_utf8(cp, t.byte_to_uni[b]);
  }
}

// GPT-2 pre-tokenizer:
//   's|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+
// implemented as a hand scanner over codepoints (same match order).
std::vector<std::string> bpe_pretokenize(const std::string& text) {
  // Decode once into (codepoint, byte offset) pairs.
  std::vector<uint32_t> cps;
  std::vector<size_t> offs;
  size_t i = 0;
  while (i < text.size()) {
    offs.push_back(i);
    cps.push_back(decode_utf8(text, i));
  }
  offs.push_back(text.size());
  const size_t n = cps.size();

  auto slice = [&](size_t a, size_t b) {
    return text.substr(offs[a], offs[b] - offs[a]);
  };
  std::vector<std::string> out;
  size_t p = 0;
  while (p < n) {
    // contractions (no leading space)
    if (cps[p] == '\'' && p + 1 < n) {
      uint32_t c1 = cps[p + 1];
      if (c1 == 's' || c1 == 't' || c1 == 'm' || c1 == 'd') {
        out.push_back(slice(p, p + 2)); p += 2; continue;
      }
      if (p + 2 < n &&
          ((c1 == 'r' && cps[p + 2] == 'e') ||
           (c1 == 'v' && cps[p + 2] == 'e') ||
           (c1 == 'l' && cps[p + 2] == 'l'))) {
        out.push_back(slice(p, p + 3)); p += 3; continue;
      }
    }
    // " ?\p{L}+" / " ?\p{N}+" / " ?[^\s\p{L}\p{N}]+"
    size_t start = p;
    size_t q = p;
    if (cps[q] == ' ' && q + 1 < n && !is_bpe_space(cps[q + 1])) q++;
    if (q < n && is_letter(cps[q])) {
      while (q < n && is_letter(cps[q])) q++;
      out.push_back(slice(start, q)); p = q; continue;
    }
    if (q < n && is_number(cps[q])) {
      while (q < n && is_number(cps[q])) q++;
      out.push_back(slice(start, q)); p = q; continue;
    }
    if (q < n && !is_bpe_space(cps[q])) {
      while (q < n && !is_bpe_space(cps[q]) && !is_letter(cps[q]) &&
             !is_number(cps[q]))
        q++;
      out.push_back(slice(start, q)); p = q; continue;
    }
    // whitespace runs: "\s+(?!\S)" then "\s+"
    q = p;
    while (q < n && is_bpe_space(cps[q])) q++;
    if (q < n && q - p >= 2) {
      // followed by non-space: leave the last whitespace char for the
      // next token's optional leading space
      out.push_back(slice(p, q - 1));
      p = q - 1;
      // a trailing single non-' ' whitespace becomes its own \s+ token
      if (cps[p] != ' ') { out.push_back(slice(p, p + 1)); p += 1; }
      continue;
    }
    if (q == n) { out.push_back(slice(p, q)); p = q; continue; }
    // single whitespace followed by non-space
    if (cps[p] == ' ') {
      // handled by the " ?" branches above unless followed by space (ruled
      // out) — reaching here means ' ' followed by something the classes
      // all rejected; emit it alone.
      out.push_back(slice(p, p + 1)); p += 1; continue;
    }
    out.push_back(slice(p, p + 1));
    p += 1;
  }
  return out;
}

// Ranked merge loop on one pre-token (bytes already mapped to symbols).
std::vector<int> bpe_word(BpeTokenizer& t, const std::string& pretoken) {
  auto cached = t.cache.find(pretoken);
  if (cached != t.cache.end()) return cached->second;

  std::vector<std::string> symbols;
  for (unsigned char b : pretoken) symbols.push_back(t.byte_to_uni[b]);

  while (symbols.size() > 1) {
    int best_rank = INT32_MAX;
    size_t best_i = 0;
    for (size_t i = 0; i + 1 < symbols.size(); i++) {
      auto it = t.merges.find(symbols[i] + '\x01' + symbols[i + 1]);
      if (it != t.merges.end() && it->second < best_rank) {
        best_rank = it->second;
        best_i = i;
      }
    }
    if (best_rank == INT32_MAX) break;
    symbols = apply_merge(symbols, symbols[best_i], symbols[best_i + 1]);
  }

  std::vector<int> ids;
  ids.reserve(symbols.size());
  for (auto& s : symbols) {
    auto it = t.vocab.find(s);
    // HF ByteLevelBPE has no unk token: out-of-vocab symbols are DROPPED
    // (only reachable when the vocab lacks part of the byte alphabet).
    if (it != t.vocab.end()) ids.push_back(it->second);
  }
  if (t.cache.size() < 65536) t.cache.emplace(pretoken, ids);
  return ids;
}

// ---------------------------------------------------------------------------
// Byte-level BPE trainer (the ByteLevelBPETokenizer.train role:
// reference utils/build_vocab.py:39-58's BPE branch)
// ---------------------------------------------------------------------------

// Minimal JSON string escape for vocab.json keys (symbols are printable
// mapped-unicode; only quote/backslash need escaping).
std::string json_escape(const std::string& s) {
  std::string out;
  for (char c : s) {
    if (c == '"' || c == '\\') out.push_back('\\');
    out.push_back(c);
  }
  return out;
}

int bpe_train_impl(const std::string& files, const std::string& specials,
                   size_t vocab_size, long min_frequency, bool lowercase,
                   const std::string& out_dir) {
  BpeTokenizer map_only;
  init_byte_map(map_only);

  // 1. Pre-token counts across all files (GPT-2 pre-tokenizer, same as
  //    encode — training and encoding must agree on word boundaries).
  std::unordered_map<std::string, long> counts;
  std::stringstream fs(files);
  std::string path;
  while (std::getline(fs, path, '\n')) {
    if (path.empty()) continue;
    std::ifstream in(path);
    if (!in) return 2;
    std::string line;
    while (std::getline(in, line)) {
      if (lowercase) line = lower_utf8(line);
      for (auto& pre : bpe_pretokenize(line)) counts[pre] += 1;
    }
  }

  // 2. Words as byte-mapped symbol sequences.
  std::vector<std::pair<std::vector<std::string>, long>> words;
  words.reserve(counts.size());
  for (auto& kv : counts) {
    std::vector<std::string> symbols;
    for (unsigned char b : kv.first) symbols.push_back(map_only.byte_to_uni[b]);
    words.emplace_back(std::move(symbols), kv.second);
  }

  // 3. Vocab: specials, then the full 256-byte alphabet sorted by mapped
  //    codepoint (HF ByteLevel.alphabet() semantics), then merges in order.
  std::vector<std::string> vocab;
  std::stringstream ss(specials);
  std::string sp;
  while (std::getline(ss, sp, '\n'))
    if (!sp.empty()) vocab.push_back(sp);
  {
    std::vector<std::string> alphabet(map_only.byte_to_uni,
                                      map_only.byte_to_uni + 256);
    std::sort(alphabet.begin(), alphabet.end());
    vocab.insert(vocab.end(), alphabet.begin(), alphabet.end());
  }

  std::vector<std::pair<std::string, std::string>> merges_out;
  while (vocab.size() < vocab_size) {
    std::map<std::pair<std::string, std::string>, long> pair_counts;
    for (auto& [symbols, count] : words)
      for (size_t i = 0; i + 1 < symbols.size(); i++)
        pair_counts[{symbols[i], symbols[i + 1]}] += count;
    if (pair_counts.empty()) break;
    // Highest count; ties break to the lexicographically smallest pair
    // (std::map iteration order), deterministically.
    auto best = pair_counts.begin();
    for (auto it = pair_counts.begin(); it != pair_counts.end(); ++it)
      if (it->second > best->second) best = it;
    if (best->second < min_frequency) break;
    const auto [left, right] = best->first;
    merges_out.emplace_back(left, right);
    vocab.push_back(left + right);
    for (auto& [symbols, count] : words)
      symbols = apply_merge(symbols, left, right);
  }

  std::ofstream vout(out_dir + "/vocab.json");
  if (!vout) return 1;
  vout << "{";
  for (size_t i = 0; i < vocab.size(); i++) {
    if (i) vout << ",";
    vout << "\"" << json_escape(vocab[i]) << "\":" << i;
  }
  vout << "}\n";
  std::ofstream mout(out_dir + "/merges.txt");
  if (!mout) return 1;
  mout << "#version: 0.2\n";
  for (auto& [l, r] : merges_out) mout << l << " " << r << "\n";
  return 0;
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

void* wp_create(const char* vocab_path, int lowercase) {
  auto* t = new Tokenizer();
  t->lowercase = lowercase != 0;
  std::ifstream in(vocab_path);
  if (!in) { delete t; return nullptr; }
  std::string line;
  int index = 0;
  while (std::getline(in, line)) {
    if (!line.empty() && line.back() == '\r') line.pop_back();
    if (line.empty()) continue;
    t->vocab.emplace(line, index);
    t->id_to_token.push_back(line);
    t->max_token_len = std::max(t->max_token_len, line.size());
    index++;
  }
  auto unk = t->vocab.find("[UNK]");
  t->unk_id = unk == t->vocab.end() ? 0 : unk->second;
  return t;
}

void wp_free(void* handle) { delete static_cast<Tokenizer*>(handle); }

int wp_vocab_size(void* handle) {
  return static_cast<int>(static_cast<Tokenizer*>(handle)->id_to_token.size());
}

int wp_token_to_id(void* handle, const char* token) {
  auto* t = static_cast<Tokenizer*>(handle);
  auto it = t->vocab.find(token);
  return it == t->vocab.end() ? -1 : it->second;
}

const char* wp_id_to_token(void* handle, int id) {
  auto* t = static_cast<Tokenizer*>(handle);
  if (id < 0 || id >= static_cast<int>(t->id_to_token.size())) return "";
  return t->id_to_token[id].c_str();
}

// Encode text; returns number of tokens. Fetch results with wp_get_ids /
// wp_get_tokens (valid until the next encode on this handle).
// len-aware so embedded NUL bytes don't truncate the input (they are
// control chars the normalizer drops, but the text AFTER them must survive).
int wp_encode(void* handle, const char* text, int len) {
  auto* t = static_cast<Tokenizer*>(handle);
  t->last_ids.clear();
  t->last_tokens_joined.clear();
  std::vector<std::string> tokens;
  for (const auto& word :
       basic_tokenize(*t, std::string(text, static_cast<size_t>(len))))
    wordpiece(*t, word, t->last_ids, tokens);
  for (size_t i = 0; i < tokens.size(); i++) {
    if (i) t->last_tokens_joined.push_back('\n');
    t->last_tokens_joined += tokens[i];
  }
  return static_cast<int>(t->last_ids.size());
}

const int* wp_get_ids(void* handle) {
  return static_cast<Tokenizer*>(handle)->last_ids.data();
}

const char* wp_get_tokens(void* handle) {
  return static_cast<Tokenizer*>(handle)->last_tokens_joined.c_str();
}

// --- byte-level BPE ---------------------------------------------------------

// vocab_lines: '\n'-joined tokens in id order (byte-mapped strings contain
// no raw whitespace, so the framing is safe); merges_lines: '\n'-joined
// "left right" pairs in rank order (the merges.txt body).
void* bpe_create(const char* vocab_lines, const char* merges_lines,
                 int lowercase) {
  auto* t = new BpeTokenizer();
  t->lowercase = lowercase != 0;
  init_byte_map(*t);
  std::stringstream vs(vocab_lines);
  std::string line;
  while (std::getline(vs, line, '\n')) {
    t->vocab.emplace(line, static_cast<int>(t->id_to_token.size()));
    t->id_to_token.push_back(line);
  }
  std::stringstream ms(merges_lines);
  int rank = 0;
  bool first_line = true;
  while (std::getline(ms, line, '\n')) {
    // Only the leading "#version: ..." header is a comment — a merge whose
    // left symbol starts with '#' (e.g. "# #") is legitimate data.
    bool header = first_line && line.rfind("#version", 0) == 0;
    first_line = false;
    if (line.empty() || header) continue;
    size_t sp = line.find(' ');
    if (sp == std::string::npos) continue;
    t->merges.emplace(line.substr(0, sp) + '\x01' + line.substr(sp + 1),
                      rank++);
  }
  return t;
}

void bpe_free(void* handle) { delete static_cast<BpeTokenizer*>(handle); }

int bpe_vocab_size(void* handle) {
  return static_cast<int>(
      static_cast<BpeTokenizer*>(handle)->id_to_token.size());
}

int bpe_token_to_id(void* handle, const char* token) {
  auto* t = static_cast<BpeTokenizer*>(handle);
  auto it = t->vocab.find(token);
  return it == t->vocab.end() ? -1 : it->second;
}

const char* bpe_id_to_token(void* handle, int id) {
  auto* t = static_cast<BpeTokenizer*>(handle);
  if (id < 0 || id >= static_cast<int>(t->id_to_token.size())) return "";
  return t->id_to_token[id].c_str();
}

int bpe_encode(void* handle, const char* text_c, int len) {
  auto* t = static_cast<BpeTokenizer*>(handle);
  t->last_ids.clear();
  t->last_tokens_joined.clear();
  std::string text(text_c, static_cast<size_t>(len));
  if (t->lowercase) text = lower_utf8(text);
  for (const auto& pre : bpe_pretokenize(text)) {
    for (int id : bpe_word(*t, pre)) t->last_ids.push_back(id);
  }
  for (size_t i = 0; i < t->last_ids.size(); i++) {
    if (i) t->last_tokens_joined.push_back('\n');
    t->last_tokens_joined += t->id_to_token[t->last_ids[i]];
  }
  return static_cast<int>(t->last_ids.size());
}

const int* bpe_get_ids(void* handle) {
  return static_cast<BpeTokenizer*>(handle)->last_ids.data();
}

const char* bpe_get_tokens(void* handle) {
  return static_cast<BpeTokenizer*>(handle)->last_tokens_joined.c_str();
}

// Train a byte-level BPE; writes vocab.json + merges.txt into out_dir.
// Returns 0 on success, 1 on write failure, 2 on unreadable input.
int bpe_train(const char* files, const char* specials, int vocab_size,
              int min_frequency, int lowercase, const char* out_dir) {
  return bpe_train_impl(files, specials, static_cast<size_t>(vocab_size),
                        min_frequency, lowercase != 0, out_dir);
}

// Train a WordPiece vocab from newline-delimited text files.
// files: '\n'-joined list of paths. specials: '\n'-joined special tokens
// (placed first, [PAD] at 0 per reference utils/build_vocab.py:64-75).
// Returns 0 on success; writes one token per line to out_path.
int wp_train(const char* files, const char* specials, int vocab_size,
             int min_frequency, int lowercase, const char* out_path) {
  Tokenizer norm;
  norm.lowercase = lowercase != 0;
  TrainerState st;
  std::stringstream fs(files);
  std::string path;
  while (std::getline(fs, path, '\n'))
    if (!path.empty()) trainer_count_file(st, norm, path);

  std::vector<std::string> specials_list;
  std::stringstream ss(specials);
  std::string sp;
  while (std::getline(ss, sp, '\n'))
    if (!sp.empty()) specials_list.push_back(sp);

  auto vocab = trainer_run(st, static_cast<size_t>(vocab_size), specials_list,
                           min_frequency);
  std::ofstream out(out_path);
  if (!out) return 1;
  for (auto& tok : vocab) out << tok << "\n";
  return 0;
}

}  // extern "C"
