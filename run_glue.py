"""GLUE finetuning runner — sequence classification / regression on TPU.

Beyond-reference capability: the reference downloads GLUE
(utils/download.py:81-101) but has no runner that consumes it; this closes
the loop with a `BertForSequenceClassification` finetune in the classic BERT
GLUE recipe (lr 2e-5, 3 epochs, warmup 0.1, AdamW, max_seq 128). All nine
tasks from the downloader's TSV layout are supported
(:mod:`bert_pytorch_tpu.data.glue`), including the STS-B regression path
(num_labels=1, MSE) and MNLI's matched/mismatched dev sets.

Follows the same conventions as run_ner.py / run_squad.py: model config
JSON supplies vocab/tokenizer, ``--init_checkpoint`` accepts this
framework's checkpoints or foreign (torch/TF) archives, results land in a
dllogger-style one-line JSON summary.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from bert_pytorch_tpu import optim, telemetry
from bert_pytorch_tpu.config import BertConfig
from bert_pytorch_tpu.data import DevicePrefetcher, glue
from bert_pytorch_tpu.data.tokenization import (
    get_bpe_tokenizer,
    get_wordpiece_tokenizer,
)
from bert_pytorch_tpu.models import BertForSequenceClassification
from bert_pytorch_tpu.models.losses import _xent_ignore
from bert_pytorch_tpu.ops.grad_utils import clip_by_global_norm
from bert_pytorch_tpu.utils import checkpoint as ckpt
from bert_pytorch_tpu.utils import logging as logger
from bert_pytorch_tpu.utils import preemption
from bert_pytorch_tpu.utils.compile_cache import enable_compile_cache


def parse_arguments(argv=None):
    parser = argparse.ArgumentParser(description="TPU BERT GLUE finetuning")
    parser.add_argument("--task", type=str, required=True,
                        choices=sorted(glue.PROCESSORS))
    parser.add_argument("--data_dir", type=str, required=True,
                        help="Directory holding the task's train/dev TSVs")
    parser.add_argument("--model_config_file", type=str, required=True)
    parser.add_argument("--init_checkpoint", type=str, default=None)
    parser.add_argument("--output_dir", type=str, default=None)
    parser.add_argument("--vocab_file", type=str, default=None)
    parser.add_argument("--uppercase", action="store_true")
    parser.add_argument("--tokenizer", type=str, default=None,
                        choices=["wordpiece", "bpe"])
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--lr", type=float, default=2e-5)
    parser.add_argument("--warmup_proportion", type=float, default=0.1)
    parser.add_argument("--clip_grad", type=float, default=1.0)
    parser.add_argument("--batch_size", type=int, default=32)
    parser.add_argument("--max_seq_len", type=int, default=128)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--compile_cache_dir", type=str, default="",
                        help="persistent XLA compilation cache directory; empty disables")
    parser.add_argument("--dtype", type=str, default="bfloat16",
                        choices=["bfloat16", "float32"])
    parser.add_argument("--skip_eval", action="store_true")
    parser.add_argument("--save_steps", type=int, default=0,
                        help="periodic checkpoint cadence (optimizer "
                             "steps): saves ride the ASYNC write path "
                             "(device snapshot + background write, "
                             "utils/checkpoint.py) so the loop never "
                             "blocks on disk; the final/emergency "
                             "checkpoint stays synchronous. 0 disables")
    # device prefetch: stage batches onto device ahead of the loop
    # (data/device_prefetch.py; one flag shared by every runner)
    from bert_pytorch_tpu.data import device_prefetch as dp_cli
    dp_cli.add_cli_args(parser)
    # telemetry (docs/telemetry.md)
    # telemetry: canonical flag set shared by every runner. Default
    # sync cadence stays 1: these are small models where a per-step
    # sync is cheap and step-exact sentinels are worth it — but since
    # PR 7 the loop itself no longer fetches the loss per step (it
    # accumulates on device; jaxlint HS101), so a user-set
    # --telemetry_sync_every N genuinely syncs only every Nth step
    # (telemetry/cli.py; docs/telemetry.md)
    telemetry.add_cli_args(parser, sync_every_default=1)
    args = parser.parse_args(argv)

    with open(args.model_config_file) as f:
        configs = json.load(f)
    if args.vocab_file is None:
        args.vocab_file = configs.get("vocab_file")
        if args.vocab_file is None:
            raise ValueError("vocab_file must be in model config or CLI")
    if args.tokenizer is None:
        args.tokenizer = configs.get("tokenizer", "wordpiece")
    return args


def batches(arrays: dict, batch_size: int, shuffle: bool, rng):
    """Yield dict minibatches; the last partial batch is padded to a full
    batch with repeated rows plus a ``valid`` mask so every jitted call sees
    one static shape (one compile, XLA-friendly)."""
    n = len(arrays["labels"])
    order = rng.permutation(n) if shuffle else np.arange(n)
    for i in range(0, n, batch_size):
        idx = order[i:i + batch_size]
        valid = np.ones(batch_size, bool)
        if len(idx) < batch_size:
            valid[len(idx):] = False
            idx = np.concatenate([idx, np.zeros(batch_size - len(idx), idx.dtype)])
        yield {k: v[idx] for k, v in arrays.items()}, valid


def main(args):
    enable_compile_cache(args.compile_cache_dir)
    processor = glue.PROCESSORS[args.task]()
    regression = processor.regression
    num_labels = 1 if regression else len(processor.labels)
    telemetry_jsonl = telemetry.default_jsonl_path(
        args, args.output_dir, "glue")
    telemetry_sink = (logger.JSONLHandler(telemetry_jsonl, overwrite=False)
                      if telemetry_jsonl else None)
    logger.init(handlers=[logger.StreamHandler()]
                + ([telemetry_sink] if telemetry_sink else []))

    if args.tokenizer == "wordpiece":
        tokenizer = get_wordpiece_tokenizer(args.vocab_file,
                                            uppercase=args.uppercase)
    else:
        tokenizer = get_bpe_tokenizer(args.vocab_file, uppercase=args.uppercase)

    splits = {"train": processor.get_train_examples(args.data_dir)}
    if not args.skip_eval:
        splits["dev"] = processor.get_dev_examples(args.data_dir)
    arrays = {
        name: glue.features_to_arrays(
            glue.convert_examples_to_features(
                examples, tokenizer, args.max_seq_len,
                processor.labels, regression),
            regression)
        for name, examples in splits.items()
    }
    logger.info(
        f"task={args.task} train={len(arrays['train']['labels'])} "
        + (f"dev={len(arrays['dev']['labels'])}" if "dev" in arrays else "")
    )

    config = BertConfig.from_json_file(args.model_config_file)
    if config.vocab_size % 8 != 0:
        config.vocab_size += 8 - (config.vocab_size % 8)
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    model = BertForSequenceClassification(config, num_labels=num_labels,
                                          dtype=dtype)

    sample = (jnp.zeros((1, args.max_seq_len), jnp.int32),) * 3
    import flax.linen as nn

    params = nn.unbox(
        model.init(jax.random.PRNGKey(args.seed), *sample))["params"]
    if args.init_checkpoint:
        from bert_pytorch_tpu.models import load_pretrained_encoder

        params = load_pretrained_encoder(args.init_checkpoint, config, params)
        logger.info(f"loaded pretrained encoder from {args.init_checkpoint}")

    steps_per_epoch = max(
        1, -(-len(arrays["train"]["labels"]) // args.batch_size))
    total_steps = steps_per_epoch * args.epochs
    schedule = optim.warmup_linear_schedule(
        args.lr, args.warmup_proportion, total_steps)
    # bias_correction=False for parity with the sibling finetune runners'
    # FusedAdam recipe (run_squad.py, run_ner.py; optim/transforms.py).
    tx = optim.adamw(schedule, weight_decay=0.01, bias_correction=False,
                     weight_decay_mask=optim.no_decay_mask)
    opt_state = tx.init(params)

    def loss_from_logits(logits, labels, valid):
        weights = valid.astype(jnp.float32)
        if regression:
            err = (logits.squeeze(-1).astype(jnp.float32) - labels) ** 2
            return jnp.sum(err * weights) / jnp.maximum(weights.sum(), 1.0)
        return _xent_ignore(
            logits.astype(jnp.float32), jnp.where(valid, labels, -1), -1)

    stats_every = telemetry.stats_every(args)

    def train_step(params, opt_state, batch, valid, dropout_rng):
        def loss_fn(p):
            logits = model.apply(
                {"params": p}, batch["input_ids"], batch["segment_ids"],
                batch["input_mask"], False, rngs={"dropout": dropout_rng})
            return loss_from_logits(logits, batch["labels"], valid)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads, _ = clip_by_global_norm(grads, args.clip_grad)
        updates, opt_state2 = tx.update(grads, opt_state, params)
        metrics = {"loss": loss}
        health = telemetry.finetune_grad_health(
            params, grads, updates, opt_state, stats_every)
        if health is not None:
            metrics["grad_health"] = health
        return optax.apply_updates(params, updates), opt_state2, metrics

    # Telemetry facade (docs/telemetry.md): step-time windows + MFU, trace
    # window, compile attribution, loss sentinel, optional heartbeat.
    from bert_pytorch_tpu.utils import flops as flops_util
    tele = telemetry.from_args(
        args,
        sink=telemetry_sink,
        seq_per_step=args.batch_size,
        flops_per_seq=flops_util.bert_finetune_flops_per_seq(
            config, args.max_seq_len, head_outputs=num_labels,
            per_token_head=False, pooled=True),
        output_dir=args.output_dir or None,
        process="glue")

    train_step = tele.instrument(
        jax.jit(train_step, donate_argnums=(0, 1)), "train_step")

    @jax.jit
    def eval_step(params, batch):
        return model.apply(
            {"params": params}, batch["input_ids"], batch["segment_ids"],
            batch["input_mask"])

    eval_step = tele.instrument(eval_step, "eval_step")

    def evaluate():
        preds, labels = [], []
        for batch, valid in batches(arrays["dev"], args.batch_size, False,
                                    np.random.default_rng(0)):
            logits = np.asarray(eval_step(params, batch), np.float32)
            out = (logits.squeeze(-1) if regression
                   else logits.argmax(axis=-1))
            preds.append(out[valid])
            labels.append(batch["labels"][valid])
        return glue.compute_metrics(
            args.task, np.concatenate(preds), np.concatenate(labels))

    rng = np.random.default_rng(args.seed)
    key = jax.random.PRNGKey(args.seed)
    t0 = time.perf_counter()
    seen = 0
    global_step = 0
    # Graceful preemption (docs/fault_tolerance.md): stop at the next
    # step boundary, write the emergency checkpoint through the normal
    # end-of-run path, exit EXIT_PREEMPTED. Handlers stay installed
    # THROUGH the checkpoint write below (the grace period may re-deliver
    # the signal; the default disposition would kill the write mid-file)
    # and are restored in the finally even on exceptions.
    stop = preemption.GracefulStop().install()
    prefetcher = None
    try:
        for epoch in range(args.epochs):
            # Epoch loss accumulates ON DEVICE: one scalar add rides each
            # step's dispatch, and the only host fetch is the epoch-end
            # mean. A per-step float(loss) here would be a blocking host
            # sync every step — jaxlint HS101 (docs/static_analysis.md)
            # now enforces what used to be a review-memory rule, and
            # --telemetry_sync_every > 1 actually buys something.
            loss_sum = None
            n_steps = 0
            # Device prefetch: the batch is staged onto device by a
            # background thread while the previous step runs; data_wait
            # then measures only featurization stalls, with the staging
            # share attributed to the h2d_wait sub-phase.
            prefetcher = DevicePrefetcher(
                batches(arrays["train"], args.batch_size, True, rng),
                stage=lambda bv: (jax.device_put(bv[0]), bv[1]),
                depth=args.device_prefetch)
            tele.attach_prefetcher(prefetcher)
            for batch, valid in tele.timed(iter(prefetcher)):
                key, sub = jax.random.split(key)
                tele.profiler.maybe_start(global_step + 1)
                with tele.profiler.annotation(global_step + 1):
                    params, opt_state, metrics = train_step(
                        params, opt_state, batch, valid, sub)
                tele.dispatch_done()
                global_step += 1
                tele.step_done(global_step, metrics)
                loss = metrics["loss"]
                loss_sum = loss if loss_sum is None else loss_sum + loss
                n_steps += 1
                # valid is the host-side numpy padding mask from
                # batches() — the stage fn device_puts only the batch.
                seen += int(valid.sum())  # jaxlint: disable=HS101
                if args.save_steps and args.output_dir \
                        and global_step % args.save_steps == 0:
                    # Periodic save, async: the loop pays the device-side
                    # snapshot only; the write overlaps training
                    # (wait_for_pending_save below joins it before exit).
                    with tele.checkpoint_stall():
                        ckpt.save_checkpoint(
                            args.output_dir, global_step,
                            {"model": params}, async_write=True)
                if stop.requested:
                    break
            prefetcher.close()
            if n_steps:
                logger.info(
                    f"epoch {epoch}: "
                    f"train_loss={float(loss_sum) / n_steps:.4f}")
            if stop.requested:
                logger.info(
                    f"termination signal ({stop.signal_name}) received; "
                    "checkpointing and exiting cleanly "
                    f"(exit code {preemption.EXIT_PREEMPTED})")
                tele.emit(preemption.preemption_record(global_step, stop))
                break
        train_time = time.perf_counter() - t0
        tele.finish(global_step, summary={
            "training_seq_per_sec":
                round(seen / train_time, 2) if train_time else 0.0})

        results = {
            "e2e_train_time": train_time,
            "training_sequences_per_second":
                seen / train_time if train_time else 0,
            "terminated_by_signal": stop.requested,
        }
        if not args.skip_eval and not stop.requested:
            results.update(evaluate())
        logger.info(
            json.dumps({"glue_summary": {"task": args.task, **results}}))

        if args.output_dir:
            os.makedirs(args.output_dir, exist_ok=True)
            # Stamped with the step actually REACHED — a preempted run's
            # emergency checkpoint must not masquerade as a fully-trained
            # ckpt_<total_steps> artifact. SYNCHRONOUS on purpose: this is
            # the durability write before exit, and it joins any in-flight
            # periodic async write to the same directory first. (No
            # checkpoint_stall wrapper: telemetry is already flushed —
            # only in-loop saves feed the ckpt_step windows.)
            ckpt.save_checkpoint(
                args.output_dir, global_step, {"model": params})
            with open(os.path.join(args.output_dir,
                                   f"eval_results_{args.task}.json"),
                      "w") as f:
                json.dump(results, f, indent=2)
        # No exit until any in-flight async periodic write has landed — a
        # fast exit must never truncate one (docs/fault_tolerance.md).
        ckpt.wait_for_pending_save()
    finally:
        if prefetcher is not None:
            prefetcher.close()
        stop.restore()
    logger.close()
    return results


if __name__ == "__main__":
    outcome = main(parse_arguments())
    if outcome.get("terminated_by_signal"):
        sys.exit(preemption.EXIT_PREEMPTED)
