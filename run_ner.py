"""NER finetuning runner — TPU-native counterpart of reference run_ner.py.

Capability parity (SURVEY.md §3.4): CoNLL-style data via
bert_pytorch_tpu.data.ner_dataset, BertForTokenClassification with
``len(labels)+1`` classes (reference run_ner.py:224; id 0 reserved),
pretrained-checkpoint warm start, AdamW(bias_correction=False) with the
``1/(1+0.05*epoch)`` LambdaLR decay (:243-245), per-step global-norm grad
clipping (:145-170), per-epoch validation and final test with macro-F1 over
non-special tokens (:127-142 — computed here in numpy, no sklearn
dependency).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from bert_pytorch_tpu import optim, telemetry
from bert_pytorch_tpu.config import BertConfig
from bert_pytorch_tpu.data import DevicePrefetcher
from bert_pytorch_tpu.data.ner_dataset import NERDataset
from bert_pytorch_tpu.data.tokenization import (
    get_bpe_tokenizer,
    get_wordpiece_tokenizer,
)
from bert_pytorch_tpu.models import BertForTokenClassification
from bert_pytorch_tpu.models.losses import token_classification_loss
from bert_pytorch_tpu.ops.grad_utils import clip_by_global_norm
from bert_pytorch_tpu.utils import checkpoint as ckpt
from bert_pytorch_tpu.utils import logging as logger
from bert_pytorch_tpu.utils import preemption
from bert_pytorch_tpu.utils.compile_cache import enable_compile_cache


def parse_arguments(argv=None):
    parser = argparse.ArgumentParser(description="TPU BERT NER finetuning")
    parser.add_argument("--train_file", type=str, required=True)
    parser.add_argument("--val_file", type=str, default=None)
    parser.add_argument("--test_file", type=str, default=None)
    parser.add_argument("--labels", type=str, nargs="+", required=True)
    parser.add_argument("--model_config_file", type=str, required=True)
    parser.add_argument("--model_checkpoint", type=str, default=None)
    parser.add_argument("--vocab_file", type=str, default=None)
    parser.add_argument("--uppercase", action="store_true")
    parser.add_argument("--tokenizer", type=str, default=None,
                        choices=["wordpiece", "bpe"])
    parser.add_argument("--epochs", type=int, default=5)
    parser.add_argument("--lr", type=float, default=5e-6)
    parser.add_argument("--clip_grad", type=float, default=5.0)
    parser.add_argument("--batch_size", type=int, default=32)
    parser.add_argument("--max_seq_len", type=int, default=128)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--output_dir", type=str, default=None,
                        help="where the finetuned model checkpoint lands "
                             "(end of run, and on graceful preemption); "
                             "omitted = no checkpoint (pre-PR-5 behavior)")
    parser.add_argument("--compile_cache_dir", type=str, default="",
                        help="persistent XLA compilation cache directory; empty disables")
    parser.add_argument("--dtype", type=str, default="bfloat16",
                        choices=["bfloat16", "float32"])
    parser.add_argument("--save_steps", type=int, default=0,
                        help="periodic checkpoint cadence (optimizer "
                             "steps): async writes (device snapshot + "
                             "background write); final/emergency stays "
                             "synchronous. 0 disables")
    # device prefetch (data/device_prefetch.py; shared runner flag)
    from bert_pytorch_tpu.data import device_prefetch as dp_cli
    dp_cli.add_cli_args(parser)
    # telemetry (docs/telemetry.md) — this runner has no output dir, so the
    # file sinks are opt-in
    # telemetry: canonical flag set shared by every runner. Default
    # sync cadence stays 1: these are small models where a per-step
    # sync is cheap and step-exact sentinels are worth it — but since
    # PR 7 the loop itself no longer fetches the loss per step (it
    # accumulates on device; jaxlint HS101), so a user-set
    # --telemetry_sync_every N genuinely syncs only every Nth step
    # (telemetry/cli.py; docs/telemetry.md)
    telemetry.add_cli_args(parser, sync_every_default=1)
    args = parser.parse_args(argv)

    with open(args.model_config_file) as f:
        configs = json.load(f)
    if args.vocab_file is None:
        args.vocab_file = configs.get("vocab_file")
        if args.vocab_file is None:
            raise ValueError("vocab_file must be in model config or CLI")
    if args.tokenizer is None:
        args.tokenizer = configs.get("tokenizer")
        if args.tokenizer is None:
            raise ValueError("tokenizer must be in model config or CLI")
    return args


def macro_f1(predictions: np.ndarray, labels: np.ndarray) -> float:
    """Macro-F1 over non-special positions (labels > 0), numpy reimplementation
    of the sklearn call at reference run_ner.py:127-142."""
    preds = predictions.argmax(axis=-1)
    keep = labels > 0
    y_true = labels[keep]
    y_pred = preds[keep]
    classes = np.unique(y_true)
    f1s = []
    for c in classes:
        tp = np.sum((y_pred == c) & (y_true == c))
        fp = np.sum((y_pred == c) & (y_true != c))
        fn = np.sum((y_pred != c) & (y_true == c))
        precision = tp / (tp + fp) if tp + fp else 0.0
        recall = tp / (tp + fn) if tp + fn else 0.0
        f1s.append(2 * precision * recall / (precision + recall)
                   if precision + recall else 0.0)
    return float(np.mean(f1s)) if f1s else 0.0


def batches(dataset, batch_size, shuffle, rng):
    order = rng.permutation(len(dataset)) if shuffle else np.arange(len(dataset))
    for i in range(0, len(order) - batch_size + 1, batch_size):
        idx = order[i:i + batch_size]
        seqs, labels, masks = zip(*(dataset[j] for j in idx))
        yield (np.stack(seqs), np.stack(labels), np.stack(masks))


def main(args):
    enable_compile_cache(args.compile_cache_dir)
    rng = np.random.default_rng(args.seed)
    telemetry_sink = (logger.JSONLHandler(args.telemetry_jsonl,
                                          overwrite=False)
                      if args.telemetry_jsonl else None)
    logger.init(handlers=[logger.StreamHandler()]
                + ([telemetry_sink] if telemetry_sink else []))

    if args.tokenizer == "wordpiece":
        tokenizer = get_wordpiece_tokenizer(args.vocab_file,
                                            uppercase=args.uppercase)
    else:
        tokenizer = get_bpe_tokenizer(args.vocab_file, uppercase=args.uppercase)

    datasets = {"train": NERDataset(args.train_file, tokenizer, args.labels,
                                    args.max_seq_len)}
    for split, path in (("val", args.val_file), ("test", args.test_file)):
        if path:
            datasets[split] = NERDataset(path, tokenizer, args.labels,
                                         args.max_seq_len)
    id_to_label = {i: l for i, l in enumerate(args.labels, start=1)}

    config = BertConfig.from_json_file(args.model_config_file)
    if config.vocab_size % 8 != 0:
        config.vocab_size += 8 - (config.vocab_size % 8)
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    model = BertForTokenClassification(
        config, num_labels=len(args.labels) + 1, dtype=dtype)

    sample = (jnp.zeros((1, args.max_seq_len), jnp.int32),) * 3
    import flax.linen as nn

    params = nn.unbox(model.init(jax.random.PRNGKey(args.seed), *sample))["params"]
    if args.model_checkpoint:
        from bert_pytorch_tpu.models import load_pretrained_encoder

        params = load_pretrained_encoder(args.model_checkpoint, config, params)
        logger.info(f"loaded pretrained encoder from {args.model_checkpoint}")

    # AdamW(bias_correction=False) + per-epoch 1/(1+0.05*epoch) decay
    # (reference run_ner.py:243-245). The epoch index is passed per step.
    base_tx = optim.adamw(1.0, bias_correction=False, weight_decay=0.0)
    opt_state = base_tx.init(params)

    stats_every = telemetry.stats_every(args)

    def train_step(params, opt_state, batch, dropout_rng, epoch):
        seqs, labels, masks = batch

        def loss_fn(p):
            logits = model.apply({"params": p}, seqs, None, masks, False,
                                 rngs={"dropout": dropout_rng})
            return token_classification_loss(logits, labels)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads, _ = clip_by_global_norm(grads, args.clip_grad)
        updates, opt_state2 = base_tx.update(grads, opt_state, params)
        lr = args.lr / (1.0 + 0.05 * epoch)
        updates = jax.tree_util.tree_map(lambda u: u * lr, updates)
        metrics = {"loss": loss}
        health = telemetry.finetune_grad_health(
            params, grads, updates, opt_state, stats_every)
        if health is not None:
            metrics["grad_health"] = health
        return optax.apply_updates(params, updates), opt_state2, metrics

    # Telemetry facade (docs/telemetry.md).
    from bert_pytorch_tpu.utils import flops as flops_util
    tele = telemetry.from_args(
        args,
        sink=telemetry_sink,
        seq_per_step=args.batch_size,
        flops_per_seq=flops_util.bert_finetune_flops_per_seq(
            config, args.max_seq_len, head_outputs=len(args.labels) + 1),
        # output_dir anchors the heartbeat/postmortem fallbacks the other
        # runners already get (run_ner gained --output_dir in PR 5 but
        # never passed it through).
        output_dir=args.output_dir or None,
        process="ner")

    train_step = tele.instrument(
        jax.jit(train_step, donate_argnums=(0, 1)), "train_step")

    @jax.jit
    def eval_step(params, seqs, masks):
        return model.apply({"params": params}, seqs, None, masks)

    eval_step = tele.instrument(eval_step, "eval_step")

    def evaluate(split):
        dataset = datasets[split]
        all_logits, all_labels, losses = [], [], []
        for seqs, labels, masks in batches(dataset, args.batch_size, False, rng):
            logits = np.asarray(eval_step(params, seqs, masks), np.float32)
            losses.append(float(token_classification_loss(
                jnp.asarray(logits), jnp.asarray(labels))))
            all_logits.append(logits)
            all_labels.append(labels)
        if not all_logits:
            return 0.0, 0.0
        f1 = macro_f1(np.concatenate(all_logits), np.concatenate(all_labels))
        return float(np.mean(losses)), f1

    key = jax.random.PRNGKey(args.seed)
    results = {}
    global_step = 0
    # Graceful preemption (docs/fault_tolerance.md): stop at the next
    # step boundary, checkpoint (with --output_dir), exit EXIT_PREEMPTED.
    # Handlers stay installed THROUGH the checkpoint write below (a
    # grace-period re-delivery must not kill it); restored in the finally.
    stop = preemption.GracefulStop().install()
    prefetcher = None
    try:
        for epoch in range(args.epochs):
            t0 = time.perf_counter()
            # Device-side epoch loss accumulation (run_glue pattern): a
            # per-step float(loss) would block on the device every step
            # (jaxlint HS101); the epoch-end mean is the only fetch.
            loss_sum = None
            n_steps = 0
            # Device prefetch + h2d_wait attribution (run_glue pattern).
            prefetcher = DevicePrefetcher(
                batches(datasets["train"], args.batch_size, True, rng),
                stage=jax.device_put, depth=args.device_prefetch)
            tele.attach_prefetcher(prefetcher)
            for batch in tele.timed(iter(prefetcher)):
                key, sub = jax.random.split(key)
                tele.profiler.maybe_start(global_step + 1)
                with tele.profiler.annotation(global_step + 1):
                    params, opt_state, metrics = train_step(
                        params, opt_state, batch, sub, epoch)
                tele.dispatch_done()
                global_step += 1
                tele.step_done(global_step, metrics)
                loss = metrics["loss"]
                loss_sum = loss if loss_sum is None else loss_sum + loss
                n_steps += 1
                if args.save_steps and args.output_dir \
                        and global_step % args.save_steps == 0:
                    # Periodic async save (joined before exit below).
                    with tele.checkpoint_stall():
                        ckpt.save_checkpoint(
                            args.output_dir, global_step,
                            {"model": params}, async_write=True)
                if stop.requested:
                    break
            prefetcher.close()
            if stop.requested:
                logger.info(
                    f"termination signal ({stop.signal_name}) received; "
                    "checkpointing and exiting cleanly "
                    f"(exit code {preemption.EXIT_PREEMPTED})")
                tele.emit(preemption.preemption_record(global_step, stop))
                break
            mean_loss = float(loss_sum) / n_steps if n_steps else float("nan")
            msg = (f"epoch {epoch}: train_loss={mean_loss:.4f} "
                   f"({time.perf_counter() - t0:.1f}s)")
            if "val" in datasets:
                val_loss, val_f1 = evaluate("val")
                results["val_f1"] = val_f1
                msg += f" val_loss={val_loss:.4f} val_f1={val_f1:.4f}"
            logger.info(msg)

        results["terminated_by_signal"] = stop.requested
        if "test" in datasets and not stop.requested:
            test_loss, test_f1 = evaluate("test")
            results["test_f1"] = test_f1
            logger.info(f"test_loss={test_loss:.4f} test_f1={test_f1:.4f}")
        tele.finish(global_step)
        if args.output_dir:
            os.makedirs(args.output_dir, exist_ok=True)
            # Synchronous on purpose: the durability write before exit;
            # joins any in-flight periodic async write first.
            ckpt.save_checkpoint(
                args.output_dir, global_step, {"model": params})
        # No exit until any in-flight async periodic write has landed.
        ckpt.wait_for_pending_save()
    finally:
        if prefetcher is not None:
            prefetcher.close()
        stop.restore()
    logger.close()
    return results


if __name__ == "__main__":
    outcome = main(parse_arguments())
    if outcome.get("terminated_by_signal"):
        sys.exit(preemption.EXIT_PREEMPTED)
