"""BERT/RoBERTa pretraining runner — TPU-native counterpart of reference
run_pretraining.py.

Capability parity (SURVEY.md §2.1 "Pretraining runner"): CLI > JSON config >
defaults argument handling, device-mesh setup (replacing NCCL DDP), bf16
policy (replacing AMP), gradient accumulation inside one jitted step
(replacing no_sync microbatching), LAMB + warmup-decay schedule, auto-resume
with phase-switch optimizer surgery, contiguous-chunk sharded data streaming,
multi-sink logging, checkpoint cadence with last-3 retention, and the
``training_seq_per_sec`` summary metric (run_pretraining.py:597-599).

Single-host example (smoke config, CPU-runnable):
  python run_pretraining.py --input_dir data/ --output_dir out/ \
      --model_config_file configs/bert_base_config.json \
      --global_batch_size 8 --local_batch_size 8 --steps 3 --max_steps 10
"""

from __future__ import annotations

import argparse
import dataclasses
import math
import os
import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from bert_pytorch_tpu import optim, pretrain, telemetry
from bert_pytorch_tpu.config import BertConfig, parse_args_with_config_file, require_args
from bert_pytorch_tpu.data import DataLoader, DistributedSampler, ShardedPretrainingDataset
from bert_pytorch_tpu.models import BertForPreTraining
from bert_pytorch_tpu.parallel import (MeshSpec, MeshSpecError, create_mesh,
                                       logical_axis_rules)
from bert_pytorch_tpu.parallel import launcher
from bert_pytorch_tpu.parallel.mesh import AXIS_DATA, AXIS_FSDP, AXIS_PIPE
from bert_pytorch_tpu.testing import faults
from bert_pytorch_tpu.utils import checkpoint as ckpt
from bert_pytorch_tpu.utils import logging as logger
from bert_pytorch_tpu.utils import preemption
from bert_pytorch_tpu.utils.compile_cache import enable_compile_cache
from bert_pytorch_tpu.utils.dist import (
    agree_on_resume_step,
    get_rank,
    get_world_size,
    is_main_process,
)


def parse_arguments(argv=None) -> argparse.Namespace:
    """Reference parse_arguments (run_pretraining.py:75-177) with TPU-mesh
    flags replacing the CUDA/apex ones."""
    parser = argparse.ArgumentParser(description="TPU BERT pretraining")
    # data / io
    parser.add_argument("--input_dir", type=str, default=None)
    parser.add_argument("--output_dir", type=str, default=None)
    parser.add_argument("--model_config_file", type=str, default=None)
    parser.add_argument("--config_file", type=str, default=None,
                        help="JSON overriding defaults; CLI overrides JSON")
    parser.add_argument("--log_prefix", type=str, default="pretraining")
    # schedule / steps
    parser.add_argument("--max_steps", type=int, default=None,
                        help="total optimizer steps of the phase (t_total)")
    parser.add_argument("--steps", type=int, default=None,
                        help="optimizer steps to run in this invocation")
    parser.add_argument("--previous_phase_end_step", type=int, default=0)
    parser.add_argument("--learning_rate", type=float, default=6e-3)
    parser.add_argument("--lr_decay", type=str, default="poly",
                        choices=["poly", "linear", "cosine", "constant"])
    parser.add_argument("--warmup_proportion", type=float, default=0.2843)
    # batch
    parser.add_argument("--global_batch_size", type=int, default=None)
    parser.add_argument("--local_batch_size", type=int, default=None)
    # masking
    parser.add_argument("--max_predictions_per_seq", type=int, default=20)
    parser.add_argument("--masked_token_fraction", type=float, default=0.15)
    # sequence packing (docs/packing.md; Krell et al. 2021,
    # arXiv:2107.02027): concatenate short samples into one row with
    # block-diagonal attention, per-sequence position restart, and
    # per-sequence NSP heads — ~2x effective phase-1 throughput on real
    # length distributions, with telemetry's padding_efficiency measuring
    # exactly what was gained
    parser.add_argument("--pack_sequences", action="store_true",
                        help="pack short samples into full rows on the fly "
                             "(data/packing.py greedy first-fit-decreasing, "
                             "packed within each shard). Shards that were "
                             "packed OFFLINE (tools/encode_data.py "
                             "--pack_sequences) are detected automatically "
                             "and need no flag")
    parser.add_argument("--max_sequences_per_pack", type=int, default=8,
                        help="cap on sequences per packed row (on-the-fly "
                             "mode; offline-packed shards carry their own). "
                             "Also scales the per-row MLM prediction "
                             "budget: max_predictions_per_seq applies per "
                             "SEQUENCE, as unpacked")
    parser.add_argument(
        "--num_workers", type=int, default=0,
        help="DataLoader producer processes (reference run_pretraining.py:"
             "394-395 num_workers=4). 0 = single background thread — KEEP "
             "THE DEFAULT at BERT shapes: the measured thread path is ~2x "
             "FASTER than process workers (14.4k vs 7.2k seq/s, "
             "LOADER_BENCH_r02.jsonl — strided workers re-read every "
             "shard). >0 pays off only if per-sample featurization grows "
             "to dominate IO (data/loader.py docstring).")
    # held-out evaluation (beyond the reference, which never evaluates
    # during pretraining; uses pretrain.make_eval_step)
    parser.add_argument("--val_input_dir", type=str, default=None,
                        help="directory of held-out HDF5 shards; enables a "
                             "validation MLM-loss pass")
    parser.add_argument("--num_steps_per_eval", type=int, default=200,
                        help="optimizer steps between validation passes")
    parser.add_argument("--eval_batches", type=int, default=16,
                        help="validation batches per pass")
    # device prefetch (data/device_prefetch.py): keep N batches resident
    # on device so data_wait measures only true producer stalls — the one
    # flag shared by every runner
    from bert_pytorch_tpu.data import device_prefetch as dp_cli
    dp_cli.add_cli_args(parser)
    # overlapped data-parallel gradient collectives (parallel/overlap.py):
    # bucket the backward's psum so early layer groups' all-reduces hide
    # under the remaining backward compute (ZeRO lineage, PAPERS.md)
    parser.add_argument("--overlap_grad_reduce", action="store_true",
                        help="explicit availability-ordered per-bucket "
                             "gradient collectives instead of the implicit "
                             "tree-wide reduction (dp strategy, first-order "
                             "optimizers; numerically exact vs the default "
                             "path at fp32 roundoff)")
    # checkpoint / logging cadence
    parser.add_argument("--num_steps_per_checkpoint", type=int, default=200)
    parser.add_argument("--keep_checkpoints", type=int, default=3)
    parser.add_argument("--checkpoint_write", type=str, default="async",
                        choices=["async", "sync"],
                        help="periodic checkpoint write mode: 'async' "
                             "snapshots the state on device and writes "
                             "from a background thread (the step pays only "
                             "the device-side copy; utils/checkpoint.py), "
                             "'sync' blocks the step for the full "
                             "fetch+serialize+write — the before/after the "
                             "BENCH_ASYNC leg and checkpoint-step p95 "
                             "telemetry compare. Final/emergency "
                             "checkpoints are always synchronous")
    parser.add_argument("--checkpoint_layout", type=str, default="gathered",
                        choices=["gathered", "sharded"],
                        help="'gathered' (default) writes one full msgpack "
                             "per checkpoint (state gathered to host); "
                             "'sharded' writes per-process shard files of "
                             "slice records plus an index, records the "
                             "mesh spec in the integrity manifest, and "
                             "loads back under ANY topology (elastic "
                             "resume: save on 8 ways, resume on 4; "
                             "utils/checkpoint.py)")
    parser.add_argument("--skip_final_checkpoint", action="store_true",
                        help="skip the end-of-run checkpoint write. For "
                             "benchmark/capture runs whose artifact is the "
                             "metrics log: at BERT-large the final state is "
                             "multi-GB and the device->host pull can dominate "
                             "a short run's wallclock. A checkpoint requested "
                             "by a termination signal is still written")
    parser.add_argument("--log_steps", type=int, default=1)
    parser.add_argument("--disable_tensorboard", action="store_true",
                        help="skip the TensorBoard sink. Its writer "
                             "backend import (torch) costs ~25s of "
                             "startup on a throttled CPU box — child "
                             "processes that never read TB events (the "
                             "chaos harness, CI smoke runs) skip it; the "
                             "JSONL/CSV/text sinks carry every record "
                             "anyway")
    parser.add_argument("--term_check_steps", type=int, default=10,
                        help="how often (in optimizer steps) to act on a "
                             "received SIGTERM/SIGUSR1: checkpoint and exit "
                             "cleanly. TPU VMs / SLURM preemption send "
                             "SIGTERM with a short grace period; the check "
                             "runs at a fixed step cadence so multi-host "
                             "jobs agree collectively on when to stop. "
                             "0 disables graceful termination")
    # data-path resilience (docs/fault_tolerance.md): HDF5 shard reads
    # retry with backoff (utils/retry.py); startup verification either
    # warn-skips unreadable shards (the reference's stance) or fails fast
    parser.add_argument("--data_read_retries", type=int, default=2,
                        help="retries per HDF5 shard open/read (exponential "
                             "backoff + jitter) before the read is a hard "
                             "failure; transient storage errors cost a "
                             "delay, not the run")
    parser.add_argument("--data_retry_base_s", type=float, default=0.2,
                        help="pre-jitter base backoff for shard-read "
                             "retries (doubles per retry, capped at 30s)")
    parser.add_argument("--shard_error_policy", type=str, default="skip",
                        choices=["skip", "abort"],
                        help="a shard unreadable past the retries at "
                             "STARTUP: 'skip' warns and trains on the "
                             "rest (reference behavior); 'abort' fails "
                             "fast. Mid-stream failures always abort — "
                             "the index space is fixed at startup")
    parser.add_argument("--fault_spec", type=str, default="",
                        help="TEST-ONLY deterministic fault injection "
                             "(testing/faults.py; docs/fault_tolerance.md), "
                             "e.g. 'die@7' or 'shard_errorx2,nonfinite@5'; "
                             "also armable via BERT_FAULTS. Empty disables")
    # telemetry (docs/telemetry.md): step-time decomposition + MFU windows,
    # profiler trace windows, compile events, failure sentinels, heartbeat,
    # hung-step watchdog — canonical flag set shared by every runner
    # (telemetry/cli.py)
    telemetry.add_cli_args(parser, window_default=20, sync_every_default=4)
    # numerics / memory
    parser.add_argument("--dtype", type=str, default="bfloat16",
                        choices=["bfloat16", "float32", "float16"],
                        help="activation dtype; bfloat16 is the TPU "
                             "default (no loss scaling needed). float16 is "
                             "the reference-parity AMP mode and enables a "
                             "dynamic loss scaler (GradScaler analog, "
                             "reference run_pretraining.py:314-318)")
    parser.add_argument("--init_loss_scale", type=float, default=2.0 ** 16,
                        help="fp16 only: initial dynamic loss scale "
                             "(default matches torch GradScaler's 2**16)")
    parser.add_argument("--loss_scale_growth_interval", type=int,
                        default=2000,
                        help="fp16 only: consecutive finite steps before "
                             "the loss scale doubles")
    parser.add_argument("--checkpoint_activations", action="store_true",
                        help="shorthand for --remat full (reference "
                             "checkpointed_forward, modeling.py:503-520)")
    parser.add_argument("--remat", type=str, default=None,
                        choices=["none", "dots", "full"],
                        help="activation rematerialization policy; 'dots' "
                             "(keep matmul outputs, recompute elementwise) "
                             "unlocks ~2x larger microbatches and is the "
                             "fastest configuration on 16GB v5e chips")
    parser.add_argument("--attention_backend", type=str, default="auto",
                        choices=["auto", "xla", "pallas", "ring"],
                        help="'auto' picks the measured winner by sequence "
                             "length: XLA <256, fused Pallas kernel >=256")
    parser.add_argument("--compile_cache_dir", type=str, default="",
                        help="persistent XLA compilation cache directory; "
                             "restarted/resumed jobs (and the bench retry "
                             "harness) reuse compiled executables instead of "
                             "recompiling (~minutes for BERT-large). Empty "
                             "disables.")
    parser.add_argument("--rng_impl", type=str, default="rbg",
                        choices=["rbg", "threefry2x32"],
                        help="dropout PRNG: 'rbg' uses the TPU hardware "
                             "random generator (~16%% faster end-to-end than "
                             "threefry, which synthesizes every mask bit in "
                             "ALU ops); threefry2x32 gives JAX's default "
                             "cross-platform reproducible streams")
    # optimizer
    parser.add_argument("--optimizer", type=str, default="lamb",
                        choices=["lamb", "adamw"])
    parser.add_argument("--weight_decay", type=float, default=0.01)
    parser.add_argument("--max_grad_norm", type=float, default=1.0)
    # K-FAC (SURVEY §2.2)
    parser.add_argument("--kfac", action="store_true")
    parser.add_argument("--kfac_stat_decay", type=float, default=0.95)
    parser.add_argument("--kfac_damping", type=float, default=0.001)
    parser.add_argument("--kfac_kl_clip", type=float, default=0.001)
    parser.add_argument("--kfac_factor_interval", type=int, default=10)
    parser.add_argument("--kfac_inv_interval", type=int, default=100)
    parser.add_argument("--kfac_inv_method", type=str, default="cholesky",
                        choices=["cholesky", "eigen"],
                        help="'cholesky' = damped factor inverses (40x "
                             "faster than TPU eigh at BERT-large factor "
                             "sizes); 'eigen' = eigenbasis preconditioning "
                             "(kfac_pytorch's eigen method)")
    parser.add_argument("--kfac_capture", type=str, default="train",
                        choices=["train", "stats"],
                        help="'train' (default): harvest Kronecker factors "
                             "from microbatch 0 of the training step's own "
                             "backward (the reference's free hook capture, "
                             "run_pretraining.py:320-355 — no extra "
                             "forward/backward at factor_interval=1). "
                             "'stats': decoupled stats pass on "
                             "--kfac_stats_batch rows every "
                             "factor_interval steps (pp strategies use "
                             "this; it is also the knob for stats batches "
                             "smaller than a microbatch)")
    parser.add_argument("--kfac_capture_microbatches", type=str,
                        default="first", choices=["first", "all"],
                        help="fused capture source on factor-due steps: "
                             "'first' taps microbatch 0 only (capture "
                             "cost amortizes over the accumulation); "
                             "'all' accumulates statistics over every "
                             "microbatch's backward — kfac_pytorch's "
                             "exact accumulation semantics, capture cost "
                             "proportional to accumulation_steps")
    parser.add_argument("--kfac_stats_batch", type=int, default=16,
                        help="total sequences (strided across the global "
                             "batch, so every data shard contributes) used "
                             "for the factor-statistics pass; the tapped "
                             "model's activation/cotangent captures are the "
                             "K-FAC memory peak, and factor EMAs over "
                             "factor_interval steps don't need the full "
                             "batch (0 = use the whole microbatch)")
    parser.add_argument("--kfac_skip_layers", type=str, nargs="+",
                        default=["embeddings", "predictions"])
    # mesh
    parser.add_argument("--mesh_data", type=int, default=-1,
                        help="data-parallel shards; -1 = all remaining "
                             "devices. With --mesh_dcn_data > 1 this is "
                             "the PER-SLICE size (total data parallelism "
                             "= mesh_data * mesh_dcn_data)")
    parser.add_argument("--mesh_fsdp", type=int, default=1)
    parser.add_argument("--mesh_pipe", type=int, default=1,
                        help="pipeline stages (with --parallel_strategy "
                             "pp/pp_tp; "
                             "accumulation microbatches become the GPipe "
                             "microbatches, so accumulation_steps must be "
                             ">= stages)")
    parser.add_argument("--mesh_seq", type=int, default=1,
                        help="context-parallel shards (with --parallel_"
                             "strategy sp: ring attention; with pp/pp_tp: "
                             "the pipeline runs manual over {pipe, seq} "
                             "with the ring body inside each stage)")
    parser.add_argument("--mesh_dcn_data", type=int, default=1,
                        help="multi-slice pods: data-parallel replicas "
                             "spanning slices over DCN (hybrid device "
                             "mesh); every other axis stays within a "
                             "slice on ICI")
    parser.add_argument("--mesh_model", type=int, default=1)
    parser.add_argument("--mesh", type=str, default=None,
                        help="declarative mesh spec, e.g. "
                             "'dp=4,fsdp=2,pipe=2,seq=1' (keys accept "
                             "pp/sp/tp aliases; parallel/mesh.py MeshSpec). "
                             "Any axis product is expressible — rules, "
                             "device mesh, and collective wiring derive "
                             "from the spec. Overrides --parallel_strategy "
                             "and the individual --mesh_* sizes")
    parser.add_argument("--parallel_strategy", type=str, default="dp",
                        choices=["dp", "fsdp", "tp", "tp_fsdp", "sp", "pp", "pp_tp"],
                        help="legacy strategy alias; lowers onto a MeshSpec "
                             "with byte-identical rules (prefer --mesh)")
    parser.add_argument("--seed", type=int, default=42)

    args = parse_args_with_config_file(parser, argv)
    require_args(args, ["input_dir", "output_dir", "model_config_file",
                        "max_steps", "global_batch_size", "local_batch_size"])
    return args


def setup_training(args):
    """Mesh + logging + accumulation math (reference setup_training,
    run_pretraining.py:180-230)."""
    jax.config.update("jax_default_prng_impl", args.rng_impl)
    enable_compile_cache(args.compile_cache_dir)
    launcher.initialize()
    if args.mesh:
        spec = MeshSpec.parse(args.mesh)
    else:
        # Legacy surface: --parallel_strategy + --mesh_* lower onto a
        # spec (byte-identical rules). The named strategies promise axis
        # shapes, so misuse of the ALIAS stays an error here even though
        # the spec itself could realize the product (--mesh lifts these).
        spec = MeshSpec.from_strategy(
            args.parallel_strategy, data=args.mesh_data,
            fsdp=args.mesh_fsdp, pipe=args.mesh_pipe, seq=args.mesh_seq,
            model=args.mesh_model, dcn_data=args.mesh_dcn_data)
        if args.mesh_pipe > 1 \
                and args.parallel_strategy not in ("pp", "pp_tp"):
            raise ValueError(
                f"--mesh_pipe {args.mesh_pipe} requires --parallel_strategy "
                "pp or pp_tp (or express the product with --mesh)")
        if args.parallel_strategy in ("pp", "pp_tp") and args.mesh_pipe < 2:
            raise ValueError(
                "--parallel_strategy pp/pp_tp needs --mesh_pipe >= 2 (a "
                "1-stage pipeline is just dp with schedule overhead)")
        if args.parallel_strategy == "pp_tp" and args.mesh_model < 2:
            raise ValueError(
                "--parallel_strategy pp_tp needs --mesh_model >= 2 "
                "(with one model shard use plain pp)")
        if args.parallel_strategy == "pp" and args.mesh_model > 1:
            # The engine would run, but plain pp replicates every stage
            # weight over the model axis: identical work on every model
            # shard at 1/model throughput — never what anyone wants.
            raise ValueError(
                f"--mesh_model {args.mesh_model} with "
                "--parallel_strategy pp replicates all stage weights "
                "over the model axis; use pp_tp (or --mesh)")
    spec.validate(packed=bool(args.pack_sequences))
    mesh = create_mesh(spec.mesh_config())
    # Record the RESOLVED spec (data=-1 replaced by the realized size):
    # checkpoint manifests and telemetry label topologies with it.
    args.mesh_spec = dataclasses.replace(
        spec, data=mesh.shape[AXIS_DATA] // spec.dcn_data)
    # Fail fast if any batch shard's pipe/seq/model replicas span hosts:
    # the per-process loaders would feed the same global rows different data.
    pretrain.check_batch_process_locality(mesh)
    args.model_output_dir = os.path.join(args.output_dir, "pretrain_ckpts")
    if is_main_process():
        os.makedirs(args.model_output_dir, exist_ok=True)

    # Telemetry sink shared between the logger (ordinary train records) and
    # the TrainTelemetry facade (its records go ONLY there); built in main().
    args.telemetry_jsonl = telemetry.default_jsonl_path(
        args, args.output_dir, args.log_prefix)
    args.heartbeat_file = args.heartbeat_file or os.path.join(
        args.output_dir, "heartbeat.json")
    args.profile_dir = args.profile_dir or os.path.join(
        args.output_dir, "profile")
    args.telemetry_sink = logger.JSONLHandler(
        args.telemetry_jsonl, overwrite=False, is_primary=is_main_process())
    handlers = [
        logger.StreamHandler(verbose=is_main_process(),
                             is_primary=is_main_process()),
        logger.FileHandler(
            os.path.join(args.output_dir, args.log_prefix + ".txt"),
            overwrite=False, is_primary=is_main_process()),
        logger.CSVHandler(
            os.path.join(args.output_dir, args.log_prefix + "_metrics.csv"),
            overwrite=False, is_primary=is_main_process()),
        args.telemetry_sink,
    ]
    if not args.disable_tensorboard:
        handlers.insert(2, logger.TensorBoardHandler(
            os.path.join(args.output_dir, "tensorboard"),
            is_primary=is_main_process()))
    logger.init(handlers=handlers)
    logger.info(
        f"mesh initialized: {dict(zip(mesh.axis_names, mesh.devices.shape))} "
        f"({jax.process_count()} processes, {len(jax.devices())} devices, "
        f"spec {args.mesh_spec.canonical()})"
    )
    if args.rng_impl != "threefry2x32":
        # rbg streams are not stable across platforms/XLA versions the way
        # threefry is — say so once, loudly, since it changes dropout draws.
        logger.info(
            f"dropout PRNG: {args.rng_impl} (hardware RNG; streams are not "
            "reproducible across platforms/XLA versions — pass --rng_impl "
            "threefry2x32 for JAX's portable default)")

    if args.dtype == "float16":
        if args.kfac:
            raise ValueError(
                "--dtype float16 is the first-order parity mode; K-FAC "
                "runs in bf16/f32 (no loss scaler needed on TPU)")
        if args.mesh_spec.pipe > 1:
            raise ValueError(
                "--dtype float16 is not supported with pipeline "
                "parallelism; use bfloat16 (the TPU default)")

    # Accumulation math (reference :213-228), in global terms: one optimizer
    # step consumes global_batch_size sequences as accumulation_steps
    # microbatches of local_batch_size per data shard.
    n_data = mesh.shape[AXIS_DATA] * mesh.shape[AXIS_FSDP]
    global_microbatch = args.local_batch_size * n_data
    if args.global_batch_size % global_microbatch != 0:
        raise ValueError(
            f"global_batch_size={args.global_batch_size} must be divisible by "
            f"local_batch_size*data_shards={global_microbatch}"
        )
    args.accumulation_steps = args.global_batch_size // global_microbatch
    if args.overlap_grad_reduce and (
            args.mesh_spec.active_axes() - {AXIS_DATA} or args.kfac
            or args.dtype == "float16"):
        # The bucketed collectives are defined over the batch axes with
        # fully-replicated params: sharded-param products, K-FAC's
        # fused capture, and the fp16 scaler keep the default path.
        raise ValueError(
            "--overlap_grad_reduce requires a pure data-parallel mesh "
            "(fsdp=pipe=seq=model=1) with a first-order optimizer "
            "(no --kfac) and bf16/fp32")
    if (args.mesh_spec.seq > 1 and args.mesh_spec.pipe == 1
            and args.attention_backend != "ring"):
        # A seq axis exists to avoid O(S^2) dense attention; never
        # silently densify (same stance as ops/attention.py's
        # non-divisible check). seq x pipe instead runs the manual ring
        # body inside the pipeline's shard_map (pretrain.py).
        logger.info("mesh seq>1: switching attention_backend to "
                    "'ring' (was '%s')" % args.attention_backend)
        args.attention_backend = "ring"
    if args.global_batch_size % jax.process_count() != 0:
        raise ValueError("global_batch_size must divide by process count")
    args.host_batch_per_step = args.global_batch_size // jax.process_count()
    return args, mesh


def prepare_model(args, mesh):
    """Model config + auto-resume discovery (reference prepare_model,
    run_pretraining.py:233-274)."""
    config = BertConfig.from_json_file(args.model_config_file)
    if config.vocab_size % 8 != 0:  # MXU-friendly padding (reference :237)
        config.vocab_size += 8 - (config.vocab_size % 8)

    model = BertForPreTraining(
        config,
        dtype={"bfloat16": jnp.bfloat16, "float16": jnp.float16,
               "float32": jnp.float32}[args.dtype],
        remat=args.remat or ("full" if args.checkpoint_activations else "none"),
        attention_backend=args.attention_backend,
    )

    # Newest VERIFIED checkpoint: the walk-back verifies each retained
    # checkpoint's integrity manifest and skips corrupt/unreadable files
    # (utils/checkpoint.py; docs/fault_tolerance.md) instead of crashing
    # the job — collecting what it skipped for the resume record below.
    skipped: list = []
    found = ckpt.load_latest_checkpoint(
        args.model_output_dir, on_skip=skipped.append)
    # Multi-host: all processes must resume from the SAME step even when
    # they observe the shared checkpoint dir differently (utils/dist.py).
    agreed = agree_on_resume_step(None if found is None else found[0])
    if agreed is None:
        found = None
    elif found is None or found[0] != agreed:
        # This process must re-load the agreed step; failure here is fatal
        # (no silent divergence).
        found = (agreed, ckpt.load_checkpoint(
            ckpt.checkpoint_path(args.model_output_dir, agreed)))
    checkpoint = None
    global_step = 0
    args.resume_step = 0
    if found is None and skipped:
        # The worst recovery case — retained checkpoints exist but NONE
        # verified/loaded — must be a loud, auditable artifact, not just
        # transient warnings before a silent restart from step 0.
        logger.info(
            f"NO loadable checkpoint: all {len(skipped)} retained "
            "checkpoint(s) failed verification/decode; training restarts "
            "from scratch (tools/verify_checkpoint.py audits the damage)")
        args.telemetry_sink.write_record({
            "kind": "fault", "tag": "telemetry",
            "fault": "resume_walk_back_exhausted", "injected": False,
            "step": 0, "skipped": skipped,
        })
    if found is not None:
        resume_step, checkpoint = found
        args.resume_step = resume_step
        if args.previous_phase_end_step > resume_step:
            raise ValueError(
                f"previous_phase_end_step={args.previous_phase_end_step} cannot "
                f"be larger than resume_step={resume_step}")
        global_step = resume_step - args.previous_phase_end_step
        logger.info(f"Resume from step {resume_step} checkpoint"
                    + (f" ({len(skipped)} newer checkpoint(s) skipped as "
                       "corrupt/unreadable)" if skipped else ""))
        # Telemetry resume record (schema v1): which step resumed and
        # exactly what the walk-back passed over — recovery decisions
        # become auditable artifacts, not log prose.
        args.telemetry_sink.write_record({
            "kind": "resume", "tag": "telemetry", "step": int(resume_step),
            "skipped": skipped,
        })
    return model, config, checkpoint, global_step


def prepare_optimizer(args, params_example=None):
    """LAMB/AdamW + schedule (reference prepare_optimizers,
    run_pretraining.py:277-357)."""
    schedule = optim.make_schedule(
        args.lr_decay, args.learning_rate, args.warmup_proportion, args.max_steps)
    mask = optim.no_decay_mask
    if args.optimizer == "lamb":
        tx = optim.lamb(
            schedule, weight_decay=args.weight_decay,
            weight_decay_mask=mask, max_grad_norm=args.max_grad_norm)
    else:
        tx = optim.adamw(schedule, weight_decay=args.weight_decay,
                         weight_decay_mask=mask)
    if args.dtype == "float16":
        # Reference-parity AMP: fp16 activations + dynamic loss scaling
        # (GradScaler, run_pretraining.py:314-318); scaler state rides in
        # the checkpoint's optimizer tree like the reference's 'scaler'.
        tx = optim.dynamic_loss_scale(
            tx, init_scale=args.init_loss_scale,
            growth_interval=args.loss_scale_growth_interval)
    return tx, schedule


def prepare_dataset(args, config, checkpoint):
    """HDF5 discovery + tokenizer-derived mask id + sharded streaming
    (reference prepare_dataset, run_pretraining.py:360-402)."""
    input_files = []
    if os.path.isfile(args.input_dir):
        input_files.append(args.input_dir)
    elif os.path.isdir(args.input_dir):
        # sorted: rglob order is filesystem-dependent, and multi-host runs
        # must agree on the index space the sampler chunks over.
        input_files = sorted(
            str(p) for p in Path(args.input_dir).rglob("*.hdf5")
            if p.is_file())

    mask_token_id = getattr(config, "mask_token_id", None)
    vocab_file = getattr(config, "vocab_file", None)
    if mask_token_id is None and vocab_file and os.path.exists(vocab_file):
        from bert_pytorch_tpu.data.tokenization import (
            get_bpe_tokenizer, get_wordpiece_tokenizer)
        kind = getattr(config, "tokenizer", "wordpiece")
        lowercase = getattr(config, "lowercase", True)
        tok = (get_wordpiece_tokenizer(vocab_file, uppercase=not lowercase)
               if kind == "wordpiece"
               else get_bpe_tokenizer(vocab_file, uppercase=not lowercase))
        # WordPiece convention first, then the BPE/RoBERTa one.
        mask_token_id = tok.token_to_id("[MASK]")
        if mask_token_id is None:
            mask_token_id = tok.token_to_id("<mask>")
    if mask_token_id is None:
        mask_token_id = 4  # synthetic-data default
        logger.info("No vocab_file/mask_token_id in model config; "
                    f"using mask_token_id={mask_token_id}")

    # Data-path resilience (docs/fault_tolerance.md): retried shard IO,
    # startup skip-vs-abort policy, fault records into the telemetry JSONL.
    resilience = dict(
        read_retries=args.data_read_retries,
        retry_base_delay_s=args.data_retry_base_s,
        shard_error_policy=args.shard_error_policy,
        on_fault=args.telemetry_sink.write_record)
    dataset = ShardedPretrainingDataset(
        input_files, int(mask_token_id), args.max_predictions_per_seq,
        args.masked_token_fraction, vocab_size=int(config.vocab_size),
        seed=args.seed + get_rank(), **resilience)
    # Sequence packing (docs/packing.md): offline-packed shards are
    # detected from the file layout; --pack_sequences packs on the fly.
    # Either way downstream sees packed rows with sequence_ids and
    # per-sequence NSP labels/cls positions.
    args.packed = bool(dataset.packed)
    args.pack_k = dataset.max_sequences_per_pack if dataset.packed else 1
    if dataset.packed:
        if args.pack_sequences:
            logger.info("shards are offline-packed; --pack_sequences "
                        "is a no-op")
        logger.info(f"offline-packed shards: up to {args.pack_k} "
                    "sequences per row")
    elif args.pack_sequences:
        from bert_pytorch_tpu.data import PackedPretrainingDataset
        dataset = PackedPretrainingDataset(
            dataset, max_sequences_per_pack=args.max_sequences_per_pack)
        args.packed = True
        args.pack_k = args.max_sequences_per_pack
        logger.info(
            f"on-the-fly sequence packing: {dataset.n_samples} samples -> "
            f"{len(dataset)} packed rows "
            f"(occupancy {dataset.occupancy:.3f}, up to "
            f"{args.pack_k} sequences per row)")
    sampler = DistributedSampler(
        dataset, num_replicas=jax.process_count(), rank=jax.process_index())
    if checkpoint is not None and "sampler" in checkpoint:
        sampler.load_state_dict(checkpoint["sampler"])
    loader = DataLoader(dataset, sampler,
                        batch_size=args.host_batch_per_step, drop_last=True,
                        num_workers=args.num_workers)
    logger.info(f"Samples in dataset: {len(dataset)}")
    logger.info(f"Samples per process: {len(sampler)}")
    logger.info(f"Sampler starting index: {sampler.index}")

    val_loader = None
    if args.val_input_dir:
        val_files = sorted(
            str(p) for p in Path(args.val_input_dir).rglob("*.hdf5")
            if p.is_file())
        val_dataset = ShardedPretrainingDataset(
            val_files, int(mask_token_id), args.max_predictions_per_seq,
            args.masked_token_fraction, vocab_size=int(config.vocab_size),
            seed=args.seed + 7919 + get_rank(), **resilience)
        val_sampler = DistributedSampler(
            val_dataset, num_replicas=jax.process_count(),
            rank=jax.process_index())
        val_loader = DataLoader(val_dataset, val_sampler,
                                batch_size=args.host_batch_per_step,
                                drop_last=True)
        logger.info(f"Validation samples: {len(val_dataset)}")
    return loader, sampler, val_loader


def main(args) -> dict:
    args, mesh = setup_training(args)
    model, config, checkpoint, global_step = prepare_model(args, mesh)
    tx, schedule = prepare_optimizer(args)
    loader, sampler, val_loader = prepare_dataset(args, config, checkpoint)

    rules = logical_axis_rules(args.mesh_spec)
    seq_len = config.max_position_embeddings
    sample = (jnp.zeros((1, seq_len), jnp.int32),) * 3
    # Packed rows: per-sequence NSP labels [B, K] + the packing arrays;
    # max_predictions_per_seq stays a per-SEQUENCE budget, so the per-ROW
    # MLM gather cap scales by the pack limit.
    packed = getattr(args, "packed", False)
    if packed:
        # Catches OFFLINE-packed shards too (auto-detected, no flag) —
        # setup_training's early check only sees --pack_sequences.
        try:
            args.mesh_spec.validate(packed=True)
        except MeshSpecError as e:
            raise ValueError(
                f"packed pretraining data: {e}; re-encode the shards "
                "unpacked or drop the seq axis") from None
    eff_max_pred = args.max_predictions_per_seq * (
        args.pack_k if packed else 1)
    batch_spec = {"input_ids": 3, "segment_ids": 3, "input_mask": 3,
                  "masked_lm_labels": 3,
                  "next_sentence_labels": 3 if packed else 2}
    if packed:
        batch_spec.update({"sequence_ids": 3, "cls_positions": 3})
    with mesh:
        fp16 = args.dtype == "float16"
        shardings = pretrain.state_shardings(mesh, model, rules, sample,
                                             loss_scaled=fp16)
        b_shardings = pretrain.batch_shardings(
            mesh, batch_spec, seq_sharded=args.mesh_spec.seq > 1)
        init_fn = pretrain.make_init_fn(model, tx, sample, shardings)
        state = init_fn(jax.random.PRNGKey(args.seed))

        if checkpoint is not None:
            # Restore onto an ABSTRACT template (shapes/dtypes only), not a
            # device_get of the live state: on a multi-host fsdp/tp mesh the
            # live state has non-addressable shards that device_get cannot
            # fetch. Every process reads the full file and device_put slices
            # out its addressable shards of the target sharding.
            abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(args.seed))
            params = ckpt.restore_tree(abstract.params, checkpoint["model"])
            opt_state = ckpt.restore_tree(
                abstract.opt_state, checkpoint["optimizer"])
            state = pretrain.TrainState(
                params=jax.device_put(params, shardings.params),
                opt_state=jax.device_put(opt_state, shardings.opt_state),
                rng=state.rng)
            if args.resume_step >= args.previous_phase_end_step > 0:
                # Phase-2 surgery (reference run_pretraining.py:298-309):
                # schedule hyperparams come from the new config; only the
                # optimizer step counter is rewritten.
                state = state.replace(
                    opt_state=optim.reset_count(state.opt_state, global_step))
                logger.info(f"Phase switch: optimizer count reset to {global_step}")

        kfac_obj = kfac_state = kfac_shardings = None
        kfac_fused = False
        if args.kfac:
            kfac_fused = args.kfac_capture == "train"
            if kfac_fused and args.mesh_spec.pipe > 1:
                # The pipeline step has no fused-capture path (factors
                # would need per-stage reassembly); fall back to the
                # decoupled stats pass.
                logger.info("kfac_capture=train is not supported with "
                            "pipeline parallelism; using 'stats'")
                kfac_fused = False
            # Tapped twin of the model (same params, factor-capture taps on;
            # reference drives kfac_pytorch hooks at run_pretraining.py:320-355).
            # The fused-capture twin keeps the main model's remat so the
            # tapped microbatch-0 backward fits the same memory budget; the
            # stats-pass twin runs a small decoupled batch where remat only
            # costs recompute.
            model_tapped = BertForPreTraining(
                config, dtype=model.dtype,
                remat=model.remat if kfac_fused else "none",
                attention_backend=args.attention_backend, kfac_tap=True)
            apply_loss, tap_shape_fn = pretrain.make_kfac_fns(
                model_tapped, next_sentence=bool(config.next_sentence),
                max_pred_per_seq=eff_max_pred)
            kfac_obj = optim.KFAC(
                apply_loss, tap_shape_fn,
                factor_decay=args.kfac_stat_decay,
                damping=args.kfac_damping,
                kl_clip=args.kfac_kl_clip,
                inv_method=args.kfac_inv_method,
                skip_layers=tuple(args.kfac_skip_layers))
            micro_b = args.global_batch_size // args.accumulation_steps
            sample_mb = {
                "input_ids": np.zeros((micro_b, seq_len), np.int32),
                "segment_ids": np.zeros((micro_b, seq_len), np.int32),
                "input_mask": np.zeros((micro_b, seq_len), np.int32),
                "masked_lm_labels": np.zeros((micro_b, seq_len), np.int32),
                "next_sentence_labels": np.zeros((micro_b,), np.int32),
            }
            kfac_state = kfac_obj.init(state.params, sample_mb)
            kfac_shardings = optim.kfac_state_shardings(mesh, kfac_state)
            if checkpoint is not None and "preconditioner" in checkpoint:
                kfac_state = ckpt.restore_tree(
                    kfac_state, checkpoint["preconditioner"])
                kfac_state = jax.device_put(kfac_state, kfac_shardings)
                # Recompute qa/qg from the restored factors: the checkpoint
                # may hold the OTHER inv_method's operators (eigenvectors vs
                # damped inverses share the same state slots/shapes), and a
                # mid-interval resume would otherwise precondition with the
                # wrong operator for up to inv_interval steps with no error.
                kfac_state = kfac_obj.update_inverses(kfac_state)
                logger.info("Restored K-FAC preconditioner state "
                            "(inverses recomputed from factors)")
            else:
                kfac_state = jax.device_put(kfac_state, kfac_shardings)
            logger.info(
                f"K-FAC enabled: {len(kfac_obj.specs)} layer groups, "
                f"capture={'train (fused)' if kfac_fused else 'stats'}, "
                f"damping={args.kfac_damping}, kl_clip={args.kfac_kl_clip}, "
                f"factor_interval={args.kfac_factor_interval}, "
                f"inv_interval={args.kfac_inv_interval}")

        # Grad-health due gate must count from THIS run's start: the host
        # reads it on a run-local 0-based sync cadence, while the restored
        # optimizer count is absolute — a resume step that is not a
        # multiple of the cadence would otherwise push every due step
        # onto an unsynced step (zero records for the whole resumed run).
        stats_phase = int(jax.device_get(
            optim.opt_step_count(state.opt_state)))

        if args.mesh_spec.pipe > 1:
            if args.accumulation_steps < mesh.shape[AXIS_PIPE]:
                raise ValueError(
                    f"pp needs accumulation_steps >= pipeline stages "
                    f"({args.accumulation_steps} < "
                    f"{mesh.shape[AXIS_PIPE]}); "
                    "raise global_batch_size or lower local_batch_size")
            train_step = pretrain.make_pp_train_step(
                model, tx, mesh, schedule=schedule,
                next_sentence=bool(config.next_sentence),
                shardings=shardings, batch_shardings_=b_shardings,
                max_pred_per_seq=eff_max_pred,
                kfac=kfac_obj, kfac_shardings=kfac_shardings,
                stats_every=telemetry.stats_every(args),
                stats_phase=stats_phase)
        else:
            train_step = pretrain.make_train_step(
                model, tx, schedule=schedule,
                next_sentence=bool(config.next_sentence),
                shardings=shardings, batch_shardings_=b_shardings,
                max_pred_per_seq=eff_max_pred,
                kfac=kfac_obj, kfac_shardings=kfac_shardings,
                kfac_capture_model=model_tapped if kfac_fused else None,
                kfac_factor_interval=args.kfac_factor_interval,
                kfac_inv_interval=args.kfac_inv_interval if kfac_fused else 0,
                kfac_capture_microbatches=args.kfac_capture_microbatches,
                loss_scale=fp16,
                stats_every=telemetry.stats_every(args),
                stats_phase=stats_phase,
                mesh=mesh,
                overlap_grad_buckets=args.overlap_grad_reduce)

        # Telemetry (docs/telemetry.md): JSONL sink shared with the logger,
        # step-time decomposition windows, profiler trace window, compile
        # attribution, failure sentinels, rank-0 heartbeat. flops_per_seq is
        # refreshed once the DATA sequence length is known (phase-1 data is
        # 128 tokens while max_position_embeddings stays 512).
        from bert_pytorch_tpu.utils import flops as flops_util
        tele = telemetry.from_args(
            args,
            sink=args.telemetry_sink,
            is_primary=is_main_process(),
            seq_per_step=args.global_batch_size,
            flops_per_seq=flops_util.bert_train_flops_per_seq(
                config, seq_len, eff_max_pred,
                next_sentence=bool(config.next_sentence)),
            # Padding-aware accounting: the step's token budget; the train
            # step's real_tokens metric divides out the pads
            # (padding_efficiency in the window records).
            tokens_per_step=args.global_batch_size * seq_len,
            output_dir=args.output_dir,
            process="pretrain")
        tele.attach_loader(loader)
        train_step = tele.instrument(train_step, "train_step")

        eval_step = None
        if val_loader is not None:
            from bert_pytorch_tpu.parallel import batch_sharding

            eval_step = tele.instrument(
                pretrain.make_eval_step(
                    model, next_sentence=bool(config.next_sentence)),
                "eval_step")
            # Keys follow the batch (offline-packed validation shards add
            # sequence_ids/cls_positions); every array shards the same way.
            eval_sharding = batch_sharding(mesh)

            # Every pass evaluates the SAME deterministic slice: the sampler
            # is reset to 0 first (the loader's prefetch over-advances it by
            # a race-dependent amount otherwise), and the batch count is a
            # pure function of the dataset size — so multi-host runs execute
            # the same number of collective eval steps on every host, and
            # logged val losses are comparable across passes and reruns.
            eval_n_batches = min(
                args.eval_batches,
                len(val_loader.sampler) // args.host_batch_per_step)

            def run_validation(params, step_no, epoch_no):
                """Held-out MLM(+NSP) loss (the reference never evaluates
                during pretraining)."""
                if eval_n_batches == 0:
                    return
                val_loader.sampler.index = 0
                loss_sum = acc_sum = 0.0
                n = 0
                for vb in val_loader:
                    vloss, vacc = eval_step(
                        params, pretrain.put_batch(
                            vb, {k: eval_sharding for k in vb}))
                    loss_sum += float(vloss)
                    acc_sum += float(vacc)
                    n += 1
                    if n >= eval_n_batches:
                        break
                logger.log(tag="val", step=step_no, epoch=epoch_no,
                           average_loss=loss_sum / n,
                           mlm_accuracy=acc_sum / n)

        steps_this_run = args.steps or (args.max_steps - global_step)
        steps_this_run = min(steps_this_run, args.max_steps - global_step)
        logger.info(f"Starting at global step {global_step}; running "
                    f"{steps_this_run} steps "
                    f"(accumulation_steps={args.accumulation_steps})")

        epoch = int(checkpoint["epoch"]) if checkpoint else 0
        step_in_run = 0
        train_start = time.perf_counter()
        samples_seen = 0
        last_metrics = {}
        done = False
        # Graceful preemption (docs/fault_tolerance.md; beyond the
        # reference, whose only fault model is die-and-resubmit, SURVEY
        # §5.3): TPU-VM maintenance events and SLURM preemption deliver
        # SIGTERM/SIGUSR1 with a short grace period; an operator's Ctrl-C
        # delivers SIGINT. The shared GracefulStop handler only sets a
        # flag; the loop acts on it at a fixed step cadence so every host
        # of a multi-host job reaches the agreement collective at the same
        # step, then the normal end-of-run epilogue writes the final
        # checkpoint and __main__ exits with EXIT_PREEMPTED.
        terminated = False
        stop = preemption.GracefulStop()
        if args.term_check_steps:
            stop.install()
        # Deterministic fault injection (testing/faults.py): inert unless
        # --fault_spec / BERT_FAULTS armed it — the chaos harness's hooks
        # into this loop (die/term/hang after the checkpoint block,
        # metric poisoning before the sentinel sees the step).
        fault_plan = (faults.arm(args.fault_spec) if args.fault_spec
                      else faults.get_plan())
        # The DATA sequence length (what the FLOP/MFU accounting must use;
        # phase-1 data is 128 tokens while max_position_embeddings stays 512).
        data_seq_len = None
        # Position of the last TRAINED sample this epoch. The sampler's live
        # ``index`` runs ahead of training by the loader queue plus the
        # device_prefetch depth (the reference's checkpoints have the same
        # skew from its 4 DataLoader workers, src/dataset.py:401-425 — data
        # those pipelines had buffered is silently skipped on resume).
        # Checkpoints therefore save THIS counter, not the live index.
        trained_index = sampler.index

        def sampler_checkpoint_state():
            s = sampler.state_dict()
            s["index"] = trained_index
            return s

        def dispatch_step(state, batch, kfac_state, global_step):
            """One optimizer step's dispatch (the only Python between
            batches; returns before the device finishes — telemetry's
            step timer owns the sync)."""
            if kfac_fused:
                # In-train capture: the step harvests factors from
                # microbatch 0's own backward, rebuilds inverses
                # in-jit on due steps from the factors it just
                # captured, and preconditions with them — the
                # exact kfac_pytorch optimizer.step() ordering
                # (hooks during backward, due inverses, update).
                # Both cadences are lax.cond-gated inside the one
                # compiled step; no host round trips.
                state, metrics, kfac_state = train_step(
                    state, batch, kfac_state)
            elif kfac_obj is not None:
                # kfac_pytorch cadence: factors (EMA) every
                # factor_interval steps from the current data, inverses
                # every inv_interval steps; both fire on the first step.
                if global_step % args.kfac_factor_interval == 0:
                    n_stats = args.kfac_stats_batch
                    if n_stats and n_stats < batch["input_ids"].shape[1]:
                        # Strided rows: every data shard of the global
                        # batch contributes to the statistics (a [:n]
                        # head-slice would sample only shard 0's data).
                        stride = batch["input_ids"].shape[1] // n_stats
                        mb0 = {k: v[0][::stride][:n_stats]
                               for k, v in batch.items()}
                    else:
                        mb0 = {k: v[0] for k, v in batch.items()}
                    kfac_state = kfac_obj.update_factors(
                        kfac_state, state.params, mb0,
                        jax.random.fold_in(
                            jax.random.PRNGKey(args.seed + 17), global_step))
                if global_step % args.kfac_inv_interval == 0:
                    kfac_state = kfac_obj.update_inverses(kfac_state)
                state, metrics = train_step(state, batch, kfac_state)
            else:
                state, metrics = train_step(state, batch)
            return state, metrics, kfac_state

        # Handlers stay installed through the final checkpoint write:
        # preemption re-delivers SIGTERM during the grace period, and
        # the default disposition would kill the write mid-file. The
        # finally also un-installs them on exceptions (in-process
        # callers must not inherit a handler over a dead flag).
        prefetcher = None
        try:
            while not done:
                sampler.set_epoch(epoch)
                # Device prefetch (data/device_prefetch.py): a background
                # thread keeps --device_prefetch batches resident on
                # device, so data_wait below measures only true producer
                # stalls and the staging share reports as the h2d_wait
                # sub-phase. One prefetcher per epoch (the iterator is
                # one-shot); closed in the finally so an abandoned epoch
                # never leaks its thread.
                prefetcher = pretrain.device_prefetch(
                    loader, args.accumulation_steps, b_shardings,
                    depth=args.device_prefetch)
                tele.attach_prefetcher(prefetcher)
                for batch in tele.timed(iter(prefetcher)):
                    # Profiler window (steps are step_in_run indices; this
                    # iteration runs step step_in_run + 1).
                    tele.profiler.maybe_start(step_in_run + 1)
                    with tele.profiler.annotation(step_in_run + 1):
                        state, metrics, kfac_state = dispatch_step(
                            state, batch, kfac_state, global_step)
                    tele.dispatch_done()
                    global_step += 1
                    step_in_run += 1
                    trained_index += args.host_batch_per_step
                    if data_seq_len is None:
                        data_seq_len = int(batch["input_ids"].shape[-1])
                        if data_seq_len != seq_len:
                            # MFU must use the DATA shape, not the model cap.
                            from bert_pytorch_tpu.utils import flops as _fl
                            tele.timer.flops_per_seq = (
                                _fl.bert_train_flops_per_seq(
                                    config, data_seq_len,
                                    eff_max_pred,
                                    next_sentence=bool(config.next_sentence)))
                            tele.timer.tokens_per_step = (
                                args.global_batch_size * data_seq_len)
                    if step_in_run > 1:  # skip step-0 compile in throughput
                        samples_seen += args.global_batch_size
                    if step_in_run == 1:
                        # Wait for the first step to EXECUTE before starting the
                        # clock (reference skips step 0 the same way, its
                        # run_pretraining.py:494-495). Dispatch of step 1 returns
                        # as soon as compilation ends; on remote-attached TPUs the
                        # executable upload still congests the link for a while,
                        # and without this barrier that tail lands inside the
                        # measured window (observed: 280 vs 400 seq/s reported
                        # for identical steady-state device throughput).
                        jax.block_until_ready(metrics)
                        train_start = time.perf_counter()
                    if fault_plan.active:
                        # Armed NaN injection replaces the fetched scalars
                        # BEFORE the sentinel observes this step.
                        metrics = fault_plan.poison_metrics(
                            global_step, metrics, emit=tele.emit)
                    # Telemetry step close-out: device sync (per cadence) +
                    # step-window emission + sentinel policy + heartbeat +
                    # watchdog note + profiler auto-stop. NonFiniteError
                    # propagates under --sentinel_policy abort.
                    tele.step_done(global_step, metrics,
                                   profile_step=step_in_run)

                    if global_step % args.log_steps == 0:
                        last_metrics = {k: float(v) for k, v in metrics.items()}
                        if not tele.last_step_synced:
                            # The float() fetches above were this step's
                            # sync; feed the sentinel/heartbeat that missed
                            # the cadence. Both train steps emit the in-jit
                            # "finite" scalar; the isfinite(loss) fallback
                            # is defensive for any step that doesn't, so a
                            # missing key can't read as healthy.
                            finite = last_metrics.get("finite")
                            if finite is None:
                                finite = (1.0 if math.isfinite(
                                    last_metrics["loss"]) else 0.0)
                            tele.sentinel.observe(
                                global_step, finite, last_metrics["loss"])
                            tele.heartbeat.beat(
                                global_step, last_metrics["loss"])
                        elapsed = time.perf_counter() - train_start
                        logger.log(
                            tag="train", step=global_step, epoch=epoch,
                            average_loss=last_metrics["loss"],
                            step_loss=last_metrics["loss"],
                            learning_rate=last_metrics.get("learning_rate", 0.0),
                            samples_per_second=samples_seen / max(elapsed, 1e-9),
                            mlm_accuracy=last_metrics.get("mlm_accuracy", 0.0),
                            grad_norm=last_metrics.get("grad_norm", 0.0))

                    if (eval_step is not None
                            and global_step % args.num_steps_per_eval == 0):
                        run_validation(state.params, global_step, epoch)

                    if global_step % args.num_steps_per_checkpoint == 0:
                        save_step = global_step + args.previous_phase_end_step
                        contents = {"model": state.params,
                                    "optimizer": state.opt_state,
                                    "sampler": sampler_checkpoint_state(),
                                    "epoch": epoch}
                        if kfac_state is not None:
                            contents["preconditioner"] = kfac_state
                        # Async (default): the loop pays only the
                        # device-side snapshot copy; the D2H fetch +
                        # msgpack + disk write overlap the next training
                        # steps. The stall context flags this step's
                        # duration (+ the save block) as a ckpt_step in
                        # the telemetry windows either way — what the
                        # checkpoint-step p95 comparison reads.
                        with tele.checkpoint_stall():
                            ckpt.save_checkpoint(
                                args.model_output_dir, save_step, contents,
                                keep=args.keep_checkpoints,
                                async_write=args.checkpoint_write == "async",
                                layout=args.checkpoint_layout,
                                mesh_spec=args.mesh_spec.as_dict())
                        logger.info(f"Saved checkpoint at step {save_step}")

                    if fault_plan.active:
                        # die/term/hang fire AFTER the checkpoint block:
                        # die@N resumes from whatever N's cadence durably
                        # wrote — the hard-preemption model under test.
                        fault_plan.fire_process_faults(
                            global_step, emit=tele.emit)

                    if (args.term_check_steps
                            and global_step % args.term_check_steps == 0):
                        flagged = stop.requested
                        if jax.process_count() > 1:
                            # Any-host semantics: the scheduler may signal hosts
                            # at different times; stop only when agreed, at the
                            # same step on every host (this allgather is the
                            # agreement point — all hosts reach it).
                            from jax.experimental import multihost_utils
                            flagged = bool(multihost_utils.process_allgather(
                                np.asarray([flagged])).any())
                        if flagged:
                            logger.info(
                                f"termination signal "
                                f"({stop.signal_name or 'peer host'}) "
                                "received; writing the final checkpoint "
                                "and exiting cleanly "
                                f"(exit code {preemption.EXIT_PREEMPTED})")
                            tele.emit(preemption.preemption_record(
                                global_step, stop))
                            terminated = True
                            done = True
                            break

                    if step_in_run >= steps_this_run or global_step >= args.max_steps:
                        done = True
                        break
                else:
                    epoch += 1
                    trained_index = 0
                    continue
                break

            if tele.profiler.active:  # run ended inside the profile window
                tele.profiler.stop(sync_target=metrics)
            if tele.profiler.done:
                logger.info(f"profiler trace written to {args.profile_dir}")

            train_time = time.perf_counter() - train_start
            seq_per_sec = samples_seen / max(train_time, 1e-9)
            logger.info(f"Total time: {train_time:.2f} s")
            logger.info(f"training_seq_per_sec = {seq_per_sec:.2f}")
            # MFU: hardware-normalised counterpart of seq/s (the reference
            # reports raw seq/s only, run_pretraining.py:597-599); 0.0 when the
            # device kind has no known peak (e.g. the CPU test mesh).
            from bert_pytorch_tpu.utils import flops as flops_util
            train_mfu = flops_util.mfu(
                seq_per_sec / max(jax.device_count(), 1),
                flops_util.bert_train_flops_per_seq(
                    config, data_seq_len or seq_len,
                    eff_max_pred,
                    next_sentence=bool(config.next_sentence)),
                jax.devices()[0].device_kind)
            if train_mfu:
                logger.info(f"training_mfu = {train_mfu:.4f}")
            # Final checkpoint so short runs resume exactly. A
            # termination-signal checkpoint overrides --skip_final_checkpoint:
            # preemption resume must survive capture-mode runs too.
            if not args.skip_final_checkpoint or terminated:
                save_step = global_step + args.previous_phase_end_step
                contents = {"model": state.params,
                            "optimizer": state.opt_state,
                            "sampler": sampler_checkpoint_state(),
                            "epoch": epoch}
                if kfac_state is not None:
                    contents["preconditioner"] = kfac_state
                # Final/emergency checkpoint stays SYNCHRONOUS: durability
                # before exit is the point (docs/fault_tolerance.md), and
                # save_checkpoint joins this directory's in-flight async
                # write first so checkpoints land in order.
                with tele.checkpoint_stall():
                    ckpt.save_checkpoint(
                        args.model_output_dir, save_step, contents,
                        keep=args.keep_checkpoints,
                        layout=args.checkpoint_layout,
                        mesh_spec=args.mesh_spec.as_dict())
            ckpt.wait_for_pending_save()
            # Flush the partial telemetry window + final heartbeat + run
            # summary (the JSONL sink itself is closed by logger.close()).
            run_summary = {
                "training_seq_per_sec": round(seq_per_sec, 2),
                "training_mfu": round(train_mfu, 4),
                "terminated_by_signal": terminated,
                # Topology label: telemetry-report groups/labels loss and
                # step-time trajectories per mesh product with this.
                "mesh_spec": args.mesh_spec.canonical(),
            }
            # Run-level padding accounting: what fraction of the token
            # budget was real work, and the throughput in real tokens —
            # the number packing moves even when seq/s (rows/s) doesn't.
            run_eff = tele.timer.run_padding_efficiency()
            if run_eff is not None:
                run_summary["padding_efficiency"] = round(run_eff, 4)
                run_summary["real_tokens_per_sec"] = round(
                    seq_per_sec * (data_seq_len or seq_len) * run_eff, 2)
            tele.finish(global_step, summary=run_summary)
            logger.close()
        finally:
            if prefetcher is not None:
                prefetcher.close()
            stop.restore()
        return {"global_step": global_step,
                "training_seq_per_sec": seq_per_sec,
                "training_mfu": train_mfu,
                "terminated_by_signal": terminated,
                **last_metrics}


if __name__ == "__main__":
    arguments = parse_arguments()
    np.random.seed(arguments.seed + get_rank())
    outcome = main(arguments)
    if outcome.get("terminated_by_signal"):
        # Distinct exit code (75 = EX_TEMPFAIL): "checkpointed cleanly
        # under preemption, resubmit me" — schedulers/drivers can key
        # auto-resubmission on it (docs/fault_tolerance.md).
        sys.exit(preemption.EXIT_PREEMPTED)
