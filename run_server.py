"""Online inference server — the serving entry point (docs/serving.md).

Loads a checkpoint PARAMS-ONLY (utils/checkpoint.py ``load_params_only``
— the optimizer/K-FAC pytrees never touch serving memory), AOT-compiles
one jitted forward per (task head, length bucket) at startup, and serves
a stdlib JSON-over-HTTP API with dynamic micro-batching and optional
request packing::

    python run_server.py --model_config_file configs/bert_base_config.json \
        --vocab_file vocab.txt --tasks fill_mask,classify \
        --classify_labels neg,pos --fill_mask_checkpoint out/ \
        --buckets 32,64,128 --max_batch_size 8 --max_wait_ms 5 --port 8000

    curl -s localhost:8000/v1/fill_mask \
        -d '{"text": "the capital of [MASK] is paris"}'
    curl -s localhost:8000/healthz
    curl -s localhost:8000/statsz
    curl -s localhost:8000/metricsz   # Prometheus text format

Per-task ``--<task>_checkpoint`` accepts either a ``ckpt_*.msgpack`` file
or a directory (the newest checkpoint is picked via
``latest_checkpoint``); a task without one serves RANDOMLY-INITIALIZED
weights (smoke/demo mode) and says so loudly. Serve telemetry
(``serve_window``/``serve_summary`` records, schema v1) lands in the
JSONL sink next to training telemetry and is summarized by
``telemetry-report``.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys

from bert_pytorch_tpu.utils import logging as logger


def parse_arguments(argv=None):
    parser = argparse.ArgumentParser(description="TPU BERT inference server")
    parser.add_argument("--model_config_file", type=str, required=True)
    parser.add_argument("--vocab_file", type=str, default=None)
    parser.add_argument("--tokenizer", type=str, default=None,
                        choices=["wordpiece", "bpe"])
    parser.add_argument("--uppercase", action="store_true")
    parser.add_argument("--tasks", type=str,
                        default="fill_mask,classify,squad,ner",
                        help="comma-separated task heads to serve")
    for task in ("fill_mask", "classify", "squad", "ner"):
        parser.add_argument(f"--{task}_checkpoint", type=str, default=None,
                            help=f"params checkpoint for the {task} head "
                                 "(file or run output dir); omitted = "
                                 "random init (demo mode)")
    parser.add_argument("--classify_labels", type=str, default="0,1",
                        help="comma-separated labels for classify")
    parser.add_argument("--ner_labels", type=str,
                        default="O,B-PER,I-PER,B-LOC,I-LOC,B-ORG,I-ORG,"
                                "B-MISC,I-MISC",
                        help="comma-separated NER tag set (ids 1-based)")
    parser.add_argument("--buckets", type=str, default="32,64,128",
                        help="length buckets; one forward is AOT-compiled "
                             "per (task, bucket) at startup")
    parser.add_argument("--max_batch_size", type=int, default=8)
    parser.add_argument("--max_wait_ms", type=float, default=5.0,
                        help="micro-batch deadline: a partial batch "
                             "dispatches when its oldest request has "
                             "waited this long")
    # Inference fast path (docs/serving.md): --quantize/--attention_backend,
    # shared with tools/batch_infer.py via one helper. Tracing/SLO knobs
    # (docs/serving.md "Request tracing & metrics") and the dispatch-plane
    # mode (docs/serving.md "Continuous batching") ride the same way.
    from bert_pytorch_tpu.serve.cli import (add_dispatch_args,
                                            add_fast_path_args,
                                            add_tracing_args)

    add_dispatch_args(parser)
    add_fast_path_args(parser)
    add_tracing_args(parser)
    parser.add_argument("--pack_requests", action="store_true",
                        help="pack several short requests per row with "
                             "block-diagonal attention (data/packing.py)")
    parser.add_argument("--max_requests_per_pack", type=int, default=4)
    parser.add_argument("--max_pending", type=int, default=1024,
                        help="pending-queue cap; submissions beyond it "
                             "shed with HTTP 503 instead of growing "
                             "memory/latency without bound")
    parser.add_argument("--host", type=str, default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--request_timeout_s", type=float, default=30.0)
    parser.add_argument("--dtype", type=str, default="bfloat16",
                        choices=["bfloat16", "float32"])
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--output_dir", type=str, default=None,
                        help="telemetry/heartbeat anchor dir")
    parser.add_argument("--telemetry_jsonl", type=str, default="",
                        help="serve telemetry JSONL sink; default "
                             "<output_dir>/serve_telemetry.jsonl")
    parser.add_argument("--heartbeat_file", type=str, default="",
                        help="resumable liveness file the dispatch loop "
                             "maintains (telemetry/sentinels.py Heartbeat "
                             "— the same file the training runners write, "
                             "read by the capture harness); default "
                             "<output_dir>/heartbeat.json, disabled "
                             "without an output_dir")
    parser.add_argument("--telemetry_window", type=int, default=64,
                        help="requests per serve_window record")
    parser.add_argument("--postmortem_file", type=str, default="",
                        help="crash flight recorder flush target "
                             "(telemetry/flightrec.py): the bounded ring "
                             "of this replica's last telemetry records + "
                             "log lines, written atomically on fault/"
                             "crash and periodically (so a SIGKILLed "
                             "replica leaves forensics for the "
                             "supervisor's postmortem harvest); default "
                             "<output_dir>/postmortem.json, disabled "
                             "without an output_dir")
    parser.add_argument("--compile_cache_dir", type=str, default="",
                        help="persistent XLA compile cache; empty disables")
    parser.add_argument("--serving_version", type=str, default="v0",
                        help="model version this replica starts on "
                             "(serve/registry.py names; reported on "
                             "/healthz, /statsz and the "
                             "bert_serve_serving_version gauge — the "
                             "router's canary split routes on it)")
    parser.add_argument("--save_init_checkpoint", type=str, default="",
                        help="write the first task's (possibly random-"
                             "init) params as ckpt_0.msgpack + integrity "
                             "manifest under this dir before serving — "
                             "gives a jax-free parent (tools/"
                             "chaos_serve.py) a real checkpoint to "
                             "publish into a model registry")
    args = parser.parse_args(argv)

    with open(args.model_config_file) as f:
        configs = json.load(f)
    if args.vocab_file is None:
        args.vocab_file = configs.get("vocab_file")
        if args.vocab_file is None:
            raise ValueError("vocab_file must be in model config or CLI")
    if args.tokenizer is None:
        args.tokenizer = configs.get("tokenizer", "wordpiece")
    return args


def build_service(args):
    """(service, telemetry_sink) — separated from main() so bench.py and
    tests can build the serving stack without binding a socket."""
    import jax.numpy as jnp

    from bert_pytorch_tpu.config import BertConfig
    from bert_pytorch_tpu.data.tokenization import (get_bpe_tokenizer,
                                                    get_wordpiece_tokenizer)
    from bert_pytorch_tpu.serve import (Batcher, InferenceEngine,
                                        ServeTelemetry, ServingService)
    from bert_pytorch_tpu.telemetry.compile_events import CompileMonitor
    from bert_pytorch_tpu.utils import checkpoint as ckpt_util

    if args.compile_cache_dir:
        from bert_pytorch_tpu.utils.compile_cache import enable_compile_cache

        # min_compile_secs=0: persist EVERY per-(task, bucket) forward —
        # the warm-restart acceptance is "second start performs zero cold
        # compiles", and the training-oriented default bar would filter
        # the seconds-scale serve executables out of the cache.
        enable_compile_cache(args.compile_cache_dir, min_compile_secs=0.0)

    config = BertConfig.from_json_file(args.model_config_file)
    if config.vocab_size % 8 != 0:
        config.vocab_size += 8 - (config.vocab_size % 8)
    if args.tokenizer == "wordpiece":
        tokenizer = get_wordpiece_tokenizer(
            args.vocab_file, uppercase=args.uppercase)
    else:
        tokenizer = get_bpe_tokenizer(
            args.vocab_file, uppercase=args.uppercase)

    def resolve_ckpt(path):
        if not path:
            return None
        if os.path.isdir(path):
            found = ckpt_util.latest_checkpoint(path)
            if found is None:
                raise FileNotFoundError(f"no ckpt_*.msgpack under {path}")
            return found
        return path

    tasks = {}
    for task in args.tasks.split(","):
        task = task.strip()
        if not task:
            continue
        options = {"checkpoint":
                   resolve_ckpt(getattr(args, f"{task}_checkpoint", None))}
        if task == "classify":
            options["labels"] = args.classify_labels.split(",")
        elif task == "ner":
            options["labels"] = args.ner_labels.split(",")
        elif task == "squad":
            options["do_lower_case"] = not args.uppercase
        tasks[task] = options
        if options["checkpoint"] is None:
            logger.info(f"task {task}: NO checkpoint — serving randomly "
                        "initialized weights (demo mode)")

    telemetry_jsonl = args.telemetry_jsonl or (
        os.path.join(args.output_dir, "serve_telemetry.jsonl")
        if args.output_dir else None)
    sink = (logger.JSONLHandler(telemetry_jsonl, overwrite=False)
            if telemetry_jsonl else None)
    # Crash flight recorder (telemetry/flightrec.py, docs/
    # observability.md): every telemetry record tees into a bounded
    # ring, flushed to postmortem.json on fault/crash and periodically —
    # the file the supervisor harvests when this replica dies.
    from bert_pytorch_tpu.telemetry.flightrec import FlightRecorder

    postmortem = getattr(args, "postmortem_file", "") or (
        os.path.join(args.output_dir, "postmortem.json")
        if args.output_dir else None)
    recorder = (FlightRecorder(postmortem, process="serve")
                .install_exit_hooks() if postmortem else None)
    emit = sink.write_record if sink else None
    if recorder is not None:
        emit = recorder.tee(emit)
    serve_tele = ServeTelemetry(
        emit=emit,
        window=args.telemetry_window)
    monitor = CompileMonitor(
        emit=emit if emit is not None else (lambda rec: None))
    # Request tracing + /metricsz (docs/serving.md "Request tracing &
    # metrics"): spans for the head-sampled fraction (and EVERY over-SLO
    # request), serve_phase decomposition windows, Prometheus export.
    from bert_pytorch_tpu.serve.cli import build_tracer

    tracer = build_tracer(args, emit=emit,
                          window=args.telemetry_window)
    # Serve heartbeat: the same resumable liveness file the five training
    # runners maintain, so the capture harness covers serving processes.
    from bert_pytorch_tpu.telemetry.sentinels import Heartbeat

    heartbeat_path = args.heartbeat_file or (
        os.path.join(args.output_dir, "heartbeat.json")
        if args.output_dir else None)
    heartbeat = Heartbeat(heartbeat_path) if heartbeat_path else None
    # On-demand profiling plane (telemetry/sampler.py, docs/
    # observability.md): POST /profilez arms a bounded host-sampler +
    # jax trace capture; the dispatch plane ticks it per boundary with
    # position = requests served. The ProfilerWindow here exists only
    # for the on-demand begin/end facility (no startup spec).
    from bert_pytorch_tpu.telemetry.profiler import ProfilerWindow
    from bert_pytorch_tpu.telemetry.sampler import CaptureController

    profile_dir = (os.path.join(args.output_dir, "profile")
                   if args.output_dir else None)
    capture = CaptureController(
        source="replica", covered_unit="requests",
        window=ProfilerWindow(None, profile_dir, enabled=bool(profile_dir)),
        trace_dir=profile_dir, emit=emit)

    engine = InferenceEngine(
        config,
        tokenizer,
        tasks,
        buckets=[int(b) for b in args.buckets.split(",")],
        max_batch_size=args.max_batch_size,
        max_requests_per_pack=(args.max_requests_per_pack
                               if args.pack_requests else 1),
        dtype=jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32,
        seed=args.seed,
        monitor=monitor,
        quantize=args.quantize,  # "none" normalizes to None in the engine
        attention_backend=args.attention_backend,
        fuse_epilogues=args.fuse_epilogues,
        epilogue_slots=args.epilogue_slots,
        autotune=args.autotune,
        autotune_cache=args.autotune_cache or None,
        version=getattr(args, "serving_version", "v0"),
    )
    batcher = Batcher(
        max_batch_size=args.max_batch_size,
        max_wait_ms=args.max_wait_ms,
        max_requests_per_pack=engine.max_requests_per_pack,
        max_pending=args.max_pending)
    service = ServingService(engine, batcher, serve_tele, tracer=tracer,
                             heartbeat=heartbeat, capture=capture,
                             dispatch_mode=getattr(args, "dispatch_mode",
                                                   "pipelined"))
    # Rides the service so main()/tests reach it without widening the
    # (service, sink) signature batch_infer/bench already consume.
    service.flight_recorder = recorder
    return service, sink


def main(args) -> int:
    """Serve until interrupted; returns the process exit code.

    A SIGTERM-initiated drain exits with ``preemption.EXIT_PREEMPTED``
    (75) — the SAME contract the five training runners hold
    (utils/preemption.py): the supervisor (serve/supervisor.py) and any
    scheduler can distinguish "drained cleanly, every accepted request
    answered" from success (0, an operator Ctrl-C) and from crashes
    (anything else). A crashed replica is restarted with backoff; a
    drained one was ASKED to stop.
    """
    from bert_pytorch_tpu.serve import make_server

    logger.init(handlers=[logger.StreamHandler()])
    service, sink = build_service(args)
    save_dir = getattr(args, "save_init_checkpoint", "")
    if save_dir:
        # Materialize the first task's params as a real, manifested
        # checkpoint BEFORE serving: the jax-free chaos/rollout parent
        # publishes this file into a model registry and swaps it back in
        # as a new version (same geometry, so the swap compiles nothing).
        from bert_pytorch_tpu.utils import checkpoint as ckpt_util

        first_task = sorted(service.engine.tasks)[0]
        ckpt_path = ckpt_util.save_checkpoint(
            save_dir, 0,
            {"model": service.engine.tasks[first_task].params, "epoch": 0})
        logger.info(f"init checkpoint for task {first_task}: {ckpt_path}")
    if service.flight_recorder is not None:
        # Log lines tee into the flight-recorder ring too: a postmortem
        # carries the replica's last words, not just its last records.
        logger.add_handler(service.flight_recorder.log_handler())
    logger.info(
        f"warming {len(service.engine.tasks)} task heads over buckets "
        f"{service.engine.buckets} "
        f"(pack={service.engine.max_requests_per_pack}, "
        f"quantize={service.engine.quantize or 'none'}, "
        f"attention={service.engine.attention_backend})")
    service.engine.warmup()
    startup = service.engine.startup or {}
    logger.info(
        f"warmup done in {startup.get('cold_start_s')}s: "
        f"{startup.get('compiles_cold')} cold compiles / "
        f"{startup.get('compiles_warm')} persistent-cache hits "
        f"({startup.get('weight_bytes', 0) / (1 << 20):.1f} MiB weights); "
        "steady-state serving recompiles nothing")
    service.start()
    server = make_server(service, host=args.host, port=args.port,
                         request_timeout_s=args.request_timeout_s)
    host, port = server.server_address[:2]
    logger.info(f"serving {sorted(service.engine.tasks)} on "
                f"http://{host}:{port} (POST /v1/<task>, GET /healthz, "
                "GET /statsz, GET /metricsz) — dispatch "
                f"{service.dispatch_mode}, tracing "
                f"{args.trace_sample_rate:.0%} head-sampled, "
                f"SLO p99 {args.slo_p99_ms:g}ms (over-SLO always traced)")

    preempted = {"signaled": False}

    def shutdown(signum, frame):
        # Graceful drain (docs/fault_tolerance.md): flip /healthz to 503
        # FIRST — load balancers stop routing on their next probe while
        # the listener is still up — then unwind through the finally
        # below, which flushes in-flight requests before stopping. The
        # flag is what turns the exit code into EXIT_PREEMPTED: only a
        # SIGTERM-initiated drain is a preemption (Ctrl-C stays 0).
        preempted["signaled"] = True
        service.begin_drain()
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, shutdown)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        logger.info("draining: rejecting new requests (healthz 503), "
                    "flushing in-flight batches, then shutting down")
        if preempted["signaled"] and service.telemetry.emit is not None:
            # The training runners' preemption fault record, serve
            # flavor: the artifact says WHY this run ended (schema v1
            # `fault` kind; step = requests served at the signal).
            # Emitted through the teed path so the flight recorder sees
            # the incident and flushes its postmortem alongside.
            service.telemetry.emit({
                "kind": "fault", "tag": "serve", "fault": "preemption",
                "signal": "SIGTERM", "injected": False,
                "step": service.telemetry.request_count(),
            })
        server.shutdown()
        service.stop()  # drain + dispatch-thread join + telemetry summary
        if sink is not None:
            sink.close()
        if service.flight_recorder is not None:
            exc = sys.exc_info()[1]
            if exc is not None and not isinstance(exc, KeyboardInterrupt):
                # An exception is escaping the serve loop: flush the
                # forensics WITH the traceback instead of deleting them
                # (a clean close would also disarm the excepthook).
                service.flight_recorder.flush("crash", exc=exc)
            else:
                # Clean close removes the postmortem; the preemption
                # fault above counts as an incident, so a drained
                # replica keeps its forensics on disk.
                service.flight_recorder.close(clean=True)
        logger.close()
    from bert_pytorch_tpu.utils import preemption

    return preemption.EXIT_PREEMPTED if preempted["signaled"] else 0


if __name__ == "__main__":
    sys.exit(main(parse_arguments()))
