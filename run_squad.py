"""SQuAD v1.1/v2.0 finetuning + prediction runner — TPU-native counterpart of
reference run_squad.py.

Capability parity (SURVEY.md §3.3): example reading / sliding-window
featurization with pickle cache, span-loss finetuning of
BertForQuestionAnswering (AdamW bias_correction=False + linear warmup — the
FusedAdam path of run_squad.py:980-996 — or BertAdam with its internal
schedule for the fp32 path, :999-1002), batched prediction into RawResults,
n-best span decoding with text realignment (bert_pytorch_tpu/squad.py), the
official-eval-script subprocess oracle (:1197-1204), and the dllogger-style
summary metrics (e2e_train_time, training_sequences_per_second,
e2e_inference_time, exact_match, F1; :1206-1224). bf16 on TPU replaces the
Apex AMP O2 path; DDP is replaced by batch sharding over the device mesh.
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from bert_pytorch_tpu import optim, pretrain, squad, telemetry
from bert_pytorch_tpu.config import BertConfig
from bert_pytorch_tpu.data.tokenization import (
    get_bpe_tokenizer,
    get_wordpiece_tokenizer,
)
from bert_pytorch_tpu.models import BertForQuestionAnswering
from bert_pytorch_tpu.models.losses import span_loss
from bert_pytorch_tpu.ops.grad_utils import global_norm
from bert_pytorch_tpu.parallel import MeshConfig, create_mesh, logical_axis_rules
from bert_pytorch_tpu.utils import checkpoint as ckpt
from bert_pytorch_tpu.utils import logging as logger
from bert_pytorch_tpu.utils import preemption
from bert_pytorch_tpu.utils.compile_cache import enable_compile_cache
from bert_pytorch_tpu.utils.dist import is_main_process


def parse_args(argv=None):
    parser = argparse.ArgumentParser(description="TPU BERT SQuAD finetuning")
    parser.add_argument("--output_dir", type=str, required=True)
    parser.add_argument("--init_checkpoint", type=str, default=None,
                        help="pretraining checkpoint (.msgpack), torch .bin/.pt, TF ckpt prefix, or pretrained archive dir")
    parser.add_argument("--config_file", type=str, required=True,
                        help="BERT model config json")
    parser.add_argument("--train_file", type=str, default=None)
    parser.add_argument("--predict_file", type=str, default=None)
    parser.add_argument("--max_seq_length", type=int, default=384)
    parser.add_argument("--doc_stride", type=int, default=128)
    parser.add_argument("--max_query_length", type=int, default=64)
    parser.add_argument("--do_train", action="store_true")
    parser.add_argument("--do_predict", action="store_true")
    parser.add_argument("--do_eval", action="store_true")
    parser.add_argument("--train_batch_size", type=int, default=32)
    parser.add_argument("--predict_batch_size", type=int, default=8)
    parser.add_argument("--learning_rate", type=float, default=3e-5)
    parser.add_argument("--num_train_epochs", type=float, default=2.0)
    parser.add_argument("--max_steps", type=int, default=-1)
    parser.add_argument("--warmup_proportion", type=float, default=0.1)
    parser.add_argument("--n_best_size", type=int, default=20)
    parser.add_argument("--max_answer_length", type=int, default=30)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--compile_cache_dir", type=str, default="",
                        help="persistent XLA compilation cache directory; empty disables")
    parser.add_argument("--gradient_accumulation_steps", type=int, default=1)
    parser.add_argument("--do_lower_case", action="store_true")
    parser.add_argument("--version_2_with_negative", action="store_true")
    parser.add_argument("--null_score_diff_threshold", type=float, default=0.0)
    parser.add_argument("--vocab_file", type=str, default=None)
    parser.add_argument("--tokenizer", type=str, default=None,
                        choices=["wordpiece", "bpe"])
    parser.add_argument("--optimizer", type=str, default="adamw",
                        choices=["adamw", "bert_adam"],
                        help="adamw+linear-warmup = the reference fp16 path; "
                             "bert_adam = its fp32 path")
    parser.add_argument("--max_grad_norm", type=float, default=1.0)
    parser.add_argument("--dtype", type=str, default="bfloat16",
                        choices=["bfloat16", "float32", "float16"],
                        help="bfloat16 is the TPU default (no loss scaling "
                             "needed); float16 is the reference-parity AMP "
                             "mode (apex O2 + GradScaler, reference "
                             "run_squad.py:980-996) with a dynamic loss "
                             "scaler")
    parser.add_argument("--init_loss_scale", type=float, default=2.0 ** 16,
                        help="fp16 only: initial dynamic loss scale "
                             "(default matches torch GradScaler's 2**16)")
    parser.add_argument("--log_freq", type=int, default=50)
    # telemetry: canonical flag set shared by every runner
    # (telemetry/cli.py; docs/telemetry.md)
    telemetry.add_cli_args(parser)
    # device prefetch (data/device_prefetch.py; shared runner flag)
    from bert_pytorch_tpu.data import device_prefetch as dp_cli
    dp_cli.add_cli_args(parser)
    parser.add_argument("--save_steps", type=int, default=0,
                        help="periodic checkpoint cadence (optimizer "
                             "steps): async writes (device snapshot + "
                             "background write); the end-of-train/"
                             "emergency checkpoint stays synchronous. "
                             "0 disables")
    parser.add_argument("--json_summary", type=str, default="squad_log.json")
    parser.add_argument("--eval_script", type=str, default=None)
    parser.add_argument("--skip_checkpoint", action="store_true")
    parser.add_argument("--skip_cache", action="store_true")
    parser.add_argument("--cache_dir", type=str, default=None)
    parser.add_argument("--mesh_data", type=int, default=-1,
                        help="data-parallel mesh size; -1 = all local devices "
                             "(batch sizes must divide it)")
    args = parser.parse_args(argv)

    # vocab/tokenizer ride in the model config (reference run_squad.py:862-876)
    with open(args.config_file) as f:
        configs = json.load(f)
    if args.vocab_file is None:
        args.vocab_file = configs.get("vocab_file")
        if args.vocab_file is None:
            raise ValueError("vocab_file must be in the model config or CLI")
    if args.tokenizer is None:
        args.tokenizer = configs.get("tokenizer")
        if args.tokenizer is None:
            raise ValueError("tokenizer must be in the model config or CLI")
    if not args.do_train and not args.do_predict:
        raise ValueError("At least one of do_train or do_predict required")
    if args.do_train and not args.train_file:
        raise ValueError("do_train requires train_file")
    if args.do_predict and not args.predict_file:
        raise ValueError("do_predict requires predict_file")
    return args


def build_tokenizer(args):
    if args.tokenizer == "wordpiece":
        return get_wordpiece_tokenizer(args.vocab_file,
                                       uppercase=not args.do_lower_case)
    return get_bpe_tokenizer(args.vocab_file, uppercase=not args.do_lower_case)


def cached_features(args, examples, tokenizer, is_training, tag):
    """Pickle-cached featurization (reference run_squad.py:1027-1043)."""
    src = args.train_file if is_training else args.predict_file
    cache_dir = args.cache_dir or os.path.dirname(os.path.abspath(src))
    cache_file = os.path.join(
        cache_dir,
        f"{os.path.basename(src)}_{args.tokenizer}_{args.max_seq_length}_"
        f"{args.doc_stride}_{args.max_query_length}_{tag}.feat")
    if os.path.exists(cache_file) and not args.skip_cache:
        with open(cache_file, "rb") as f:
            return pickle.load(f)
    features = squad.convert_examples_to_features(
        examples, tokenizer, args.max_seq_length, args.doc_stride,
        args.max_query_length, is_training)
    if not args.skip_cache and is_main_process():
        try:
            with open(cache_file, "wb") as f:
                pickle.dump(features, f)
        except OSError:
            pass
    return features


def load_init_params(args, abstract_params, config):
    """Start from a pretraining checkpoint: copy the shared 'bert' encoder
    subtree; the QA head keeps its fresh init (the strict=False analog of
    reference run_squad.py:957-961).

    Accepts our msgpack checkpoints AND foreign pretrained archives — a
    directory with config.json + pytorch_model.bin / bert_model.ckpt.*, a
    torch .bin/.pt file, or a TF checkpoint prefix (the reference
    from_pretrained surface, modeling.py:659-799)."""
    from bert_pytorch_tpu.models import load_pretrained_encoder

    target = jax.device_get(abstract_params)
    return load_pretrained_encoder(
        args.init_checkpoint, config, target, fallback_full_tree=True)


def features_to_arrays(features, is_training):
    arrays = {
        "input_ids": np.asarray([f.input_ids for f in features], np.int32),
        "segment_ids": np.asarray([f.segment_ids for f in features], np.int32),
        "input_mask": np.asarray([f.input_mask for f in features], np.int32),
    }
    if is_training:
        arrays["start_positions"] = np.asarray(
            [f.start_position for f in features], np.int32)
        arrays["end_positions"] = np.asarray(
            [f.end_position for f in features], np.int32)
    return arrays


def main(args):
    enable_compile_cache(args.compile_cache_dir)
    np.random.seed(args.seed)
    devices = None
    if args.mesh_data > 0:
        devices = jax.devices()[: args.mesh_data]
    mesh = create_mesh(MeshConfig(data=-1), devices=devices)
    os.makedirs(args.output_dir, exist_ok=True)
    args.telemetry_jsonl = telemetry.default_jsonl_path(
        args, args.output_dir, "squad")
    args.heartbeat_file = args.heartbeat_file or os.path.join(
        args.output_dir, "heartbeat.json")
    args.profile_dir = args.profile_dir or os.path.join(
        args.output_dir, "profile")
    # Sink shared between the logger (train records) and TrainTelemetry
    # (docs/telemetry.md); telemetry records go ONLY to the JSONL.
    telemetry_sink = logger.JSONLHandler(
        args.telemetry_jsonl, overwrite=False, is_primary=is_main_process())
    logger.init(handlers=[
        logger.StreamHandler(verbose=is_main_process(),
                             is_primary=is_main_process()),
        logger.FileHandler(os.path.join(args.output_dir, args.json_summary),
                           is_primary=is_main_process()),
        telemetry_sink,
    ])

    config = BertConfig.from_json_file(args.config_file)
    if config.vocab_size % 8 != 0:
        config.vocab_size += 8 - (config.vocab_size % 8)
    dtype = {"bfloat16": jnp.bfloat16, "float16": jnp.float16,
             "float32": jnp.float32}[args.dtype]
    model = BertForQuestionAnswering(config, dtype=dtype)
    tokenizer = build_tokenizer(args)
    rules = logical_axis_rules("dp")

    seq = args.max_seq_length
    sample = (jnp.zeros((1, seq), jnp.int32),) * 3
    summary = {}

    with mesh:
        shardings_abstract = jax.eval_shape(
            lambda r: model.init(r, *sample), jax.random.PRNGKey(0))
        import flax.linen as nn
        from bert_pytorch_tpu.parallel.sharding import params_shardings

        p_shardings = params_shardings(mesh, shardings_abstract, rules)["params"]
        init_params = nn.unbox(
            jax.jit(lambda r: model.init(r, *sample),
                    out_shardings={"params": p_shardings})(
                jax.random.PRNGKey(args.seed)))["params"]
        if args.init_checkpoint:
            host_params = load_init_params(args, init_params, config)
            init_params = jax.device_put(host_params, p_shardings)
        params = init_params

        batch_sh = pretrain.batch_shardings(
            mesh, {"input_ids": 2, "segment_ids": 2, "input_mask": 2,
                   "start_positions": 1, "end_positions": 1})
        # [B,...] (no accumulation axis): batch axis 0 over data mesh axes
        from jax.sharding import NamedSharding, PartitionSpec as P

        from bert_pytorch_tpu.parallel.mesh import AXIS_DATA, AXIS_FSDP
        batch_sh = {k: NamedSharding(mesh, P((AXIS_DATA, AXIS_FSDP)))
                    for k in batch_sh}

        # Telemetry facade (docs/telemetry.md): step-time windows + MFU,
        # profiler trace window, compile attribution, non-finite sentinel
        # (host-side isfinite on the fetched loss), rank-0 heartbeat.
        from bert_pytorch_tpu.utils import flops as flops_util
        tele = telemetry.from_args(
            args,
            sink=telemetry_sink,
            is_primary=is_main_process(),
            seq_per_step=args.train_batch_size if args.do_train else None,
            flops_per_seq=flops_util.bert_finetune_flops_per_seq(
                config, args.max_seq_length, head_outputs=2),
            output_dir=args.output_dir,
            process="squad")

        if args.do_train:
            train_examples = squad.read_squad_examples(
                args.train_file, True, args.version_2_with_negative)
            train_features = cached_features(
                args, train_examples, tokenizer, True, "train")
            n = len(train_features)
            micro_bs = args.train_batch_size // args.gradient_accumulation_steps
            steps_per_epoch = n // args.train_batch_size
            total_steps = (args.max_steps if args.max_steps > 0 else
                           int(steps_per_epoch * args.num_train_epochs))
            logger.info(f"training features: {n}, optimizer steps: {total_steps}")

            mask = optim.no_decay_mask
            if args.optimizer == "adamw":
                schedule = optim.warmup_linear_schedule(
                    args.learning_rate, args.warmup_proportion, total_steps,
                    offset=0)
                tx = optim.adamw(schedule, bias_correction=False,
                                 weight_decay_mask=mask)
            else:
                tx = optim.bert_adam(
                    args.learning_rate, schedule="warmup_linear",
                    warmup=args.warmup_proportion, t_total=total_steps,
                    weight_decay_mask=mask)
            fp16 = args.dtype == "float16"
            if fp16:
                # Reference-parity AMP (apex O2 + loss scaling,
                # run_squad.py:980-996): the scaler state rides in
                # opt_state like the reference's amp state.
                tx = optim.dynamic_loss_scale(
                    tx, init_scale=args.init_loss_scale)
            opt_state = tx.init(params)

            stats_every = telemetry.stats_every(args)

            def train_step(params, opt_state, batch, rng):
                loss_scale = opt_state.scale if fp16 else 1.0

                def loss_fn(p):
                    start_logits, end_logits = model.apply(
                        {"params": p}, batch["input_ids"],
                        batch["segment_ids"], batch["input_mask"],
                        False, rngs={"dropout": rng})
                    loss = span_loss(start_logits, end_logits,
                                     batch["start_positions"],
                                     batch["end_positions"])
                    return loss * loss_scale, loss
                (_, loss), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
                if args.optimizer == "adamw" and args.max_grad_norm > 0:
                    # grads carry loss_scale in fp16; clip on the TRUE norm
                    # (the multiplicative clip commutes with the wrapper's
                    # unscale)
                    gnorm = global_norm(grads) / loss_scale
                    scale = jnp.minimum(1.0, args.max_grad_norm / (gnorm + 1e-6))
                    grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
                updates, opt_state2 = tx.update(grads, opt_state, params)
                import optax
                metrics = {"loss": loss}
                health = telemetry.finetune_grad_health(
                    params, grads, updates, opt_state, stats_every,
                    fp16_scale=loss_scale if fp16 else None)
                if health is not None:
                    metrics["grad_health"] = health
                return optax.apply_updates(params, updates), opt_state2, metrics

            train_step = tele.instrument(
                jax.jit(train_step, donate_argnums=(0, 1)), "train_step")

            rng = jax.random.PRNGKey(args.seed)
            order = np.random.permutation(n)
            global_step = 0
            t_start = time.perf_counter()
            seqs = 0
            epoch = 0
            losses = []

            def epoch_batches():
                """Featurize one epoch's HOST batches; the device
                prefetcher below stages them onto device ahead of the
                loop, so data_wait measures featurization stalls only
                (staging reports as the h2d_wait sub-phase)."""
                for i in range(0, n - args.train_batch_size + 1,
                               args.train_batch_size):
                    idx = order[i:i + args.train_batch_size]
                    feats = [train_features[j] for j in idx]
                    yield features_to_arrays(feats, True)

            from bert_pytorch_tpu.data import DevicePrefetcher

            def epoch_prefetcher():
                p = DevicePrefetcher(
                    epoch_batches(),
                    stage=lambda arrays: {
                        k: jax.device_put(v, batch_sh[k])
                        for k, v in arrays.items()},
                    depth=args.device_prefetch)
                tele.attach_prefetcher(p)
                return p

            # Graceful preemption (docs/fault_tolerance.md): stop at the
            # next step boundary, checkpoint via the normal end-of-train
            # write below, exit EXIT_PREEMPTED from __main__.
            # Handlers stay installed THROUGH the end-of-train checkpoint
            # write below (a grace-period re-delivery must not kill it);
            # restored in the finally even on exceptions.
            stop = preemption.GracefulStop().install()
            prefetcher = None
            try:
                while global_step < total_steps and not stop.requested:
                    prefetcher = epoch_prefetcher()
                    for batch in tele.timed(iter(prefetcher)):
                        rng, sub = jax.random.split(rng)
                        tele.profiler.maybe_start(global_step + 1)
                        with tele.profiler.annotation(global_step + 1):
                            params, opt_state, metrics = train_step(
                                params, opt_state, batch, sub)
                        tele.dispatch_done()
                        global_step += 1
                        seqs += args.train_batch_size
                        loss = metrics["loss"]
                        tele.step_done(global_step, metrics)
                        if global_step % args.log_freq == 0:
                            losses.append(float(loss))
                            logger.log(tag="train", step=global_step,
                                       step_loss=float(loss),
                                       samples_per_second=seqs / (
                                           time.perf_counter() - t_start))
                        if args.save_steps and not args.skip_checkpoint \
                                and is_main_process() \
                                and global_step % args.save_steps == 0:
                            # Periodic async save (device snapshot +
                            # background write; joined before the final
                            # write / predict reads below).
                            with tele.checkpoint_stall():
                                ckpt.save_checkpoint(
                                    args.output_dir, global_step,
                                    {"model": params,
                                     "config": config.to_dict()},
                                    keep=1, async_write=True)
                        if global_step >= total_steps or stop.requested:
                            break
                    prefetcher.close()
                    epoch += 1
                    order = np.random.permutation(n)
                if stop.requested:
                    logger.info(
                        f"termination signal ({stop.signal_name}) received; "
                        "checkpointing and exiting cleanly "
                        f"(exit code {preemption.EXIT_PREEMPTED})")
                    tele.emit(
                        preemption.preemption_record(global_step, stop))
                    summary["terminated_by_signal"] = True
                train_time = time.perf_counter() - t_start
                summary["e2e_train_time"] = train_time
                summary["training_sequences_per_second"] = seqs / train_time
                summary["final_loss"] = float(loss)
                tele.finish(global_step, summary={
                    "training_seq_per_sec": round(seqs / train_time, 2)})

                if not args.skip_checkpoint and is_main_process():
                    # A preemption stop must still land this write — it IS
                    # the emergency checkpoint for this runner. Synchronous
                    # on purpose; it joins any in-flight periodic async
                    # write to the same directory first, so checkpoints
                    # land in order. (No checkpoint_stall wrapper:
                    # telemetry is already flushed.)
                    ckpt.save_checkpoint(args.output_dir, global_step,
                                         {"model": params,
                                          "config": config.to_dict()},
                                         keep=1)
                # Join any in-flight async write BEFORE the predict path
                # below reads checkpoints back / the process exits.
                ckpt.wait_for_pending_save()
            finally:
                if prefetcher is not None:
                    prefetcher.close()
                stop.restore()

        if args.do_predict and not summary.get("terminated_by_signal"):
            # A preempted run exits after its emergency checkpoint; the
            # grace period is for durability, not for inference.
            eval_examples = squad.read_squad_examples(
                args.predict_file, False, args.version_2_with_negative)
            eval_features = cached_features(
                args, eval_examples, tokenizer, False, "predict")
            logger.info(f"predict features: {len(eval_features)}")

            @jax.jit
            def predict_step(params, batch):
                return model.apply({"params": params}, batch["input_ids"],
                                   batch["segment_ids"], batch["input_mask"])

            predict_step = tele.instrument(predict_step, "predict_step")

            t_infer = time.perf_counter()
            results = []
            bs = args.predict_batch_size
            # pad to full batches for static shapes
            padded = list(eval_features)
            while len(padded) % bs != 0:
                padded.append(eval_features[-1])
            for i in range(0, len(padded), bs):
                feats = padded[i:i + bs]
                arrays = features_to_arrays(feats, False)
                batch = {k: jax.device_put(v, batch_sh[k])
                         for k, v in arrays.items()}
                start_logits, end_logits = predict_step(params, batch)
                start_logits = np.asarray(start_logits, np.float32)
                end_logits = np.asarray(end_logits, np.float32)
                for j, f in enumerate(feats):
                    if i + j < len(eval_features):
                        results.append(squad.RawResult(
                            unique_id=f.unique_id,
                            start_logits=start_logits[j].tolist(),
                            end_logits=end_logits[j].tolist()))
            summary["e2e_inference_time"] = time.perf_counter() - t_infer

            answers, nbest, null_odds = squad.get_answers(
                eval_examples, eval_features, results, args)
            output_prediction_file = os.path.join(
                args.output_dir, "predictions.json")
            with open(output_prediction_file, "w") as f:
                f.write(json.dumps(answers, indent=4) + "\n")
            with open(os.path.join(args.output_dir,
                                   "nbest_predictions.json"), "w") as f:
                f.write(json.dumps(nbest, indent=4) + "\n")
            output_null_odds_file = None
            if args.version_2_with_negative:
                # The v2.0 official metric's best-threshold search
                # consumes these (reference writes the same file,
                # run_squad.py:1190-1194).
                output_null_odds_file = os.path.join(
                    args.output_dir, "null_odds.json")
                with open(output_null_odds_file, "w") as f:
                    f.write(json.dumps(null_odds, indent=4) + "\n")

            if args.do_eval and args.eval_script:
                # Official-oracle evaluation (reference run_squad.py:1197-1204)
                eval_cmd = [sys.executable, args.eval_script,
                            args.predict_file, output_prediction_file]
                if output_null_odds_file:
                    eval_cmd += ["--na-prob-file", output_null_odds_file,
                                 "--na-prob-thresh",
                                 str(args.null_score_diff_threshold)]
                proc = subprocess.run(
                    eval_cmd, capture_output=True, text=True, check=True)
                scores = json.loads(proc.stdout)
                summary["exact_match"] = scores.get("exact_match")
                summary["F1"] = scores.get("f1")

    logger.log(tag="summary", step=0, **{
        k: v for k, v in summary.items() if isinstance(v, (int, float))})
    logger.info(f"summary: {summary}")
    logger.close()
    return summary


if __name__ == "__main__":
    outcome = main(parse_args())
    if outcome.get("terminated_by_signal"):
        sys.exit(preemption.EXIT_PREEMPTED)
