"""SWAG multiple-choice finetuning runner.

Beyond-reference capability: the reference defines ``BertForMultipleChoice``
(modeling.py:1131-1197) but nothing in that repo can train it. This runner
finetunes the 4-way choice head on SWAG-format CSVs in the original SWAG
BERT recipe (lr 2e-5, 3 epochs, warmup 0.1; the original recipe's max seq 80
is raised to a TPU-friendly default of 128) and reports choice accuracy.

Same conventions as run_glue.py: model config JSON supplies vocab/tokenizer,
``--init_checkpoint`` accepts native or foreign (torch/TF) archives, one
JSON summary line at the end.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from bert_pytorch_tpu import optim, telemetry
from bert_pytorch_tpu.config import BertConfig
from bert_pytorch_tpu.data import swag
from bert_pytorch_tpu.data.tokenization import (
    get_bpe_tokenizer,
    get_wordpiece_tokenizer,
)
from bert_pytorch_tpu.models import BertForMultipleChoice
from bert_pytorch_tpu.ops.grad_utils import clip_by_global_norm
from bert_pytorch_tpu.utils import checkpoint as ckpt
from bert_pytorch_tpu.utils import logging as logger
from bert_pytorch_tpu.utils import preemption
from bert_pytorch_tpu.utils.compile_cache import enable_compile_cache
from bert_pytorch_tpu.data import DevicePrefetcher
from run_glue import batches  # padded fixed-shape batches + valid mask


def parse_arguments(argv=None):
    parser = argparse.ArgumentParser(description="TPU BERT SWAG finetuning")
    parser.add_argument("--train_file", type=str, required=True)
    parser.add_argument("--val_file", type=str, default=None)
    parser.add_argument("--model_config_file", type=str, required=True)
    parser.add_argument("--init_checkpoint", type=str, default=None)
    parser.add_argument("--output_dir", type=str, default=None)
    parser.add_argument("--vocab_file", type=str, default=None)
    parser.add_argument("--uppercase", action="store_true")
    parser.add_argument("--tokenizer", type=str, default=None,
                        choices=["wordpiece", "bpe"])
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--lr", type=float, default=2e-5)
    parser.add_argument("--warmup_proportion", type=float, default=0.1)
    parser.add_argument("--clip_grad", type=float, default=1.0)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--max_seq_len", type=int, default=128)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--compile_cache_dir", type=str, default="",
                        help="persistent XLA compilation cache directory; empty disables")
    parser.add_argument("--dtype", type=str, default="bfloat16",
                        choices=["bfloat16", "float32"])
    parser.add_argument("--save_steps", type=int, default=0,
                        help="periodic checkpoint cadence (optimizer "
                             "steps): async writes (device snapshot + "
                             "background write); final/emergency stays "
                             "synchronous. 0 disables")
    # device prefetch (data/device_prefetch.py; shared runner flag)
    from bert_pytorch_tpu.data import device_prefetch as dp_cli
    dp_cli.add_cli_args(parser)
    # telemetry (docs/telemetry.md)
    # telemetry: canonical flag set shared by every runner. Default
    # sync cadence stays 1: these are small models where a per-step
    # sync is cheap and step-exact sentinels are worth it — but since
    # PR 7 the loop itself no longer fetches the loss per step (it
    # accumulates on device; jaxlint HS101), so a user-set
    # --telemetry_sync_every N genuinely syncs only every Nth step
    # (telemetry/cli.py; docs/telemetry.md)
    telemetry.add_cli_args(parser, sync_every_default=1)
    args = parser.parse_args(argv)

    with open(args.model_config_file) as f:
        configs = json.load(f)
    if args.vocab_file is None:
        args.vocab_file = configs.get("vocab_file")
        if args.vocab_file is None:
            raise ValueError("vocab_file must be in model config or CLI")
    if args.tokenizer is None:
        args.tokenizer = configs.get("tokenizer", "wordpiece")
    return args


def main(args):
    enable_compile_cache(args.compile_cache_dir)
    telemetry_jsonl = telemetry.default_jsonl_path(
        args, args.output_dir, "swag")
    telemetry_sink = (logger.JSONLHandler(telemetry_jsonl, overwrite=False)
                      if telemetry_jsonl else None)
    logger.init(handlers=[logger.StreamHandler()]
                + ([telemetry_sink] if telemetry_sink else []))
    if args.tokenizer == "wordpiece":
        tokenizer = get_wordpiece_tokenizer(args.vocab_file,
                                            uppercase=args.uppercase)
    else:
        tokenizer = get_bpe_tokenizer(args.vocab_file, uppercase=args.uppercase)

    arrays = {"train": swag.convert_examples_to_arrays(
        swag.read_swag_examples(args.train_file), tokenizer, args.max_seq_len)}
    if args.val_file:
        arrays["val"] = swag.convert_examples_to_arrays(
            swag.read_swag_examples(args.val_file), tokenizer,
            args.max_seq_len)
    logger.info("examples: " + " ".join(
        f"{k}={len(v['labels'])}" for k, v in arrays.items()))

    config = BertConfig.from_json_file(args.model_config_file)
    if config.vocab_size % 8 != 0:
        config.vocab_size += 8 - (config.vocab_size % 8)
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    model = BertForMultipleChoice(config, num_choices=swag.NUM_CHOICES,
                                  dtype=dtype)

    sample = (jnp.zeros((1, swag.NUM_CHOICES, args.max_seq_len), jnp.int32),) * 3
    import flax.linen as nn

    params = nn.unbox(
        model.init(jax.random.PRNGKey(args.seed), *sample))["params"]
    if args.init_checkpoint:
        from bert_pytorch_tpu.models import load_pretrained_encoder

        params = load_pretrained_encoder(args.init_checkpoint, config, params)
        logger.info(f"loaded pretrained encoder from {args.init_checkpoint}")

    steps_per_epoch = max(
        1, -(-len(arrays["train"]["labels"]) // args.batch_size))
    total_steps = steps_per_epoch * args.epochs
    schedule = optim.warmup_linear_schedule(
        args.lr, args.warmup_proportion, total_steps)
    tx = optim.adamw(schedule, weight_decay=0.01, bias_correction=False,
                     weight_decay_mask=optim.no_decay_mask)
    opt_state = tx.init(params)

    def scores_fn(p, batch, dropout_rng=None):
        deterministic = dropout_rng is None
        rngs = None if deterministic else {"dropout": dropout_rng}
        return model.apply(
            {"params": p}, batch["input_ids"], batch["segment_ids"],
            batch["input_mask"], deterministic, rngs=rngs)

    stats_every = telemetry.stats_every(args)

    def train_step(params, opt_state, batch, valid, dropout_rng):
        def loss_fn(p):
            scores = scores_fn(p, batch, dropout_rng)  # [B, C]
            per_ex = optax.softmax_cross_entropy_with_integer_labels(
                scores.astype(jnp.float32), batch["labels"])
            weights = valid.astype(jnp.float32)
            return jnp.sum(per_ex * weights) / jnp.maximum(weights.sum(), 1.0)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads, _ = clip_by_global_norm(grads, args.clip_grad)
        updates, opt_state2 = tx.update(grads, opt_state, params)
        metrics = {"loss": loss}
        health = telemetry.finetune_grad_health(
            params, grads, updates, opt_state, stats_every)
        if health is not None:
            metrics["grad_health"] = health
        return optax.apply_updates(params, updates), opt_state2, metrics

    # Telemetry facade (docs/telemetry.md). One SWAG example is
    # NUM_CHOICES encoder passes, so flops_per_seq scales by the choices.
    from bert_pytorch_tpu.utils import flops as flops_util
    tele = telemetry.from_args(
        args,
        sink=telemetry_sink,
        seq_per_step=args.batch_size,
        flops_per_seq=swag.NUM_CHOICES
        * flops_util.bert_finetune_flops_per_seq(
            config, args.max_seq_len, head_outputs=1,
            per_token_head=False, pooled=True),
        output_dir=args.output_dir or None,
        process="swag")

    train_step = tele.instrument(
        jax.jit(train_step, donate_argnums=(0, 1)), "train_step")
    eval_step = tele.instrument(jax.jit(scores_fn), "eval_step")

    def evaluate():
        correct = total = 0
        for batch, valid in batches(arrays["val"], args.batch_size, False,
                                    np.random.default_rng(0)):
            scores = np.asarray(eval_step(params, batch), np.float32)
            preds = scores.argmax(axis=-1)
            correct += int(((preds == batch["labels"]) & valid).sum())
            total += int(valid.sum())
        return correct / max(total, 1)

    rng = np.random.default_rng(args.seed)
    key = jax.random.PRNGKey(args.seed)
    t0 = time.perf_counter()
    seen = 0
    global_step = 0
    # Graceful preemption (docs/fault_tolerance.md): stop at the next
    # step boundary, checkpoint through the normal end-of-run path,
    # exit EXIT_PREEMPTED. Handlers stay installed THROUGH the
    # checkpoint write below (a grace-period re-delivery must not kill
    # it); restored in the finally even on exceptions.
    stop = preemption.GracefulStop().install()
    prefetcher = None
    try:
        for epoch in range(args.epochs):
            # Device-side epoch loss accumulation (run_glue pattern): a
            # per-step float(loss) would block on the device every step
            # (jaxlint HS101); the epoch-end mean is the only fetch.
            loss_sum = None
            n_steps = 0
            # Device prefetch + h2d_wait attribution (run_glue pattern).
            prefetcher = DevicePrefetcher(
                batches(arrays["train"], args.batch_size, True, rng),
                stage=lambda bv: (jax.device_put(bv[0]), bv[1]),
                depth=args.device_prefetch)
            tele.attach_prefetcher(prefetcher)
            for batch, valid in tele.timed(iter(prefetcher)):
                key, sub = jax.random.split(key)
                tele.profiler.maybe_start(global_step + 1)
                with tele.profiler.annotation(global_step + 1):
                    params, opt_state, metrics = train_step(
                        params, opt_state, batch, valid, sub)
                tele.dispatch_done()
                global_step += 1
                tele.step_done(global_step, metrics)
                loss = metrics["loss"]
                loss_sum = loss if loss_sum is None else loss_sum + loss
                n_steps += 1
                # valid is the host-side numpy padding mask from
                # batches() — the stage fn device_puts only the batch.
                seen += int(valid.sum())  # jaxlint: disable=HS101
                if args.save_steps and args.output_dir \
                        and global_step % args.save_steps == 0:
                    # Periodic async save (joined before exit below).
                    with tele.checkpoint_stall():
                        ckpt.save_checkpoint(
                            args.output_dir, global_step,
                            {"model": params}, async_write=True)
                if stop.requested:
                    break
            prefetcher.close()
            if n_steps:
                logger.info(
                    f"epoch {epoch}: "
                    f"train_loss={float(loss_sum) / n_steps:.4f}")
            if stop.requested:
                logger.info(
                    f"termination signal ({stop.signal_name}) received; "
                    "checkpointing and exiting cleanly "
                    f"(exit code {preemption.EXIT_PREEMPTED})")
                tele.emit(preemption.preemption_record(global_step, stop))
                break
        train_time = time.perf_counter() - t0
        tele.finish(global_step, summary={
            "training_seq_per_sec":
                round(seen / train_time, 2) if train_time else 0.0})

        results = {
            "e2e_train_time": train_time,
            "training_sequences_per_second":
                seen / train_time if train_time else 0,
            "terminated_by_signal": stop.requested,
        }
        if args.val_file and not stop.requested:
            results["accuracy"] = evaluate()
        logger.info(json.dumps({"swag_summary": results}))

        if args.output_dir:
            os.makedirs(args.output_dir, exist_ok=True)
            # Stamped with the step actually REACHED (see run_glue.py).
            # Synchronous on purpose: the durability write before exit;
            # joins any in-flight periodic async write first. (No
            # checkpoint_stall wrapper: telemetry is already flushed.)
            ckpt.save_checkpoint(
                args.output_dir, global_step, {"model": params})
            with open(os.path.join(args.output_dir,
                                   "eval_results_swag.json"), "w") as f:
                json.dump(results, f, indent=2)
        # No exit until any in-flight async periodic write has landed.
        ckpt.wait_for_pending_save()
    finally:
        if prefetcher is not None:
            prefetcher.close()
        stop.restore()
    logger.close()
    return results


if __name__ == "__main__":
    outcome = main(parse_arguments())
    if outcome.get("terminated_by_signal"):
        sys.exit(preemption.EXIT_PREEMPTED)
