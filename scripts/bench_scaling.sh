#!/bin/bash
# Intra-host scaling-efficiency sweep (BASELINE.md north star: seq/s/chip
# at N chips vs at the base size). Runs bench.py at each power-of-two
# device count up to the host's chip count and appends one JSON line per
# point to the output file; efficiency(N) = value(N) / value(base).
#
#   bash scripts/bench_scaling.sh [out_file] [base_n]
#
# Multi-host pods sweep by launching with fewer hosts instead (bench.py
# refuses BENCH_DEVICES under multi-process — see the config guard).
set -uo pipefail
cd "$(dirname "$0")/.."
OUT=${1:-SCALING.jsonl}
BASE=${2:-1}
N_AVAIL=$(python -c "import jax; print(len(jax.devices()))")
: > "$OUT"
failures=0
n=$BASE
while [ "$n" -le "$N_AVAIL" ]; do
  echo "== scaling point: $n devices"
  if BENCH_DEVICES=$n python bench.py >> "$OUT" 2> /dev/null; then
    tail -1 "$OUT"
  else
    echo "   FAILED at $n devices"
    failures=$((failures + 1))
  fi
  n=$((n * 2))
done
echo "bench_scaling done: $(wc -l < "$OUT") points in $OUT ($failures failed)"
exit "$failures"
