#!/bin/bash
# One-command on-chip evidence capture for round 2 (VERDICT r1 next-steps
# 1, 2, 6, 8): bench JSONs with MFU, the LAMB-vs-K-FAC convergence CSV, the
# hardware smoke, and the full offline pretrain->finetune->eval chain.
#
#   bash scripts/capture_r02.sh [logdir]
#
# No `set -e`: each leg runs regardless of earlier failures so a transient
# backend drop costs one artifact, not the whole capture. Exit code is the
# number of failed legs.
set -uo pipefail
cd "$(dirname "$0")/.."
LOGS=${1:-/tmp/capture_r02}
mkdir -p "$LOGS"
failures=0

leg () {  # name, cmd...
  local name=$1; shift
  echo "== capture leg: $name"
  if "$@" > "$LOGS/$name.log" 2>&1; then
    echo "   OK ($name)"
  else
    echo "   FAILED ($name) — tail:"; tail -5 "$LOGS/$name.log"
    failures=$((failures + 1))
  fi
}

bench_leg () {  # name, env pairs...
  local name=$1; shift
  echo "== capture leg: $name"
  if env "$@" python bench.py > "$LOGS/$name.json" 2> "$LOGS/$name.log"; then
    echo "   $(cat "$LOGS/$name.json")"
    # Only successful legs become repo-root artifacts: a failed leg's
    # error JSON must never clobber a previously captured good number.
    cp "$LOGS/$name.json" .
  else
    echo "   FAILED ($name) — $(tail -2 "$LOGS/$name.log" | head -1)"
    failures=$((failures + 1))
  fi
}

bench_leg bench_phase1 BENCH_PHASE=1
bench_leg bench_phase2 BENCH_PHASE=2
bench_leg bench_kfac BENCH_KFAC=1
bench_leg bench_seq1024 BENCH_SEQ=1024

leg convergence bash scripts/convergence_r02.sh /tmp/bert_conv_r02 \
    CONVERGENCE_r02.csv
leg smoke_and_e2e bash scripts/smoke_tpu.sh /tmp/bert_tpu_smoke_r02

echo "capture_r02 done: $failures failed legs; logs in $LOGS"
ls -la BENCH*.json bench_*.json CONVERGENCE_r02.csv E2E_r02.json 2>/dev/null
exit "$failures"
