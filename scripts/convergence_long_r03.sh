#!/bin/bash
# Long anchored convergence run (VERDICT r2 #6): thousands of steps at a
# budget-feasible geometry — BERT-base on the offline chain's
# document-structured corpus — with loss-at-milestone targets stated IN
# ADVANCE (written to a milestones JSON before the run starts; the final
# artifact records pass/fail against it). This is the single-chip proxy
# for BASELINE.md's phase-1+2-to-reference-loss north star; the model's
# numerical agreement with the HF torch forward (tests/test_convert.py)
# anchors the loss scale to an external implementation.
#
#   bash scripts/convergence_long_r03.sh [workdir]
#
# RESUMABLE (the tunnel drops mid-run): unlike the 200-step capture, this
# leg checkpoints every 250 steps and auto-resumes from the latest
# checkpoint, so a tunnel drop costs at most 250 steps of progress.
# Artifacts: CONVERGENCE_LONG_r03.csv + LONG_RUN_r03.json (milestones,
# measured losses, verdict per milestone).
set -euo pipefail
cd "$(dirname "$0")/.."
W=${1:-/tmp/bert_conv_long_r03}
# Artifact prefix: empty (default) writes the repo-root chip artifacts;
# CPU sanity runs MUST set LONG_ARTIFACT_PREFIX to a scratch path so a
# sanity pass can never masquerade as (or suppress) the chip capture.
PREFIX=${LONG_ARTIFACT_PREFIX:-}
MODEL=${LONG_MODEL:-bert_base}
STEPS=${LONG_STEPS:-5000}
LOCAL_BATCH=${LONG_LOCAL_BATCH:-64}
GLOBAL_BATCH=${LONG_GLOBAL_BATCH:-256}
# LAMB sqrt LR scaling from the phase-1 recipe: 6e-3 * sqrt(256/65536).
LR=${LONG_LR:-3.75e-4}
CACHE=${BENCH_COMPILE_CACHE_DIR:-${XDG_CACHE_HOME:-$HOME/.cache}/bert_tpu_jax_cache}
mkdir -p "$W"

source scripts/lib_synth_corpus.sh
synth_corpus_build "$W" "$MODEL" 8 3

# Milestones STATED IN ADVANCE (a pre-registration: written before any
# training step runs, never overwritten). Grounded on the r02 on-chip
# BERT-large leg over the same corpus family (7.03 -> 4.65 in 200 steps at
# gbs 512) scaled for the smaller model, smaller batch, and longer
# horizon; "floor" values are must-pass, "target" values are expected.
if [ ! -f "$W/milestones.json" ]; then
  cat > "$W/milestones.json" <<'EOF'
{
  "stated_before_run": true,
  "floor": {"500": 6.2, "1000": 5.8, "2000": 5.3, "5000": 4.7},
  "target": {"500": 5.6, "1000": 5.1, "2000": 4.5, "5000": 3.8},
  "final_mlm_accuracy_floor": 0.18
}
EOF
fi

echo "== $MODEL, $STEPS steps, gbs $GLOBAL_BATCH, LR $LR (auto-resume on)"
python run_pretraining.py --input_dir "$W/encoded" \
    --output_dir "$W/run" \
    --model_config_file "$W/model.json" \
    --global_batch_size "$GLOBAL_BATCH" --local_batch_size "$LOCAL_BATCH" \
    --steps "$STEPS" --max_steps "$STEPS" \
    --learning_rate "$LR" --warmup_proportion 0.1 \
    --max_predictions_per_seq 20 --remat dots \
    --log_prefix log --log_steps 5 --num_steps_per_checkpoint 250 \
    --compile_cache_dir "$CACHE"

echo "== artifact: ${PREFIX}CONVERGENCE_LONG_r03.csv + ${PREFIX}LONG_RUN_r03.json"
python - "$W" "$STEPS" "$GLOBAL_BATCH" "$MODEL" "$LR" "$PREFIX" <<'EOF'
import csv, json, sys
w, steps, gbs, model, lr, prefix = sys.argv[1:7]
rows = [r for r in csv.DictReader(open(f"{w}/run/log_metrics.csv"))
        if r["tag"] == "train"]
with open(f"{prefix}CONVERGENCE_LONG_r03.csv", "w", newline="") as fo:
    wr = csv.writer(fo)
    wr.writerow(["optimizer", "step", "loss", "mlm_accuracy",
                 "learning_rate", "samples_per_second"])
    for r in rows:
        wr.writerow(["lamb", r["step"], r["step_loss"], r["mlm_accuracy"],
                     r["learning_rate"], r.get("samples_per_second", "")])
ms = json.load(open(f"{w}/milestones.json"))
by_step = {int(r["step"]): r for r in rows}
checks = {}
for kind in ("floor", "target"):
    for s, bound in ms[kind].items():
        row = by_step.get(int(s))
        got = float(row["step_loss"]) if row else None
        checks[f"{kind}@{s}"] = {
            "bound": bound, "loss": got,
            "pass": got is not None and got <= bound}
final = rows[-1]
acc = float(final["mlm_accuracy"])
checks["final_mlm_accuracy_floor"] = {
    "bound": ms["final_mlm_accuracy_floor"], "mlm_accuracy": acc,
    "pass": acc >= ms["final_mlm_accuracy_floor"]}
out = {
    "run": {"model": model, "steps": int(final["step"]),
            "global_batch": int(gbs), "learning_rate": lr,
            "final_loss": float(final["step_loss"]),
            "final_mlm_accuracy": acc},
    "milestones": ms, "checks": checks,
    "all_floors_pass": all(v["pass"] for k, v in checks.items()
                           if k.startswith("floor") or k.startswith("final")),
}
json.dump(out, open(f"{prefix}LONG_RUN_r03.json", "w"), indent=1)
print(json.dumps(out["checks"], indent=1))
print("all floors pass:", out["all_floors_pass"])
EOF
if [ -z "$PREFIX" ]; then
  python tools/plot_convergence.py CONVERGENCE_LONG_r03.csv \
      docs/convergence_long_r03.png \
      "BERT-base long run (gbs 256, LAMB, one v5e chip)"
else
  python tools/plot_convergence.py "${PREFIX}CONVERGENCE_LONG_r03.csv" \
      "${PREFIX}convergence_long_sanity.png"
fi
echo "long convergence OK"
