#!/bin/bash
# Convergence capture: BERT-large at recipe-shaped hyperparameters on real
# (synthesized, document-structured) data, LAMB vs K-FAC at equal steps.
#
#   bash scripts/convergence_r02.sh [workdir] [out_csv]
#
# Produces <out_csv> with columns optimizer,step,loss,mlm_accuracy,
# learning_rate — the driver-committable artifact behind BASELINE.md's
# "reference MLM loss @ step" north star (VERDICT r1 next-step #2).
#
# Time-boxing: the full phase-1 recipe (gbs 65536, LR 6e-3, 7038 steps)
# is a multi-day run; this capture keeps the recipe's SHAPE — LAMB +
# poly-decay warmup, accumulation-simulated global batch (8 microbatches),
# per-chip batch 64, seq 128, max_pred 20 — at gbs 512 with the LAMB
# square-root LR scaling 6e-3 * sqrt(512/65536) ~= 5.3e-4. CONV_MODEL=
# bert_base and CONV_STEPS shrink it further for CPU sanity runs.
#
# RESUMABLE: the TPU tunnel drops on a multi-minute cadence, so a retry
# must not redo finished work. The synthetic corpus build is deterministic
# (fixed seeds) and skipped when its outputs exist; a leg whose metrics
# CSV already holds all $STEPS train rows is skipped; an interrupted leg's
# partial output dir is cleared so its logs never mix; and the per-workdir
# XLA compile cache makes a leg retry skip the BERT-large recompile.
set -euo pipefail
cd "$(dirname "$0")/.."
W=${1:-/tmp/bert_conv}
OUT=${2:-CONVERGENCE_r02.csv}
MODEL=${CONV_MODEL:-bert_large_uncased}
STEPS=${CONV_STEPS:-200}
LOCAL_BATCH=${CONV_LOCAL_BATCH:-64}
GLOBAL_BATCH=${CONV_GLOBAL_BATCH:-512}
LR=${CONV_LR:-5.3e-4}
# Per-user scratch cache shared by the runner-based capture legs
# (bench.py itself uses the committed in-repo .jax_cache/ default).
CACHE=${BENCH_COMPILE_CACHE_DIR:-${XDG_CACHE_HOME:-$HOME/.cache}/bert_tpu_jax_cache}
mkdir -p "$W"

# The data-build marker records only what the data depends on (the model
# config's geometry source); run hyperparameters are stamped per leg so a
# sweep point never rebuilds the deterministic corpus.
STAMP="model=$MODEL"
RUN_STAMP="steps=$STEPS lb=$LOCAL_BATCH gb=$GLOBAL_BATCH lr=$LR"
if [ ! -f "$W/.data_ok" ] || [ "$(cat "$W/.data_ok")" != "$STAMP" ]; then
  rm -rf "$W" && mkdir -p "$W"
  echo "== corpus -> HDF5 (document-structured synthetic text)"
  python -m bert_pytorch_tpu.tools.make_synthetic_text corpus \
      --output_dir "$W/formatted" --num_files 4 --articles_per_file 2500 \
      --seed 0
  python -m bert_pytorch_tpu.tools.shard \
      --input_glob "$W/formatted/*.txt" \
      --output_dir "$W/sharded" --max_bytes_per_shard 2M
  python -m bert_pytorch_tpu.tools.build_vocab \
      --input_glob "$W/sharded/*.txt" \
      --output "$W/vocab.txt" --vocab_size 8192 --min_frequency 1
  python -m bert_pytorch_tpu.tools.encode_data \
      --input_dir "$W/sharded" --output_dir "$W/encoded" \
      --vocab_file "$W/vocab.txt" --max_seq_len 128 --next_seq_prob 0.5

  echo "== model config ($MODEL geometry, trained vocab)"
  python - "$W" "$MODEL" <<'EOF'
import json, sys
w, model = sys.argv[1:3]
cfg = json.load(open(f"configs/{model}_config.json"))
cfg["vocab_size"] = sum(1 for l in open(f"{w}/vocab.txt") if l.strip())
cfg.update(vocab_file=f"{w}/vocab.txt", tokenizer="wordpiece",
           lowercase=True)
json.dump(cfg, open(f"{w}/model.json", "w"))
print("vocab entries:", cfg["vocab_size"])
EOF
  echo "$STAMP" > "$W/.data_ok"
else
  echo "== corpus/encode/config reused from $W (matching '$STAMP')"
fi

leg_done () {  # name -> 0 if the leg completed under the SAME run stamp
  local csv="$W/$1/log_metrics.csv" stamp="$W/$1/.leg_ok"
  [ -f "$csv" ] && [ -f "$stamp" ] && \
    [ "$(cat "$stamp")" = "$RUN_STAMP" ] && \
    [ "$(grep -c '^train,' "$csv" 2>/dev/null || true)" -ge "$STEPS" ]
}

run_leg () {  # name, extra args...
  local name=$1; shift
  if leg_done "$name"; then
    echo "== $name: already complete ($STEPS steps), skipping"
    return 0
  fi
  # Clear any partial previous attempt: with no mid-run checkpoints the
  # leg restarts from step 0, and append-mode logs must not mix runs.
  rm -rf "$W/$name"
  echo "== $name: $STEPS steps, gbs $GLOBAL_BATCH (accumulation), LR $LR"
  python run_pretraining.py --input_dir "$W/encoded" \
      --output_dir "$W/$name" \
      --model_config_file "$W/model.json" \
      --global_batch_size "$GLOBAL_BATCH" --local_batch_size "$LOCAL_BATCH" \
      --steps "$STEPS" --max_steps "$STEPS" \
      --learning_rate "$LR" --warmup_proportion 0.1 \
      --max_predictions_per_seq 20 --remat dots \
      --log_prefix log --log_steps 1 --num_steps_per_checkpoint 100000 \
      --compile_cache_dir "$CACHE" \
      "$@"
  echo "$RUN_STAMP" > "$W/$name/.leg_ok"
}

run_leg lamb
run_leg kfac --kfac

echo "== merge CSVs -> $OUT"
python - "$W" "$OUT" <<'EOF'
import csv, sys
w, out = sys.argv[1:3]
with open(out, "w", newline="") as fo:
    wr = csv.writer(fo)
    wr.writerow(["optimizer", "step", "loss", "mlm_accuracy",
                 "learning_rate"])
    for opt in ("lamb", "kfac"):
        with open(f"{w}/{opt}/log_metrics.csv") as fi:
            for rec in csv.DictReader(fi):
                if rec["tag"] != "train":
                    continue
                wr.writerow([opt, rec["step"], rec["step_loss"],
                             rec["mlm_accuracy"], rec["learning_rate"]])
print(open(out).read().splitlines()[0])
print(f"rows: {sum(1 for _ in open(out)) - 1}")
EOF
# Refresh the committed figure only for the real capture: the repo-root
# artifact at the default BERT-large/200-step profile. CPU sanity runs
# (different OUT, or CONV_MODEL/CONV_STEPS overrides with the default OUT)
# must not clobber the chip plot with mislabeled data.
if [ "$OUT" = "CONVERGENCE_r02.csv" ] && [ "$MODEL" = "bert_large_uncased" ] \
    && [ "$STEPS" = "200" ]; then
  python tools/plot_convergence.py "$OUT" docs/convergence_r02.png
fi
echo "convergence capture OK -> $OUT"
