#!/bin/bash
# Round-3 convergence capture: BERT-large at recipe-shaped hyperparameters,
# LAMB vs K-FAC, on real (synthesized, document-structured) data.
#
#   bash scripts/convergence_r03.sh [workdir] [out_csv]
#
# VERDICT r2 #2: the only committed LAMB-vs-K-FAC comparison ran K-FAC at
# this repo's cheap default cadence (factors/10, inverses/100, damping
# 1e-3) and showed it 0.07 BEHIND LAMB at equal steps. The reference's
# operating point is much hotter: factors EVERY step, inverses every 10,
# damping 3e-3 (/root/reference/run_pretraining.py:133-149). This capture
# runs three legs at equal steps — LAMB, K-FAC at the reference point, and
# K-FAC at the cheap cadence — and merges them with the per-row
# samples_per_second so tools/summarize_convergence.py can compare at
# equal steps AND equal wallclock.
#
# Produces <out_csv> with columns optimizer,step,loss,mlm_accuracy,
# learning_rate,samples_per_second.
#
# RESUMABLE: deterministic data build skipped when present; finished legs
# (stamped with their run hyperparameters) skip; interrupted legs restart
# clean; all legs share one persistent compile cache.
set -euo pipefail
cd "$(dirname "$0")/.."
W=${1:-/tmp/bert_conv_r03}
OUT=${2:-CONVERGENCE_r03.csv}
MODEL=${CONV_MODEL:-bert_large_uncased}
STEPS=${CONV_STEPS:-200}
LOCAL_BATCH=${CONV_LOCAL_BATCH:-64}
GLOBAL_BATCH=${CONV_GLOBAL_BATCH:-512}
# LAMB sqrt LR scaling from the phase-1 recipe: 6e-3 * sqrt(512/65536).
LR=${CONV_LR:-5.3e-4}
# K-FAC legs run a smaller microbatch (same global batch via deeper
# accumulation): the fused-capture step with in-jit inverses peaked
# 2.41 MB OVER the v5e chip's 15.75G usable HBM at lb=64 (args 6.75G +
# program temps 8.99G, measured 2026-08-02); halving the microbatch
# shrinks the activation temps by gigabytes. Gradients are identical at
# equal global batch; per-row samples_per_second still charges the real
# (slightly higher) accumulation overhead to the equal-wallclock
# comparison. LAMB stays at the full microbatch — each optimizer runs at
# its best feasible single-chip config. The default derives from
# LOCAL_BATCH so CPU-sanity overrides (CONV_LOCAL_BATCH=8 etc.) scale
# with it instead of tripping the gbs divisibility check.
KFAC_LB=${CONV_KFAC_LOCAL_BATCH:-$((LOCAL_BATCH / 2))}
[ "$KFAC_LB" -lt 1 ] && KFAC_LB=1
CACHE=${BENCH_COMPILE_CACHE_DIR:-${XDG_CACHE_HOME:-$HOME/.cache}/bert_tpu_jax_cache}
mkdir -p "$W"

RUN_STAMP="steps=$STEPS lb=$LOCAL_BATCH gb=$GLOBAL_BATCH lr=$LR"
source scripts/lib_synth_corpus.sh
synth_corpus_build "$W" "$MODEL" 4 0

# Per-leg stamp: the shared geometry plus LEG_STAMP_EXTRA (set by the
# caller for leg-specific knobs that change the trajectory or the
# wallclock accounting, e.g. the K-FAC legs' smaller microbatch). A leg
# completed under a different microbatch must NOT pass leg_done, or its
# stale rows would be merged into the CSV labeled as the new config.
leg_done () {  # name -> 0 if the leg completed under the SAME run stamp
  local csv="$W/$1/log_metrics.csv" stamp="$W/$1/.leg_ok"
  [ -f "$csv" ] && [ -f "$stamp" ] && \
    [ "$(cat "$stamp")" = "$RUN_STAMP${LEG_STAMP_EXTRA:-}" ] && \
    [ "$(grep -c '^train,' "$csv" 2>/dev/null || true)" -ge "$STEPS" ]
}

run_leg () {  # name, extra args...
  local name=$1; shift
  if leg_done "$name"; then
    echo "== $name: already complete ($STEPS steps), skipping"
    return 0
  fi
  # Clear any partial previous attempt: with no mid-run checkpoints the
  # leg restarts from step 0, and append-mode logs must not mix runs.
  rm -rf "$W/$name"
  echo "== $name: $STEPS steps, gbs $GLOBAL_BATCH (accumulation), LR $LR"
  python run_pretraining.py --input_dir "$W/encoded" \
      --output_dir "$W/$name" \
      --model_config_file "$W/model.json" \
      --global_batch_size "$GLOBAL_BATCH" --local_batch_size "$LOCAL_BATCH" \
      --steps "$STEPS" --max_steps "$STEPS" \
      --learning_rate "$LR" --warmup_proportion 0.1 \
      --max_predictions_per_seq 20 --remat dots \
      --log_prefix log --log_steps 1 --num_steps_per_checkpoint 100000 \
      --skip_final_checkpoint \
      --compile_cache_dir "$CACHE" \
      "$@"
  echo "$RUN_STAMP${LEG_STAMP_EXTRA:-}" > "$W/$name/.leg_ok"
}

run_leg lamb
# K-FAC at the REFERENCE operating point (run_pretraining.py:133-149:
# factors every step from the live batch scale, inverses every 10,
# damping 3e-3, kl_clip 1e-3, stat_decay 0.95).
# argparse last-wins: the trailing --local_batch_size overrides
# run_leg's fixed $LOCAL_BATCH for the memory-bound K-FAC legs.
LEG_STAMP_EXTRA=" kfac_lb=$KFAC_LB"
run_leg kfac_ref --kfac --kfac_factor_interval 1 --kfac_inv_interval 10 \
    --kfac_damping 3e-3 --kfac_kl_clip 1e-3 --kfac_stat_decay 0.95 \
    --kfac_stats_batch "$KFAC_LB" --local_batch_size "$KFAC_LB"
# K-FAC at this repo's cheap default cadence (the r02 configuration).
run_leg kfac --kfac --local_batch_size "$KFAC_LB"
LEG_STAMP_EXTRA=""

echo "== merge CSVs -> $OUT"
python - "$W" "$OUT" <<'EOF'
import csv, os, sys
w, out = sys.argv[1:3]
with open(out, "w", newline="") as fo:
    wr = csv.writer(fo)
    wr.writerow(["optimizer", "step", "loss", "mlm_accuracy",
                 "learning_rate", "samples_per_second"])
    for opt in ("lamb", "kfac_ref", "kfac"):
        path = f"{w}/{opt}/log_metrics.csv"
        if not os.path.exists(path):
            continue
        with open(path) as fi:
            for rec in csv.DictReader(fi):
                if rec["tag"] != "train":
                    continue
                wr.writerow([opt, rec["step"], rec["step_loss"],
                             rec["mlm_accuracy"], rec["learning_rate"],
                             rec.get("samples_per_second", "")])
print(open(out).read().splitlines()[0])
print(f"rows: {sum(1 for _ in open(out)) - 1}")
EOF
python tools/summarize_convergence.py "$OUT" > "${OUT%.csv}_summary.json"
cat "${OUT%.csv}_summary.json"
# Refresh the committed figure only for the real capture profile; CPU
# sanity runs must not clobber the chip plot with mislabeled data.
if [ "$OUT" = "CONVERGENCE_r03.csv" ] && [ "$MODEL" = "bert_large_uncased" ] \
    && [ "$STEPS" = "200" ]; then
  python tools/plot_convergence.py "$OUT" docs/convergence_r03.png \
      "BERT-large pretraining loss (gbs 512, recipe-shaped LR, one v5e chip)"
fi
echo "convergence capture OK -> $OUT"
