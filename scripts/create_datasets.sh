#!/bin/bash
# Offline dataset build orchestration (reference scripts/create_datasets.sh):
# download -> format -> shard -> vocab -> encode-to-HDF5.
set -euo pipefail
DATA_DIR=${DATA_DIR:-data}
VOCAB_SIZE=${VOCAB_SIZE:-30522}

python -m bert_pytorch_tpu.tools.download --dataset wikicorpus --output_dir "$DATA_DIR/download"
python -m bert_pytorch_tpu.tools.download --dataset squad --output_dir "$DATA_DIR/download"

# wikiextractor (external, as in the reference) converts the XML dump:
#   python -m wikiextractor.WikiExtractor "$DATA_DIR/download/wikicorpus/wikicorpus.xml" \
#       --json -o "$DATA_DIR/extracted"

python -m bert_pytorch_tpu.tools.format \
    --input_glob "$DATA_DIR/extracted/**/wiki_*" --dataset wiki \
    --output_dir "$DATA_DIR/formatted"

python -m bert_pytorch_tpu.tools.shard \
    --input_glob "$DATA_DIR/formatted/*.txt" \
    --output_dir "$DATA_DIR/sharded" --max_bytes_per_shard 250M

python -m bert_pytorch_tpu.tools.build_vocab \
    --input_glob "$DATA_DIR/sharded/*.txt" \
    --output "$DATA_DIR/vocab/wordpiece-vocab-${VOCAB_SIZE}.txt" \
    --vocab_size "$VOCAB_SIZE"

# phase 1: seq 128; phase 2: seq 512 (reference create_datasets.sh:130-140)
python -m bert_pytorch_tpu.tools.encode_data \
    --input_dir "$DATA_DIR/sharded" --output_dir "$DATA_DIR/encoded/phase1" \
    --vocab_file "$DATA_DIR/vocab/wordpiece-vocab-${VOCAB_SIZE}.txt" \
    --max_seq_len 128 --next_seq_prob 0.5
python -m bert_pytorch_tpu.tools.encode_data \
    --input_dir "$DATA_DIR/sharded" --output_dir "$DATA_DIR/encoded/phase2" \
    --vocab_file "$DATA_DIR/vocab/wordpiece-vocab-${VOCAB_SIZE}.txt" \
    --max_seq_len 512 --next_seq_prob 0.5
