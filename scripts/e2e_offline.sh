#!/bin/bash
# Full offline capability chain on locally synthesized data (zero egress):
#
#   synthesize text -> format/shard -> train WordPiece vocab (C++ trainer)
#   -> encode to HDF5 -> pretrain -> SQuAD-style finetune from the
#   pretraining checkpoint -> predict on a HELD-OUT dev set -> official
#   EM/F1 eval subprocess -> one JSON artifact.
#
# This is the reference's create_datasets.sh:85-141 + run_squad.py:1197-1224
# loop, proven end to end rather than piecewise (VERDICT r1 next-step #8).
#
#   bash scripts/e2e_offline.sh [workdir] [result_json]
#
# Profile via E2E_PROFILE: "tiny" (default; CPU-runnable in ~5 min, 2-layer
# model) or "chip" (BERT-base, a few hundred pretrain steps — run on TPU).
#
# RESUMABLE (same scheme as convergence_r02.sh): the data build is stamped
# by profile and skipped when already complete; the pretrain leg is skipped
# when its final checkpoint exists (and auto-resumes from any partial
# checkpoint otherwise); the finetune leg is skipped when the dev-set
# predictions exist, restarting from the pretrained checkpoint if
# interrupted. The shared compile cache covers recompiles either way.
set -euo pipefail
# Per-user scratch cache for the runner legs (not the world-shared /tmp,
# where another user could pre-seed entries that JAX deserializes as
# executables). bench.py itself uses the committed in-repo .jax_cache/.
CACHE=${BENCH_COMPILE_CACHE_DIR:-${XDG_CACHE_HOME:-$HOME/.cache}/bert_tpu_jax_cache}
cd "$(dirname "$0")/.."
W=${1:-/tmp/bert_e2e}
RESULT=${2:-$W/e2e_result.json}
PROFILE=${E2E_PROFILE:-tiny}
mkdir -p "$W"

if [ "$PROFILE" = "chip" ]; then
  ART_PER_FILE=2000; VOCAB=8192
  HID=768; LAYERS=12; HEADS=12; FFN=3072
  PRETRAIN_STEPS=300; PRETRAIN_BATCH=64; LR=1e-3; CKPT_EVERY=100
  SQUAD_PARAS=400; SQUAD_STEPS=300; SQUAD_BATCH=32
else
  ART_PER_FILE=150; VOCAB=2048
  HID=128; LAYERS=2; HEADS=4; FFN=512
  PRETRAIN_STEPS=20; PRETRAIN_BATCH=16; LR=1e-3; CKPT_EVERY=10
  SQUAD_PARAS=40; SQUAD_STEPS=20; SQUAD_BATCH=8
fi

STAMP="profile=$PROFILE v2"
if [ ! -f "$W/.data_ok" ] || [ "$(cat "$W/.data_ok")" != "$STAMP" ]; then
  if [ -f "$W/.data_ok" ]; then
    echo "!! profile stamp mismatch (have '$(cat "$W/.data_ok")', want" \
         "'$STAMP') — REBUILDING $W from scratch"
  fi
  rm -rf "$W" && mkdir -p "$W"

  echo "== 1. synthesize corpus (shared fact world, seed 0)"
  python -m bert_pytorch_tpu.tools.make_synthetic_text corpus \
      --output_dir "$W/formatted" --num_files 4 \
      --articles_per_file "$ART_PER_FILE" --seed 0

  echo "== 2. shard on article boundaries"
  python -m bert_pytorch_tpu.tools.shard \
      --input_glob "$W/formatted/*.txt" \
      --output_dir "$W/sharded" --max_bytes_per_shard 200k

  echo "== 3. train WordPiece vocab (C++ trainer)"
  python -m bert_pytorch_tpu.tools.build_vocab \
      --input_glob "$W/sharded/*.txt" \
      --output "$W/vocab.txt" --vocab_size "$VOCAB" --min_frequency 1

  echo "== 4. encode documents -> HDF5 pretraining shards"
  python -m bert_pytorch_tpu.tools.encode_data \
      --input_dir "$W/sharded" --output_dir "$W/encoded" \
      --vocab_file "$W/vocab.txt" --max_seq_len 128 --next_seq_prob 0.5

  echo "== 5. model config sized to the trained vocab"
  python - "$W" "$HID" "$LAYERS" "$HEADS" "$FFN" <<'EOF'
import json, sys
w, hid, layers, heads, ffn = sys.argv[1], *map(int, sys.argv[2:])
n_vocab = sum(1 for l in open(f"{w}/vocab.txt") if l.strip())
json.dump({
    "vocab_size": n_vocab, "hidden_size": hid, "num_hidden_layers": layers,
    "num_attention_heads": heads, "intermediate_size": ffn,
    "max_position_embeddings": 512, "type_vocab_size": 2,
    "next_sentence": True, "vocab_file": f"{w}/vocab.txt",
    "tokenizer": "wordpiece", "lowercase": True,
}, open(f"{w}/model.json", "w"))
print("vocab entries:", n_vocab)
EOF

  echo "== 5b. synthesize SQuAD train + HELD-OUT dev (same fact world)"
  python -m bert_pytorch_tpu.tools.make_synthetic_text squad \
      --output "$W/squad_train.json" --paragraphs "$SQUAD_PARAS" \
      --qas_per_paragraph 3 --seed 11 --fact_seed 0
  python -m bert_pytorch_tpu.tools.make_synthetic_text squad \
      --output "$W/squad_dev.json" --paragraphs $((SQUAD_PARAS / 4)) \
      --qas_per_paragraph 3 --seed 97 --fact_seed 0

  echo "== 5c. synthesize SQuAD v2.0 train + dev (1/3 impossible questions)"
  python -m bert_pytorch_tpu.tools.make_synthetic_text squad \
      --output "$W/squad_v2_train.json" --paragraphs "$SQUAD_PARAS" \
      --qas_per_paragraph 3 --seed 23 --fact_seed 0 --impossible_frac 0.33
  python -m bert_pytorch_tpu.tools.make_synthetic_text squad \
      --output "$W/squad_v2_dev.json" --paragraphs $((SQUAD_PARAS / 4)) \
      --qas_per_paragraph 3 --seed 131 --fact_seed 0 --impossible_frac 0.33

  echo "$STAMP" > "$W/.data_ok"
else
  echo "== corpus/vocab/encode/squad data reused from $W ('$STAMP')"
fi

echo "== 6. pretrain"
if [ -f "$W/pretrain/pretrain_ckpts/ckpt_$PRETRAIN_STEPS.msgpack" ]; then
  echo "   already complete (ckpt_$PRETRAIN_STEPS exists), skipping"
else
  # Partial checkpoints are NOT cleared: run_pretraining auto-resumes from
  # the newest one, and CKPT_EVERY is below the step count so mid-run
  # checkpoints genuinely exist (an interrupted 300-step chip leg redoes
  # at most the last 100 steps, not the whole run).
  # local batch = global / device count (run_pretraining requires the
  # global batch to divide by local_batch x data shards; on an 8-chip host
  # the per-chip batch is PRETRAIN_BATCH/8). Device count is only probed
  # when the leg actually runs — a skipped rerun stays tunnel-independent.
  NDEV=$(python -c "import jax; print(len(jax.devices()))")
  LOCAL_BATCH=$((PRETRAIN_BATCH / NDEV))
  if [ "$LOCAL_BATCH" -lt 1 ]; then LOCAL_BATCH=1; fi
  # round the global batch to LOCAL*NDEV so the divisibility check always
  # holds (e.g. 16 samples on 6 devices -> local 2, global 12)
  PRETRAIN_BATCH=$((LOCAL_BATCH * NDEV))
  python run_pretraining.py --input_dir "$W/encoded" \
      --output_dir "$W/pretrain" \
      --model_config_file "$W/model.json" \
      --global_batch_size "$PRETRAIN_BATCH" --local_batch_size "$LOCAL_BATCH" \
      --steps "$PRETRAIN_STEPS" --max_steps "$PRETRAIN_STEPS" \
      --learning_rate "$LR" --warmup_proportion 0.1 \
      --max_predictions_per_seq 20 \
      --log_prefix log --num_steps_per_checkpoint "$CKPT_EVERY" \
      --compile_cache_dir "$CACHE"
fi
CKPT=$(ls -t "$W"/pretrain/pretrain_ckpts/ckpt_*.msgpack | head -1)
echo "pretrained checkpoint: $CKPT"

echo "== 7. finetune from the pretraining checkpoint + official eval"
if [ -f "$W/squad_out/predictions.json" ]; then
  echo "   already complete (predictions.json exists), skipping"
else
  rm -rf "$W/squad_out"
  python run_squad.py \
      --output_dir "$W/squad_out" \
      --config_file "$W/model.json" \
      --init_checkpoint "$CKPT" \
      --train_file "$W/squad_train.json" \
      --predict_file "$W/squad_dev.json" \
      --do_train --do_predict --do_eval --do_lower_case \
      --eval_script scripts/squad_evaluate_v11.py \
      --train_batch_size "$SQUAD_BATCH" --predict_batch_size "$SQUAD_BATCH" \
      --max_steps "$SQUAD_STEPS" --max_seq_length 128 \
      --doc_stride 64 --max_query_length 24 \
      --learning_rate 5e-5 --skip_cache \
      --compile_cache_dir "$CACHE"
fi

echo "== 7b. SQuAD v2.0 finetune (impossible questions) + official v2 eval"
if [ -f "$W/squad_v2_out/null_odds.json" ]; then
  # null_odds.json is written AFTER predictions.json; gating on the
  # last-written artifact keeps an interrupted leg re-runnable
  echo "   already complete (v2 null_odds.json exists), skipping"
else
  rm -rf "$W/squad_v2_out"
  python run_squad.py \
      --output_dir "$W/squad_v2_out" \
      --config_file "$W/model.json" \
      --init_checkpoint "$CKPT" \
      --train_file "$W/squad_v2_train.json" \
      --predict_file "$W/squad_v2_dev.json" \
      --do_train --do_predict --do_eval --do_lower_case \
      --version_2_with_negative \
      --eval_script scripts/squad_evaluate_v20.py \
      --train_batch_size "$SQUAD_BATCH" --predict_batch_size "$SQUAD_BATCH" \
      --max_steps "$SQUAD_STEPS" --max_seq_length 128 \
      --doc_stride 64 --max_query_length 24 \
      --learning_rate 5e-5 --skip_cache \
      --compile_cache_dir "$CACHE"
fi

echo "== 8. EM/F1 artifact (re-run the official metrics on both dev sets)"
SCORES=$(python scripts/squad_evaluate_v11.py \
    "$W/squad_dev.json" "$W/squad_out/predictions.json")
SCORES_V2=$(python scripts/squad_evaluate_v20.py \
    "$W/squad_v2_dev.json" "$W/squad_v2_out/predictions.json" \
    --na-prob-file "$W/squad_v2_out/null_odds.json")
python - "$RESULT" "$PROFILE" "$SCORES" "$SCORES_V2" <<'EOF'
import json, sys
result, profile = sys.argv[1], sys.argv[2]
scores, v2 = json.loads(sys.argv[3]), json.loads(sys.argv[4])
out = {"metric": "e2e_offline_squad", "profile": profile,
       "exact_match": scores["exact_match"], "f1": scores["f1"],
       "v2": {k: v2[k] for k in (
           "exact", "f1", "total", "HasAns_exact", "HasAns_f1",
           "NoAns_exact", "NoAns_f1", "best_exact", "best_exact_thresh",
           "best_f1", "best_f1_thresh") if k in v2}}
json.dump(out, open(result, "w"), indent=2)
print(json.dumps(out))
EOF
echo "e2e_offline OK -> $RESULT"
