#!/bin/bash
# Kill stray training processes on this host (reference
# scripts/kill_python_procs.sh:3-4 — its GPU-process killer).
pkill -f run_pretraining.py
pkill -f run_squad.py
pkill -f run_ner.py
