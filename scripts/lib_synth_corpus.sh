# Shared corpus->shard->vocab->HDF5->model.json build for the capture
# scripts (convergence_r03.sh, convergence_long_r03.sh). Source this file,
# then call:
#
#   synth_corpus_build WORKDIR MODEL_CONFIG_NAME NUM_FILES SEED
#
# Deterministic and stamped: a workdir whose stamp matches is reused
# as-is (tunnel-drop retries must not redo finished work); any mismatch
# rebuilds from scratch. Produces $W/encoded (HDF5 shards) and
# $W/model.json (the named configs/ geometry with the trained vocab).
synth_corpus_build() {
  local W=$1 MODEL=$2 NUM_FILES=$3 SEED=$4
  local STAMP="model=$MODEL files=$NUM_FILES seed=$SEED"
  if [ -f "$W/.data_ok" ] && [ "$(cat "$W/.data_ok")" = "$STAMP" ]; then
    echo "== corpus/encode/config reused from $W (matching '$STAMP')"
    return 0
  fi
  rm -rf "$W" && mkdir -p "$W"
  echo "== corpus -> HDF5 ($NUM_FILES files, document-structured synthetic text)"
  python -m bert_pytorch_tpu.tools.make_synthetic_text corpus \
      --output_dir "$W/formatted" --num_files "$NUM_FILES" \
      --articles_per_file 2500 --seed "$SEED"
  python -m bert_pytorch_tpu.tools.shard \
      --input_glob "$W/formatted/*.txt" \
      --output_dir "$W/sharded" --max_bytes_per_shard 2M
  python -m bert_pytorch_tpu.tools.build_vocab \
      --input_glob "$W/sharded/*.txt" \
      --output "$W/vocab.txt" --vocab_size 8192 --min_frequency 1
  python -m bert_pytorch_tpu.tools.encode_data \
      --input_dir "$W/sharded" --output_dir "$W/encoded" \
      --vocab_file "$W/vocab.txt" --max_seq_len 128 --next_seq_prob 0.5

  echo "== model config ($MODEL geometry, trained vocab)"
  python - "$W" "$MODEL" <<'EOF'
import json, sys
w, model = sys.argv[1:3]
cfg = json.load(open(f"configs/{model}_config.json"))
cfg["vocab_size"] = sum(1 for l in open(f"{w}/vocab.txt") if l.strip())
cfg.update(vocab_file=f"{w}/vocab.txt", tokenizer="wordpiece",
           lowercase=True)
json.dump(cfg, open(f"{w}/model.json", "w"))
print("vocab entries:", cfg["vocab_size"])
EOF
  echo "$STAMP" > "$W/.data_ok"
}
