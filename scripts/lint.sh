#!/usr/bin/env bash
# Pre-commit convenience wrapper for the unified lint gate:
#   jaxlint (docs/static_analysis.md) over the package + runners + tools,
#   then the telemetry record schema over repo-root *.jsonl artifacts.
#
#   scripts/lint.sh                # everything
#   scripts/lint.sh FOO.jsonl      # code + just this artifact
#
# jax-free and fast (~5 s): safe as a git pre-commit hook on machines
# without the accelerator stack:
#   ln -s ../../scripts/lint.sh .git/hooks/pre-commit
set -euo pipefail
cd "$(dirname "$0")/.."
exec python tools/check_all.py "$@"
