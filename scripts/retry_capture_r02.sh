#!/bin/bash
# Persistent retry harness for the on-chip capture legs that a TPU-tunnel
# drop interrupted (the tunnel has been observed to come and go on a
# multi-minute to multi-hour cadence). Probes the backend with a short
# timeout; when it answers, runs whichever legs have not yet produced
# their repo-root artifact, each under a hard timeout so a mid-leg drop
# costs bounded wall clock, then goes back to probing. Exits when every
# artifact exists or the deadline passes.
#
#   bash scripts/retry_capture_r02.sh [deadline_epoch_s] [logdir]
set -uo pipefail
cd "$(dirname "$0")/.."
DEADLINE=${1:-$(($(date +%s) + 9 * 3600))}
LOGS=${2:-/tmp/retry_capture_r02}
mkdir -p "$LOGS"

probe() {
  timeout 150 python - <<'EOF' >/dev/null 2>&1
import jax
assert jax.devices()[0].platform in ("tpu", "axon") or "TPU" in jax.devices()[0].device_kind
EOF
}

have_seq1024() { [ -f bench_seq1024.json ] && ! grep -q '"error"' bench_seq1024.json; }
have_seq2048() { [ -f bench_seq2048.json ] && ! grep -q '"error"' bench_seq2048.json; }
have_convergence() { [ -f CONVERGENCE_r02.csv ]; }
have_e2e() { [ -f E2E_r02.json ]; }

have_sweep() { [ -f SWEEP_r02.jsonl ] && [ "$(wc -l < SWEEP_r02.jsonl)" -ge 7 ]; }

run_sweep() {
  # Opportunistic phase-1 microbatch sweep once the evidence legs are in:
  # one captured line per batch size (the ARCHITECTURE.md tuning-surface
  # numbers, re-measured live). Short measure window keeps it ~2min/point.
  : > "$LOGS/sweep.tmp"
  # batch points on the default XLA attention path, then the fused Pallas
  # kernel at seq 128 (its bh-batched tiles postdate the recorded 366-vs-396
  # XLA win — re-measure whether it closes the gap) at the two best batches.
  for pt in 48: 52: 56: 60: 64: 56:pallas 64:pallas; do
    b=${pt%%:*}; attn=${pt#*:}
    tag="$b${attn:+_$attn}"
    # Resume-per-point: a pass interrupted by a tunnel drop keeps its
    # already-measured points on disk and only re-runs the missing ones.
    if { [ -s "$LOGS/sweep_$tag.json" ] && ! grep -q '"error"' "$LOGS/sweep_$tag.json"; } \
        || env BENCH_LOCAL_BATCH="$b" ${attn:+BENCH_ATTN=$attn} \
        BENCH_MEASURE_STEPS=12 BENCH_ATTEMPTS=1 \
        timeout 900 python bench.py > "$LOGS/sweep_$tag.json" 2> "$LOGS/sweep_$tag.log"
    then
      python - "$b" "${attn:-xla}" "$LOGS/sweep_$tag.json" >> "$LOGS/sweep.tmp" <<'EOF'
import json, sys
b, attn, path = sys.argv[1:4]
rec = json.load(open(path))
rec["local_batch"] = int(b)
rec["attention"] = attn
print(json.dumps(rec))
EOF
      echo "   sweep $tag: $(tail -1 "$LOGS/sweep.tmp")"
    else
      echo "   sweep $tag FAILED; aborting sweep pass"
      return 1
    fi
  done
  mv "$LOGS/sweep.tmp" SWEEP_r02.jsonl
}

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if have_seq1024 && have_seq2048 && have_convergence && have_e2e && have_sweep; then
    echo "retry_capture_r02: all artifacts captured"
    exit 0
  fi
  if ! probe; then
    echo "$(date +%H:%M:%S) backend down; sleeping 120s"
    sleep 120
    continue
  fi
  echo "$(date +%H:%M:%S) backend up"
  if ! have_convergence; then
    echo "== leg: convergence"
    if timeout 4500 bash scripts/convergence_r02.sh /tmp/bert_conv_r02 \
        CONVERGENCE_r02.csv > "$LOGS/convergence.log" 2>&1; then
      echo "   OK (convergence)"
    else
      echo "   FAILED (convergence); tail:"; tail -3 "$LOGS/convergence.log"
    fi
  fi
  if ! have_e2e; then
    echo "== leg: smoke_and_e2e"
    if timeout 3600 bash scripts/smoke_tpu.sh /tmp/bert_tpu_smoke_r02 \
        > "$LOGS/smoke.log" 2>&1; then
      echo "   OK (smoke_and_e2e)"
    else
      echo "   FAILED (smoke_and_e2e); tail:"; tail -3 "$LOGS/smoke.log"
    fi
  fi
  # Long-sequence bench legs (the seq-1024 compile through the tunnel blew
  # the default 600s child timeout once; the per-seq numbers give each
  # compile room to finish, growing with the sequence length).
  run_seq_leg() {  # seq, attempt_timeout_s, budget_s, hard_timeout_s
    local seq=$1 at=$2 bs=$3 ht=$4
    echo "== leg: bench_seq$seq"
    if env BENCH_SEQ="$seq" BENCH_ATTEMPT_TIMEOUT_S="$at" BENCH_BUDGET_S="$bs" \
        timeout "$ht" python bench.py \
        > "$LOGS/seq$seq.json" 2> "$LOGS/seq$seq.log"
    then
      cp "$LOGS/seq$seq.json" "bench_seq$seq.json"
      echo "   $(cat "bench_seq$seq.json")"
    else
      echo "   FAILED (seq$seq); $(tail -1 "$LOGS/seq$seq.log" 2>/dev/null)"
    fi
  }
  if ! have_seq1024; then run_seq_leg 1024 1800 2100 2400; fi
  if ! have_seq2048; then run_seq_leg 2048 2400 2700 3000; fi
  if have_seq1024 && have_seq2048 && have_convergence && have_e2e \
      && ! have_sweep; then
    echo "== leg: batch sweep"
    run_sweep || true
  fi
done
echo "retry_capture_r02: deadline reached"
have_seq1024; s=$?; have_seq2048; s2=$?; have_convergence; c=$?
have_e2e; e=$?; have_sweep; w=$?
echo "captured: seq1024=$((1-s)) seq2048=$((1-s2)) convergence=$((1-c))" \
     "e2e=$((1-e)) sweep=$((1-w))"
