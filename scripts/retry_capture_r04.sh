#!/bin/bash
# Round-4 persistent capture harness (supersedes retry_capture_r03.sh —
# same legs and artifact names, plus the fused-K-FAC capture-cost leg).
# Probes the flaky TPU tunnel; when it answers, runs whichever capture
# legs have not yet produced their repo-root artifact, IN PRIORITY ORDER
# (VERDICT r3 "Next round"):
#
#   1. Warm the IN-REPO persistent compile cache (.jax_cache/) for the
#      driver's bench shapes, then COLD-VERIFY: a fresh `python bench.py`
#      with only the committed cache must emit a real number in <600s —
#      the property whose absence zeroed BENCH_r01/r02/r03. Also warms
#      the degraded BERT-base fallback entry.
#   2. LAMB vs K-FAC (reference operating point + cheap cadence)
#      convergence with equal-step AND equal-wallclock accounting (the
#      K-FAC legs now run the FUSED in-train capture, the round-4
#      structural fix).
#   3. Remaining bench legs: phase2, kfac (fused capture),
#      kfac capture-cost A/B (lamb vs stats vs fused at BERT-large,
#      factor_interval=1), seq1024, seq2048.
#   4. Chip-profile offline e2e chain -> E2E_r03.json.
#   5. Long anchored convergence run (resumable; retried each window).
#   6. Phase-1 batch/backend sweep -> SWEEP_r03.jsonl.
#
# Each captured artifact is git-committed immediately (tunnel windows are
# scarce; an artifact must survive even if the session dies right after).
# Touch .stop_capture in the repo root to make the harness exit at the
# next loop boundary (do this before the driver's end-of-round bench so
# the harness cannot contend for the chip).
#
#   bash scripts/retry_capture_r04.sh [deadline_epoch_s] [logdir]
set -uo pipefail
cd "$(dirname "$0")/.."
DEADLINE=${1:-$(($(date +%s) + 10 * 3600))}
LOGS=${2:-/tmp/retry_capture_r04}
mkdir -p "$LOGS"
# Leg workdirs — used by both the invocations and the progress probes;
# keep them in one place so the probes can't drift off the real paths.
CONV_W=/tmp/bert_conv_r03
LONG_W=/tmp/bert_conv_long_r03
SMOKE_W=/tmp/bert_tpu_smoke_r03
# Cache split: bench.py invocations use its default in-repo cache
# (.jax_cache/, committed); the runner-based legs (convergence, smoke,
# e2e, long run) use their scripts' own per-user scratch default. Nothing
# is exported here — smoke_tpu.sh runs `python bench.py` internally, and
# an exported BENCH_COMPILE_CACHE_DIR would wrongly divert those bench
# legs off the committed cache.

probe() {
  timeout 150 python - <<'EOF' >/dev/null 2>&1
import jax
d = jax.devices()[0]
assert d.platform in ("tpu", "axon") or "TPU" in d.device_kind
EOF
}

commit_artifacts() {  # msg, paths...
  local msg=$1; shift
  local existing=()
  # Schema-lint each JSONL before it can land: the tier-1 suite lints
  # every COMMITTED repo-root *.jsonl, so a malformed capture committed
  # here would break the next round's tests. Only the offending file is
  # dropped from the commit (kept on disk for inspection) — the other
  # artifacts of the leg (bench JSONs, the warm cache) must still
  # survive the session, which is this harness's whole point.
  for p in "$@"; do
    [ -e "$p" ] || continue
    case "$p" in
      *.jsonl)
        # One gate for everything (PR 7): tools/check_all.py runs the
        # schema lint on the artifact; --skip-jaxlint because a code
        # finding elsewhere in the repo must not drop a bench artifact
        # from the commit (tier-1 owns the code gate).
        if ! python tools/check_all.py --skip-jaxlint "$p" \
            >> "$LOGS/schema_lint.log" 2>&1; then
          echo "   SCHEMA LINT FAILED for $p; dropping it from this" \
               "commit (see $LOGS/schema_lint.log)"
          continue
        fi;;
    esac
    existing+=("$p")
  done
  [ "${#existing[@]}" -eq 0 ] && return 0
  git add -f -- "${existing[@]}" 2>> "$LOGS/git.log" || true
  if ! git diff --cached --quiet; then
    git commit -q -m "$msg" >> "$LOGS/git.log" 2>&1 \
      && echo "   committed: $msg" \
      || { echo "   COMMIT FAILED: $msg"; git reset -q; }
  fi
}

good_json() { [ -f "$1" ] && ! grep -q '"error"' "$1" \
  && ! grep -q '"value": 0.0' "$1"; }

# A leg that fails WHILE THE BACKEND IS STILL ALIVE is its own fault
# (e.g. an OOM): count it, and after 2 such failures stop retrying so a
# deterministic failure can't block every lower-priority leg for the rest
# of a scarce window (the r04 kfac-convergence OOM looped exactly that
# way). Tunnel-death failures are not counted — the leg gets fresh tries
# in later windows. Pass timeouts on the resumable legs are excused ONLY
# when the pass demonstrably advanced (a new sub-leg stamp, checkpoint,
# or sweep point); a timeout with zero progress is a strike like any
# other failure. To re-enable a given-up leg after fixing its cause:
# rm "$LOGS/fail_<leg>".
fails() { cat "$LOGS/fail_$1" 2>/dev/null || echo 0; }
gave_up() { [ "$(fails "$1")" -ge 2 ]; }
# A pass that makes real progress proves the leg is not deterministically
# broken — forget earlier strikes so two UNRELATED transient failures
# spread across many windows can't retire a steadily-advancing leg.
clear_fail() { rm -f "$LOGS/fail_$1"; }
bump_fail() {
  if probe; then
    local n=$(( $(fails "$1") + 1 ))
    echo "$n" > "$LOGS/fail_$1"
    echo "   fail #$n for $1 with backend alive$(gave_up "$1" \
      && echo ' — giving up on this leg (rm '"$LOGS/fail_$1"' to retry)')"
  else
    echo "   $1 failed with backend down; not counted"
  fi
}

bench_warm() {  # artifact, timeout_s, env pairs...
  local art=$1 t=$2; shift 2
  echo "== leg: warm $art"
  if env "$@" BENCH_DEGRADE=0 BENCH_ATTEMPTS=1 \
      BENCH_ATTEMPT_TIMEOUT_S=$((t - 60)) BENCH_BUDGET_S=$((t - 30)) \
      timeout "$t" python bench.py > "$LOGS/$art.tmp" 2> "$LOGS/$art.log" \
      && good_json "$LOGS/$art.tmp"; then
    cp "$LOGS/$art.tmp" "$art"
    echo "   $(cat "$art")"
    return 0
  fi
  echo "   FAILED ($art): $(tail -1 "$LOGS/$art.log" 2>/dev/null | cut -c1-160)"
  return 1
}

have_phase1()   { good_json bench_phase1.json && [ -f COLD_BENCH_r03.json ]; }
have_degraded() { [ -f "$LOGS/degraded_warm.json" ]; }
have_conv()     { [ -f CONVERGENCE_r03.csv ]; }
have_phase2()   { good_json bench_phase2.json && grep -q pallas "$LOGS/.phase2_r03_done" 2>/dev/null; }
have_kfacb()    { good_json bench_kfac.json && [ -f "$LOGS/.kfac_r04_done" ]; }
have_kfac_cap() { [ -f KFAC_CAPTURE_BENCH_chip_r04.jsonl ] \
  && grep -q kfac_fused KFAC_CAPTURE_BENCH_chip_r04.jsonl; }
have_seq1024()  { good_json bench_seq1024.json; }
have_seq2048()  { good_json bench_seq2048.json; }
have_e2e()      { [ -f E2E_r03.json ]; }
have_long()     { [ -f LONG_RUN_r03.json ]; }
have_sweep()    { [ -f SWEEP_r03.jsonl ] && [ "$(wc -l < SWEEP_r03.jsonl)" -ge 12 ]; }

# One leg list shared by all_done, the gating ifs (via pending), and the
# end-of-run report — add a leg in one place.
LEGS="phase1 degraded conv phase2 kfacb kfac_cap seq1024 seq2048 e2e long sweep"
pending() { ! "have_$1" && ! gave_up "$1"; }
all_done() {
  local l
  for l in $LEGS; do "have_$l" || gave_up "$l" || return 1; done
}

run_sweep() {
  : > "$LOGS/sweep.tmp"
  # Points are batch:attn:remat. Three families (VERDICT r2 #3):
  #  - XLA-attention batch points around the known 56-peak;
  #  - the fused Pallas kernel at seq 128 (re-measure whether the
  #    bh-batched tiles close the 366-vs-396 gap the r02 verdict
  #    flagged);
  #  - remat=none legs: the fused kernel's O(S) memory may fit the
  #    batch WITHOUT rematerialization — 'dots' recompute is pure
  #    overhead if the activations fit, and r02 measured no-remat
  #    winning at batch 32 (327 vs ~281).
  # batch : attn : remat : pallas bh-block override (G)
  for pt in 48::: 52::: 56::: 60::: 64::: 56:pallas:: 64:pallas:: \
            56:pallas:none: 64:pallas:none: 56::none: \
            56:pallas::32 64:pallas::32; do
    IFS=: read -r b attn remat g <<< "$pt"
    tag="$b${attn:+_$attn}${remat:+_remat_$remat}${g:+_g$g}"
    if { [ -s "$LOGS/sweep_$tag.json" ] && good_json "$LOGS/sweep_$tag.json"; } \
        || env BENCH_LOCAL_BATCH="$b" ${attn:+BENCH_ATTN=$attn} \
        ${remat:+BENCH_REMAT=$remat} ${g:+PALLAS_ATTN_BH_BLOCK=$g} \
        BENCH_MEASURE_STEPS=12 BENCH_ATTEMPTS=1 BENCH_DEGRADE=0 \
        timeout 900 python bench.py > "$LOGS/sweep_$tag.json" 2> "$LOGS/sweep_$tag.log"
    then
      python - "$b" "${attn:-xla}" "${remat:-dots}" "${g:-0}" \
          "$LOGS/sweep_$tag.json" >> "$LOGS/sweep.tmp" <<'EOF'
import json, sys
b, attn, remat, g, path = sys.argv[1:6]
rec = json.load(open(path))
rec["local_batch"] = int(b)
rec["attention"] = attn
rec["remat"] = remat
if int(g):
    rec["bh_block"] = int(g)
print(json.dumps(rec))
EOF
      echo "   sweep $tag: $(tail -1 "$LOGS/sweep.tmp")"
    else
      # An OOM (possible on the no-remat legs) is a data point, not a
      # harness failure: record it and keep sweeping.
      if grep -qi "resource exhausted\|out of memory" "$LOGS/sweep_$tag.log"; then
        echo "{\"local_batch\": $b, \"attention\": \"${attn:-xla}\"," \
             "\"remat\": \"${remat:-dots}\"${g:+, \"bh_block\": $g}," \
             "\"oom\": true}" >> "$LOGS/sweep.tmp"
        echo "   sweep $tag: OOM (recorded)"
      else
        echo "   sweep $tag FAILED; aborting sweep pass"
        return 1
      fi
    fi
  done
  mv "$LOGS/sweep.tmp" SWEEP_r03.jsonl
}

report() {  # per-leg status incl. give-up state (so a NO that needs a
            # fail_<leg> reset is distinguishable from a never-ran leg)
  local l
  for l in $LEGS; do
    if "have_$l"; then echo "  $l: yes"
    elif gave_up "$l"; then echo "  $l: NO (gave up after $(fails "$l") failures; rm $LOGS/fail_$l to retry)"
    else echo "  $l: NO"
    fi
  done
}

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  [ -f .stop_capture ] && { echo "stop_capture flag set; exiting"; exit 0; }
  if all_done; then
    echo "retry_capture_r04: all legs resolved (captured or gave up):"
    report
    exit 0
  fi
  if ! probe; then
    echo "$(date +%H:%M:%S) backend down; sleeping 120s"
    sleep 120
    continue
  fi
  echo "$(date +%H:%M:%S) backend up"

  # -- P1: committed warm cache + cold-verified driver bench ------------
  if pending phase1; then
    if ! bench_warm bench_phase1.json 2850 BENCH_PHASE=1; then
      bump_fail phase1
    else
      echo "== leg: cold-verify (fresh process, committed cache only)"
      if env BENCH_ATTEMPTS=1 BENCH_ATTEMPT_TIMEOUT_S=540 \
          BENCH_BUDGET_S=560 BENCH_DEGRADE=0 \
          timeout 600 python bench.py > "$LOGS/cold.tmp" 2> "$LOGS/cold.log" \
          && good_json "$LOGS/cold.tmp"; then
        python - "$LOGS/cold.tmp" > COLD_BENCH_r03.json <<'EOF'
import json, sys, time
rec = json.load(open(sys.argv[1]))
rec["cold_start_verified"] = "fresh process, warm committed .jax_cache, <600s"
print(json.dumps(rec))
EOF
        echo "   cold-verify OK: $(cat COLD_BENCH_r03.json)"
      else
        echo "   cold-verify FAILED: $(tail -1 "$LOGS/cold.log" | cut -c1-160)"
        # Counted: without this a deterministic cold-verify failure
        # would re-run the whole ~50-min warm+verify leg every window.
        bump_fail phase1
      fi
      commit_artifacts "Capture r03 phase-1 bench; commit the warm compile cache" \
        .jax_cache bench_phase1.json COLD_BENCH_r03.json
    fi
    continue  # re-probe between legs: windows are short
  fi
  if pending degraded; then
    echo "== leg: warm degraded (BERT-base) fallback cache entry"
    if env BENCH_DEGRADED=1 BENCH_ATTEMPTS=1 BENCH_ATTEMPT_TIMEOUT_S=1500 \
        BENCH_BUDGET_S=1530 BENCH_DEGRADE=0 \
        timeout 1600 python bench.py > "$LOGS/degraded_warm.json" \
        2> "$LOGS/degraded_warm.log" \
        && good_json "$LOGS/degraded_warm.json"; then
      echo "   $(cat "$LOGS/degraded_warm.json")"
      commit_artifacts "Warm the degraded-fallback bench cache entry" .jax_cache
    else
      rm -f "$LOGS/degraded_warm.json"
      echo "   FAILED (degraded warm)"
      bump_fail degraded
    fi
    continue
  fi

  # -- P2: K-FAC convergence (reference point + cheap cadence) ----------
  if pending conv; then
    echo "== leg: convergence (LAMB vs K-FAC x2)"
    # Progress unit = a sub-leg stamp (.leg_ok) written DURING this pass
    # (mtime probe, not a count: a pass that re-completes a sub-leg whose
    # stale stamp run_leg just cleared leaves the count unchanged but is
    # real progress). An individual sub-leg restarts from step 0 when
    # interrupted, but completed sub-legs skip on the next pass.
    touch "$LOGS/conv_pass_start"
    timeout 7200 \
        bash scripts/convergence_r03.sh "$CONV_W" CONVERGENCE_r03.csv \
        > "$LOGS/convergence.log" 2>&1
    rc=$?
    if [ "$rc" -eq 0 ]; then
      clear_fail conv
      commit_artifacts "Capture r03 on-chip LAMB-vs-K-FAC convergence (equal step + wallclock)" \
        CONVERGENCE_r03.csv CONVERGENCE_r03_summary.json docs/convergence_r03.png
    elif find "$CONV_W" -mindepth 2 -maxdepth 2 -name .leg_ok \
        -newer "$LOGS/conv_pass_start" 2>/dev/null | grep -q .; then
      echo "   convergence pass ended (rc=$rc) after completing a sub-leg; resumes"
      clear_fail conv
    else
      echo "   FAILED (convergence, rc=$rc, no sub-leg progress); tail:"
      tail -3 "$LOGS/convergence.log"
      bump_fail conv
    fi
    continue
  fi

  # -- P3: remaining bench legs ----------------------------------------
  if pending phase2; then
    if bench_warm bench_phase2.json 2850 BENCH_PHASE=2; then
      echo pallas > "$LOGS/.phase2_r03_done"
      commit_artifacts "Capture r03 phase-2 bench; extend the committed cache" \
        .jax_cache bench_phase2.json
    else
      bump_fail phase2
    fi
    continue
  fi
  if pending kfacb; then
    # Fused in-train capture is the BENCH_KFAC_CAPTURE default now; the
    # r02-committed 236-seq/s number was the stats mode.
    if bench_warm bench_kfac.json 2850 BENCH_KFAC=1; then
      : > "$LOGS/.kfac_r04_done"
      commit_artifacts "Capture r04 K-FAC bench (fused in-train capture)" \
        .jax_cache bench_kfac.json
    else
      bump_fail kfacb
    fi
    continue
  fi
  if pending kfac_cap; then
    echo "== leg: K-FAC capture-cost A/B (lamb vs stats vs fused, interval 1)"
    if timeout 3600 python tools/bench_kfac_capture.py \
        --hidden 1024 --layers 24 --heads 16 --vocab 30528 --seq 128 \
        --batch 32 --max_pred 20 --remat dots --dtype bfloat16 \
        --steps 10 --warmup 3 --out KFAC_CAPTURE_BENCH_chip_r04.jsonl \
        > "$LOGS/kfac_capture.log" 2>&1 \
        && grep -q kfac_fused KFAC_CAPTURE_BENCH_chip_r04.jsonl; then
      echo "   $(tail -1 KFAC_CAPTURE_BENCH_chip_r04.jsonl)"
      commit_artifacts \
        "Capture r04 on-chip K-FAC capture-cost A/B (fused vs stats)" \
        KFAC_CAPTURE_BENCH_chip_r04.jsonl
    else
      rm -f KFAC_CAPTURE_BENCH_chip_r04.jsonl
      echo "   FAILED (kfac capture A/B): $(tail -1 "$LOGS/kfac_capture.log" \
        2>/dev/null | cut -c1-160)"
      bump_fail kfac_cap
    fi
    continue
  fi
  if pending seq1024; then
    if bench_warm bench_seq1024.json 2400 BENCH_SEQ=1024; then
      commit_artifacts "Capture r03 seq-1024 long-context bench" \
        .jax_cache bench_seq1024.json
    else
      bump_fail seq1024
    fi
    continue
  fi
  if pending seq2048; then
    if bench_warm bench_seq2048.json 3000 BENCH_SEQ=2048; then
      commit_artifacts "Capture r03 seq-2048 long-context bench" \
        .jax_cache bench_seq2048.json
    else
      bump_fail seq2048
    fi
    continue
  fi

  # -- P4: chip e2e -----------------------------------------------------
  if pending e2e; then
    echo "== leg: smoke_and_e2e"
    timeout 3600 bash scripts/smoke_tpu.sh "$SMOKE_W" \
        > "$LOGS/smoke.log" 2>&1
    rc=$?
    if [ "$rc" -eq 0 ]; then
      commit_artifacts "Capture r03 chip-profile offline e2e chain" E2E_r03.json
    else
      echo "   FAILED (smoke_and_e2e, rc=$rc); tail:"; tail -3 "$LOGS/smoke.log"
      bump_fail e2e
    fi
    continue
  fi

  # -- P5: long anchored convergence (resumable across windows) ---------
  if pending long; then
    echo "== leg: long convergence (resumable pass)"
    # Progress-aware timeout handling: this leg auto-resumes from its
    # 250-step checkpoints, so a 3600s pass timeout is fine AS LONG AS
    # the pass advanced the latest checkpoint; a pass that times out
    # with zero checkpoint progress counts as a failure. NUMERIC max of
    # the ckpt_<step> names — the names are unpadded, so a lexicographic
    # max would stall at e.g. ckpt_750 while ckpt_1000+ accrue (and
    # timeout-killed writes can leave tmp* litter that sorts last).
    latest_long_ckpt() {
      ls "$LONG_W"/run/pretrain_ckpts 2>/dev/null \
        | grep -oE '^ckpt_[0-9]+' | sed 's/ckpt_//' | sort -n | tail -1
    }
    # Direct liveness evidence (docs/telemetry.md): run_pretraining
    # atomically maintains <run>/heartbeat.json with a monotonic per-step
    # counter that RESUMES across restarts. Counter advance across the
    # pass means the run was training when the window closed — finer than
    # the 250-step checkpoint cadence (a pass killed at step 240 shows
    # zero checkpoint progress but 240 trained steps), and not foolable
    # by tmp-file litter the way mtime probes were.
    long_hb_counter() {
      grep -oE '"counter": *[0-9]+' "$LONG_W/run/heartbeat.json" \
        2>/dev/null | grep -oE '[0-9]+' || echo 0
    }
    ckpt_before=$(latest_long_ckpt)
    hb_before=$(long_hb_counter)
    timeout 3600 bash scripts/convergence_long_r03.sh "$LONG_W" \
        > "$LOGS/long.log" 2>&1
    rc=$?
    if [ "$rc" -eq 0 ]; then
      clear_fail long
      commit_artifacts "Capture r03 long anchored convergence run (pre-stated milestones)" \
        CONVERGENCE_LONG_r03.csv LONG_RUN_r03.json docs/convergence_long_r03.png
    elif [ "$(latest_long_ckpt)" != "$ckpt_before" ] \
        || [ "$(long_hb_counter)" -gt "$hb_before" ]; then
      echo "   long pass ended (rc=$rc) alive (ckpt $ckpt_before ->" \
        "$(latest_long_ckpt), heartbeat $hb_before -> $(long_hb_counter));" \
        "resumes next window"
      clear_fail long
    else
      echo "   long pass FAILED (rc=$rc, no checkpoint or heartbeat progress): $(tail -1 "$LOGS/long.log" | cut -c1-160)"
      bump_fail long
    fi
    continue
  fi

  # -- P6: sweep --------------------------------------------------------
  if pending sweep; then
    echo "== leg: batch/backend sweep"
    # Per-point resumable (run_sweep reuses good cached sweep_*.json):
    # a failing pass that still banked at least one NEW point is
    # progress, same policy as the conv/long legs. Count GOOD points —
    # a failed point also leaves a (bad) sweep_*.json behind.
    count_good_sweep() {
      local n=0 f
      for f in "$LOGS"/sweep_*.json; do
        [ -s "$f" ] && good_json "$f" && n=$((n + 1))
      done
      echo "$n"
    }
    sweep_pts_before=$(count_good_sweep)
    if run_sweep; then
      clear_fail sweep
      commit_artifacts "Capture r03 phase-1 batch/backend sweep" SWEEP_r03.jsonl
    elif [ "$(count_good_sweep)" -gt "$sweep_pts_before" ]; then
      echo "   sweep pass banked new points before failing; resumes"
      clear_fail sweep
    else
      bump_fail sweep
    fi
  fi
done
echo "retry_capture_r04: deadline reached"
report
