#!/bin/bash
# Round-4 persistent capture harness (supersedes retry_capture_r03.sh —
# same legs and artifact names, plus the fused-K-FAC capture-cost leg).
# Probes the flaky TPU tunnel; when it answers, runs whichever capture
# legs have not yet produced their repo-root artifact, IN PRIORITY ORDER
# (VERDICT r3 "Next round"):
#
#   1. Warm the IN-REPO persistent compile cache (.jax_cache/) for the
#      driver's bench shapes, then COLD-VERIFY: a fresh `python bench.py`
#      with only the committed cache must emit a real number in <600s —
#      the property whose absence zeroed BENCH_r01/r02/r03. Also warms
#      the degraded BERT-base fallback entry.
#   2. LAMB vs K-FAC (reference operating point + cheap cadence)
#      convergence with equal-step AND equal-wallclock accounting (the
#      K-FAC legs now run the FUSED in-train capture, the round-4
#      structural fix).
#   3. Remaining bench legs: phase2, kfac (fused capture),
#      kfac capture-cost A/B (lamb vs stats vs fused at BERT-large,
#      factor_interval=1), seq1024, seq2048.
#   4. Chip-profile offline e2e chain -> E2E_r03.json.
#   5. Long anchored convergence run (resumable; retried each window).
#   6. Phase-1 batch/backend sweep -> SWEEP_r03.jsonl.
#
# Each captured artifact is git-committed immediately (tunnel windows are
# scarce; an artifact must survive even if the session dies right after).
# Touch .stop_capture in the repo root to make the harness exit at the
# next loop boundary (do this before the driver's end-of-round bench so
# the harness cannot contend for the chip).
#
#   bash scripts/retry_capture_r04.sh [deadline_epoch_s] [logdir]
set -uo pipefail
cd "$(dirname "$0")/.."
DEADLINE=${1:-$(($(date +%s) + 10 * 3600))}
LOGS=${2:-/tmp/retry_capture_r04}
mkdir -p "$LOGS"
# Cache split: bench.py invocations use its default in-repo cache
# (.jax_cache/, committed); the runner-based legs (convergence, smoke,
# e2e, long run) use their scripts' own per-user scratch default. Nothing
# is exported here — smoke_tpu.sh runs `python bench.py` internally, and
# an exported BENCH_COMPILE_CACHE_DIR would wrongly divert those bench
# legs off the committed cache.

probe() {
  timeout 150 python - <<'EOF' >/dev/null 2>&1
import jax
d = jax.devices()[0]
assert d.platform in ("tpu", "axon") or "TPU" in d.device_kind
EOF
}

commit_artifacts() {  # msg, paths...
  local msg=$1; shift
  local existing=()
  for p in "$@"; do [ -e "$p" ] && existing+=("$p"); done
  [ "${#existing[@]}" -eq 0 ] && return 0
  git add -f -- "${existing[@]}" 2>> "$LOGS/git.log" || true
  if ! git diff --cached --quiet; then
    git commit -q -m "$msg" >> "$LOGS/git.log" 2>&1 \
      && echo "   committed: $msg" \
      || { echo "   COMMIT FAILED: $msg"; git reset -q; }
  fi
}

good_json() { [ -f "$1" ] && ! grep -q '"error"' "$1" \
  && ! grep -q '"value": 0.0' "$1"; }

bench_warm() {  # artifact, timeout_s, env pairs...
  local art=$1 t=$2; shift 2
  echo "== leg: warm $art"
  if env "$@" BENCH_DEGRADE=0 BENCH_ATTEMPTS=1 \
      BENCH_ATTEMPT_TIMEOUT_S=$((t - 60)) BENCH_BUDGET_S=$((t - 30)) \
      timeout "$t" python bench.py > "$LOGS/$art.tmp" 2> "$LOGS/$art.log" \
      && good_json "$LOGS/$art.tmp"; then
    cp "$LOGS/$art.tmp" "$art"
    echo "   $(cat "$art")"
    return 0
  fi
  echo "   FAILED ($art): $(tail -1 "$LOGS/$art.log" 2>/dev/null | cut -c1-160)"
  return 1
}

have_phase1()   { good_json bench_phase1.json && [ -f COLD_BENCH_r03.json ]; }
have_degraded() { [ -f "$LOGS/degraded_warm.json" ]; }
have_conv()     { [ -f CONVERGENCE_r03.csv ]; }
have_phase2()   { good_json bench_phase2.json && grep -q pallas "$LOGS/.phase2_r03_done" 2>/dev/null; }
have_kfacb()    { good_json bench_kfac.json && [ -f "$LOGS/.kfac_r04_done" ]; }
have_kfac_cap() { [ -f KFAC_CAPTURE_BENCH_chip_r04.jsonl ] \
  && grep -q kfac_fused KFAC_CAPTURE_BENCH_chip_r04.jsonl; }
have_seq1024()  { good_json bench_seq1024.json; }
have_seq2048()  { good_json bench_seq2048.json; }
have_e2e()      { [ -f E2E_r03.json ]; }
have_long()     { [ -f LONG_RUN_r03.json ]; }
have_sweep()    { [ -f SWEEP_r03.jsonl ] && [ "$(wc -l < SWEEP_r03.jsonl)" -ge 12 ]; }

all_done() {
  have_phase1 && have_degraded && have_conv && have_phase2 && have_kfacb \
    && have_kfac_cap && have_seq1024 && have_seq2048 && have_e2e \
    && have_long && have_sweep
}

run_sweep() {
  : > "$LOGS/sweep.tmp"
  # Points are batch:attn:remat. Three families (VERDICT r2 #3):
  #  - XLA-attention batch points around the known 56-peak;
  #  - the fused Pallas kernel at seq 128 (re-measure whether the
  #    bh-batched tiles close the 366-vs-396 gap the r02 verdict
  #    flagged);
  #  - remat=none legs: the fused kernel's O(S) memory may fit the
  #    batch WITHOUT rematerialization — 'dots' recompute is pure
  #    overhead if the activations fit, and r02 measured no-remat
  #    winning at batch 32 (327 vs ~281).
  # batch : attn : remat : pallas bh-block override (G)
  for pt in 48::: 52::: 56::: 60::: 64::: 56:pallas:: 64:pallas:: \
            56:pallas:none: 64:pallas:none: 56::none: \
            56:pallas::32 64:pallas::32; do
    IFS=: read -r b attn remat g <<< "$pt"
    tag="$b${attn:+_$attn}${remat:+_remat_$remat}${g:+_g$g}"
    if { [ -s "$LOGS/sweep_$tag.json" ] && good_json "$LOGS/sweep_$tag.json"; } \
        || env BENCH_LOCAL_BATCH="$b" ${attn:+BENCH_ATTN=$attn} \
        ${remat:+BENCH_REMAT=$remat} ${g:+PALLAS_ATTN_BH_BLOCK=$g} \
        BENCH_MEASURE_STEPS=12 BENCH_ATTEMPTS=1 BENCH_DEGRADE=0 \
        timeout 900 python bench.py > "$LOGS/sweep_$tag.json" 2> "$LOGS/sweep_$tag.log"
    then
      python - "$b" "${attn:-xla}" "${remat:-dots}" "${g:-0}" \
          "$LOGS/sweep_$tag.json" >> "$LOGS/sweep.tmp" <<'EOF'
import json, sys
b, attn, remat, g, path = sys.argv[1:6]
rec = json.load(open(path))
rec["local_batch"] = int(b)
rec["attention"] = attn
rec["remat"] = remat
if int(g):
    rec["bh_block"] = int(g)
print(json.dumps(rec))
EOF
      echo "   sweep $tag: $(tail -1 "$LOGS/sweep.tmp")"
    else
      # An OOM (possible on the no-remat legs) is a data point, not a
      # harness failure: record it and keep sweeping.
      if grep -qi "resource exhausted\|out of memory" "$LOGS/sweep_$tag.log"; then
        echo "{\"local_batch\": $b, \"attention\": \"${attn:-xla}\"," \
             "\"remat\": \"${remat:-dots}\"${g:+, \"bh_block\": $g}," \
             "\"oom\": true}" >> "$LOGS/sweep.tmp"
        echo "   sweep $tag: OOM (recorded)"
      else
        echo "   sweep $tag FAILED; aborting sweep pass"
        return 1
      fi
    fi
  done
  mv "$LOGS/sweep.tmp" SWEEP_r03.jsonl
}

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  [ -f .stop_capture ] && { echo "stop_capture flag set; exiting"; exit 0; }
  if all_done; then
    echo "retry_capture_r04: all artifacts captured"
    exit 0
  fi
  if ! probe; then
    echo "$(date +%H:%M:%S) backend down; sleeping 120s"
    sleep 120
    continue
  fi
  echo "$(date +%H:%M:%S) backend up"

  # -- P1: committed warm cache + cold-verified driver bench ------------
  if ! have_phase1; then
    if bench_warm bench_phase1.json 2850 BENCH_PHASE=1; then
      echo "== leg: cold-verify (fresh process, committed cache only)"
      if env BENCH_ATTEMPTS=1 BENCH_ATTEMPT_TIMEOUT_S=540 \
          BENCH_BUDGET_S=560 BENCH_DEGRADE=0 \
          timeout 600 python bench.py > "$LOGS/cold.tmp" 2> "$LOGS/cold.log" \
          && good_json "$LOGS/cold.tmp"; then
        python - "$LOGS/cold.tmp" > COLD_BENCH_r03.json <<'EOF'
import json, sys, time
rec = json.load(open(sys.argv[1]))
rec["cold_start_verified"] = "fresh process, warm committed .jax_cache, <600s"
print(json.dumps(rec))
EOF
        echo "   cold-verify OK: $(cat COLD_BENCH_r03.json)"
      else
        echo "   cold-verify FAILED: $(tail -1 "$LOGS/cold.log" | cut -c1-160)"
      fi
      commit_artifacts "Capture r03 phase-1 bench; commit the warm compile cache" \
        .jax_cache bench_phase1.json COLD_BENCH_r03.json
    fi
    continue  # re-probe between legs: windows are short
  fi
  if ! have_degraded; then
    echo "== leg: warm degraded (BERT-base) fallback cache entry"
    if env BENCH_DEGRADED=1 BENCH_ATTEMPTS=1 BENCH_ATTEMPT_TIMEOUT_S=1500 \
        BENCH_BUDGET_S=1530 BENCH_DEGRADE=0 \
        timeout 1600 python bench.py > "$LOGS/degraded_warm.json" \
        2> "$LOGS/degraded_warm.log" \
        && good_json "$LOGS/degraded_warm.json"; then
      echo "   $(cat "$LOGS/degraded_warm.json")"
      commit_artifacts "Warm the degraded-fallback bench cache entry" .jax_cache
    else
      rm -f "$LOGS/degraded_warm.json"
      echo "   FAILED (degraded warm)"
    fi
    continue
  fi

  # -- P2: K-FAC convergence (reference point + cheap cadence) ----------
  if ! have_conv; then
    echo "== leg: convergence (LAMB vs K-FAC x2)"
    if timeout 7200 \
        bash scripts/convergence_r03.sh /tmp/bert_conv_r03 CONVERGENCE_r03.csv \
        > "$LOGS/convergence.log" 2>&1; then
      commit_artifacts "Capture r03 on-chip LAMB-vs-K-FAC convergence (equal step + wallclock)" \
        CONVERGENCE_r03.csv CONVERGENCE_r03_summary.json docs/convergence_r03.png
    else
      echo "   FAILED (convergence); tail:"; tail -3 "$LOGS/convergence.log"
    fi
    continue
  fi

  # -- P3: remaining bench legs ----------------------------------------
  if ! have_phase2; then
    if bench_warm bench_phase2.json 2850 BENCH_PHASE=2; then
      echo pallas > "$LOGS/.phase2_r03_done"
      commit_artifacts "Capture r03 phase-2 bench; extend the committed cache" \
        .jax_cache bench_phase2.json
    fi
    continue
  fi
  if ! have_kfacb; then
    # Fused in-train capture is the BENCH_KFAC_CAPTURE default now; the
    # r02-committed 236-seq/s number was the stats mode.
    if bench_warm bench_kfac.json 2850 BENCH_KFAC=1; then
      : > "$LOGS/.kfac_r04_done"
      commit_artifacts "Capture r04 K-FAC bench (fused in-train capture)" \
        .jax_cache bench_kfac.json
    fi
    continue
  fi
  if ! have_kfac_cap; then
    echo "== leg: K-FAC capture-cost A/B (lamb vs stats vs fused, interval 1)"
    if timeout 3600 python tools/bench_kfac_capture.py \
        --hidden 1024 --layers 24 --heads 16 --vocab 30528 --seq 128 \
        --batch 32 --max_pred 20 --remat dots --dtype bfloat16 \
        --steps 10 --warmup 3 --out KFAC_CAPTURE_BENCH_chip_r04.jsonl \
        > "$LOGS/kfac_capture.log" 2>&1 \
        && grep -q kfac_fused KFAC_CAPTURE_BENCH_chip_r04.jsonl; then
      echo "   $(tail -1 KFAC_CAPTURE_BENCH_chip_r04.jsonl)"
      commit_artifacts \
        "Capture r04 on-chip K-FAC capture-cost A/B (fused vs stats)" \
        KFAC_CAPTURE_BENCH_chip_r04.jsonl
    else
      rm -f KFAC_CAPTURE_BENCH_chip_r04.jsonl
      echo "   FAILED (kfac capture A/B): $(tail -1 "$LOGS/kfac_capture.log" \
        2>/dev/null | cut -c1-160)"
    fi
    continue
  fi
  if ! have_seq1024; then
    bench_warm bench_seq1024.json 2400 BENCH_SEQ=1024 \
      && commit_artifacts "Capture r03 seq-1024 long-context bench" \
           .jax_cache bench_seq1024.json
    continue
  fi
  if ! have_seq2048; then
    bench_warm bench_seq2048.json 3000 BENCH_SEQ=2048 \
      && commit_artifacts "Capture r03 seq-2048 long-context bench" \
           .jax_cache bench_seq2048.json
    continue
  fi

  # -- P4: chip e2e -----------------------------------------------------
  if ! have_e2e; then
    echo "== leg: smoke_and_e2e"
    if timeout 3600 \
        bash scripts/smoke_tpu.sh /tmp/bert_tpu_smoke_r03 \
        > "$LOGS/smoke.log" 2>&1; then
      commit_artifacts "Capture r03 chip-profile offline e2e chain" E2E_r03.json
    else
      echo "   FAILED (smoke_and_e2e); tail:"; tail -3 "$LOGS/smoke.log"
    fi
    continue
  fi

  # -- P5: long anchored convergence (resumable across windows) ---------
  if ! have_long; then
    echo "== leg: long convergence (resumable pass)"
    if timeout 3600 \
        bash scripts/convergence_long_r03.sh /tmp/bert_conv_long_r03 \
        > "$LOGS/long.log" 2>&1; then
      commit_artifacts "Capture r03 long anchored convergence run (pre-stated milestones)" \
        CONVERGENCE_LONG_r03.csv LONG_RUN_r03.json docs/convergence_long_r03.png
    else
      echo "   long pass ended (will resume): $(tail -1 "$LOGS/long.log" | cut -c1-160)"
    fi
    continue
  fi

  # -- P6: sweep --------------------------------------------------------
  if ! have_sweep; then
    echo "== leg: batch/backend sweep"
    run_sweep && commit_artifacts "Capture r03 phase-1 batch/backend sweep" \
      SWEEP_r03.jsonl || true
  fi
done
echo "retry_capture_r04: deadline reached"
for f in have_phase1 have_degraded have_conv have_phase2 have_kfacb \
         have_kfac_cap have_seq1024 have_seq2048 have_e2e have_long \
         have_sweep; do
  $f && echo "  $f: yes" || echo "  $f: NO"
done
