#!/bin/bash
# GLUE finetune + eval in the classic BERT recipe (lr 2e-5, 3 epochs,
# warmup 0.1, seq 128). The reference only downloads GLUE
# (utils/download.py:81-101); this runner closes the loop.
# Usage: TASK=mrpc GLUE_DIR=data/download/glue ./scripts/run_glue.sh
set -euo pipefail
TASK=${TASK:-mrpc}
GLUE_DIR=${GLUE_DIR:-data/download/glue}
declare -A DIRS=(
    [cola]=CoLA [sst-2]=SST-2 [mrpc]=MRPC [sts-b]=STS-B [qqp]=QQP
    [mnli]=MNLI [mnli-mm]=MNLI [qnli]=QNLI [rte]=RTE [wnli]=WNLI
)
python run_glue.py \
    --task "$TASK" \
    --data_dir "$GLUE_DIR/${DIRS[$TASK]}" \
    --model_config_file configs/bert_large_uncased_config.json \
    --init_checkpoint "${INIT_CKPT:?set INIT_CKPT to a pretraining checkpoint}" \
    --output_dir "results/glue_$TASK" \
    --lr 2e-5 --epochs 3 --warmup_proportion 0.1 \
    --batch_size 32 --max_seq_len 128
