#!/bin/bash
# NER finetune on CoNLL-style datasets (reference scripts/run_ner.sh).
set -euo pipefail
DATASET=${DATASET:-CoNLL-2003}
case "$DATASET" in
  CoNLL-2003) LABELS="O B-PER I-PER B-ORG I-ORG B-MISC I-MISC B-LOC I-LOC" ;;
  JNLPBA) LABELS="O I-DNA B-DNA I-RNA B-RNA I-cell_line B-cell_line I-protein B-protein I-cell_type B-cell_type" ;;
  NCBI) LABELS="O B-Disease I-Disease" ;;
  BC5CDR) LABELS="O B-Entity I-Entity" ;;
  *) echo "Unknown dataset $DATASET"; exit 1 ;;
esac
DATA_DIR=${DATA_DIR:?set DATA_DIR to the CoNLL data directory}
python run_ner.py \
    --train_file "$DATA_DIR/train.txt" \
    --val_file "$DATA_DIR/dev.txt" \
    --test_file "$DATA_DIR/test.txt" \
    --labels $LABELS \
    --model_config_file configs/bert_large_uncased_config.json \
    --model_checkpoint "${INIT_CKPT:?set INIT_CKPT}" \
    --lr 5e-6 --epochs 5 --batch_size 32 --max_seq_len 128 --uppercase
