#!/bin/bash
# Cloud TPU VM launcher: run the same command on every host of the pod slice
# (gcloud alpha compute tpus tpu-vm ssh --worker=all). jax.distributed
# auto-discovers the pod topology on TPU VMs, so no nodefile inference is
# needed (bert_pytorch_tpu/parallel/launcher.py).
set -euo pipefail
TPU_NAME=${1:?usage: run_pretraining_tpu_vm.sh <tpu-name> [phase]}
PHASE=${2:-1}
gcloud alpha compute tpus tpu-vm ssh "$TPU_NAME" --worker=all --command "
  cd $(pwd) && python run_pretraining.py \
    --input_dir data/encoded/phase${PHASE} \
    --output_dir results/bert_pretraining \
    --model_config_file configs/bert_large_uncased_config.json \
    --config_file configs/bert_pretraining_phase${PHASE}_config.json"
