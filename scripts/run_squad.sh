#!/bin/bash
# SQuAD finetune + eval (reference scripts/run_squad.sh:23-46 recipe:
# lr 3e-5, 2 epochs, seq 384, doc_stride 128).
set -euo pipefail
SQUAD_DIR=${SQUAD_DIR:-data/download/squad/v1.1}
python run_squad.py \
    --do_train --do_predict --do_eval --do_lower_case \
    --train_file "$SQUAD_DIR/train-v1.1.json" \
    --predict_file "$SQUAD_DIR/dev-v1.1.json" \
    --eval_script "$SQUAD_DIR/evaluate-v1.1.py" \
    --config_file configs/bert_large_uncased_config.json \
    --init_checkpoint "${INIT_CKPT:?set INIT_CKPT to a pretraining checkpoint}" \
    --output_dir results/squad \
    --learning_rate 3e-5 --num_train_epochs 2 \
    --max_seq_length 384 --doc_stride 128 --train_batch_size 32
