#!/bin/bash
# SWAG multiple-choice finetune + eval (original SWAG BERT recipe:
# lr 2e-5, 3 epochs, warmup 0.1). Beyond-reference: the reference defines
# BertForMultipleChoice but has no runner for it.
# Data: python -m bert_pytorch_tpu.tools.download --dataset swag --output_dir data/download
set -euo pipefail
SWAG_DIR=${SWAG_DIR:-data/download/swag}
python run_swag.py \
    --train_file "$SWAG_DIR/train.csv" \
    --val_file "$SWAG_DIR/val.csv" \
    --model_config_file configs/bert_large_uncased_config.json \
    --init_checkpoint "${INIT_CKPT:?set INIT_CKPT to a pretraining checkpoint}" \
    --output_dir results/swag \
    --lr 2e-5 --epochs 3 --warmup_proportion 0.1 \
    --batch_size 16 --max_seq_len 128
