#!/bin/bash
# On-chip smoke: the hardware-only behaviors the CPU test suite cannot cover
# (tests/ pins an 8-device virtual CPU mesh; see tests/conftest.py).
# Run on any machine with a real TPU attached. ~10 minutes.
#
#   bash scripts/smoke_tpu.sh [workdir]
#
# Covers: compiled (Mosaic) Pallas kernels incl. in-kernel hardware-PRNG
# dropout, bf16 end-to-end pretraining with checkpoint + resume, the fused
# attention backend at seq 512, and the three bench modes.
set -euo pipefail
# Per-user scratch cache for the runner legs. bench.py uses its own
# in-repo committed default (.jax_cache/) — see retry_capture_r03.sh
# for the split rationale.
CACHE=${BENCH_COMPILE_CACHE_DIR:-${XDG_CACHE_HOME:-$HOME/.cache}/bert_tpu_jax_cache}
cd "$(dirname "$0")/.."
WORK=${1:-/tmp/bert_tpu_smoke}
# Clear only this script's own (cheap) legs; "$WORK/e2e" is e2e_offline.sh's
# RESUMABLE workdir — wiping it would redo the full chip pretrain+finetune
# chain after every tunnel-drop retry.
rm -rf "$WORK/seq128" "$WORK/seq512" "$WORK/out128" "$WORK/out512"
mkdir -p "$WORK"

echo "== synthetic shards"
python -m bert_pytorch_tpu.tools.make_synthetic_data \
    --output_dir "$WORK/seq128" --num_shards 2 --samples_per_shard 256 \
    --seq_len 128 --vocab_size 30522 --seed 1
python -m bert_pytorch_tpu.tools.make_synthetic_data \
    --output_dir "$WORK/seq512" --num_shards 1 --samples_per_shard 96 \
    --seq_len 512 --vocab_size 30522 --seed 2

echo "== on-chip kernel checks (hardware PRNG dropout determinism/stats)"
python -m pytest tests/test_ops.py -q -p no:cacheprovider \
    -k "pallas_dropout_on_tpu or flash" \
    --override-ini addopts= || true  # conftest pins CPU; informational only

echo "== bf16 pretraining + auto-resume (BERT-large, seq 128)"
python run_pretraining.py --input_dir "$WORK/seq128" \
    --output_dir "$WORK/out128" \
    --model_config_file configs/bert_large_uncased_config.json \
    --global_batch_size 56 --local_batch_size 56 --steps 3 --max_steps 6 \
    --learning_rate 6e-3 --warmup_proportion 0.28 \
    --max_predictions_per_seq 20 --remat dots \
    --log_prefix "$WORK/out128/log" --num_steps_per_checkpoint 1000 \
    --compile_cache_dir "$CACHE"
python run_pretraining.py --input_dir "$WORK/seq128" \
    --output_dir "$WORK/out128" \
    --model_config_file configs/bert_large_uncased_config.json \
    --global_batch_size 56 --local_batch_size 56 --steps 3 --max_steps 6 \
    --learning_rate 6e-3 --warmup_proportion 0.28 \
    --max_predictions_per_seq 20 --remat dots \
    --log_prefix "$WORK/out128/log" --num_steps_per_checkpoint 1000 \
    --compile_cache_dir "$CACHE"

echo "== fused Pallas attention at seq 512"
python run_pretraining.py --input_dir "$WORK/seq512" \
    --output_dir "$WORK/out512" \
    --model_config_file configs/bert_large_uncased_config.json \
    --global_batch_size 28 --local_batch_size 28 --steps 3 --max_steps 3 \
    --learning_rate 4e-3 --warmup_proportion 0.1 \
    --max_predictions_per_seq 80 --remat dots --attention_backend pallas \
    --log_prefix "$WORK/out512/log" --num_steps_per_checkpoint 5000 \
    --compile_cache_dir "$CACHE"

echo "== benches (phase 1, phase 2, K-FAC)"
python bench.py
BENCH_PHASE=2 python bench.py
BENCH_KFAC=1 python bench.py

echo "== full offline chain: corpus -> vocab -> encode -> pretrain -> SQuAD"
E2E_PROFILE=chip bash scripts/e2e_offline.sh "$WORK/e2e" "$PWD/E2E_r03.json"

echo "smoke_tpu OK"
