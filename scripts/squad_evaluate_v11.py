"""SQuAD v1.1 evaluation metric (exact match + token F1).

Implements the official metric's published algorithm — answer
normalization (lowercase, strip punctuation/articles, squash whitespace),
max over gold answers, macro-average over questions — so the finetune
runner's official-eval subprocess hook (run_squad.py --do_eval
--eval_script, parity with reference run_squad.py:1197-1204) works in
this zero-egress environment where the upstream evaluate-v1.1.py cannot
be downloaded.

Usage (the interface run_squad.py invokes):
    python squad_evaluate_v11.py <dataset.json> <predictions.json>
Prints one JSON object: {"exact_match": float, "f1": float} (percent).
"""

import collections
import json
import re
import string
import sys


def normalize_answer(s: str) -> str:
    s = s.lower()
    s = "".join(ch for ch in s if ch not in set(string.punctuation))
    s = re.sub(r"\b(a|an|the)\b", " ", s)
    return " ".join(s.split())


def f1_score(prediction: str, ground_truth: str) -> float:
    pred_tokens = normalize_answer(prediction).split()
    gold_tokens = normalize_answer(ground_truth).split()
    common = collections.Counter(pred_tokens) & collections.Counter(gold_tokens)
    num_same = sum(common.values())
    if num_same == 0:
        return 0.0
    precision = num_same / len(pred_tokens)
    recall = num_same / len(gold_tokens)
    return 2 * precision * recall / (precision + recall)


def exact_match_score(prediction: str, ground_truth: str) -> float:
    return float(normalize_answer(prediction) == normalize_answer(ground_truth))


def evaluate(dataset, predictions) -> dict:
    f1 = em = total = 0
    for article in dataset:
        for paragraph in article["paragraphs"]:
            for qa in paragraph["qas"]:
                total += 1
                if qa["id"] not in predictions:
                    print(f"Unanswered question {qa['id']} will receive "
                          "score 0.", file=sys.stderr)
                    continue
                golds = [a["text"] for a in qa["answers"]]
                pred = predictions[qa["id"]]
                em += max(exact_match_score(pred, g) for g in golds)
                f1 += max(f1_score(pred, g) for g in golds)
    return {"exact_match": 100.0 * em / total, "f1": 100.0 * f1 / total}


def main():
    if len(sys.argv) != 3:
        print(f"usage: {sys.argv[0]} <dataset.json> <predictions.json>",
              file=sys.stderr)
        sys.exit(1)
    with open(sys.argv[1]) as f:
        dataset = json.load(f)["data"]
    with open(sys.argv[2]) as f:
        predictions = json.load(f)
    print(json.dumps(evaluate(dataset, predictions)))


if __name__ == "__main__":
    main()
