"""SQuAD v2.0 evaluation metric (EM + F1 with no-answer accounting).

Implements the official v2.0 metric's published algorithm — answer
normalization, max over gold answers, empty-string handling for
impossible questions, HasAns/NoAns breakdowns, and the best-threshold
search over no-answer scores — so the finetune runner's official-eval
subprocess hook (run_squad.py --do_eval --eval_script, parity with
reference run_squad.py:1197-1204; the reference fetches the upstream
evaluate-v2.0.py at utils/download.py:119-120) works in this zero-egress
environment.

Usage (the interface run_squad.py invokes):
    python squad_evaluate_v20.py <dataset.json> <predictions.json> \
        [--na-prob-file null_odds.json] [--na-prob-thresh 0.0]

Prints one JSON object with exact_match / f1 (percent, the keys the
runner's summary parses) plus the official breakdown keys (total,
HasAns_*, NoAns_*, and — when --na-prob-file is given — best_exact,
best_exact_thresh, best_f1, best_f1_thresh).

Note on no-answer scores: the runner's null_odds.json holds the decode's
null score DIFF (null_score - best_non_null_score; higher = more likely
unanswerable, threshold semantics of --null_score_diff_threshold). Any
monotone unanswerability score works for the threshold search; only the
*_thresh outputs are in the score's own units.
"""

from __future__ import annotations

import argparse
import collections
import json
import re
import string
import sys


def normalize_answer(s: str) -> str:
    s = s.lower()
    s = "".join(ch for ch in s if ch not in set(string.punctuation))
    s = re.sub(r"\b(a|an|the)\b", " ", s)
    return " ".join(s.split())


def get_tokens(s: str) -> list:
    return normalize_answer(s).split() if s else []


def compute_exact(a_gold: str, a_pred: str) -> int:
    return int(normalize_answer(a_gold) == normalize_answer(a_pred))


def compute_f1(a_gold: str, a_pred: str) -> float:
    gold_toks = get_tokens(a_gold)
    pred_toks = get_tokens(a_pred)
    common = collections.Counter(gold_toks) & collections.Counter(pred_toks)
    num_same = sum(common.values())
    if not gold_toks or not pred_toks:
        # Either is a no-answer: F1 is 1 iff both are.
        return float(gold_toks == pred_toks)
    if num_same == 0:
        return 0.0
    precision = num_same / len(pred_toks)
    recall = num_same / len(gold_toks)
    return 2 * precision * recall / (precision + recall)


def iter_qas(dataset):
    for article in dataset:
        for paragraph in article["paragraphs"]:
            for qa in paragraph["qas"]:
                yield qa


def get_raw_scores(dataset, predictions):
    exact, f1 = {}, {}
    for qa in iter_qas(dataset):
        qid = qa["id"]
        golds = [a["text"] for a in qa["answers"]
                 if normalize_answer(a["text"])]
        if not golds:
            golds = [""]  # unanswerable: the only correct answer is ""
        if qid not in predictions:
            print(f"Missing prediction for {qid}", file=sys.stderr)
            continue
        pred = predictions[qid]
        exact[qid] = max(compute_exact(g, pred) for g in golds)
        f1[qid] = max(compute_f1(g, pred) for g in golds)
    return exact, f1


def apply_no_ans_threshold(scores, na_probs, qid_to_has_ans, thresh):
    out = {}
    for qid, s in scores.items():
        if na_probs[qid] > thresh:
            out[qid] = float(not qid_to_has_ans[qid])
        else:
            out[qid] = s
    return out


def make_eval_dict(exact, f1, qid_list=None):
    qids = list(exact) if qid_list is None else qid_list
    total = len(qids)
    if not total:
        # No scored questions at all (e.g. empty predictions): a zero
        # score, not a crash — the runner's eval subprocess must always
        # get parseable output.
        return collections.OrderedDict(
            [("exact", 0.0), ("f1", 0.0), ("total", 0)])
    return collections.OrderedDict([
        ("exact", 100.0 * sum(exact[q] for q in qids) / total),
        ("f1", 100.0 * sum(f1[q] for q in qids) / total),
        ("total", total),
    ])


def find_best_thresh(preds, scores, na_probs, qid_to_has_ans):
    """Sweep the no-answer threshold from -inf upward; at -inf every
    question is predicted unanswerable (score = #no-answer questions)."""
    if not scores:
        return 0.0, 0.0
    cur_score = best_score = sum(
        1 for q in qid_to_has_ans if not qid_to_has_ans[q])
    best_thresh = 0.0
    for qid in sorted(na_probs, key=lambda q: na_probs[q]):
        if qid not in scores:
            continue
        if qid_to_has_ans[qid]:
            diff = scores[qid]
        else:
            diff = -1 if preds[qid] else 0
        cur_score += diff
        if cur_score > best_score:
            best_score = cur_score
            best_thresh = na_probs[qid]
    return 100.0 * best_score / len(scores), best_thresh


def evaluate(dataset, predictions, na_probs=None, na_prob_thresh=0.0):
    qid_to_has_ans = {
        qa["id"]: bool(
            [a for a in qa["answers"] if normalize_answer(a["text"])])
        for qa in iter_qas(dataset)}
    exact_raw, f1_raw = get_raw_scores(dataset, predictions)
    if na_probs is None:
        exact, f1 = exact_raw, f1_raw
    else:
        exact = apply_no_ans_threshold(
            exact_raw, na_probs, qid_to_has_ans, na_prob_thresh)
        f1 = apply_no_ans_threshold(
            f1_raw, na_probs, qid_to_has_ans, na_prob_thresh)
    out = make_eval_dict(exact, f1)
    has_ans = [q for q in exact if qid_to_has_ans[q]]
    no_ans = [q for q in exact if not qid_to_has_ans[q]]
    for prefix, qids in (("HasAns", has_ans), ("NoAns", no_ans)):
        if qids:
            sub = make_eval_dict(exact, f1, qids)
            for k, v in sub.items():
                out[f"{prefix}_{k}"] = v
    if na_probs is not None:
        best_exact, exact_thresh = find_best_thresh(
            predictions, exact_raw, na_probs, qid_to_has_ans)
        best_f1, f1_thresh = find_best_thresh(
            predictions, f1_raw, na_probs, qid_to_has_ans)
        out["best_exact"] = best_exact
        out["best_exact_thresh"] = exact_thresh
        out["best_f1"] = best_f1
        out["best_f1_thresh"] = f1_thresh
    # Keys the runner's summary parser reads (same contract as v1.1).
    out["exact_match"] = out["exact"]
    return dict(out)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("dataset")
    p.add_argument("predictions")
    p.add_argument("--na-prob-file", default=None)
    p.add_argument("--na-prob-thresh", type=float, default=0.0)
    args = p.parse_args(argv)
    with open(args.dataset) as f:
        dataset = json.load(f)["data"]
    with open(args.predictions) as f:
        predictions = json.load(f)
    na_probs = None
    if args.na_prob_file:
        with open(args.na_prob_file) as f:
            na_probs = json.load(f)
    print(json.dumps(evaluate(
        dataset, predictions, na_probs, args.na_prob_thresh)))


if __name__ == "__main__":
    main()
