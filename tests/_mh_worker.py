"""Worker for the 2-process multi-host integration tests.

Modes (argv[4], default "dp"):
  dp    — data-parallel train steps; both ranks must agree on losses.
  fsdp  — fully-sharded params over both processes, then the multi-host
          checkpoint leg: train 2 steps, save (the process_allgather
          collective path of utils/checkpoint.py — params are sharded
          across processes, so each rank holds NON-addressable shards of
          the other's), restore onto a fresh state, and verify the next
          step from the restored state matches the next step from the
          live state exactly (SURVEY §5.4's multi-host sharded
          checkpoint; reference rank-0 torch.save
          run_pretraining.py:513-523).
  pp    — GPipe pipeline over a 2-stage 'pipe' axis laid out so stage 0
          lives in process 0 and stage 1 in process 1: the stage-to-stage
          ppermute CROSSES the process boundary (the default id-ordered
          mesh would keep pipe partners intra-process — later mesh axes
          vary fastest). Both ranks must agree on losses.
  pp_tp — pipeline x tensor parallelism, same cross-process pipe layout;
          the per-stage tensor-parallel collectives stay intra-process
          (one binary process boundary cannot straddle both axes).
  sp    — multi-host long context in the production layout: 'data' splits
          the hosts (per-rank loader slices stay valid), 'seq' shards the
          sequence WITHIN each host (ring attention's ppermute rides the
          intra-host links), ring attention backend end to end.
  pp_sp — pipeline x sequence parallelism: the cross-process pipe layout
          of 'pp' with 'seq' sharded intra-process — the {pipe, seq}
          manual region's stage ppermute crosses the process boundary
          while the ring K/V rotation stays intra-process.
  dcn   — multi-slice hybrid mesh (MeshConfig(dcn_data=2) with process
          granules — the CPU analog of slices): 2 DCN data replicas x 4
          ICI data shards; the gradient all-reduce spans the process
          boundary exactly once along the data axis.
  kfac  — K-FAC across both processes on the dp mesh: tapped-stats factor
          update, batched inverse update, preconditioned train steps; both
          ranks must agree on losses (the factor statistics and the
          preconditioned gradient reductions are global collectives).
  kfac_fused — same mesh, but the whole K-FAC flow in ONE compiled step:
          fused in-train factor capture from microbatch 0's backward +
          cond-gated in-jit inverse rebuilds + preconditioning.
"""
import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

coordinator, n_proc, rank = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
mode = sys.argv[4] if len(sys.argv) > 4 else "dp"
jax.distributed.initialize(
    coordinator_address=coordinator, num_processes=n_proc, process_id=rank)

import jax.numpy as jnp
import numpy as np
from jax.experimental import multihost_utils

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bert_pytorch_tpu import optim, pretrain
from bert_pytorch_tpu.config import BertConfig
from bert_pytorch_tpu.models import BertForPreTraining
from bert_pytorch_tpu.parallel import MeshConfig, create_mesh, logical_axis_rules
from bert_pytorch_tpu.utils import checkpoint as ckpt

assert jax.process_count() == n_proc, jax.process_count()
assert len(jax.devices()) == 4 * n_proc, len(jax.devices())

config = BertConfig(vocab_size=64, hidden_size=16, num_hidden_layers=2,
                    num_attention_heads=2, intermediate_size=32,
                    max_position_embeddings=16, next_sentence=True)
model = BertForPreTraining(
    config, dtype=jnp.float32,
    attention_backend="ring" if mode == "sp" else "xla")
if mode == "fsdp":
    mesh = create_mesh(MeshConfig(data=-1, fsdp=4 * n_proc))
    rules = logical_axis_rules("fsdp")
elif mode == "pp":
    # Reorder devices so 'pipe' is the slowest-varying axis: stage p gets
    # process p's devices, so the ppermute crosses the process boundary.
    # create_mesh reshapes the list into (data,fsdp,pipe,seq,model) in C
    # order; for shape (4,1,2,1,1) flat = d*2 + p, so put devs[p*4+d] there.
    devs = jax.devices()
    order = [devs[p * 4 + d] for d in range(4) for p in range(2)]
    mesh = create_mesh(MeshConfig(data=-1, pipe=2), devices=order)
    rules = logical_axis_rules("pp")
elif mode == "pp_tp":
    # Same cross-process pipe layout; shape (2,1,2,1,2) has
    # flat = d*4 + p*2 + m, so position [d,p,m] gets devs[p*4 + d*2 + m]
    # (model partners stay intra-process — flat diff 1 inside a process).
    devs = jax.devices()
    order = [devs[p * 4 + d * 2 + m]
             for d in range(2) for p in range(2) for m in range(2)]
    mesh = create_mesh(MeshConfig(data=-1, pipe=2, model=2), devices=order)
    rules = logical_axis_rules("pp_tp")
elif mode == "sp":
    # id-ordered: 'data' (slowest) splits the processes, 'seq' stays
    # intra-process — check_batch_process_locality's supported layout.
    mesh = create_mesh(MeshConfig(data=-1, seq=4))
    rules = logical_axis_rules("sp")
elif mode == "pp_sp":
    # Cross-process pipe with intra-process seq: shape (2,1,2,2,1) has
    # flat = d*4 + p*2 + s, so position [d,p,s] gets devs[p*4 + d*2 + s]
    # (seq partners differ by flat 1 inside a process; pipe partners
    # differ by 4 — the process stride).
    devs = jax.devices()
    order = [devs[p * 4 + d * 2 + s]
             for d in range(2) for p in range(2) for s in range(2)]
    mesh = create_mesh(MeshConfig(data=-1, pipe=2, seq=2), devices=order)
    rules = logical_axis_rules("pp")
elif mode == "dcn":
    mesh = create_mesh(MeshConfig(
        data=-1, dcn_data=2, dcn_process_granule=True))
    # The hybrid layout puts the DCN granule stride on the data axis's
    # SLOWEST dimension: each contiguous half must be one process's
    # devices (the property that keeps every other axis granule-local).
    flat = mesh.devices.reshape(-1)
    assert {d.process_index for d in flat[:4]} in ({0}, {1}), flat[:4]
    assert ({d.process_index for d in flat[:4]}
            != {d.process_index for d in flat[4:]}), flat
    rules = logical_axis_rules("dp")
else:
    mesh = create_mesh(MeshConfig(data=-1))
    rules = logical_axis_rules("dp")
schedule = optim.warmup_poly_schedule(1e-3, 0.1, 50)
tx = optim.lamb(schedule, weight_decay_mask=optim.no_decay_mask)
S = 16
local_b = 8  # per process; global batch 16
accum = 2 if mode.startswith("pp") else 1  # pp needs >= stages microbatches
sample = (jnp.zeros((1, S), jnp.int32),) * 3

# dp/fsdp: every process's devices own DISTINCT global batch rows, so each
# rank contributes its own (rank-seeded) local slice. pp modes: the
# cross-process pipe layout makes each process a pipe-REPLICA of every
# batch row — put_batch then requires the full global batch, byte-identical
# on both ranks (a real constraint of host-spanning pipeline stages: every
# stage host must see the same input feed).
if mode.startswith("pp"):
    rng = np.random.default_rng(0)
    n_rows = local_b * n_proc
else:
    rng = np.random.default_rng(rank)
    n_rows = local_b
host = {
    "input_ids": rng.integers(0, 64, (n_rows, S)).astype(np.int32),
    "segment_ids": np.zeros((n_rows, S), np.int32),
    "input_mask": np.ones((n_rows, S), np.int32),
    "masked_lm_labels": np.where(rng.random((n_rows, S)) < 0.2,
                                 rng.integers(0, 64, (n_rows, S)),
                                 -1).astype(np.int32),
    "next_sentence_labels": rng.integers(0, 2, (n_rows,)).astype(np.int32),
}
with mesh:
    sh = pretrain.state_shardings(mesh, model, rules, sample)
    bs = pretrain.batch_shardings(
        mesh, {"input_ids": 3, "segment_ids": 3, "input_mask": 3,
               "masked_lm_labels": 3, "next_sentence_labels": 2},
        seq_sharded=(mode in ("sp", "pp_sp")))
    if not mode.startswith("pp"):
        # pp modes deliberately violate locality (cross-process pipe) and
        # compensate with a byte-identical replicated feed; the sliced-feed
        # modes must satisfy the guard the runner enforces.
        pretrain.check_batch_process_locality(mesh)
    init_fn = pretrain.make_init_fn(model, tx, sample, sh)
    state = init_fn(jax.random.PRNGKey(0))
    kfac_obj = kstate = None
    if mode in ("kfac", "kfac_fused"):
        tapped = BertForPreTraining(config, dtype=jnp.float32, kfac_tap=True)
        apply_loss, tap_shape_fn = pretrain.make_kfac_fns(tapped, True)
        kfac_obj = optim.KFAC(apply_loss, tap_shape_fn)
    if mode.startswith("pp"):
        step = pretrain.make_pp_train_step(model, tx, mesh, schedule=schedule,
            next_sentence=True, shardings=sh, batch_shardings_=bs)
    elif mode in ("kfac", "kfac_fused"):
        pass  # built after kstate shardings below
    else:
        step = pretrain.make_train_step(model, tx, schedule=schedule,
            next_sentence=True, shardings=sh, batch_shardings_=bs)
    # multi-host path of put_batch: each process contributes its local slice
    batch = pretrain.put_batch(pretrain.stack_microbatches(host, accum), bs)
    losses = []
    if mode == "kfac":
        mb0 = {k: v[0] for k, v in batch.items()}
        kstate = kfac_obj.init(state.params, host)
        kshard = optim.kfac_state_shardings(mesh, kstate)
        kstate = jax.device_put(kstate, kshard)
        step = pretrain.make_train_step(model, tx, schedule=schedule,
            next_sentence=True, shardings=sh, batch_shardings_=bs,
            kfac=kfac_obj, kfac_shardings=kshard)
        for i in range(3):
            kstate = kfac_obj.update_factors(
                kstate, state.params, mb0, jax.random.PRNGKey(i))
            kstate = kfac_obj.update_inverses(kstate)
            state, metrics = step(state, batch, kstate)
            losses.append(float(metrics["loss"]))
    elif mode == "kfac_fused":
        # Fused in-train capture + cond-gated in-jit inverses, with the
        # factor stacks sharded across BOTH processes' devices: the
        # whole K-FAC flow is one compiled step per iteration.
        kstate = kfac_obj.init(state.params, host)
        kshard = optim.kfac_state_shardings(mesh, kstate)
        kstate = jax.device_put(kstate, kshard)
        step = pretrain.make_train_step(model, tx, schedule=schedule,
            next_sentence=True, shardings=sh, batch_shardings_=bs,
            kfac=kfac_obj, kfac_shardings=kshard,
            kfac_capture_model=tapped, kfac_factor_interval=1,
            kfac_inv_interval=2)
        for i in range(3):
            state, metrics, kstate = step(state, batch, kstate)
            losses.append(float(metrics["loss"]))
        assert int(kstate.count) == 3, int(kstate.count)
    else:
        for _ in range(2 if mode == "fsdp" else 3):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))

    if mode == "fsdp":
        # The params really are sharded across the two processes — the
        # checkpoint save MUST exercise the collective gather path.
        p0 = jax.tree_util.tree_leaves(state.params)[0]
        assert not p0.is_fully_addressable, p0.sharding
        out_dir = sys.argv[5]
        ckpt.save_checkpoint(out_dir, 2, {
            "model": state.params,
            "optimizer": state.opt_state,
            "rng": state.rng,
        })
        ckpt.wait_for_pending_save()
        # Rank 1 must not read before rank 0's atomic rename lands.
        multihost_utils.sync_global_devices("mh_ckpt_written")

        state, metrics = step(state, batch)  # live continuation
        losses.append(float(metrics["loss"]))

        step_no, loaded = ckpt.load_latest_checkpoint(out_dir)
        assert step_no == 2, step_no
        # Restore exactly as run_pretraining.py does: onto an ABSTRACT
        # template (a device_get of live fsdp state would fail — the
        # non-addressable-shards defect this test exists to catch).
        abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
        restored = pretrain.TrainState(
            params=jax.device_put(
                ckpt.restore_tree(abstract.params, loaded["model"]),
                sh.params),
            opt_state=jax.device_put(
                ckpt.restore_tree(abstract.opt_state, loaded["optimizer"]),
                sh.opt_state),
            rng=jax.device_put(
                ckpt.restore_tree(abstract.rng, loaded["rng"]), sh.rng),
        )
        restored, r_metrics = step(restored, batch)
        live, resumed = losses[-1], float(r_metrics["loss"])
        assert abs(live - resumed) < 1e-6, (live, resumed)
        print(f"RANK{rank} CKPT OK live={live:.6f} resumed={resumed:.6f}",
              flush=True)

print(f"RANK{rank} OK losses={['%.4f' % l for l in losses]}", flush=True)
