"""Worker for the 2-process multi-host integration test."""
import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

coordinator, n_proc, rank = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
jax.distributed.initialize(
    coordinator_address=coordinator, num_processes=n_proc, process_id=rank)

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bert_pytorch_tpu import optim, pretrain
from bert_pytorch_tpu.config import BertConfig
from bert_pytorch_tpu.models import BertForPreTraining
from bert_pytorch_tpu.parallel import MeshConfig, create_mesh, logical_axis_rules

assert jax.process_count() == n_proc, jax.process_count()
assert len(jax.devices()) == 4 * n_proc, len(jax.devices())

config = BertConfig(vocab_size=64, hidden_size=16, num_hidden_layers=2,
                    num_attention_heads=2, intermediate_size=32,
                    max_position_embeddings=16, next_sentence=True)
model = BertForPreTraining(config, dtype=jnp.float32)
mesh = create_mesh(MeshConfig(data=-1))
rules = logical_axis_rules("dp")
schedule = optim.warmup_poly_schedule(1e-3, 0.1, 50)
tx = optim.lamb(schedule, weight_decay_mask=optim.no_decay_mask)
S = 16
local_b = 8  # per process; global batch 16
sample = (jnp.zeros((1, S), jnp.int32),) * 3

rng = np.random.default_rng(rank)
host = {
    "input_ids": rng.integers(0, 64, (local_b, S)).astype(np.int32),
    "segment_ids": np.zeros((local_b, S), np.int32),
    "input_mask": np.ones((local_b, S), np.int32),
    "masked_lm_labels": np.where(rng.random((local_b, S)) < 0.2,
                                 rng.integers(0, 64, (local_b, S)),
                                 -1).astype(np.int32),
    "next_sentence_labels": rng.integers(0, 2, (local_b,)).astype(np.int32),
}
with mesh:
    sh = pretrain.state_shardings(mesh, model, rules, sample)
    bs = pretrain.batch_shardings(mesh, {"input_ids": 3, "segment_ids": 3,
        "input_mask": 3, "masked_lm_labels": 3, "next_sentence_labels": 2})
    state = pretrain.make_init_fn(model, tx, sample, sh)(jax.random.PRNGKey(0))
    step = pretrain.make_train_step(model, tx, schedule=schedule,
        next_sentence=True, shardings=sh, batch_shardings_=bs)
    # multi-host path of put_batch: each process contributes its local slice
    batch = pretrain.put_batch(pretrain.stack_microbatches(host, 1), bs)
    losses = []
    for _ in range(3):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
print(f"RANK{rank} OK losses={['%.4f' % l for l in losses]}", flush=True)
