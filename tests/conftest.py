"""Test harness configuration.

Multi-device logic is tested on a virtual 8-device CPU mesh
(``--xla_force_host_platform_device_count=8``) — the TPU-world analog of the
reference's Gloo-backend CPU test harness (reference src/dataset.py:455).
These env vars must be set before jax initializes its backends, hence the
module-level assignment in conftest.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import sys  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

# A TPU plugin in the environment may force jax_platforms via jax.config at
# interpreter startup (sitecustomize), which overrides the JAX_PLATFORMS env
# var — so the config override is the only reliable way to pin tests to the
# virtual CPU mesh.
jax.config.update("jax_platforms", "cpu")

# The CPU backend's default matmul precision truncates inputs to bf16 (TPU
# MXU emulation), which would drown kernel-vs-reference comparisons in 1e-2
# noise. Tests compare numerics, so force true fp32 matmuls.
jax.config.update("jax_default_matmul_precision", "highest")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_prng_impl():
    """run_pretraining sets the process-global PRNG impl (--rng_impl, default
    'rbg'); reset it so tests that ran after a runner test see the same
    threefry streams as tests that ran first."""
    yield
    jax.config.update("jax_default_prng_impl", "threefry2x32")


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def tiny_config():
    from bert_pytorch_tpu.config import BertConfig

    return BertConfig(
        vocab_size=128,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        intermediate_size=64,
        max_position_embeddings=64,
        type_vocab_size=2,
        next_sentence=True,
    )
