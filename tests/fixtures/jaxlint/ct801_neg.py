"""CT801 negative: registered kinds pass; a dynamic kind is out of this
tier's reach (the runtime schema lint still judges the artifact)."""


def emit_window(sink, step):
    sink.write({"kind": "train_window", "step": step, "loss": 0.0})


def emit_dynamic(record, kind):
    record["kind"] = kind
    return record
