"""CT801 positive: record kinds emitted off the schema registry (the
fixture registry lives in tests/fixtures/jaxlint/telemetry/schema.py,
passed as program context by the tests)."""


def emit_window(sink, step):
    sink.write({"kind": "train_windw", "step": step, "loss": 0.0})


def emit_fault(record):
    record["kind"] = "falt"
    return record
