"""CT802 negative: every declared flag is read — directly, via literal
getattr, via an f-string getattr pattern, or named in a key list — and
programmatic ``args.x = ...`` stores count as declarations."""
import argparse

TASKS = ("glue", "squad")


def build_parser():
    parser = argparse.ArgumentParser()
    parser.add_argument("--log-steps", type=int)
    parser.add_argument("--seed", type=int)
    parser.add_argument("--glue-checkpoint")
    parser.add_argument("--squad-checkpoint")
    parser.add_argument("--resume-step", type=int)
    return parser


def require_args(names):
    return names


def main():
    args = build_parser().parse_args()
    seed = getattr(args, "seed", 0)
    for task in TASKS:
        print(getattr(args, f"{task}_checkpoint"))
    require_args(["resume_step"])
    args.derived_total = args.log_steps * seed
    return args.derived_total
